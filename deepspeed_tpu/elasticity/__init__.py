from deepspeed_tpu.elasticity.elasticity import (ElasticityConfig, ElasticityConfigError,
                                                 ElasticityError,
                                                 ElasticityIncompatibleWorldSize,
                                                 compute_elastic_config,
                                                 elasticity_enabled)
