"""Elastic training agent: worker supervision + membership-change restart.

Reference: ``deepspeed/elasticity/elastic_agent.py:28`` (``DSElasticAgent``
subclasses torch-elastic's ``LocalElasticAgent``: monitors workers,
restarts the group on failure/membership change, propagates env).

TPU redesign: there is no torch-elastic rendezvous; membership is the
accelerator pod itself.  The agent supervises the per-host worker
processes spawned by the ``dst`` launcher, and on a worker failure or a
resource-set change it kills the group and relaunches with a batch
configuration re-solved by the elasticity solver
(``elasticity.compute_elastic_config``) for the new world size —
restart-with-reshard replaces in-band recovery, with resumable
checkpoints carrying the state (SURVEY §5.3's TPU mapping).
"""

import os
import random
import signal
import subprocess
import time
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.comm.recovery import (RECOVERY_EXIT_CODES,
                                         RENDEZVOUS_DIR_ENV,
                                         consume_recovery_marker)
from deepspeed_tpu.elasticity.elasticity import compute_elastic_config
from deepspeed_tpu.runtime.fault_tolerance import (PREEMPTION_EXIT_CODES,
                                                   backoff_delay)
from deepspeed_tpu.utils.logging import log_dist, logger


class WorkerSpec:
    """What to run on each (re)start: argv template + env.

    ``cmd`` may be a list (fixed argv) or a callable returning the argv —
    callables receive the restart's full env dict (so launchers that bake
    env exports into the command, pdsh/mpirun, pick up the re-solved batch
    config and the live host set) and are invoked per (re)start."""

    def __init__(self, cmd, env: Optional[Dict[str, str]] = None):
        self.cmd = cmd
        self.env = dict(env or {})

    def argv(self, env: Optional[Dict[str, str]] = None) -> List[str]:
        if callable(self.cmd):
            import inspect
            params = inspect.signature(self.cmd).parameters
            return list(self.cmd(env or {}) if params else self.cmd())
        return list(self.cmd)


class DSElasticAgent:

    def __init__(self, spec: WorkerSpec, ds_config: Optional[Dict] = None,
                 max_restarts: int = 3, monitor_interval: float = 1.0,
                 world_size_fn: Optional[Callable[[], int]] = None,
                 telemetry=None,
                 restart_backoff_s: float = 1.0,
                 restart_backoff_max_s: float = 30.0,
                 restart_jitter: float = 0.2,
                 stability_window_s: float = 300.0,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None):
        """``world_size_fn`` reports the currently-available world size
        (pod metadata / scheduler probe); a change triggers a restart with
        a re-solved elastic batch config.  ``telemetry`` (a TelemetryHub)
        receives a structured ``worker_exit`` record for every worker-group
        exit — failure, membership change, clean finish, or give-up — so
        restarts leave an audit trail instead of happening silently.

        Restart hygiene: crash restarts back off exponentially
        (``restart_backoff_s`` → ``restart_backoff_max_s``, ±``restart_jitter``
        relative noise against stampedes), and a group that stayed up for
        ``stability_window_s`` seconds resets the restart budget — a crash
        every few hours must not accumulate toward give-up forever.
        Workers exiting with the preemption code (143 / -SIGTERM) restart
        immediately without touching the budget: the scheduler took the
        machine, the program did nothing wrong.  Coordinator-confirmed
        recovery exits (reserved codes 113/114, or SIGKILL with a fresh
        rendezvous marker — see :meth:`_recovery_exit_cause`) are treated
        the same way.  The knobs are overridable
        via the ``fault_tolerance`` block of ``ds_config``.  ``sleep_fn``
        and ``rng`` are injectable so tests never wall-clock sleep."""
        self.spec = spec
        self.ds_config = ds_config or {}
        ftc = self.ds_config.get("fault_tolerance", {})
        self.max_restarts = max_restarts
        self.monitor_interval = monitor_interval
        self.world_size_fn = world_size_fn or (lambda: 1)
        self.telemetry = telemetry
        self.restart_backoff_s = float(
            ftc.get("restart_backoff_s", restart_backoff_s))
        self.restart_backoff_max_s = float(
            ftc.get("restart_backoff_max_s", restart_backoff_max_s))
        self.restart_jitter = float(ftc.get("restart_jitter", restart_jitter))
        self.stability_window_s = float(
            ftc.get("stability_window_s", stability_window_s))
        self._sleep = sleep_fn
        self._rng = rng
        self.restart_count = 0
        self.preemption_count = 0
        self.recovery_count = 0
        self._proc: Optional[subprocess.Popen] = None
        self._world = None
        self._start_t: Optional[float] = None
        self._last_backoff_s = 0.0

    def _emit_worker_exit(self, exit_code, reason: str):
        if self.telemetry is None:
            return
        try:
            self.telemetry.emit("worker_exit", {
                "exit_code": exit_code,
                "reason": reason,
                "restart_count": self.restart_count,
                "preemption_count": self.preemption_count,
                "uptime_s": (time.monotonic() - self._start_t
                             if self._start_t is not None else None),
                "backoff_s": self._last_backoff_s,
                "world_size": self._world,
                "pid": self._proc.pid if self._proc is not None else None,
            })
            self.telemetry.flush()
        except Exception as e:
            logger.warning(f"elastic agent: worker_exit emission failed: {e}")

    def _emit_downtime(self, t_down: float, reason: str, exit_code):
        """Structured ``downtime`` record: the worker_exit→restart gap
        (detection + reap + backoff + relaunch), the raw material for the
        goodput ledger's cross-attempt ``downtime`` category
        (``telemetry/ledger.py:fold_goodput``)."""
        if self.telemetry is None:
            return
        try:
            self.telemetry.emit("downtime", {
                "downtime_s": time.monotonic() - t_down,
                "backoff_s": self._last_backoff_s,
                "reason": reason,
                "exit_code": exit_code,
                "restart_count": self.restart_count,
                "preemption_count": self.preemption_count,
                "world_size": self._world,
            })
            self.telemetry.flush()
        except Exception as e:
            logger.warning(f"elastic agent: downtime emission failed: {e}")

    def _recovery_exit_cause(self, rc) -> Optional[str]:
        """Classify a worker exit as a coordinator-directed recovery exit.

        Two confirmation paths, mirroring the recovery ladder's two ways
        of retiring a process (``comm/recovery.py``):

        * reserved exit codes (113 restart rung / 114 mesh-shrink
          exclusion) are self-describing — the marker, when present,
          only refines the cause string;
        * ``SIGKILL`` (rc ``-9``) is ambiguous (OOM killer kills the same
          way), so it counts as recovery **only** when the coordinator
          left a fresh ``recovery_exit.json`` marker in the rendezvous
          dir — coordinator-confirmed, per the abort protocol.

        Returns the cause string, or None for an ordinary failure."""
        if rc not in RECOVERY_EXIT_CODES and rc != -signal.SIGKILL:
            return None   # don't burn the one-shot marker on other exits
        rdv_dir = (self.spec.env.get(RENDEZVOUS_DIR_ENV)
                   or os.environ.get(RENDEZVOUS_DIR_ENV))
        marker = (consume_recovery_marker(rdv_dir)
                  if rdv_dir else None)
        if rc in RECOVERY_EXIT_CODES:
            cause = (marker or {}).get("cause") or (
                "mesh_shrink" if rc == RECOVERY_EXIT_CODES[1] else "restart")
            return cause
        if rc == -signal.SIGKILL and marker is not None:
            return (marker.get("cause") or "rank_killed")
        return None

    # ------------------------------------------------------------------ #
    def _elastic_env(self, world: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.spec.env)
        env["DS_ELASTIC_WORLD_SIZE"] = str(world)
        if self.ds_config.get("elasticity", {}).get("enabled", False):
            batch, _valid, micro = compute_elastic_config(
                self.ds_config, "0.0", world_size=world,
                return_microbatch=True)
            env["DS_ELASTIC_TRAIN_BATCH"] = str(batch)
            env["DS_ELASTIC_MICRO_BATCH"] = str(micro)
            log_dist(f"elastic agent: world={world} -> train_batch={batch}, "
                     f"micro={micro}", ranks=[0])
        return env

    def _start(self, world: int):
        self._world = world
        env = self._elastic_env(world)
        self._proc = subprocess.Popen(self.spec.argv(env), env=env,
                                      start_new_session=True)
        self._start_t = time.monotonic()
        log_dist(f"elastic agent: started workers (pid {self._proc.pid}, "
                 f"world {world})", ranks=[0])

    def _stop(self, reason: str = "stop", timeout: float = 15.0):
        """Terminate and REAP the whole worker process group, then emit a
        structured ``worker_exit`` record.  Returns the group leader's
        exit code (None if it had already been collected).

        Reaping matters: the launcher's children share the leader's
        process group (``start_new_session=True``), and without an
        explicit ``waitpid`` sweep over ``-pgid`` they linger as zombies
        across restarts until the agent itself exits."""
        if self._proc is None:
            return None
        rc = self._proc.poll()
        try:
            pgid = os.getpgid(self._proc.pid)
        except ProcessLookupError:
            pgid = self._proc.pid
        if rc is None:
            try:   # kill the whole process group (launcher children incl.)
                os.killpg(pgid, signal.SIGTERM)
            except ProcessLookupError:
                pass
            try:
                rc = self._proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(pgid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
                rc = self._proc.wait()
        # sweep the rest of the group (scoped to -pgid: never steal other
        # children of this process)
        while True:
            try:
                pid, _status = os.waitpid(-pgid, os.WNOHANG)
            except ChildProcessError:
                break
            except OSError:
                break
            if pid == 0:
                break
        self._emit_worker_exit(rc, reason)
        return rc

    # ------------------------------------------------------------------ #
    def run(self, max_steps: Optional[int] = None) -> int:
        """Supervise until the workers exit cleanly, restarts are
        exhausted, or ``max_steps`` monitor ticks pass (testing hook).
        Returns the final exit code."""
        self._start(self.world_size_fn())
        ticks = 0
        while True:
            time.sleep(self.monitor_interval)
            ticks += 1
            rc = self._proc.poll()
            if rc is not None:
                # leader already exited — _stop degrades to reap-and-emit
                if rc == 0:
                    log_dist("elastic agent: workers finished", ranks=[0])
                    self._stop(reason="clean_exit")
                    return 0
                uptime = (time.monotonic() - self._start_t
                          if self._start_t is not None else 0.0)
                recovery_cause = self._recovery_exit_cause(rc)
                if recovery_cause is not None:
                    # the recovery coordinator retired this group on
                    # purpose (ladder rung exit or confirmed rank kill):
                    # like a preemption, the program did nothing wrong —
                    # restart now, burn no crash budget
                    self.recovery_count += 1
                    self._last_backoff_s = 0.0
                    log_dist(f"elastic agent: recovery exit (rc={rc}, "
                             f"cause={recovery_cause}, uptime "
                             f"{uptime:.1f}s) — restarting immediately",
                             ranks=[0])
                    t_down = time.monotonic()
                    self._stop(reason=f"recovery:{recovery_cause}")
                    self._start(self.world_size_fn())
                    self._emit_downtime(
                        t_down, f"recovery:{recovery_cause}", rc)
                    continue
                if rc in PREEMPTION_EXIT_CODES:
                    # the scheduler reclaimed the machine, not a bug:
                    # restart now, leave the crash budget untouched
                    self.preemption_count += 1
                    self._last_backoff_s = 0.0
                    log_dist(f"elastic agent: workers preempted (rc={rc}, "
                             f"uptime {uptime:.1f}s) — restarting "
                             f"immediately", ranks=[0])
                    t_down = time.monotonic()
                    self._stop(reason="preemption")
                    self._start(self.world_size_fn())
                    self._emit_downtime(t_down, "preemption", rc)
                    continue
                if uptime >= self.stability_window_s and self.restart_count:
                    # the group ran long enough to call the previous
                    # failures transient — the budget regenerates
                    log_dist(f"elastic agent: {uptime:.0f}s of stable uptime; "
                             f"resetting restart budget", ranks=[0])
                    self.restart_count = 0
                if self.restart_count >= self.max_restarts:
                    logger.error(f"elastic agent: giving up after "
                                 f"{self.restart_count} restarts (rc={rc})")
                    self._stop(reason="max_restarts_exceeded")
                    return rc
                self.restart_count += 1
                self._last_backoff_s = backoff_delay(
                    self.restart_count, self.restart_backoff_s,
                    self.restart_backoff_max_s, self.restart_jitter,
                    rng=self._rng)
                log_dist(f"elastic agent: worker failure rc={rc} — restart "
                         f"{self.restart_count}/{self.max_restarts} in "
                         f"{self._last_backoff_s:.2f}s", ranks=[0])
                t_down = time.monotonic()
                self._stop(reason="worker_failure")
                self._sleep(self._last_backoff_s)
                self._start(self.world_size_fn())
                self._emit_downtime(t_down, "worker_failure", rc)
                continue
            world = self.world_size_fn()
            if world != self._world:
                # membership change (preemption / scale-up): restart with a
                # re-solved batch config; checkpoints reshard on resume
                log_dist(f"elastic agent: membership {self._world} -> {world}; "
                         f"restarting", ranks=[0])
                t_down = time.monotonic()
                old_world = self._world
                self._last_backoff_s = 0.0
                self._stop(reason=f"membership_change:{old_world}->{world}")
                self._start(world)
                self._emit_downtime(
                    t_down, f"membership_change:{old_world}->{world}", rc)
            if max_steps is not None and ticks >= max_steps:
                self._stop(reason="max_steps")
                return 0
