"""Elastic batch-size / device-count solver.

Reference semantics: ``deepspeed/elasticity/elasticity.py`` —
``compute_elastic_config:233`` with v0.1 (``:83``) and v0.2 (``:126``,
adds model-parallel + chips-per-host divisibility).  Pure math, no device
code: given ``max_train_batch_size`` and candidate ``micro_batch_sizes``,
find the total batch size compatible with the largest set of chip counts,
so the scheduler may scale the job up/down without changing convergence
(global batch = micro x grad_accum x dp_world stays fixed).

On TPU the "gpu count" is a chip count and ``num_gpus_per_node`` maps to
chips-per-host (8 for v5e hosts); v0.2's node granularity is exactly
pod-slice granularity.
"""

import math
import os
import json
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

LATEST_ELASTICITY_VERSION = 0.2
DEEPSPEED_ELASTICITY_CONFIG = "DEEPSPEED_ELASTICITY_CONFIG"

# highly composite numbers — scaling factors that maximize divisor count
# (same classic sequence the reference uses; supports batch sizes to 720K)
_HCN = [1, 2, 4, 6, 12, 24, 36, 48, 60, 120, 180, 240, 360, 720, 840, 1260,
        1680, 2520, 5040, 7560, 10080, 15120, 20160, 25200, 27720, 45360,
        50400, 55440, 83160, 110880, 166320, 221760, 277200, 332640, 498960,
        554400, 665280, 720720]


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Config block (reference ``elasticity/config.py``)."""

    def __init__(self, d: Dict):
        self.enabled = d.get("enabled", False)
        if "max_train_batch_size" not in d:
            raise ElasticityConfigError("max_train_batch_size is required in elasticity config")
        if "micro_batch_sizes" not in d:
            raise ElasticityConfigError("micro_batch_sizes is required in elasticity config")
        self.max_acceptable_batch_size = int(d["max_train_batch_size"])
        self.micro_batches = [int(m) for m in d["micro_batch_sizes"]]
        if not self.micro_batches or any(m <= 0 for m in self.micro_batches):
            raise ElasticityConfigError(f"micro_batch_sizes must be positive: {self.micro_batches}")
        self.min_gpus = int(d.get("min_gpus", 1))
        self.max_gpus = int(d.get("max_gpus", -1))
        if self.min_gpus < 1 or (self.max_gpus != -1 and self.max_gpus < self.min_gpus):
            raise ElasticityConfigError(f"bad min/max gpus: {self.min_gpus}/{self.max_gpus}")
        self.model_parallel_size = int(d.get("model_parallel_size", 1))
        self.num_gpus_per_node = int(d.get("num_gpus_per_node", 1))
        self.min_time = d.get("min_time", 0)
        self.version = float(d.get("version", 0.2))
        self.prefer_larger_batch_size = d.get("prefer_larger_batch_size", True)
        self.ignore_non_elastic_batch_info = d.get("ignore_non_elastic_batch_info", False)


def _candidate_batch_sizes(bases: List[int], max_batch: int) -> List[int]:
    """Scale each base by the largest highly-composite factor keeping the
    product <= max_batch (maximizes the divisor structure of the result)."""
    out = set()
    for b in bases:
        if b >= max_batch:
            out.add(b)
            continue
        limit = max_batch // b
        factor = max(h for h in _HCN if h <= limit)
        out.add(factor * b)
    return sorted(out)


def _valid_gpu_counts(batch: int, micro_batches: List[int], lo: int, hi: int) -> List[int]:
    """All chip counts g in [lo, hi] such that some micro batch divides
    batch/g exactly (i.e. batch = micro x gas x g for integer gas)."""
    valid = set()
    for mb in micro_batches:
        if batch % mb:
            continue
        total = batch // mb          # = g * gas
        for g in range(lo, min(hi, total) + 1):
            if total % g == 0:
                valid.add(g)
    return sorted(valid)


def _solve_v01(micro_batches: List[int], max_batch: int, min_gpus: int,
               max_gpus: int, prefer_larger: bool) -> Tuple[int, List[int]]:
    if any(mb > max_batch for mb in micro_batches):
        raise ElasticityError(
            f"all micro batches {micro_batches} must be <= max_train_batch_size {max_batch}")
    lcm = micro_batches[0]
    for mb in micro_batches[1:]:
        lcm = lcm * mb // math.gcd(lcm, mb)
    candidates = _candidate_batch_sizes(list(micro_batches) + [lcm], max_batch)
    best_batch, best_valid = min(micro_batches), []
    for batch in candidates:
        valid = _valid_gpu_counts(batch, micro_batches, min_gpus, max_gpus)
        better = (len(valid) > len(best_valid)
                  or (len(valid) == len(best_valid)
                      and ((prefer_larger and batch > best_batch)
                           or (not prefer_larger and batch < best_batch))))
        if better:
            best_batch, best_valid = batch, valid
    return best_batch, best_valid


def _solve_v02(micro_batches, max_batch, current_num_gpus, min_gpus, max_gpus,
               prefer_larger, num_gpus_per_node, model_parallel_size):
    """Node-granular variant: chips come in whole hosts; MP divides a host."""
    if num_gpus_per_node % model_parallel_size != 0:
        raise ElasticityError(
            f"chips per host ({num_gpus_per_node}) must be divisible by "
            f"model_parallel_size ({model_parallel_size})")
    dp_per_node = num_gpus_per_node // model_parallel_size

    def pick_micro(batch):
        chosen = None
        for mb in micro_batches:
            if (batch // current_num_gpus) % mb == 0:
                if chosen is None or (prefer_larger and mb > chosen):
                    chosen = mb
        return chosen

    node_batch, node_counts = _solve_v01(
        micro_batches, int(max_batch / dp_per_node),
        max(int(min_gpus / num_gpus_per_node), 1),
        max(int(max_gpus / num_gpus_per_node), 1), prefer_larger)
    batch = int(node_batch) * dp_per_node
    valid_dp = [n * dp_per_node for n in node_counts]
    if current_num_gpus // model_parallel_size in valid_dp:
        return batch, valid_dp, pick_micro(batch)

    # current world size not in the elastic set: fit a batch to it exactly
    current_dp = (current_num_gpus / num_gpus_per_node) * dp_per_node
    fitted = [int(math.floor(max_batch / (mb * current_dp))) * mb * current_dp
              for mb in micro_batches]
    batch = int(max(fitted) if prefer_larger else min(fitted))
    return batch, [int(current_dp)], pick_micro(batch)


def elasticity_enabled(ds_config: Dict) -> bool:
    return ds_config.get("elasticity", {}).get("enabled", False)


def ensure_immutable_elastic_config(runtime_config: Dict):
    """Cross-check the scheduler's view against runtime (reference ``:208``)."""
    if DEEPSPEED_ELASTICITY_CONFIG in os.environ:
        sched = ElasticityConfig(json.loads(os.environ[DEEPSPEED_ELASTICITY_CONFIG]))
        run = ElasticityConfig(runtime_config)
        for field in ("max_acceptable_batch_size", "micro_batches", "version"):
            if getattr(sched, field) != getattr(run, field):
                raise ElasticityConfigError(
                    f"elastic config '{field}' differs between scheduler "
                    f"({getattr(sched, field)}) and runtime ({getattr(run, field)})")
    else:
        logger.warning("DEEPSPEED_ELASTICITY_CONFIG not set; scheduler may scale "
                       "with incompatible chip counts")


def compute_elastic_config(ds_config: Dict, target_deepspeed_version: str = "0.0",
                           world_size: int = 0, return_microbatch: bool = False):
    """Main entry (reference ``compute_elastic_config:233``): returns
    (final_batch_size, valid_gpus[, micro_batch])."""
    if not isinstance(ds_config, dict):
        raise ValueError(f"expected dict config, got {type(ds_config)}")
    if "elasticity" not in ds_config:
        raise ElasticityConfigError("'elasticity' block missing from config")
    cfg = ElasticityConfig(ds_config["elasticity"])
    if not cfg.enabled:
        raise ElasticityConfigError("elasticity is not enabled in config")
    max_gpus = cfg.max_gpus if cfg.max_gpus != -1 else (
        cfg.max_acceptable_batch_size // min(cfg.micro_batches))

    micro = None
    if cfg.version >= 0.2:
        current = world_size if world_size > 0 else cfg.num_gpus_per_node
        batch, valid, micro = _solve_v02(
            cfg.micro_batches, cfg.max_acceptable_batch_size, current,
            cfg.min_gpus, max_gpus, cfg.prefer_larger_batch_size,
            cfg.num_gpus_per_node, cfg.model_parallel_size)
    else:
        batch, valid = _solve_v01(cfg.micro_batches, cfg.max_acceptable_batch_size,
                                  cfg.min_gpus, max_gpus, cfg.prefer_larger_batch_size)
        if world_size > 0:
            if world_size not in valid:
                raise ElasticityIncompatibleWorldSize(
                    f"world size {world_size} not in valid set {valid}")
            for mb in sorted(cfg.micro_batches,
                             reverse=cfg.prefer_larger_batch_size):
                if (batch // world_size) % mb == 0:
                    micro = mb
                    break
    if return_microbatch:
        return batch, valid, micro
    return batch, valid
