"""Flops profiler.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py:FlopsProfiler:23``
— monkey-patches torch functions to count MACs and hooks modules for
latency, printing aggregate + per-module tables.  TPU-native redesign:

* aggregate FLOPs/bytes come from the compiled executable's
  ``cost_analysis()`` — the same HLO that runs, no estimation error;
* the per-module table comes from walking the *jaxpr*: every equation's
  FLOPs are computed analytically (dot_general/conv from shapes,
  elementwise from output size), scaled through ``scan``/``while`` trip
  counts, and attributed to the ``jax.named_scope`` name stack — the jaxpr
  is the module tree, no hooks needed.

``module_depth`` truncates the name-stack depth, ``top_modules`` limits
rows, ``detailed`` toggles the table — the reference's knobs, honored.
"""

import time
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def analyze_fn_cost(fn, *args, **kwargs) -> Dict[str, float]:
    """FLOPs/bytes estimate of one jitted callable via XLA cost analysis."""
    try:
        lowered = jax.jit(fn).lower(*args, **kwargs)
        compiled = lowered.compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        return {
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", cost.get("bytes_accessed", 0.0))),
        }
    except Exception as e:  # cost analysis is best-effort on some backends
        logger.debug(f"cost_analysis unavailable: {e}")
        return {"flops": 0.0, "bytes_accessed": 0.0}


# --------------------------------------------------------------------------- #
# Analytic per-equation FLOP rules
# --------------------------------------------------------------------------- #
def _size(aval) -> int:
    try:
        return int(np.prod(aval.shape)) if aval.shape else 1
    except Exception:
        return 0


def _dot_general_flops(eqn) -> int:
    (lhs, rhs) = eqn.invars[0].aval, eqn.invars[1].aval
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    contract = int(np.prod([lhs.shape[i] for i in lc])) if lc else 1
    batch = int(np.prod([lhs.shape[i] for i in lb])) if lb else 1
    m = int(np.prod([lhs.shape[i] for i in range(len(lhs.shape))
                     if i not in lc and i not in lb]))
    n = int(np.prod([rhs.shape[i] for i in range(len(rhs.shape))
                     if i not in rc and i not in rb]))
    return 2 * batch * m * n * contract


def _conv_flops(eqn) -> int:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval
    return 2 * _size(out) * int(np.prod(rhs.shape[:-1])) // max(rhs.shape[-1], 1)


_ELEMENTWISE2 = {"add", "sub", "mul", "div", "max", "min", "pow", "and", "or",
                 "xor", "atan2", "rem"}
_ELEMENTWISE1 = {"exp", "log", "tanh", "logistic", "rsqrt", "sqrt", "neg",
                 "abs", "sign", "erf", "erf_inv", "sin", "cos", "floor",
                 "ceil", "round", "is_finite", "integer_pow", "cbrt", "log1p",
                 "expm1", "not"}
_REDUCE = {"reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
           "reduce_and", "reduce_or", "argmax", "argmin", "cumsum",
           "cumlogsumexp", "cummax", "cummin", "cumprod", "reduce_precision"}


def _eqn_flops(eqn) -> int:
    name = eqn.primitive.name
    if name == "dot_general":
        return _dot_general_flops(eqn)
    if name == "conv_general_dilated":
        return _conv_flops(eqn)
    if name in _ELEMENTWISE2 or name in _ELEMENTWISE1:
        return max((_size(v.aval) for v in eqn.outvars), default=0)
    if name in _REDUCE:
        return max((_size(v.aval) for v in eqn.invars), default=0)
    return 0


def _scope(eqn, prefix: str) -> str:
    stack = getattr(eqn.source_info, "name_stack", None)
    name = str(stack) if stack is not None else ""
    return "/".join(p for p in (prefix, name) if p)


def _walk(jaxpr, table: Dict[Tuple[str, str], List[int]], mult: int,
          prefix: str):
    for eqn in jaxpr.eqns:
        trips = 1
        if eqn.primitive.name == "scan":
            trips = int(eqn.params.get("length", 1))
        inner = [v for k, v in eqn.params.items()
                 if k in ("jaxpr", "call_jaxpr", "cond_jaxpr", "body_jaxpr")]
        if eqn.primitive.name == "cond":
            inner = list(eqn.params.get("branches", ()))
        for sub in inner:
            # the inner jaxpr's name stack restarts at the transform
            # boundary; carry the equation's own scope down as a prefix
            _walk(getattr(sub, "jaxpr", sub), table, mult * trips,
                  _scope(eqn, prefix))
        if not inner:
            f = _eqn_flops(eqn)
            if f:
                key = (_scope(eqn, prefix) or "<top>", eqn.primitive.name)
                table[key][0] += f * mult
                table[key][1] += mult


def jaxpr_cost_table(fn, *args, module_depth: Optional[int] = None,
                     **kwargs) -> List[Tuple[str, str, int, int]]:
    """[(scope, primitive, flops, calls)] sorted by flops desc.

    The per-module analogue of the reference's hook tables: scopes are
    ``jax.named_scope``/module names recorded in the jaxpr, primitives are
    the ops charged to them.  ``module_depth`` truncates scope paths (rows
    collapsing onto the same truncated path are merged).
    """
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    table: Dict[Tuple[str, str], List[int]] = defaultdict(lambda: [0, 0])
    _walk(closed.jaxpr, table, 1, "")
    if module_depth and module_depth > 0:
        merged: Dict[Tuple[str, str], List[int]] = defaultdict(lambda: [0, 0])
        for (scope, prim), (f, c) in table.items():
            short = "/".join(scope.split("/")[:module_depth])
            merged[(short, prim)][0] += f
            merged[(short, prim)][1] += c
        table = merged
    rows = [(scope, prim, f, c) for (scope, prim), (f, c) in table.items()]
    rows.sort(key=lambda r: -r[2])
    return rows


# --------------------------------------------------------------------------- #
class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler``; enabled by the
    ``flops_profiler`` config block and consulted at ``profile_step``)."""

    def __init__(self, engine=None, model=None):
        self.engine = engine
        self.started = False
        self.flops_per_step: Optional[float] = None
        self._t0 = None
        self.latency = 0.0
        self._tables: Dict[Any, List[Tuple[str, str, int, int]]] = {}

    def _step_fn_and_args(self, batch):
        eng = self.engine
        return (lambda p, b: eng._value_and_grad(p, b, jax.random.PRNGKey(0), 1.0),
                (eng.state.params, batch))

    def start_profile(self, batch=None, ignore_list=None, num_micro_steps: int = 1):
        if self.started:
            return
        self.started = True
        self._t0 = time.time()
        if self.engine is not None and self.flops_per_step is None and batch is not None:
            try:
                fn, args = self._step_fn_and_args(batch)
                cost = analyze_fn_cost(fn, *args)
                self.flops_per_step = cost["flops"] * num_micro_steps
                self._micro_steps = num_micro_steps
                # keep only shapes/dtypes for later re-tracing — holding the
                # device batch itself would pin a micro-batch of HBM
                self._profile_args = (fn, jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
                    if hasattr(x, "shape") else x, args))
            except Exception as e:
                logger.debug(f"flops profile failed: {e}")
                self.flops_per_step = 0.0

    def stop_profile(self):
        if not self.started:
            return
        self.latency = time.time() - (self._t0 or time.time())
        self.started = False

    def get_total_flops(self, as_string: bool = False):
        f = self.flops_per_step or 0.0
        return number_to_string(f, "FLOPs") if as_string else f

    def get_total_duration(self, as_string: bool = False):
        return duration_to_string(self.latency) if as_string else self.latency

    def module_table(self, module_depth=-1, top_modules=50):
        """Per-scope cost rows (computed lazily from the traced step;
        cached per requested depth)."""
        depth = None if module_depth in (-1, None) else module_depth
        if depth not in self._tables and getattr(self, "_profile_args", None):
            fn, args = self._profile_args
            try:
                self._tables[depth] = jaxpr_cost_table(fn, *args,
                                                       module_depth=depth)
            except Exception as e:
                logger.debug(f"jaxpr cost table failed: {e}")
                self._tables[depth] = []
        return self._tables.get(depth, [])[:top_modules]

    def breakdown_payload(self, module_depth=-1, top_modules=20):
        """Cost table as a flat JSON-ready payload — emitted once through
        the TelemetryHub as a ``flops_breakdown`` record so span timelines
        carry FLOPs attribution (tools/trace_merge.py folds it in)."""
        return {
            "flops_per_step": float(self.flops_per_step or 0.0),
            "latency_s": float(self.latency),
            "modules": [
                {"scope": scope, "op": prim, "flops": int(flops),
                 "calls": int(calls)}
                for scope, prim, flops, calls in self.module_table(
                    module_depth=module_depth, top_modules=top_modules)
            ],
        }

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=3,
                            detailed=True, output_file=None):
        lines = [f"flops per step: {self.get_total_flops(True)}, "
                 f"latency: {self.get_total_duration(True)}"]
        if self.latency > 0 and self.flops_per_step:
            lines[0] += (f", achieved: "
                         f"{number_to_string(self.flops_per_step / self.latency, 'FLOPS')}")
        if detailed:
            rows = self.module_table(module_depth=module_depth,
                                     top_modules=max(top_modules, 1))
            if rows:
                width = max(len(r[0]) for r in rows)
                lines.append(f"{'module':<{width}}  {'op':<20} {'GFLOPs':>10} {'calls':>8}")
                for scope, prim, flops, calls in rows:
                    lines.append(f"{scope:<{width}}  {prim:<20} "
                                 f"{flops / 1e9:>10.3f} {calls:>8}")
        msg = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(msg + "\n")
        log_dist(msg, ranks=[0])

    def end_profile(self):
        self.stop_profile()


def number_to_string(num, units=None, precision=2):
    if units is None:
        units = ""
    for scale, suffix in [(1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")]:
        if abs(num) >= scale:
            return f"{num / scale:.{precision}f} {suffix}{units}"
    return f"{num:.{precision}f} {units}"


def duration_to_string(seconds, precision=2):
    if seconds >= 1:
        return f"{seconds:.{precision}f} s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.{precision}f} ms"
    return f"{seconds * 1e6:.{precision}f} us"
