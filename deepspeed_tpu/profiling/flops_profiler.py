"""Flops profiler.

Reference: ``deepspeed/profiling/flops_profiler/profiler.py:FlopsProfiler:23``
— monkey-patches torch functions to count MACs and hooks modules for
latency.  TPU-native: XLA already knows the cost of every compiled program;
we read it from the lowered/compiled executable's ``cost_analysis()``
(an analytic cost model over the same HLO that runs), plus wall-clock
per-step latency for achieved FLOPS.
"""

import time
from typing import Any, Dict, Optional

import jax

from deepspeed_tpu.utils.logging import log_dist, logger


def analyze_fn_cost(fn, *args, **kwargs) -> Dict[str, float]:
    """FLOPs/bytes estimate of one jitted callable via XLA cost analysis."""
    try:
        lowered = jax.jit(fn).lower(*args, **kwargs)
        compiled = lowered.compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, list):  # older jax returns [dict]
            cost = cost[0]
        return {
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", cost.get("bytes_accessed", 0.0))),
        }
    except Exception as e:  # cost analysis is best-effort on some backends
        logger.debug(f"cost_analysis unavailable: {e}")
        return {"flops": 0.0, "bytes_accessed": 0.0}


class FlopsProfiler:
    """Engine-attached profiler (reference ``FlopsProfiler``; enabled by the
    ``flops_profiler`` config block and consulted at ``profile_step``)."""

    def __init__(self, engine=None, model=None):
        self.engine = engine
        self.started = False
        self.flops_per_step: Optional[float] = None
        self._t0 = None
        self.latency = 0.0

    def start_profile(self, batch=None, ignore_list=None, num_micro_steps: int = 1):
        if self.started:
            return
        self.started = True
        self._t0 = time.time()
        if self.engine is not None and self.flops_per_step is None and batch is not None:
            try:
                cost = analyze_fn_cost(
                    lambda p, b: self.engine._value_and_grad(p, b, jax.random.PRNGKey(0), 1.0),
                    self.engine.state.params, batch)
                self.flops_per_step = cost["flops"] * num_micro_steps
            except Exception as e:
                logger.debug(f"flops profile failed: {e}")
                self.flops_per_step = 0.0

    def stop_profile(self):
        if not self.started:
            return
        self.latency = time.time() - (self._t0 or time.time())
        self.started = False

    def get_total_flops(self, as_string: bool = False):
        f = self.flops_per_step or 0.0
        return number_to_string(f, "FLOPs") if as_string else f

    def get_total_duration(self, as_string: bool = False):
        return duration_to_string(self.latency) if as_string else self.latency

    def print_model_profile(self, profile_step=1, module_depth=-1, top_modules=1,
                            detailed=True, output_file=None):
        msg = (f"flops per step: {self.get_total_flops(True)}, "
               f"latency: {self.get_total_duration(True)}")
        if output_file:
            with open(output_file, "w") as f:
                f.write(msg + "\n")
        log_dist(msg, ranks=[0])

    def end_profile(self):
        self.stop_profile()


def number_to_string(num, units=None, precision=2):
    if units is None:
        units = ""
    for scale, suffix in [(1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")]:
        if abs(num) >= scale:
            return f"{num / scale:.{precision}f} {suffix}{units}"
    return f"{num:.{precision}f} {units}"


def duration_to_string(seconds, precision=2):
    if seconds >= 1:
        return f"{seconds:.{precision}f} s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.{precision}f} ms"
    return f"{seconds * 1e6:.{precision}f} us"
