"""Compression primitives: fake quantization and pruning masks.

Functional counterparts of the reference's compressed layer methods
(``deepspeed/compression/basic_layer.py``: ``LinearLayer_Compress``
enable_weight_quantization / enable_*_pruning and ``QuantAct``) and the
``csrc/quantization`` fake-quant kernels.  Torch mutates module state;
here every technique is a pure array transform the training step jits —
fake-quantized weights get straight-through gradients via
``stop_gradient`` algebra, masks are computed from weight statistics.
"""

from typing import Optional

import jax
import jax.numpy as jnp


def _ste(w, wq):
    """Straight-through estimator: forward wq, gradient of identity."""
    return w + jax.lax.stop_gradient(wq - w)


def _grouped(w, groups: int):
    flat = w.reshape(-1)
    n = flat.shape[0]
    g = max(1, min(groups, n))
    pad = (-n) % g
    flat = jnp.pad(flat, (0, pad))
    return flat.reshape(g, -1), n, w.shape


def quantize_weight(w, bits: int, quant_type: str = "symmetric",
                    rounding: str = "nearest", groups: int = 1,
                    rng: Optional[jax.Array] = None):
    """Fake-quantize ``w`` to ``bits`` with STE gradients.

    symmetric: scale = max|w| per group, levels in [-(2^{b-1}-1), 2^{b-1}-1];
    asymmetric: affine min/max mapping to [0, 2^b - 1];
    stochastic rounding uses ``rng`` (the reference's
    ``WEIGHT_QUANTIZE_STOCHASTIC_ROUNDING``).
    """
    gw, n, shape = _grouped(w.astype(jnp.float32), groups)

    def rnd(x):
        if rounding == "stochastic":
            assert rng is not None, "stochastic rounding needs an rng"
            return jnp.floor(x + jax.random.uniform(rng, x.shape))
        return jnp.round(x)

    if quant_type == "symmetric":
        qmax = 2.0 ** (bits - 1) - 1
        scale = jnp.max(jnp.abs(gw), axis=1, keepdims=True) / qmax
        scale = jnp.maximum(scale, 1e-12)
        q = jnp.clip(rnd(gw / scale), -qmax, qmax) * scale
    elif quant_type == "asymmetric":
        qmax = 2.0 ** bits - 1
        lo = jnp.min(gw, axis=1, keepdims=True)
        hi = jnp.max(gw, axis=1, keepdims=True)
        scale = jnp.maximum((hi - lo) / qmax, 1e-12)
        q = jnp.clip(rnd((gw - lo) / scale), 0, qmax) * scale + lo
    else:
        raise ValueError(f"unknown quantization_type {quant_type!r}")

    wq = q.reshape(-1)[:n].reshape(shape).astype(w.dtype)
    return _ste(w, wq)


def quantize_activation(x, bits: int = 8, quant_type: str = "symmetric",
                        dynamic: bool = True, static_range: float = 1.0):
    """Activation fake-quant (reference ``QuantAct``): dynamic per-tensor
    range or a calibrated static range."""
    xf = x.astype(jnp.float32)
    if quant_type == "symmetric":
        qmax = 2.0 ** (bits - 1) - 1
        r = jnp.max(jnp.abs(xf)) if dynamic else static_range
        scale = jnp.maximum(r / qmax, 1e-12)
        q = jnp.clip(jnp.round(xf / scale), -qmax, qmax) * scale
    else:
        qmax = 2.0 ** bits - 1
        lo = jnp.min(xf) if dynamic else -static_range
        hi = jnp.max(xf) if dynamic else static_range
        scale = jnp.maximum((hi - lo) / qmax, 1e-12)
        q = jnp.clip(jnp.round((xf - lo) / scale), 0, qmax) * scale + lo
    return _ste(x, q.astype(x.dtype))


# --------------------------------------------------------------------------- #
# Pruning masks (reference enable_{sparse,row,head,channel}_pruning; methods
# 'l1' = magnitude, 'topk' = keep largest by |w|)
# --------------------------------------------------------------------------- #
def _threshold_keep(scores, ratio):
    """Boolean mask keeping the top (1 - ratio) fraction by score."""
    k = scores.size - int(round(scores.size * ratio))
    if k <= 0:
        return jnp.zeros_like(scores, dtype=bool)
    thresh = jnp.sort(scores.reshape(-1))[-k]
    return scores >= thresh


def sparse_mask(w, ratio: float, method: str = "l1"):
    """Elementwise (unstructured) mask dropping ``ratio`` of the weights."""
    scores = jnp.abs(w.astype(jnp.float32))
    if method not in ("l1", "topk"):
        raise ValueError(f"unknown pruning method {method!r}")
    return _threshold_keep(scores, ratio)


def row_mask(w, ratio: float, method: str = "l1"):
    """[out] mask over output rows; ``w`` is [..., in, out] (column-major
    dense layout used by this framework's blocks)."""
    scores = jnp.linalg.norm(w.astype(jnp.float32).reshape(-1, w.shape[-1]),
                             ord=1, axis=0)
    return _threshold_keep(scores, ratio)


def channel_mask(w, ratio: float, method: str = "l1"):
    """[in] mask over input channels (dim -2)."""
    wf = jnp.moveaxis(w.astype(jnp.float32), -2, 0).reshape(w.shape[-2], -1)
    scores = jnp.linalg.norm(wf, ord=1, axis=1)
    return _threshold_keep(scores, ratio)


def head_mask(w, ratio: float, num_heads: int):
    """[num_heads] mask over attention heads; ``w`` is the output
    projection [..., E, E] whose INPUT dim is split into heads."""
    E = w.shape[-2]
    assert E % num_heads == 0, f"{E} not divisible into {num_heads} heads"
    per = E // num_heads
    wf = w.astype(jnp.float32).reshape(-1, num_heads, per, w.shape[-1])
    scores = jnp.sum(jnp.abs(wf), axis=(0, 2, 3))
    return _threshold_keep(scores, ratio)


def apply_row_mask(w, mask):
    return w * mask.astype(w.dtype)


def apply_head_mask(w, mask, num_heads: int):
    E = w.shape[-2]
    per = E // num_heads
    m = jnp.repeat(mask, per).astype(w.dtype)       # [E]
    return w * m[..., :, None]
