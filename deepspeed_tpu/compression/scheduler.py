"""Compression schedule: when each technique switches on.

Reference: ``deepspeed/compression/scheduler.py``
(``compression_scheduler``): each technique has a ``schedule_offset``
(global step at which it activates); ``check_all_modules`` flips layer
flags once the offset passes.  Here the scheduler returns a static
enabled-dict; the engine re-jits the (pure) transform when a flag flips
— bounded by the number of techniques.
"""

from typing import Dict

from deepspeed_tpu.utils.logging import log_dist

TECHNIQUES = ("weight_quantization", "activation_quantization",
              "sparse_pruning", "row_pruning", "head_pruning",
              "channel_pruning")


class CompressionScheduler:

    def __init__(self, compression_config: Dict):
        self.config = compression_config or {}
        self.offsets: Dict[str, int] = {}
        self.enabled: Dict[str, bool] = {}
        for t in TECHNIQUES:
            shared = (self.config.get(t, {}) or {}).get("shared_parameters", {})
            if shared.get("enabled", False):
                self.offsets[t] = int(shared.get("schedule_offset", 0))
                self.enabled[t] = False

    def check_all_modules(self, global_step: int) -> Dict[str, bool]:
        """Enabled-flags for ``global_step``; logs each activation once."""
        for t, off in self.offsets.items():
            if not self.enabled[t] and global_step >= off:
                self.enabled[t] = True
                log_dist(f"compression: {t} active from step {global_step}",
                         ranks=[0])
        return dict(self.enabled)

    # per-technique views (reference check_* methods)
    def check_weight_quantization(self, step):
        return self.check_all_modules(step).get("weight_quantization", False)

    def check_sparse_pruning(self, step):
        return self.check_all_modules(step).get("sparse_pruning", False)

    def check_row_pruning(self, step):
        return self.check_all_modules(step).get("row_pruning", False)

    def check_head_pruning(self, step):
        return self.check_all_modules(step).get("head_pruning", False)

    def check_channel_pruning(self, step):
        return self.check_all_modules(step).get("channel_pruning", False)
