from deepspeed_tpu.compression.basic_ops import (apply_head_mask,
                                                 apply_row_mask, channel_mask,
                                                 head_mask, quantize_activation,
                                                 quantize_weight, row_mask,
                                                 sparse_mask)
from deepspeed_tpu.compression.compress import (CompressionSpec,
                                                init_compression,
                                                redundancy_clean)
from deepspeed_tpu.compression.layer_reduction import (apply_layer_reduction,
                                                       student_initialization,
                                                       student_model_config)
from deepspeed_tpu.compression.scheduler import CompressionScheduler

__all__ = ["quantize_weight", "quantize_activation", "sparse_mask",
           "row_mask", "channel_mask", "head_mask", "apply_row_mask",
           "apply_head_mask", "init_compression", "redundancy_clean",
           "CompressionSpec", "CompressionScheduler",
           "apply_layer_reduction", "student_initialization",
           "student_model_config"]
