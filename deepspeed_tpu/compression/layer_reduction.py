"""Layer reduction + distillation initialization (KD student setup).

Reference: ``deepspeed/compression/compress.py:167``
(``student_initialization``): given a trained TEACHER, build a shallower
STUDENT whose layer ``s`` starts from teacher layer ``teacher_layer[s]``
and whose embeddings/head (``other_module_name``) copy over — the
TinyBERT/MiniLM-style task-agnostic distillation recipe.

Config block (reference ``compression/constants.py``)::

    "compression_training": {
      "layer_reduction": {
        "enabled": true,
        "keep_number_layer": 6,
        "teacher_layer": [1, 3, 5, 7, 9, 11],
        "module_name_prefix": "blocks",      # param-tree analogue
        "other_module_name": ["wte", "wpe"]  # informational: non-block
      }                                      # leaves ALWAYS copy here
    }

TPU-native: models stack layers as ``[L, ...]`` scan leaves, so selecting
teacher layers is ONE gather per leaf (``leaf[teacher_layer]``) instead of
the reference's per-module ``recursive_getattr`` + ``copy.deepcopy`` walk.
Non-scan ``h{i}`` dicts are re-keyed.  The caller passes the teacher's
param tree and model config; back comes the student's — the functional
equivalent of mutating the student model in place.
"""

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import log_dist


def layer_reduction_config(ds_config: Dict) -> Optional[Dict]:
    """The enabled ``layer_reduction`` block, or None."""
    cfg = (ds_config.get("compression_training", ds_config) or {})
    lr = cfg.get("layer_reduction", {}) or {}
    return lr if lr.get("enabled", False) else None


def _select_layers(blocks, teacher_layer: List[int], prefix: str):
    if isinstance(blocks, dict) and any(k.startswith("h") and k[1:].isdigit()
                                        for k in blocks):
        # non-scan layout: h{teacher_layer[s]} -> h{s}
        return {f"h{s}": blocks[f"h{t}"] for s, t in enumerate(teacher_layer)}
    # scan layout: every leaf carries a leading [L] dim — one gather.
    # Bounds-check eagerly: jax gather CLAMPS out-of-range indices, which
    # would silently distill from the wrong teacher layer.
    L = int(jax.tree.leaves(blocks)[0].shape[0])
    assert all(0 <= t < L for t in teacher_layer), (
        f"teacher_layer {teacher_layer} out of range for {L} teacher layers")
    idx = jnp.asarray(teacher_layer)
    return jax.tree.map(lambda a: a[idx], blocks)


def student_initialization(teacher_params: Dict, ds_config: Dict,
                           blocks_key: Optional[str] = None) -> Dict:
    """Student params from teacher params per the layer_reduction block
    (reference ``student_initialization:184``).  Every non-block leaf
    (embeddings, final LN, head — the reference's ``other_module_name``)
    is copied as-is; the block stack keeps only ``teacher_layer``."""
    lr = layer_reduction_config(ds_config)
    assert lr is not None, "layer_reduction not enabled in config"
    teacher_layer = list(lr["teacher_layer"])
    keep = int(lr.get("keep_number_layer", len(teacher_layer)))
    assert len(teacher_layer) == keep, (
        f"teacher_layer has {len(teacher_layer)} entries but "
        f"keep_number_layer={keep} (reference asserts the same match)")
    blocks_key = blocks_key or lr.get("module_name_prefix", "blocks")
    assert blocks_key in teacher_params, (
        f"param tree has no {blocks_key!r} stack; keys: "
        f"{list(teacher_params)}")
    student = dict(teacher_params)
    student[blocks_key] = _select_layers(teacher_params[blocks_key],
                                         teacher_layer, blocks_key)
    log_dist(f"layer_reduction: student keeps teacher layers "
             f"{teacher_layer}", ranks=[0])
    return student


def student_model_config(model_cfg: Any, ds_config: Dict) -> Any:
    """The student's model config: same architecture, ``keep_number_layer``
    layers (works for GPTConfig.n_layer and BertConfig.num_hidden_layers)."""
    lr = layer_reduction_config(ds_config)
    assert lr is not None, "layer_reduction not enabled in config"
    keep = int(lr.get("keep_number_layer", len(lr["teacher_layer"])))
    for field in ("n_layer", "num_hidden_layers"):
        if hasattr(model_cfg, field):
            return dataclasses.replace(model_cfg, **{field: keep})
    raise ValueError(f"model config {type(model_cfg).__name__} has no "
                     "layer-count field (n_layer / num_hidden_layers)")


def apply_layer_reduction(model_cfg: Any, teacher_params: Dict,
                          ds_config: Dict) -> Tuple[Any, Dict]:
    """(student_cfg, student_params) in one call — the functional
    analogue of the reference's in-place student mutation."""
    return (student_model_config(model_cfg, ds_config),
            student_initialization(teacher_params, ds_config))
