"""Compression orchestration: config → per-leaf technique binding →
pure parameter transform.

Reference: ``deepspeed/compression/compress.py:95`` (``init_compression``
walks the model and swaps layers for compressed variants bound to the
config's ``different_groups`` module patterns) and ``:123``
(``redundancy_clean`` physically shrinks pruned weights).  Functional
redesign: ``init_compression`` builds a :class:`CompressionSpec` mapping
param-tree leaf paths (regex, the module-name analogue) to techniques;
``spec.transform(params, step, rng)`` is a pure function the engine's
train step jits; ``redundancy_clean`` returns a smaller pytree.

TP composition: the reference needs TP-aware compressed-layer variants
(``basic_layer.py:611,767,802`` — LinearLayer_Compress forks for row/
column parallelism) because its masks live inside sharded torch modules.
Here the transform runs on the LOGICAL param tree inside the jitted step,
BEFORE GSPMD partitions anything: masks/quantization shard exactly like
the weights they wrap, so every technique is TP/ZeRO-safe with zero extra
code.  ``layer_reduction`` (student distillation init) lives in
``layer_reduction.py``.
"""

import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.compression import basic_ops as ops
from deepspeed_tpu.compression.scheduler import TECHNIQUES, CompressionScheduler
from deepspeed_tpu.utils.logging import log_dist


def _path_str(path) -> str:
    parts = []
    for p in path:
        parts.append(str(getattr(p, "key", getattr(p, "idx", p))))
    return "/".join(parts)


class LeafPlan:
    """Techniques bound to one parameter leaf."""

    def __init__(self):
        self.weight_quant: Optional[Dict] = None
        self.sparse: Optional[Dict] = None
        self.row: Optional[Dict] = None
        self.head: Optional[Dict] = None
        self.channel: Optional[Dict] = None

    def active(self) -> List[str]:
        return [k for k in ("weight_quant", "sparse", "row", "head", "channel")
                if getattr(self, k) is not None]


class CompressionSpec:

    def __init__(self, plans: Dict[str, LeafPlan], scheduler: CompressionScheduler,
                 activation_quant: Optional[Dict] = None):
        self.plans = plans
        self.scheduler = scheduler
        # model-side technique (reference QuantAct): the engine flips the
        # model's activation_quant_bits when this is set — a parameter
        # transform cannot reach activations
        self.activation_quant = activation_quant

    def transform(self, params, enabled: Dict[str, bool],
                  rng: Optional[jax.Array] = None):
        """Pure param transform: apply every technique that is both bound
        and schedule-enabled.  Jit-safe (``enabled`` is static)."""
        flat = jax.tree_util.tree_leaves_with_path(params)

        def one(path, w):
            plan = self.plans.get(_path_str(path))
            if plan is None or not hasattr(w, "ndim") or w.ndim < 2:
                return w
            if plan.sparse and enabled.get("sparse_pruning"):
                w = w * ops.sparse_mask(w, plan.sparse["ratio"],
                                        plan.sparse.get("method", "l1")).astype(w.dtype)
            if plan.row and enabled.get("row_pruning"):
                w = ops.apply_row_mask(
                    w, ops.row_mask(w, plan.row["ratio"],
                                    plan.row.get("method", "l1")))
            if plan.channel and enabled.get("channel_pruning"):
                m = ops.channel_mask(w, plan.channel["ratio"])
                w = w * jnp.expand_dims(m, -1).astype(w.dtype)
            if plan.head and enabled.get("head_pruning"):
                w = ops.apply_head_mask(
                    w, ops.head_mask(w, plan.head["ratio"],
                                     plan.head["num_heads"]),
                    plan.head["num_heads"])
            if plan.weight_quant and enabled.get("weight_quantization"):
                q = plan.weight_quant
                w = ops.quantize_weight(
                    w, q.get("target_bits", 8),
                    quant_type=q.get("quantization_type", "symmetric"),
                    rounding=q.get("rounding", "nearest"),
                    groups=q.get("quantize_groups", 1),
                    rng=rng)
            return w

        leaves = [one(p, w) for p, w in flat]
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), leaves)


def _technique_groups(cfg: Dict, technique: str) -> List[Tuple[Dict, List[str]]]:
    """[(params, [patterns])] for every enabled group of a technique."""
    t = cfg.get(technique, {})
    if not t.get("shared_parameters", {}).get("enabled", False):
        return []
    shared = t["shared_parameters"]
    out = []
    for _, group in (t.get("different_groups", {}) or {}).items():
        gp = dict(shared)
        gp.update(group.get("params", {}))
        out.append((gp, list(group.get("modules", ["*"]))))
    if not out:
        out.append((dict(shared), ["*"]))
    return out


def _matches(name: str, patterns: List[str]) -> bool:
    for pat in patterns:
        if pat == "*" or re.search(pat, name):
            return True
    return False


def init_compression(params, ds_config: Dict,
                     num_heads: Optional[int] = None) -> CompressionSpec:
    """Bind the ``compression_training`` config block to a param pytree.

    ``num_heads`` feeds head pruning (the reference reads it from the
    group's ``related_modules``/mpu; here the caller states it)."""
    cfg = ds_config.get("compression_training", ds_config) or {}
    plans: Dict[str, LeafPlan] = {}

    def plan(name) -> LeafPlan:
        return plans.setdefault(name, LeafPlan())

    names = [_path_str(p) for p, _ in jax.tree_util.tree_leaves_with_path(params)]
    for gp, pats in _technique_groups(cfg, "weight_quantization"):
        for n in names:
            if _matches(n, pats):
                plan(n).weight_quant = gp
    for technique, attr in (("sparse_pruning", "sparse"), ("row_pruning", "row"),
                            ("channel_pruning", "channel")):
        for gp, pats in _technique_groups(cfg, technique):
            for n in names:
                if _matches(n, pats):
                    setattr(plan(n), attr, {"ratio": gp.get("dense_ratio",
                                                            gp.get("ratio", 0.5)),
                                            "method": gp.get("method", "l1")})
    for gp, pats in _technique_groups(cfg, "head_pruning"):
        nh = gp.get("num_heads", num_heads)
        assert nh, "head_pruning needs num_heads"
        for n in names:
            if _matches(n, pats):
                plan(n).head = {"ratio": gp.get("dense_ratio", gp.get("ratio", 0.5)),
                                "num_heads": int(nh)}

    scheduler = CompressionScheduler(cfg)
    bound = sum(len(p.active()) for p in plans.values())
    log_dist(f"init_compression: {bound} technique bindings over "
             f"{len(plans)} leaves", ranks=[0])
    aq = cfg.get("activation_quantization", {}).get("shared_parameters", {})
    activation_quant = None
    if aq.get("enabled", False):
        activation_quant = {
            "bits": int(aq.get("quantize_bits", {}).get("start_bits", 8))
            if isinstance(aq.get("quantize_bits"), dict)
            else int(aq.get("bits", 8)),
            "type": str(aq.get("quantization_type", "symmetric")),
        }
    return CompressionSpec(plans, scheduler, activation_quant=activation_quant)


def redundancy_clean(params, spec: CompressionSpec,
                     num_heads: Optional[int] = None):
    """Physically remove pruned rows/channels (reference
    ``redundancy_clean``/``fix_*_pruning_helper(dim_reduction=True)``):
    returns a new pytree where row-pruned outputs and channel-pruned
    inputs are sliced away.  Cross-layer dim consistency is the caller's
    concern (as in the reference, which cleans matched module pairs)."""
    flat = jax.tree_util.tree_leaves_with_path(params)

    def one(path, w):
        plan = spec.plans.get(_path_str(path))
        if plan is None or not hasattr(w, "ndim") or w.ndim < 2:
            return w
        if plan.row:
            keep = np.asarray(ops.row_mask(w, plan.row["ratio"]))
            w = jnp.compress(keep, w, axis=-1)
        if plan.channel:
            keep = np.asarray(ops.channel_mask(w, plan.channel["ratio"]))
            w = jnp.compress(keep, w, axis=-2)
        return w

    leaves = [one(p, w) for p, w in flat]
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), leaves)
