"""Continuous-batching serving engine over the paged KV arena.

The inference stack's ``generate()`` serves one static batch per call; this
engine serves a *stream*: requests join and leave the decode batch every
step without recompilation.  The trick is shape discipline — exactly TWO
programs are ever compiled, both traces of one jitted step function:

* **decode**: ``[max_batch_size, 1]`` tokens over the arena — every active
  sequence advances one token; inactive slots carry trash-block write
  coordinates and all-trash block tables, so batch composition is pure
  traced *data*;
* **prefill**: ``[1, prefill_chunk]`` tokens — one prompt chunk per step
  (chunked prefill), so a long prompt never stalls the decode batch for
  more than one chunk's latency.

Block tables, positions, and write maps are int32 inputs produced by the
host-side :class:`PagedKVAllocator` / :class:`ServingScheduler`; the arena
arrays are donated back to the step on accelerators, so the KV cache is
updated in place.  The e2e contract (tests/unit/serving): greedy outputs
are token-identical to sequential ``generate()``, even across
preempt→evict→recompute cycles, because recompute re-prefills a prefix of
the identical deterministic stream.

Decoding is greedy (the sampler the sequential path uses at
``temperature=0``, including the padded-vocab mask); sampled decoding is
future work and is rejected at ``submit()``.
"""

import time
from contextlib import nullcontext
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.comm.bounded import BoundedCollective, CollectiveTimeout
from deepspeed_tpu.runtime.offload import StagingError
from deepspeed_tpu.serving.config import DeepSpeedServingConfig
from deepspeed_tpu.serving.kv_cache import (ArenaExhausted, PagedKVAllocator,
                                            init_arena)
from deepspeed_tpu.serving.kv_tiering import KVTieringManager
from deepspeed_tpu.serving.prefix_cache import PrefixCache
from deepspeed_tpu.serving.scheduler import (DECODE, EXPIRED, FINISHED,
                                             SHED_LEVELS, SLO_PRIORITY,
                                             AdmissionController,
                                             DeadlineExceeded, Request,
                                             ServingScheduler, ShedError)
from deepspeed_tpu.telemetry.tracing import get_global_tracer
from deepspeed_tpu.testing.fault_injection import (FaultInjected, fault_point,
                                                   release_wedges)
from deepspeed_tpu.utils.logging import log_dist


class ServeStepTimeout(RuntimeError):
    """A compiled serve step (decode or prefill dispatch) exceeded
    ``serve_step_timeout_s``.  Raised *after* the engine has recovered
    in-process (programs re-jitted, arena rebuilt, every in-flight request
    requeued for recompute) — ``run()``/``result()`` keep driving; a bare
    ``step()`` caller sees the incident."""

    def __init__(self, message, op=None, deadline_s=None, step=None):
        super().__init__(message)
        self.op = op
        self.deadline_s = deadline_s
        self.step = step


class ServeFuture:
    """Handle for one submitted request.  ``result()`` drives the engine's
    step loop until this request finishes (single-threaded serving — there
    is no background thread; whoever waits, steps)."""

    def __init__(self, engine: "ServingEngine", request: Request):
        self._engine = engine
        self.request = request

    @property
    def done(self) -> bool:
        return self.request.state == FINISHED

    @property
    def token_ids(self) -> List[int]:
        """Generated tokens so far (excludes the prompt)."""
        return list(self.request.generated)

    def result(self, max_steps: int = 100_000,
               timeout_s: Optional[float] = None) -> List[int]:
        """Drive until this request finishes.  ``timeout_s`` bounds the
        wait in wall-clock seconds (checked at step boundaries — pair it
        with ``serve_step_timeout_s`` so a wedged *dispatch* cannot park
        the caller inside one step forever).  Raises
        :class:`DeadlineExceeded` if the request's own SLO deadline
        cancelled it."""
        deadline = (None if timeout_s is None
                    else self._engine._clock() + float(timeout_s))
        for _ in range(max_steps):
            if self.done:
                return self.token_ids
            if self.request.state == EXPIRED:
                raise DeadlineExceeded(
                    f"request {self.request.rid} missed its "
                    f"{self.request.slo!r}-class deadline and was cancelled")
            if deadline is not None and self._engine._clock() >= deadline:
                raise TimeoutError(
                    f"request {self.request.rid} unfinished after "
                    f"{timeout_s}s")
            try:
                self._engine.step()
            except ServeStepTimeout:
                # the engine already recovered (state requeued for
                # recompute); keep driving under the same bounds
                continue
        raise TimeoutError(
            f"request {self.request.rid} unfinished after {max_steps} steps")


class _TieringAdapter:
    """Bridges the scheduler's request-level spill/restage hooks to the
    :class:`KVTieringManager`'s rid/block-level API, and owns the
    ``kv_spill``/``kv_restage`` telemetry.  Only blocks a sequence has
    actually *written* (``blocks_for_tokens(prefilled)``) are spilled —
    a growth block allocated for the next token holds garbage."""

    def __init__(self, engine: "ServingEngine"):
        self.engine = engine
        self.mgr = engine.tiering

    def spill(self, req: Request):
        eng = self.engine
        n = eng.alloc.blocks_for_tokens(req.prefilled)
        blocks = eng.alloc.owned_blocks(req.rid)[:n]
        tier = self.mgr.spill(req.rid, blocks, eng._k_pages, eng._v_pages,
                              req.prefilled)
        if tier is not None:
            eng._emit("kv_spill", {
                "rid": req.rid, "slo": req.slo, "tier": tier,
                "blocks": len(blocks), "tokens": req.prefilled,
                "bytes": self.mgr.chunk_bytes(eng._k_pages, len(blocks)),
            }, step=eng.step_count)
        return tier

    def begin_restage(self, req: Request) -> None:
        self.mgr.begin_restage(req.rid)

    def restage_ready(self, req: Request) -> bool:
        return self.mgr.restage_ready(req.rid)

    def restage(self, req: Request) -> bool:
        eng = self.engine
        n = eng.alloc.blocks_for_tokens(req.spilled_tokens)
        dest = eng.alloc.owned_blocks(req.rid)[:n]
        try:
            fault_point("serve.restage", rid=req.rid)
            eng._k_pages, eng._v_pages, info = self.mgr.restage(
                req.rid, eng._k_pages, eng._v_pages, dest)
        except (KeyError, StagingError, FaultInjected) as e:
            # unreadable/missing chunk: drop the record and recompute —
            # the destructive-evict contract still yields identical tokens
            self.mgr.discard(req.rid)
            eng._emit("kv_restage", {"rid": req.rid, "ok": False,
                                     "error": str(e)}, step=eng.step_count)
            return False
        eng._emit("kv_restage", {
            "rid": req.rid, "ok": True, "source": info["source"],
            "ready": info["ready"], "wait_ms": info["wait_s"] * 1000.0,
            "blocks": info["blocks"], "tokens": info["tokens"],
            "bytes": info["bytes"],
        }, step=eng.step_count)
        return True

    def discard(self, req: Request) -> None:
        self.mgr.discard(req.rid)

    def describe_tiers(self) -> str:
        return self.mgr.describe()


class ServingEngine:
    """``submit()/step()/run()`` over a model implementing ``paged_step``
    (the GPT family, ``models/gpt.py:gpt_paged_step``)."""

    def __init__(self, model, config: Optional[DeepSpeedServingConfig] = None,
                 params=None, seed: Optional[int] = None, telemetry=None,
                 tracer=None):
        import jax
        import jax.numpy as jnp
        cfg = config or DeepSpeedServingConfig()
        self._config = cfg
        self.telemetry = telemetry
        self.tracer = tracer
        # live metrics plane: gauges + step-time histograms are updated
        # directly (host wall-clock / scheduler counts, zero device syncs);
        # event-derived metrics (TTFT, preemptions, restages) flow through
        # the hub's MetricsSink on the periodic flush below — one source
        # of truth per metric, no double counting.
        self.registry = getattr(telemetry, "registry", None)
        if self.registry is not None:
            r = self.registry
            self._g_queue = r.gauge("serve_queue_depth")
            self._g_active = r.gauge("serve_active")
            self._g_blocks = r.gauge("serve_blocks_in_use")
            self._g_blocks_total = r.gauge("serve_blocks_total")
            self._g_blocks_total.set(cfg.num_blocks)
            self._g_host_bytes = r.gauge("serve_kv_host_bytes")
            self._g_nvme_bytes = r.gauge("serve_kv_nvme_bytes")
            self._g_prefix_rate = r.gauge("prefix_hit_rate")
            self._h_step = r.histogram("serve_step_ms")
            self._h_decode = r.histogram("serve_decode_step_ms")
        self.dtype = cfg.jnp_dtype
        assert hasattr(model, "paged_step") and hasattr(model, "cfg"), (
            "ServingEngine needs a model with .cfg and .paged_step(...) "
            "(the GPT family)")
        # serve in the configured dtype without mutating the caller's model
        if model.cfg.dtype != self.dtype:
            import copy
            import dataclasses
            model = copy.copy(model)
            model.cfg = dataclasses.replace(model.cfg, dtype=self.dtype)
        self.module = model
        mcfg = model.cfg

        if params is None:
            assert hasattr(model, "init_params"), (
                "pass params= or a model with init_params(rng)")
            params = model.init_params(
                jax.random.PRNGKey(cfg.seed if seed is None else seed))
        self.params = jax.tree.map(
            lambda p: jnp.asarray(p, self.dtype)
            if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating) else p,
            params)

        # ---- paged arena + control plane --------------------------------- #
        self.max_blocks_per_seq = (cfg.max_blocks_per_seq
                                   or -(-mcfg.n_positions // cfg.block_size))
        self.alloc = PagedKVAllocator(cfg.num_blocks, cfg.block_size,
                                      self.max_blocks_per_seq)
        self.sched = ServingScheduler(cfg, self.alloc, cfg.max_batch_size)
        self.sched.on_preempt = self._on_preempt
        self._k_pages, self._v_pages = init_arena(
            mcfg, cfg.num_blocks, cfg.block_size, dtype=self.dtype)

        # ---- tiered spill/restage + prefix sharing (both opt-in) ---------- #
        self.tiering: Optional[KVTieringManager] = None
        self.prefix: Optional[PrefixCache] = None
        if cfg.kv_tiering:
            self.tiering = KVTieringManager(
                offload_dir=cfg.kv_offload_dir,
                host_cache_bytes=cfg.kv_host_cache_bytes,
                spill_budget_bytes=cfg.kv_spill_budget_bytes,
                spill_chunk_blocks=cfg.kv_spill_chunk_blocks,
                ring_depth=cfg.kv_ring_depth)
            self.sched.tiering = _TieringAdapter(self)
        if cfg.prefix_cache:
            self.prefix = PrefixCache(self.alloc,
                                      max_blocks=cfg.prefix_cache_blocks)
            self.sched.prefix_cache = self.prefix
            self.sched.on_prefix_hit = self._on_prefix_hit

        # ---- the (single) jitted step ------------------------------------ #
        def step_fn(params, ids, positions, kp, vp, tables, wb, wo):
            logits, kp, vp = model.paged_step(params, ids, positions, kp, vp,
                                              tables, wb, wo)
            if mcfg.padded_vocab != mcfg.vocab_size:
                vmask = jnp.arange(mcfg.padded_vocab) < mcfg.vocab_size
                logits = jnp.where(vmask[None, None], logits, -1e30)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), kp, vp

        # arena donation = in-place KV update; CPU can't donate (jax warns
        # and copies), so only donate on real accelerators
        donate = (3, 4) if jax.default_backend() != "cpu" else ()
        self._raw_step_fn = step_fn
        self._donate = donate
        self._step_fn = jax.jit(step_fn, donate_argnums=donate)

        # ---- resilience plane -------------------------------------------- #
        self._clock = time.monotonic
        self.admission = AdmissionController(cfg)
        # bounded step dispatch: a wedged compiled program raises
        # ServeStepTimeout instead of parking the engine thread forever.
        # on_timeout releases fault-injection wedges so the abandoned
        # worker drains instead of leaking (mirrors comm/recovery.py).
        self._bounded: Optional[BoundedCollective] = None
        if cfg.serve_step_timeout_s and cfg.serve_step_timeout_s > 0.0:
            self._bounded = BoundedCollective(
                deadline_s=float(cfg.serve_step_timeout_s),
                on_timeout=lambda err: release_wedges())
        # phases whose program has already compiled: the first dispatch of
        # each phase runs inline (unbounded) because XLA compilation is
        # legitimate work that routinely exceeds a steady-state step
        # deadline — bounding it would fire a spurious incident at startup
        self._warm_phases: set = set()
        self.incident_count = 0
        self.last_recovery_s = 0.0
        self._incident: Optional[Dict[str, Any]] = None  # /healthz latch

        self._rid_counter = 0
        self._futures: Dict[int, ServeFuture] = {}
        self.step_count = 0
        self.tokens_generated = 0
        self._started = time.monotonic()
        self._closed = False
        self._owns_telemetry = False    # init_serving flips for dict-built hubs
        # goodput ledger (telemetry/ledger.py): reuse the hub's ledger in
        # serve mode — step() attributes wall time, finished requests feed
        # the per-SLO tokens-within-TTFT-bound accounting
        self.ledger = getattr(telemetry, "ledger", None)
        self._restage_wait_ms = 0.0
        if self.ledger is not None:
            self.ledger.mode = "serve"
            self.ledger.slo_ttft_bounds_ms.update(
                {str(k): float(v)
                 for k, v in (cfg.slo_ttft_bound_ms or {}).items()})
            self.ledger.mark()
        obs = getattr(telemetry, "obs_server", None)
        if obs is not None:
            obs.add_health_check("serve_arena", self._arena_health)
            obs.add_health_check("serve_incident", self._incident_health)
        log_dist(
            f"ServingEngine ready: slots={cfg.max_batch_size}, "
            f"arena={cfg.num_blocks}x{cfg.block_size} tok "
            f"(max {self.max_blocks_per_seq} blocks/seq), "
            f"prefill_chunk={cfg.prefill_chunk}, dtype={self.dtype.__name__}",
            ranks=[0])

    # ------------------------------------------------------------------ #
    def _span(self, name, **args):
        tr = self.tracer if self.tracer is not None else get_global_tracer()
        return tr.span(name, **args) if tr is not None else nullcontext()

    def _emit(self, kind, payload, step=None):
        if (self.ledger is not None and kind == "kv_restage"
                and payload.get("ok")):
            # exposed restage wait attributes to offload_stall on next step
            self._restage_wait_ms += float(payload.get("wait_ms", 0.0))
        if self.telemetry is not None:
            self.telemetry.emit(kind, payload, step=step)

    def _arena_health(self):
        """`/healthz` contribution: arena + tier occupancy (always ``ok``
        on its own — oversubscription is a designed-for state; the gauges
        give the operator the occupancy picture)."""
        st = self.sched.stats()
        total = int(self._config.num_blocks)
        used = int(st.get("blocks_in_use", 0))
        out = {"ok": True, "blocks_in_use": used, "blocks_total": total,
               "occupancy": round(used / total, 4) if total else 0.0,
               "active": int(st.get("active", 0)),
               "queue_depth": int(st.get("queue_depth", 0))}
        if self.tiering is not None:
            ts = self.tiering.stats()
            for key in ("kv_host_bytes", "kv_nvme_bytes"):
                if key in ts:
                    out[key] = ts[key]
        return out

    def _incident_health(self):
        """`/healthz` contribution: unhealthy while a serve incident is
        latched — a wedged step recovered in-process but the engine has
        not yet completed a clean step.  The latch clears on the first
        clean step after recovery."""
        out = {"ok": self._incident is None,
               "incidents": self.incident_count,
               "last_recovery_s": round(self.last_recovery_s, 4)}
        if self._incident is not None:
            out.update({k: self._incident[k] for k in ("step", "phase")})
        return out

    # ---- request lifecycle robustness --------------------------------- #
    def _expire_deadlines(self):
        """Cancel every request whose per-class deadline has passed —
        called at the step boundary, so a cancellation never races a
        compiled dispatch.  Frees arena blocks + staged tier copies and
        books the accumulated prefill as wasted compute."""
        if not self._config.deadline_ms:
            return
        now = self._clock()
        for req in self.sched.expired(now):
            wasted = req.prefilled
            self.sched.cancel(req)
            if self.ledger is not None:
                self.ledger.note_serve_expired(req.slo, wasted)
            self._emit("serve_expired", {
                "rid": req.rid, "slo": req.slo,
                "age_ms": (now - req.arrival) * 1000.0,
                "deadline_ms": (req.deadline_at - req.arrival) * 1000.0,
                "generated": len(req.generated),
                "wasted_prefill_tokens": wasted,
            }, step=self.step_count)

    def _update_admission(self):
        """Advance the shed ladder from the queue-age and TTFT-burn
        signals; rung changes are telemetered (and gauge-fed via the
        MetricsSink on flush)."""
        age = self.sched.oldest_wait_s(self._clock())
        state = "ok"
        mon = getattr(self.telemetry, "slo_monitor", None)
        if mon is not None:
            try:
                state = mon.state_for_metric("serve_ttft_ms")
            except Exception:
                state = "ok"
        prev = self.admission.level
        level = self.admission.evaluate(age, state)
        if level != prev:
            self._emit("serve_shed", {
                "event": "level", "level": level,
                "from": SHED_LEVELS[prev], "to": self.admission.level_name,
                "queue_age_ms": age * 1000.0, "ttft_state": state,
            }, step=self.step_count)

    # ---- bounded dispatch + incident recovery -------------------------- #
    def _dispatch(self, phase: str, *args):
        """Run one compiled step under the ``serve_step_timeout_s``
        deadline (inline when unbounded).  The host materialization of the
        token row happens *inside* the bounded callable — that device sync
        is exactly where a wedged program parks the thread.  The first
        dispatch of each phase (and the first after an incident re-jit)
        runs inline: it compiles, and compile time is not a wedge."""
        def work():
            fault_point("serve.step", step=self.step_count, phase=phase)
            tokens, kp, vp = self._step_fn(self.params, *args)
            return np.asarray(tokens), kp, vp
        if self._bounded is None or phase not in self._warm_phases:
            out = work()
            self._warm_phases.add(phase)
            return out
        try:
            return self._bounded.run(work, op=phase, noun="serve step")
        except CollectiveTimeout as e:
            raise ServeStepTimeout(
                f"serve {phase} step {self.step_count} exceeded its "
                f"{e.deadline_s:.3f}s deadline", op=phase,
                deadline_s=e.deadline_s, step=self.step_count) from e

    def _recover_incident(self, err: ServeStepTimeout):
        """In-process recovery from a wedged compiled step: drop the
        (possibly poisoned) executables and arena, rebuild from allocator
        + tier metadata, and requeue every in-flight request with
        ``prefilled=0`` — the preemption recompute contract, so the token
        streams continue identically.  Spilled host/NVMe copies of
        *waiting* requests survive (they never touch the device arena).
        Latches ``/healthz`` unhealthy until the first clean step."""
        import jax
        t0 = self._clock()
        self.incident_count += 1
        cfg, mcfg = self._config, self.module.cfg
        self._emit("serve_incident", {
            "event": "begin", "phase": err.op, "step": self.step_count,
            "deadline_s": err.deadline_s, "incident": self.incident_count,
            "in_flight": len(self.sched.active),
        }, step=self.step_count)
        if self.ledger is not None:
            # resident KV is about to be discarded: its prefill recomputes
            for r in self.sched.active.values():
                self.ledger.note_wasted_prefill(r.slo, r.prefilled)
        if self.tiering is not None:
            # no in-flight copy-ring task may still reference the arena
            # arrays we are about to drop
            self.tiering.drain()
        self._step_fn = jax.jit(self._raw_step_fn,
                                donate_argnums=self._donate)
        self._warm_phases.clear()   # fresh jit: first dispatches recompile
        self.alloc = PagedKVAllocator(cfg.num_blocks, cfg.block_size,
                                      self.max_blocks_per_seq)
        self._k_pages, self._v_pages = init_arena(
            mcfg, cfg.num_blocks, cfg.block_size, dtype=self.dtype)
        if self.prefix is not None:
            # cached pins point at pre-incident arena content: rebuild
            self.prefix = PrefixCache(self.alloc,
                                      max_blocks=cfg.prefix_cache_blocks)
            self.sched.prefix_cache = self.prefix
        requeued = self.sched.requeue_for_recovery(self.alloc)
        self._incident = {"at": t0, "step": self.step_count,
                          "phase": err.op}
        self.last_recovery_s = self._clock() - t0
        if self.ledger is not None:
            # the wedge wait (the expired deadline) plus the rebuild are
            # incident seconds, not productive step time
            self.ledger.note_comm_recovery(
                (err.deadline_s or 0.0) + self.last_recovery_s)
        self._emit("serve_incident", {
            "event": "recovered", "phase": err.op, "step": self.step_count,
            "requeued": len(requeued), "lost": 0,
            "recovery_s": self.last_recovery_s,
            "deadline_s": err.deadline_s, "incident": self.incident_count,
        }, step=self.step_count)

    def _on_preempt(self, victim: Request):
        if self.ledger is not None and not victim.spilled:
            # eviction without a spill record: the prefill is recomputed
            # from scratch on resume — those tokens are wasted work
            self.ledger.note_wasted_prefill(victim.slo, victim.prefilled)
        self._emit("serve_preempt", {
            "rid": victim.rid, "slo": victim.slo,
            "generated": len(victim.generated),
            "preemptions": victim.preemptions,
            "spilled": victim.spilled,
        }, step=self.step_count)

    def _on_prefix_hit(self, req: Request, blocks: List[int]):
        self._emit("prefix_hit", {
            "rid": req.rid, "slo": req.slo, "blocks": len(blocks),
            "tokens": len(blocks) * self._config.block_size,
            "prompt_tokens": len(req.prompt),
        }, step=self.step_count)

    def compiled_programs(self) -> int:
        """Number of XLA programs behind the serving step (the e2e test
        asserts this stays <= 2: one decode trace + one prefill trace)."""
        return int(self._step_fn._cache_size())

    # ------------------------------------------------------------------ #
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               slo: str = "standard", temperature: float = 0.0) -> ServeFuture:
        """Queue one request; returns a :class:`ServeFuture`."""
        if temperature:
            raise NotImplementedError(
                "serving is greedy-only in this PR (temperature=0)")
        if slo not in SLO_PRIORITY:
            raise ValueError(
                f"unknown slo class {slo!r}; expected one of "
                f"{sorted(SLO_PRIORITY)} (a typo here would otherwise "
                "silently demote the request to 'standard')")
        cfg, mcfg = self._config, self.module.cfg
        if not self.admission.admit_ok(slo):
            self._emit("serve_shed", {
                "event": "rejected", "slo": slo,
                "level": self.admission.level,
                "level_name": self.admission.level_name,
                "queue_depth": len(self.sched.waiting),
            }, step=self.step_count)
            raise ShedError(
                f"admission ladder at {self.admission.level_name!r} is "
                f"shedding {slo!r}-class requests (retry later or raise "
                "the class)", slo=slo, level=self.admission.level)
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        assert prompt, "empty prompt"
        mnt = int(max_new_tokens or cfg.max_new_tokens_default)
        # brownout rung: degrade before rejecting
        mnt = self.admission.cap_new_tokens(mnt)
        total = len(prompt) + mnt
        if total > mcfg.n_positions:
            raise ValueError(f"prompt+max_new_tokens {total} exceeds "
                             f"n_positions {mcfg.n_positions}")
        if self.alloc.blocks_for_tokens(total) > min(
                cfg.num_blocks - 1, self.max_blocks_per_seq):
            raise ArenaExhausted(
                f"request needs {self.alloc.blocks_for_tokens(total)} blocks; "
                f"arena ceiling is "
                f"{min(cfg.num_blocks - 1, self.max_blocks_per_seq)}")
        self._rid_counter += 1
        req = Request(rid=self._rid_counter, prompt=prompt,
                      max_new_tokens=mnt, slo=slo, arrival=self._clock())
        dl = float((cfg.deadline_ms or {}).get(slo, 0.0) or 0.0)
        if dl > 0.0:
            req.deadline_at = req.arrival + dl / 1e3
        self.sched.submit(req)
        fut = ServeFuture(self, req)
        self._futures[req.rid] = fut
        self._emit("serve_request", {
            "event": "submitted", "rid": req.rid, "slo": slo,
            "prompt_tokens": len(prompt), "max_new_tokens": mnt,
            "queue_depth": len(self.sched.waiting),
        }, step=self.step_count)
        return fut

    # ------------------------------------------------------------------ #
    def step(self) -> Dict[str, Any]:
        """One engine step: expire deadlines, advance the shed ladder,
        admit, run one prefill chunk, run one decode step over every
        decode-ready sequence.  Returns the step stats.  A wedged compiled
        dispatch raises :class:`ServeStepTimeout` *after* in-process
        recovery (see :meth:`_recover_incident`)."""
        self._expire_deadlines()
        self._update_admission()
        self.sched.admit()
        prefill_tokens = 0
        t_step = time.monotonic() if self.registry is not None else 0.0
        try:
            with self._span("serve.step", step=self.step_count):
                pf = self.sched.next_prefill()
                if pf is not None:
                    req, start, n = pf
                    with self._span("serve.prefill", rid=req.rid, start=start,
                                    tokens=n):
                        self._run_prefill(req, start, n)
                    prefill_tokens = n
                # growth pass, oldest/strongest first: each decode step
                # writes one token per sequence, so capacity must exist
                # before the batch is built; eviction here removes victims
                # from `active`
                decode = sorted(self.sched.decode_batch(),
                                key=lambda r: (r.priority, r.admit_seq))
                for r in decode:
                    if r.state == DECODE:      # not evicted by an earlier r
                        self.sched.ensure_capacity(r, r.prefilled + 1)
                decode = self.sched.decode_batch()
                if decode:
                    t_dec = (time.monotonic() if self.registry is not None
                             else 0.0)
                    with self._span("serve.decode", batch=len(decode)):
                        self._run_decode(decode)
                    if self.registry is not None:
                        self._h_decode.observe(
                            (time.monotonic() - t_dec) * 1e3)
        except ServeStepTimeout as err:
            self._recover_incident(err)
            raise
        if self._incident is not None:
            # first clean step after an incident: release the latch
            self._emit("serve_incident", {
                "event": "cleared", "phase": self._incident["phase"],
                "incident_step": self._incident["step"],
            }, step=self.step_count)
            self._incident = None
        self.step_count += 1
        if self.ledger is not None:
            self.ledger.on_step(self.step_count,
                                offload_wait_s=self._restage_wait_ms / 1e3)
            self._restage_wait_ms = 0.0
        stats = dict(self.sched.stats(), decode_batch=len(decode),
                     prefill_tokens=prefill_tokens,
                     tokens_generated=self.tokens_generated,
                     shed_level=self.admission.level,
                     incidents=self.incident_count,
                     elapsed_ms=(time.monotonic() - self._started) * 1000.0)
        if self.tiering is not None:
            stats.update(self.tiering.stats())
        if self.prefix is not None:
            stats.update(self.prefix.stats())
        if self.registry is not None:
            self._h_step.observe((time.monotonic() - t_step) * 1e3)
            for gauge, key in ((self._g_queue, "queue_depth"),
                               (self._g_active, "active"),
                               (self._g_blocks, "blocks_in_use"),
                               (self._g_host_bytes, "kv_host_bytes"),
                               (self._g_nvme_bytes, "kv_nvme_bytes")):
                v = stats.get(key)
                if isinstance(v, (int, float)):
                    gauge.set(v)
            lookups = stats.get("prefix_lookups")
            if lookups:
                self._g_prefix_rate.set(
                    int(stats.get("prefix_hits", 0)) / int(lookups))
        if (self.telemetry is not None and self._config.telemetry_every
                and self.step_count % self._config.telemetry_every == 0):
            self._emit("serve_step", stats, step=self.step_count)
            if self.registry is not None:
                # drain the emit buffer so event-derived metrics (TTFT,
                # restage, preemption) stay live for /metrics scrapes,
                # then run the pod fold at its own cadence
                self.telemetry.flush()
                self.telemetry.maybe_snapshot(self.step_count)
        return stats

    def run(self, max_steps: int = 1_000_000) -> int:
        """Drive until every queued/active request finishes (expired
        requests leave the queue by cancellation).  A ServeStepTimeout
        incident does not abort the drain — the engine recovered before
        raising, so the loop keeps going; the step bound still applies
        (wedged attempts count toward it).  Returns the number of
        completed engine steps."""
        start = self.step_count
        steps = 0
        while self.sched.has_work:
            if steps >= max_steps:
                raise TimeoutError(f"serving drain exceeded {max_steps} steps")
            try:
                self.step()
            except ServeStepTimeout:
                pass       # recovered in-process; requests are requeued
            steps += 1
        return self.step_count - start

    def close(self):
        """Release the resilience + tiering backends: stop the bounded
        dispatch worker, drain the tiering copy ring, close the staging
        pool (and an owned tempdir / telemetry hub).  Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._bounded is not None:
            self._bounded.shutdown()
        if self.tiering is not None:
            self.tiering.drain()
            self.tiering.close()
        if self._owns_telemetry and self.telemetry is not None:
            try:
                self.telemetry.close()
            except Exception:
                pass

    # ---- warm restart -------------------------------------------------- #
    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready warm-restart state: the scheduler queue + per-request
        progress — prompts, generated-so-far, remaining deadline — but NOT
        KV bytes (recompute on restore keeps the snapshot tiny and the
        token streams identical).  Take it between steps; an elastic-agent
        relaunch feeds it to :meth:`restore` on a fresh engine."""
        now = self._clock()
        in_flight = sorted(
            list(self.sched.waiting) + list(self.sched.active.values()),
            key=lambda r: r.submit_seq)
        reqs = []
        for r in in_flight:
            reqs.append({
                "rid": r.rid,
                "prompt": [int(t) for t in r.prompt],
                "generated": [int(t) for t in r.generated],
                "max_new_tokens": int(r.max_new_tokens),
                "slo": r.slo,
                "age_s": now - r.arrival,
                "deadline_remaining_s": (
                    None if r.deadline_at is None else r.deadline_at - now),
                "preemptions": int(r.preemptions),
            })
        return {"schema": 1, "requests": reqs,
                "rid_counter": int(self._rid_counter),
                "step_count": int(self.step_count)}

    def restore(self, snap: Dict[str, Any]) -> List[ServeFuture]:
        """Resume a :meth:`snapshot` on this (idle) engine: every request
        re-enters the waiting queue with ``prefilled=0`` — admission
        re-prefills prompt + generated-so-far, so greedy decoding
        continues the identical stream.  Remaining deadlines are
        re-anchored to this engine's clock (already-expired ones cancel on
        the first step).  Returns the new futures in submit order."""
        assert not self.sched.waiting and not self.sched.active, (
            "restore() needs an idle engine (fresh or fully drained)")
        now = self._clock()
        futures = []
        for d in snap.get("requests", []):
            req = Request(rid=int(d["rid"]),
                          prompt=[int(t) for t in d["prompt"]],
                          max_new_tokens=int(d["max_new_tokens"]),
                          slo=str(d.get("slo", "standard")),
                          arrival=now - float(d.get("age_s", 0.0)))
            req.generated = [int(t) for t in d.get("generated", [])]
            req.preemptions = int(d.get("preemptions", 0))
            rem = d.get("deadline_remaining_s")
            if rem is not None:
                req.deadline_at = now + float(rem)
            self.sched.submit(req)
            fut = ServeFuture(self, req)
            self._futures[req.rid] = fut
            futures.append(fut)
        self._rid_counter = max(self._rid_counter,
                                int(snap.get("rid_counter", 0)))
        return futures

    # ------------------------------------------------------------------ #
    def _run_prefill(self, req: Request, start: int, n: int):
        import jax.numpy as jnp
        C = self._config.prefill_chunk
        MB = self.max_blocks_per_seq
        ctx = req.context
        ids = np.zeros((1, C), np.int32)
        ids[0, :n] = ctx[start:start + n]
        positions = np.asarray([start], np.int32)
        tables = self.alloc.block_table(req.rid)[None]           # [1, MB]
        wb, wo = self.alloc.write_map(req.rid, start, C, n_valid=n)
        tokens, self._k_pages, self._v_pages = self._dispatch(
            "prefill", jnp.asarray(ids), jnp.asarray(positions),
            self._k_pages, self._v_pages, jnp.asarray(tables),
            jnp.asarray(wb[None]), jnp.asarray(wo[None]))
        req.prefilled += n
        if req.prefilled >= req.prefill_len:
            if self.prefix is not None and not self.admission.brownout:
                # the prompt's full blocks now hold valid KV: pin them for
                # later requests sharing this prefix (idempotent re-insert;
                # paused under brownout — pinning competes with admission
                # for blocks exactly when the arena is the bottleneck)
                self.prefix.insert(req.prompt,
                                   self.alloc.owned_blocks(req.rid))
            # the chunk holding the last context token also yields the next
            # token — first-token latency includes no extra decode step
            req.state = DECODE
            self._append_token(req, int(tokens[0, n - 1]))

    def _run_decode(self, reqs: List[Request]):
        import jax.numpy as jnp
        B = self._config.max_batch_size
        MB = self.max_blocks_per_seq
        ids = np.zeros((B, 1), np.int32)
        positions = np.zeros((B,), np.int32)
        tables = np.zeros((B, MB), np.int32)      # trash-only for idle slots
        wb = np.zeros((B, 1), np.int32)
        wo = np.zeros((B, 1), np.int32)
        for r in reqs:
            s = r.slot
            ids[s, 0] = r.context[-1]
            positions[s] = r.prefilled
            tables[s] = self.alloc.block_table(r.rid)
            wb[s], wo[s] = self.alloc.write_map(r.rid, r.prefilled, 1)
        tokens, self._k_pages, self._v_pages = self._dispatch(
            "decode", jnp.asarray(ids), jnp.asarray(positions),
            self._k_pages, self._v_pages, jnp.asarray(tables),
            jnp.asarray(wb), jnp.asarray(wo))
        for r in reqs:
            r.prefilled += 1          # the fed token's KV is now resident
            self._append_token(r, int(tokens[r.slot, 0]))

    def _append_token(self, req: Request, tok: int):
        req.generated.append(tok)
        self.tokens_generated += 1
        if req.first_token_at is None:
            req.first_token_at = self._clock()
        if req.done(self._config.eos_token_id):
            req.finished_at = self._clock()
            self.sched.finish(req)
            ttft = req.first_token_at - req.arrival
            latency = req.finished_at - req.arrival
            if self.ledger is not None:
                self.ledger.note_serve_request(req.slo, ttft * 1000.0,
                                               len(req.generated))
            self._emit("serve_request", {
                "event": "finished", "rid": req.rid, "slo": req.slo,
                "prompt_tokens": len(req.prompt),
                "new_tokens": len(req.generated),
                "ttft_ms": ttft * 1000.0,
                "latency_ms": latency * 1000.0,
                "tokens_per_sec": len(req.generated) / max(latency, 1e-9),
                "preemptions": req.preemptions,
            }, step=self.step_count)


def init_serving(model=None, config=None, **kwargs):
    """Module-level helper in the ``deepspeed.init_inference`` style: merge
    a ``{"serving": {...}}`` (or flat) config dict + kwargs.  The nested
    form is collapsed FIRST and kwargs applied after, so engine kwargs
    (``params=``, ``telemetry=``, ...) are never silently discarded by a
    full ds_config — and explicit kwargs always win over config keys."""
    cfg_dict = dict(config or {})
    if "serving" in cfg_dict:
        cfg_dict = dict(cfg_dict["serving"])
    cfg_dict.update(kwargs)
    params = cfg_dict.pop("params", None)
    telemetry = cfg_dict.pop("telemetry", None)
    tracer = cfg_dict.pop("tracer", None)
    seed = cfg_dict.pop("model_seed", None)
    owns_telemetry = False
    if isinstance(telemetry, dict):
        from deepspeed_tpu.runtime.config import DeepSpeedTelemetryConfig
        from deepspeed_tpu.telemetry import TelemetryHub
        tcfg = DeepSpeedTelemetryConfig(**telemetry)
        telemetry = TelemetryHub.from_config(tcfg) if tcfg.enabled else None
        owns_telemetry = telemetry is not None
    cfg = DeepSpeedServingConfig(**cfg_dict)
    eng = ServingEngine(model, config=cfg, params=params, seed=seed,
                        telemetry=telemetry, tracer=tracer)
    # a hub built here from a config dict has no other owner: the engine
    # closes it (final flush + ops-server shutdown) on close()
    eng._owns_telemetry = owns_telemetry
    return eng
