"""Paged KV-cache allocator — fixed-size blocks in a preallocated arena.

The serving-side analogue of ZeRO-Infinity's memory virtualization (arxiv
2104.07857): a sequence's LOGICAL KV memory is decoupled from PHYSICAL HBM
placement, so arena capacity — not batch shape — is the binding constraint.
The device arena is ``[n_layer, num_blocks, block_size, kv_heads, head_dim]``
per K and V; this module owns the host-side bookkeeping:

* a free list of physical block ids (block 0 is reserved as the TRASH
  block: padded/inactive tokens scatter their K/V there, so the compiled
  step needs no write predication);
* a per-sequence block table in logical order, padded to
  ``max_blocks_per_seq`` with trash for the traced ``[B, MB]`` input;
* **per-block refcounts**: a block may be shared by several sequences (the
  prefix cache attaches a cached system-prompt block to every request that
  matches it) plus the cache itself; a block returns to the free list only
  when its last reference drops.  Divergence is copy-on-write by
  construction: only *full*, immutable prompt blocks are ever shared, so
  every KV write lands in a private (refcount-1, single-owner) block;
* eviction: a preempted sequence returns every block to the free list and
  is later *recomputed* (re-prefilled over prompt + generated-so-far) — or,
  with tiering enabled, its block contents are spilled to host/NVMe first
  and *restored* on re-admission (``serving/kv_tiering.py``).

All methods are O(blocks touched); nothing here ever touches jax.
"""

from typing import Dict, List, Optional

import numpy as np


class ArenaExhausted(Exception):
    """No free blocks and the caller chose not to (or could not) evict."""


class PagedKVAllocator:
    """Host-side free-list allocator over ``num_blocks`` physical blocks.

    Block 0 is the trash block and is never handed out; usable capacity is
    ``num_blocks - 1`` blocks = ``(num_blocks - 1) * block_size`` tokens.
    """

    TRASH = 0

    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int):
        assert num_blocks >= 2, "arena needs >= 1 usable block + trash"
        assert block_size >= 1 and max_blocks_per_seq >= 1
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        # LIFO free list: recently-freed blocks are reused first (their
        # pages are hot, and stale contents are fully overwritten before
        # any masked-in position can read them)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._owned: Dict[object, List[int]] = {}   # seq id -> blocks, logical order
        # block id -> total references (sequence owners + prefix-cache pins);
        # a block is live iff it has an entry here, free iff it is in _free
        self._refs: Dict[int, int] = {}
        self.eviction_count = 0

    # -- capacity queries -------------------------------------------------- #
    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def blocks_in_use(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        return -(-max(0, int(n_tokens)) // self.block_size)

    def capacity_tokens(self) -> int:
        """Largest single-sequence footprint this arena can ever hold."""
        return min(self.num_blocks - 1, self.max_blocks_per_seq) * self.block_size

    def can_allocate(self, seq_id, n_tokens: int) -> bool:
        need = self.blocks_for_tokens(n_tokens) - len(self._owned.get(seq_id, ()))
        return need <= self.free_blocks

    # -- lifecycle --------------------------------------------------------- #
    def allocate(self, seq_id, n_tokens: int) -> bool:
        """Grow ``seq_id``'s block list to cover ``n_tokens`` logical
        tokens.  Returns False when the free list cannot cover the growth —
        the scheduler then evicts a victim and retries.
        Raises when a single sequence exceeds ``max_blocks_per_seq``.

        Partial-growth contract: a failed growth is all-or-nothing.  The
        free-list check happens before any block is popped, so on False a
        nonempty owner's ``_owned`` list is byte-identical to before the
        call (the scheduler may already have written KV into those blocks;
        mutating the list here would orphan live device state), and an
        owner that was empty is removed rather than left as a zero-block
        entry.  The post-assert pins this down so a future rewrite of the
        growth loop cannot quietly reintroduce partial growth."""
        owned = self._owned.setdefault(seq_id, [])
        before = len(owned)
        need = self.blocks_for_tokens(n_tokens)
        if need > self.max_blocks_per_seq:
            raise ArenaExhausted(
                f"sequence needs {need} blocks > max_blocks_per_seq "
                f"{self.max_blocks_per_seq}")
        grow = need - before
        if grow <= 0:
            return True
        if grow > len(self._free):
            if not owned:
                del self._owned[seq_id]
            assert len(self._owned.get(seq_id, ())) == before, (
                "failed growth mutated _owned")
            return False
        for _ in range(grow):
            b = self._free.pop()
            self._refs[b] = 1
            owned.append(b)
        return True

    def free(self, seq_id) -> int:
        """Drop ``seq_id``'s reference on every owned block; blocks whose
        last reference this was return to the free list.  Idempotent on
        unknown ids (a finished-then-evicted race is not an error)."""
        blocks = self._owned.pop(seq_id, [])
        # unref in reverse logical order so unshared blocks re-enter the
        # LIFO free list in the same order the pre-refcount free() used
        for b in reversed(blocks):
            self.unref(b)
        return len(blocks)

    def evict(self, seq_id) -> int:
        """Preemption-path free: same reclamation, counted separately so
        telemetry can distinguish completion from eviction."""
        n = self.free(seq_id)
        if n:
            self.eviction_count += 1
        return n

    # -- sharing (prefix cache) -------------------------------------------- #
    def ref(self, block: int) -> None:
        """Add a reference to a live block (prefix-cache pin or attach)."""
        assert block in self._refs, f"ref on non-live block {block}"
        self._refs[block] += 1

    def unref(self, block: int) -> bool:
        """Drop one reference; returns True when the block was actually
        freed (last reference gone → back on the free list)."""
        refs = self._refs.get(block)
        assert refs is not None and refs > 0, f"unref on dead block {block}"
        if refs > 1:
            self._refs[block] = refs - 1
            return False
        del self._refs[block]
        self._free.append(block)
        return True

    def adopt(self, seq_id, blocks: List[int]) -> None:
        """Attach already-live (cached-prefix) blocks as ``seq_id``'s
        logical prefix, copy-free: each gains a reference.  Must precede
        any private growth — the shared blocks are the sequence's first
        logical blocks, and they are full by construction, so every later
        write lands past them in private blocks (structural COW)."""
        assert not self._owned.get(seq_id), (
            f"adopt must precede private growth for {seq_id}")
        for b in blocks:
            self.ref(b)
        self._owned[seq_id] = list(blocks)

    def owned_blocks(self, seq_id) -> List[int]:
        """Copy of ``seq_id``'s physical block list, logical order."""
        return list(self._owned.get(seq_id, ()))

    # -- table / write-map construction (traced-input shaping) ------------- #
    def block_table(self, seq_id) -> np.ndarray:
        """[max_blocks_per_seq] int32 physical ids, trash-padded."""
        table = np.full((self.max_blocks_per_seq,), self.TRASH, np.int32)
        owned = self._owned.get(seq_id, ())
        table[:len(owned)] = owned
        return table

    def write_map(self, seq_id, start: int, n_tokens: int,
                  n_valid: Optional[int] = None):
        """Physical (block, offset) for tokens at logical positions
        ``start .. start + n_tokens - 1``; positions past ``n_valid``
        (pad tail of a bucketed prefill chunk) are routed to the trash
        block.  → ([n_tokens] int32 blocks, [n_tokens] int32 offsets)."""
        owned = self._owned.get(seq_id, ())
        pos = start + np.arange(int(n_tokens))
        logical = pos // self.block_size
        nv = int(n_tokens) if n_valid is None else min(int(n_valid), int(n_tokens))
        assert nv == 0 or logical[nv - 1] < max(len(owned), 1), (
            f"write past allocation: pos {pos[nv - 1]} needs block "
            f"{logical[nv - 1]}, own {len(owned)}")
        phys = np.asarray([owned[b] if b < len(owned) else self.TRASH
                           for b in logical], np.int32)
        off = (pos % self.block_size).astype(np.int32)
        if n_valid is not None and n_valid < n_tokens:
            phys[n_valid:] = self.TRASH
        return phys, off

    # -- invariants (tests) ------------------------------------------------ #
    def check_consistent(self):
        """Every physical block is exactly one of: trash, free, or live
        with refcount >= 1 — and a live block's references account for
        every sequence holding it (sharing beyond the owner count is the
        prefix cache's pin).  Raises AssertionError on violation."""
        owners: Dict[int, int] = {}
        for seq_id, blocks in self._owned.items():
            in_seq = set()
            for b in blocks:
                assert 0 < b < self.num_blocks, f"bad block id {b}"
                assert b not in in_seq, f"block {b} twice in {seq_id}"
                in_seq.add(b)
                owners[b] = owners.get(b, 0) + 1
        for b, refs in self._refs.items():
            assert 0 < b < self.num_blocks, f"bad live block id {b}"
            assert refs >= 1, f"live block {b} with refcount {refs}"
        for b, n in owners.items():
            assert n <= self._refs.get(b, 0), (
                f"block {b}: {n} owners > {self._refs.get(b, 0)} refs")
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate free-list entry"
        assert not (free & self._refs.keys()), (
            f"blocks both free and live: {sorted(free & self._refs.keys())}")
        assert self.TRASH not in free and self.TRASH not in self._refs, (
            "trash block handed out")
        covered = {self.TRASH} | free | self._refs.keys()
        assert len(covered) == self.num_blocks, (
            f"leaked blocks: {self.num_blocks - len(covered)}")


def init_arena(cfg, num_blocks: int, block_size: int, dtype=None):
    """Device arena pair for ``models/gpt.py:gpt_paged_step``:
    K/V ``[n_layer, num_blocks, block_size, kv_heads, head_dim]``."""
    import jax.numpy as jnp
    dtype = dtype or cfg.dtype
    shape = (cfg.n_layer, num_blocks, block_size, cfg.kv_heads, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def arena_bytes(cfg, num_blocks: int, block_size: int, dtype_bytes: int = 2) -> int:
    return (2 * cfg.n_layer * num_blocks * block_size * cfg.kv_heads
            * cfg.head_dim * dtype_bytes)
