"""Serving subsystem: continuous batching over a paged KV-cache arena.

Layering (host control plane → device data plane):

* :mod:`deepspeed_tpu.serving.kv_cache` — refcounted free-list block
  allocator + per-sequence block tables over a preallocated device arena;
* :mod:`deepspeed_tpu.serving.scheduler` — admission, chunked prefill,
  SLO-class preemption with the spill→evict reclamation ladder;
* :mod:`deepspeed_tpu.serving.kv_tiering` — spill/restage of preempted KV
  through the PR 10 host/NVMe offload store (recompute → restore);
* :mod:`deepspeed_tpu.serving.prefix_cache` — refcounted trie sharing
  full prompt blocks across requests (prefill-once system prompts);
* :mod:`deepspeed_tpu.serving.engine` — the two-program (decode + prefill)
  jitted step and the ``submit()/step()/run()`` surface;
* config: :class:`DeepSpeedServingConfig`, the ``"serving"`` ds_config key.
"""

from deepspeed_tpu.serving.config import DeepSpeedServingConfig
from deepspeed_tpu.serving.engine import ServeFuture, ServingEngine, init_serving
from deepspeed_tpu.serving.kv_cache import (ArenaExhausted, PagedKVAllocator,
                                            arena_bytes, init_arena)
from deepspeed_tpu.serving.kv_tiering import KVTieringManager
from deepspeed_tpu.serving.prefix_cache import PrefixCache
from deepspeed_tpu.serving.scheduler import (QueueFull, Request,
                                             ServingScheduler, SLO_PRIORITY)

__all__ = [
    "ArenaExhausted",
    "DeepSpeedServingConfig",
    "KVTieringManager",
    "PagedKVAllocator",
    "PrefixCache",
    "QueueFull",
    "Request",
    "SLO_PRIORITY",
    "ServeFuture",
    "ServingEngine",
    "ServingScheduler",
    "arena_bytes",
    "init_arena",
    "init_serving",
]
