"""Serving subsystem: continuous batching over a paged KV-cache arena.

Layering (host control plane → device data plane):

* :mod:`deepspeed_tpu.serving.kv_cache` — free-list block allocator +
  per-sequence block tables over a preallocated device arena;
* :mod:`deepspeed_tpu.serving.scheduler` — admission, chunked prefill,
  SLO-class preemption with eviction/recompute;
* :mod:`deepspeed_tpu.serving.engine` — the two-program (decode + prefill)
  jitted step and the ``submit()/step()/run()`` surface;
* config: :class:`DeepSpeedServingConfig`, the ``"serving"`` ds_config key.
"""

from deepspeed_tpu.serving.config import DeepSpeedServingConfig
from deepspeed_tpu.serving.engine import ServeFuture, ServingEngine, init_serving
from deepspeed_tpu.serving.kv_cache import (ArenaExhausted, PagedKVAllocator,
                                            arena_bytes, init_arena)
from deepspeed_tpu.serving.scheduler import (QueueFull, Request,
                                             ServingScheduler, SLO_PRIORITY)

__all__ = [
    "ArenaExhausted",
    "DeepSpeedServingConfig",
    "PagedKVAllocator",
    "QueueFull",
    "Request",
    "SLO_PRIORITY",
    "ServeFuture",
    "ServingEngine",
    "ServingScheduler",
    "arena_bytes",
    "init_arena",
    "init_serving",
]
