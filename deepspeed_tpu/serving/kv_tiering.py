"""Tiered KV cache — spill evicted sequences to host/NVMe, restore on
re-admission.

The serving-side mirror of the PR 10 offload engine: where the trainer
virtualizes optimizer state across hbm/host/nvme (ZeRO-Infinity, arxiv
2104.07857), this module virtualizes the *paged KV arena*.  A sequence
the scheduler would otherwise destructively evict instead has its block
contents gathered device→host through a bounded copy ring and handed to a
:class:`~deepspeed_tpu.runtime.offload.TieredStore` (host LRU bounded by
``kv_host_cache_bytes``, write-through to CRC-framed NVMe chunks).  On
re-admission the bytes restage through the store's async prefetch ring —
kicked while the sequence still waits, polled via ``restage_ready`` so
admission happens only once the window is resident (the T3 move, arxiv
2401.16677: overlap the restore against decode of everything else) — and
are scattered back into freshly allocated blocks.  Restore is bitwise
(the store CRC-verifies every chunk), so greedy token-identity holds by
construction rather than by recompute.

Coherence is epoch-keyed: every spill of a sequence gets a fresh
``kvseq/<rid>/<epoch>`` key and removes its predecessor, and a restage or
discard removes the key outright — so a finished sequence's stale bytes
can never resurface in a reused block id (the PR 10 stale-chunk race,
closed on the serving path).

Copy plumbing is two tiny jits (a ``take`` gather and an ``at[].set``
scatter over fixed ``spill_chunk_blocks``-sized chunks) — deliberately
separate from the engine's step function, whose compiled-program count
stays at two.  Pad lanes of both route to physical block 0, the trash
block, which is garbage-by-design.
"""

import shutil
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.runtime.offload import (StagingPool, TieredStore,
                                           TIER_HOST, TIER_NVME)


@dataclass
class SpillRecord:
    key: str
    nbytes: int
    tokens: int
    n_blocks: int
    epoch: int


class KVTieringManager:
    """Owns the spill/restage data path for one serving engine.

    The engine thread drives spill/restage; the staging pool's worker
    threads complete the I/O — so the bookkeeping below is shared state.
    Discipline (enforced by dslint's lock-discipline pass, which covers
    ``deepspeed_tpu/serving/``): ``_lock`` wraps only dict/counter
    mutation, never a store or staging call — those block on disk and
    backpressure, and must not stall a concurrent ``stats()``.
    """

    def __init__(self, offload_dir: Optional[str] = None,
                 host_cache_bytes: int = 1 << 30,
                 spill_budget_bytes: int = 0,
                 spill_chunk_blocks: int = 8,
                 ring_depth: int = 2):
        if offload_dir is None:
            offload_dir = tempfile.mkdtemp(prefix="dst-kv-tier-")
            self._owns_dir = True
        else:
            self._owns_dir = False
        self.offload_dir = offload_dir
        self.host_cache_bytes = int(host_cache_bytes)
        self.spill_budget_bytes = int(spill_budget_bytes)  # 0 = unbounded
        self.spill_chunk_blocks = max(1, int(spill_chunk_blocks))
        self.ring_depth = max(1, int(ring_depth))
        self.staging = StagingPool(offload_dir)
        self.store = TieredStore(self.staging, max_in_cpu=self.host_cache_bytes)
        self._lock = threading.Lock()
        self._seqs: Dict[int, SpillRecord] = {}   # guarded-by: _lock
        self._epoch = 0                           # guarded-by: _lock
        self._spilled_bytes = 0                   # guarded-by: _lock
        self.spill_count = 0                      # guarded-by: _lock
        self.restage_count = 0                    # guarded-by: _lock
        self.restage_wait_s = 0.0                 # guarded-by: _lock
        self._gather = None      # lazy jits, engine-thread only
        self._scatter = None
        self._closed = False

    # ---- copy plumbing -------------------------------------------------- #
    def _copy_fns(self, kp):
        """Build (once) the chunk gather/scatter jits for this arena's
        shape/dtype.  Donation on the scatter updates the arena in place
        on accelerators; CPU cannot donate (jax warns and copies)."""
        if self._gather is None:
            import jax
            import jax.numpy as jnp

            def gather(kp, vp, idx):
                return jnp.take(kp, idx, axis=1), jnp.take(vp, idx, axis=1)

            def scatter(kp, vp, idx, kb, vb):
                return kp.at[:, idx].set(kb), vp.at[:, idx].set(vb)

            donate = (0, 1) if jax.default_backend() != "cpu" else ()
            self._gather = jax.jit(gather)
            self._scatter = jax.jit(scatter, donate_argnums=donate)
        return self._gather, self._scatter

    def _gather_to_host(self, kp, vp, blocks: List[int]) -> np.ndarray:
        """Bounded copy ring, device→host: dispatch up to ``ring_depth``
        chunk gathers before draining the oldest (``np.asarray`` is the
        D2H sync point), so the transfer overlaps the next gather's
        dispatch.  → one ``[2, L, n_blocks, BS, H, D]`` host array."""
        import jax.numpy as jnp
        gather, _ = self._copy_fns(kp)
        CH = self.spill_chunk_blocks
        ring: deque = deque()
        k_parts, v_parts = [], []

        def drain_one():
            dk, dv, n = ring.popleft()
            k_parts.append(np.asarray(dk)[:, :n])
            v_parts.append(np.asarray(dv)[:, :n])

        for off in range(0, len(blocks), CH):
            chunk = blocks[off:off + CH]
            idx = np.zeros((CH,), np.int32)   # pad lanes gather trash
            idx[:len(chunk)] = chunk
            ring.append((*gather(kp, vp, jnp.asarray(idx)), len(chunk)))
            if len(ring) >= self.ring_depth:
                drain_one()
        while ring:
            drain_one()
        return np.stack([np.concatenate(k_parts, axis=1),
                         np.concatenate(v_parts, axis=1)])

    def _scatter_from_host(self, kp, vp, data: np.ndarray,
                           dest_blocks: List[int]):
        """Host→device, same chunking; returns the updated arena pair."""
        import jax.numpy as jnp
        _, scatter = self._copy_fns(kp)
        CH = self.spill_chunk_blocks
        L, _, BS, H, D = kp.shape
        hk, hv = data[0], data[1]
        for off in range(0, len(dest_blocks), CH):
            chunk = dest_blocks[off:off + CH]
            n = len(chunk)
            idx = np.zeros((CH,), np.int32)   # pad lanes scatter to trash
            idx[:n] = chunk
            kb = np.zeros((L, CH, BS, H, D), hk.dtype)
            vb = np.zeros((L, CH, BS, H, D), hk.dtype)
            kb[:, :n] = hk[:, off:off + n]
            vb[:, :n] = hv[:, off:off + n]
            kp, vp = scatter(kp, vp, jnp.asarray(idx),
                             jnp.asarray(kb), jnp.asarray(vb))
        return kp, vp

    # ---- capacity ------------------------------------------------------- #
    def chunk_bytes(self, kp, n_blocks: int) -> int:
        """Spill footprint of ``n_blocks`` arena blocks (K and V)."""
        L, _, BS, H, D = kp.shape
        return 2 * L * int(n_blocks) * BS * H * D * np.dtype(kp.dtype).itemsize

    def can_spill(self, nbytes: int) -> bool:
        """Whether the spill budget admits ``nbytes`` more.  Budget 0 is
        unbounded — the host+NVMe tier is then 'full' only when the disk
        itself fails, which surfaces as a StagingError."""
        if not self.spill_budget_bytes:
            return True
        with self._lock:
            return self._spilled_bytes + nbytes <= self.spill_budget_bytes

    # ---- spill path ------------------------------------------------------ #
    def spill(self, rid: int, blocks: List[int], kp, vp,
              tokens: int) -> Optional[str]:  # may-block: staging backpressure
        """Capture ``rid``'s block contents into the tiered store before
        its arena blocks are reclaimed.  Returns the landing tier
        (``"host"``/``"nvme"``) or None when the spill budget refuses —
        the caller then falls back to destructive evict+recompute."""
        nbytes = self.chunk_bytes(kp, len(blocks))
        if not blocks or not self.can_spill(nbytes):
            return None
        with self._lock:
            self._epoch += 1
            epoch = self._epoch
            old = self._seqs.pop(rid, None)
            if old is not None:
                self._spilled_bytes -= old.nbytes
        if old is not None:
            # superseded spill: its epoch key must not outlive this one
            self.store.remove(old.key)
        key = f"kvseq/{rid}/{epoch}"
        if nbytes > self.host_cache_bytes:
            # larger than the whole host cache: ship the device buffers
            # straight to staging (worker-side DMA), don't wash the LRU
            import jax.numpy as jnp
            idx = jnp.asarray(np.asarray(blocks, np.int32))
            self.store.put_device(
                key, jnp.stack([jnp.take(kp, idx, axis=1),
                                jnp.take(vp, idx, axis=1)]))
        else:
            self.store.put(key, self._gather_to_host(kp, vp, blocks))
        with self._lock:
            self._seqs[rid] = SpillRecord(key=key, nbytes=nbytes,
                                          tokens=int(tokens),
                                          n_blocks=len(blocks), epoch=epoch)
            self._spilled_bytes += nbytes
            self.spill_count += 1
        return TIER_HOST if TIER_HOST in self.store.residency(key) else TIER_NVME

    def spilled_tokens(self, rid: int) -> int:
        with self._lock:
            rec = self._seqs.get(rid)
            return rec.tokens if rec is not None else 0

    def is_spilled(self, rid: int) -> bool:
        with self._lock:
            return rid in self._seqs

    # ---- restage path ---------------------------------------------------- #
    def begin_restage(self, rid: int) -> None:
        """Kick the async prefetch for ``rid``'s spilled bytes (idempotent;
        a no-op when host-resident or already in flight)."""
        with self._lock:
            rec = self._seqs.get(rid)
        if rec is not None:
            self.store.prefetch([rec.key])

    def restage_ready(self, rid: int) -> bool:
        """True when ``restage`` would not block on the NVMe read."""
        with self._lock:
            rec = self._seqs.get(rid)
        return rec is not None and self.store.ready(rec.key)

    def restage(self, rid: int, kp, vp,  # may-block: joins the chunk read
                dest_blocks: List[int]) -> Tuple[Any, Any, Dict[str, Any]]:
        """Restore ``rid``'s spilled KV into ``dest_blocks`` and drop the
        spill record + chunk.  Returns ``(kp, vp, info)`` — the arena pair
        is rebuilt by the scatter jit.  Raises KeyError when ``rid`` has
        no spill record and StagingError when the bytes are unreadable
        (the caller falls back to recompute)."""
        with self._lock:
            rec = self._seqs.get(rid)
        if rec is None:
            raise KeyError(f"no spill record for rid {rid}")
        assert len(dest_blocks) == rec.n_blocks, (
            f"restage of {rec.n_blocks} blocks into {len(dest_blocks)}")
        ready = self.store.ready(rec.key)
        source = (TIER_HOST if TIER_HOST in self.store.residency(rec.key)
                  else TIER_NVME)
        t0 = time.perf_counter()
        data = self.store.get(rec.key)
        wait = time.perf_counter() - t0
        kp, vp = self._scatter_from_host(kp, vp, data, dest_blocks)
        with self._lock:
            self._seqs.pop(rid, None)
            self._spilled_bytes -= rec.nbytes
            self.restage_count += 1
            self.restage_wait_s += wait
        self.store.remove(rec.key)   # restored: the staged copy is dead
        return kp, vp, {"source": source, "ready": ready, "wait_s": wait,
                        "bytes": rec.nbytes, "blocks": rec.n_blocks,
                        "tokens": rec.tokens}

    def discard(self, rid: int) -> bool:
        """Drop ``rid``'s spill record and every staged copy (sequence
        finished or fell back to recompute).  The remove joins any
        in-flight write first, so a reused key epoch can never read these
        bytes back."""
        with self._lock:
            rec = self._seqs.pop(rid, None)
            if rec is not None:
                self._spilled_bytes -= rec.nbytes
        if rec is None:
            return False
        self.store.remove(rec.key)
        return True

    # ---- introspection / lifecycle --------------------------------------- #
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {"kv_spilled_seqs": len(self._seqs),
                   "kv_spilled_bytes": self._spilled_bytes,
                   "kv_spills": self.spill_count,
                   "kv_restages": self.restage_count,
                   "kv_restage_wait_ms": self.restage_wait_s * 1000.0}
        store = self.store.stats()
        out["kv_host_bytes"] = store.get("host_bytes", 0)
        out["kv_nvme_bytes"] = self.staging.total_bytes()
        out["kv_ring_hits"] = store.get("ring_hits", 0)
        out["kv_ring_misses"] = store.get("ring_misses", 0)
        return out

    def describe(self) -> str:
        """Tier occupancy summary for ArenaExhausted messages."""
        s = self.stats()
        budget = (f"{self.spill_budget_bytes}B budget"
                  if self.spill_budget_bytes else "unbounded")
        return (f"host {s['kv_host_bytes']}B/{self.host_cache_bytes}B, "
                f"nvme {s['kv_nvme_bytes']}B ({budget}), "
                f"{s['kv_spilled_seqs']} spilled seqs")

    def drain(self) -> None:
        """Join every in-flight copy-ring task (spill writes, restage
        prefetch reads) without closing the backend.  The serving engine
        calls this before close() and during wedge recovery — after a
        drain no staged task can still reference the old arena arrays."""
        if not self._closed:
            self.staging.drain()

    def close(self) -> None:
        """Idempotent shutdown: drain staging, drop an owned tempdir."""
        if self._closed:
            return
        self._closed = True
        self.staging.close()
        if self._owns_dir:
            shutil.rmtree(self.offload_dir, ignore_errors=True)
