"""Refcounted prefix cache — shared system prompts prefilled once.

A radix-style trie over *block-aligned* token chunks: each edge is one
``block_size``-token tuple, each node pins one physical KV block in the
:class:`~deepspeed_tpu.serving.kv_cache.PagedKVAllocator` (the node holds
a reference, so the block survives its original sequence finishing).  A
request whose prompt starts with a cached chunk path adopts those blocks
copy-free — prefill skips the matched tokens entirely.

Copy-on-write is structural rather than mechanical: only FULL prompt
blocks are ever inserted, and a match is capped strictly below the prompt
length, so every KV write a sequence performs (its unmatched prompt tail
and all generated tokens) lands in private refcount-1 blocks.  Divergence
after a shared prefix therefore never mutates a shared block — there is
nothing to copy.

The cache is a *reclaimable* tenant of the arena: ``release(n)`` drops
least-recently-used leaf pins until ``n`` blocks actually return to the
free list, which the scheduler uses as the first (non-destructive) rung of
its eviction ladder.  Nothing here touches jax; the blocks' device
contents are whatever prefill wrote, untouched.
"""

from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.serving.kv_cache import PagedKVAllocator


class _Node:
    __slots__ = ("children", "block", "last_use")

    def __init__(self, block: Optional[int] = None):
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.block = block          # physical block this node pins (root: None)
        self.last_use = 0


class PrefixCache:
    """Trie of block-aligned prompt chunks over refcounted arena blocks.

    ``max_blocks`` bounds how many arena blocks the cache may pin
    (0 = unbounded up to the arena); past it, LRU leaves are dropped.
    """

    def __init__(self, alloc: PagedKVAllocator, max_blocks: int = 0):
        self.alloc = alloc
        self.block_size = alloc.block_size
        self.max_blocks = int(max_blocks)
        self._root = _Node()
        self._clock = 0                 # monotonic touch counter (LRU key)
        self.cached_blocks = 0
        self.lookups = 0
        self.hits = 0
        self.insertions = 0
        self.released_blocks = 0

    # ---- read path ----------------------------------------------------- #
    def lookup(self, prompt: List[int]) -> List[int]:
        """Longest cached block-aligned prefix of ``prompt`` → physical
        block list (possibly empty).  The match is capped at
        ``(len(prompt) - 1) // block_size`` chunks — strictly shorter than
        the prompt — so at least one prompt token always goes through
        prefill and the completing chunk still yields the first new token
        from real logits."""
        self.lookups += 1
        self._clock += 1
        max_chunks = max(0, (len(prompt) - 1) // self.block_size)
        node, blocks = self._root, []
        for i in range(max_chunks):
            chunk = tuple(prompt[i * self.block_size:(i + 1) * self.block_size])
            child = node.children.get(chunk)
            if child is None:
                break
            child.last_use = self._clock
            blocks.append(child.block)
            node = child
        if blocks:
            self.hits += 1
        return blocks

    # ---- write path ---------------------------------------------------- #
    def insert(self, prompt: List[int], blocks: List[int]) -> int:
        """Pin ``prompt``'s full blocks into the trie.  ``blocks`` is the
        sequence's physical block list in logical order; only the first
        ``len(prompt) // block_size`` (full prompt chunks) are eligible.
        Existing nodes keep their original block (idempotent — re-inserting
        a shared prompt adds no references); new nodes take one reference
        each.  Returns how many new blocks were pinned."""
        n = min(len(prompt) // self.block_size, len(blocks))
        node, added = self._root, 0
        self._clock += 1
        for i in range(n):
            chunk = tuple(prompt[i * self.block_size:(i + 1) * self.block_size])
            child = node.children.get(chunk)
            if child is None:
                child = _Node(block=blocks[i])
                self.alloc.ref(blocks[i])
                node.children[chunk] = child
                self.cached_blocks += 1
                self.insertions += 1
                added += 1
            child.last_use = self._clock
            node = child
        if self.max_blocks:
            while self.cached_blocks > self.max_blocks:
                if not self._drop_lru_leaf():
                    break
        return added

    # ---- reclamation ---------------------------------------------------- #
    def _lru_leaf(self) -> Optional[Tuple[_Node, Tuple[int, ...], _Node]]:
        """(parent, edge, leaf) of the least-recently-used leaf, or None."""
        best = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            for edge, child in node.children.items():
                if child.children:
                    stack.append(child)
                elif best is None or child.last_use < best[2].last_use:
                    best = (node, edge, child)
        return best

    def _drop_lru_leaf(self) -> bool:
        """Unpin one LRU leaf; returns whether a pin was dropped.  The
        block only re-enters the free list if no sequence still holds it —
        unref's return value tells ``release`` how much was reclaimed."""
        found = self._lru_leaf()
        if found is None:
            return False
        parent, edge, leaf = found
        del parent.children[edge]
        self.cached_blocks -= 1
        if self.alloc.unref(leaf.block):
            self.released_blocks += 1
        return True

    def release(self, n_blocks: int) -> int:
        """Drop LRU leaves until ``n_blocks`` blocks actually returned to
        the free list (or the cache is empty).  Returns blocks freed."""
        before = self.released_blocks
        while self.released_blocks - before < n_blocks:
            if not self._drop_lru_leaf():
                break
        return self.released_blocks - before

    # ---- introspection -------------------------------------------------- #
    def stats(self) -> Dict[str, int]:
        return {"prefix_lookups": self.lookups,
                "prefix_hits": self.hits,
                "prefix_cached_blocks": self.cached_blocks,
                "prefix_insertions": self.insertions,
                "prefix_released_blocks": self.released_blocks}
