"""Serving config — the ``"serving"`` block of the ds_config document.

The reference snapshot (v0.8.3) predates DeepSpeed-FastGen, so there is no
reference config surface to mirror; the knobs follow the same shape
philosophy as the rest of ``runtime/config.py``: one pydantic block, safe
defaults, every field documented where it is consumed.

Sizing guidance (README § Serving): ``block_size`` trades internal
fragmentation (last-block waste, avg block_size/2 tokens per sequence)
against block-table length and scatter/gather granularity — 16 suits toy
and CPU runs, 32–64 suits real HBM arenas.  ``num_blocks`` bounds the
arena: total KV bytes = 2 * n_layer * num_blocks * block_size * kv_heads *
head_dim * dtype_bytes.
"""

from typing import Dict, Optional

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedServingConfig(DeepSpeedConfigModel):
    """``serving`` block — continuous batching + paged KV cache
    (``deepspeed_tpu/serving/``).  See README § Serving."""
    enabled: bool = False
    # ---- paged KV arena -------------------------------------------------- #
    block_size: int = 16          # tokens per physical KV block
    num_blocks: int = 256         # arena capacity in blocks (incl. trash)
    max_blocks_per_seq: int = 0   # 0 -> ceil(n_positions / block_size)
    # ---- continuous batching --------------------------------------------- #
    max_batch_size: int = 8       # decode slots (fixed compiled batch shape)
    prefill_chunk: int = 64       # chunked-prefill tokens per engine step
    max_queue: int = 1024         # waiting-queue bound; submit raises past it
    # ---- scheduling ------------------------------------------------------ #
    slo_preemption: bool = True   # higher SLO classes may evict lower ones
    # per-class TTFT bounds (ms) for the goodput ledger's tokens-within-
    # bound accounting; unset classes use telemetry/ledger.py defaults
    slo_ttft_bound_ms: Dict[str, float] = Field(default_factory=dict)
    max_new_tokens_default: int = 64
    eos_token_id: Optional[int] = None
    # ---- tiered KV (serving/kv_tiering.py) -------------------------------- #
    kv_tiering: bool = False          # spill preempted KV to host/NVMe
    kv_offload_dir: Optional[str] = None   # None -> private tempdir
    kv_host_cache_bytes: int = 1 << 30     # host-LRU tier budget
    kv_spill_budget_bytes: int = 0         # total spill cap; 0 = unbounded
    kv_spill_chunk_blocks: int = 8         # copy-ring chunk (blocks)
    kv_ring_depth: int = 2                 # outstanding D2H chunk gathers
    # ---- prefix cache (serving/prefix_cache.py) --------------------------- #
    prefix_cache: bool = False        # share full prompt blocks, refcounted
    prefix_cache_blocks: int = 0      # pinned-block cap; 0 = unbounded
    # ---- resilience (README § Serving resilience) -------------------------- #
    # per-class request deadline (ms from arrival); an expired request is
    # cancelled at the next step boundary, its blocks freed and its prefill
    # booked as wasted.  Unset/0 classes have no deadline.
    deadline_ms: Dict[str, float] = Field(default_factory=dict)
    # bounded step dispatch (comm/bounded.py): a compiled serve step that
    # exceeds this raises ServeStepTimeout and triggers in-process
    # recovery instead of hanging the engine forever.  0 = inline dispatch.
    serve_step_timeout_s: float = 0.0
    # adaptive admission ladder (scheduler.AdmissionController): the
    # oldest-waiting age that trips brownout; 2x trips batch-class shed,
    # 4x sheds standard too.  0 disables the queue-age signal (the
    # SLOMonitor TTFT-burn signal still drives the ladder when wired).
    queue_age_watermark_ms: float = 0.0
    brownout_max_new_tokens: int = 0  # brownout cap on max_new_tokens; 0 = off
    shed_recovery_steps: int = 16     # calm step evaluations per rung down
    # ---- numerics / misc ------------------------------------------------- #
    dtype: str = "bfloat16"
    seed: int = 0
    telemetry_every: int = 8      # serve_step gauge cadence (engine steps)

    @property
    def jnp_dtype(self):
        import jax.numpy as jnp
        return {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
                "float16": jnp.float16, "fp16": jnp.float16,
                "float32": jnp.float32, "fp32": jnp.float32,
                "float": jnp.float32}[str(self.dtype)]
