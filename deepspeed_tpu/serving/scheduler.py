"""Request queue + continuous-batching scheduler.

Pure-host control plane for ``serving/engine.py``: admission, the
prefill/decode split with chunked prefill, and SLO-class preemption.  The
scheduler owns request state and drives the :class:`PagedKVAllocator`; it
never touches jax, so every policy below is unit-testable on CPU in
microseconds.

Scheduling policy (see README § Serving):

* **Admission** is continuous: whenever a decode slot and enough arena
  blocks are free, the best waiting request — ordered by (SLO priority,
  submit order) — is admitted.  Head-of-line blocking on an arena-full
  condition is deliberate: skipping ahead would starve long prompts.
* **Chunked prefill**: one prompt chunk (``prefill_chunk`` tokens) is
  processed per engine step, so a long prompt never stalls the decode
  batch for more than one chunk's latency.
* **Preemption** frees a victim's blocks (eviction) and requeues it for
  *recompute* — on resume the prompt + generated-so-far is re-prefilled,
  which under greedy decoding continues the identical token stream.
  Victim order is weakest SLO class first, then youngest admission, and
  never the requester — so the oldest admitted request always progresses
  and the eviction loop terminates.
"""

import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.serving.kv_cache import ArenaExhausted, PagedKVAllocator

# SLO classes, strongest first; lower number = higher priority.
SLO_PRIORITY = {"realtime": 0, "standard": 1, "batch": 2}

WAITING = "waiting"
PREFILL = "prefill"
DECODE = "decode"
FINISHED = "finished"


class QueueFull(Exception):
    """submit() past ``max_queue`` — shed load at the front door."""


@dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    slo: str = "standard"
    arrival: float = 0.0               # host clock, supplied by the engine
    state: str = WAITING
    generated: List[int] = field(default_factory=list)
    prefilled: int = 0                 # context tokens with KV in the arena
    prefill_len: int = 0               # prefill target, set at admission
    slot: int = -1                     # decode-batch slot while active
    submit_seq: int = -1               # FIFO key (stable across preemption)
    admit_seq: int = -1                # youngest-victim key, per admission
    preemptions: int = 0
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None

    @property
    def priority(self) -> int:
        return SLO_PRIORITY.get(self.slo, SLO_PRIORITY["standard"])

    @property
    def context(self) -> List[int]:
        """Tokens whose KV must exist before the next decode step."""
        return self.prompt + self.generated

    @property
    def needs_prefill(self) -> bool:
        return self.state == PREFILL and self.prefilled < self.prefill_len

    def done(self, eos_token_id: Optional[int]) -> bool:
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (eos_token_id is not None and self.generated
                and self.generated[-1] == eos_token_id)


class ServingScheduler:
    def __init__(self, cfg, allocator: PagedKVAllocator, num_slots: int):
        self.cfg = cfg
        self.alloc = allocator
        self.num_slots = int(num_slots)
        self.waiting: deque = deque()
        self.active: Dict[int, Request] = {}      # slot -> request
        self._free_slots: List[int] = list(range(self.num_slots - 1, -1, -1))
        self._submit_counter = itertools.count()
        self._admit_counter = itertools.count()
        self.preemption_count = 0
        self.finished_count = 0
        # engine hook: called with the victim after each eviction (telemetry)
        self.on_preempt = None

    # ---- intake ----------------------------------------------------------- #
    def submit(self, req: Request) -> Request:
        if len(self.waiting) >= self.cfg.max_queue:
            raise QueueFull(f"waiting queue at max_queue={self.cfg.max_queue}")
        req.submit_seq = next(self._submit_counter)
        req.state = WAITING
        self.waiting.append(req)
        return req

    def _pop_best_waiting(self) -> Optional[Request]:
        if not self.waiting:
            return None
        best = min(self.waiting, key=lambda r: (r.priority, r.submit_seq))
        self.waiting.remove(best)
        return best

    # ---- admission -------------------------------------------------------- #
    def admit(self) -> List[Request]:
        """Fill free decode slots from the waiting queue.  Returns the
        newly admitted requests (their prefill starts next step)."""
        admitted = []
        while self._free_slots:
            req = self._pop_best_waiting()
            if req is None:
                break
            target = len(req.context)
            while not self.alloc.allocate(req.rid, target):
                victim = self._admission_victim(req)
                if victim is None:
                    # Arena full and nothing evictable below this class:
                    # head-of-line blocks until decode frees capacity.
                    self.waiting.appendleft(req)
                    return admitted
                self.preempt(victim)
            req.slot = self._free_slots.pop()
            req.admit_seq = next(self._admit_counter)
            req.prefill_len = target
            req.prefilled = 0
            req.state = PREFILL
            self.active[req.slot] = req
            admitted.append(req)
        return admitted

    # ---- preemption ------------------------------------------------------- #
    def _victim_order(self, candidates: List[Request]) -> List[Request]:
        # weakest SLO class first, then youngest admission
        return sorted(candidates, key=lambda r: (-r.priority, -r.admit_seq))

    def _admission_victim(self, incoming: Request) -> Optional[Request]:
        if not self.cfg.slo_preemption:
            return None
        weaker = [r for r in self.active.values()
                  if r.priority > incoming.priority]
        order = self._victim_order(weaker)
        return order[0] if order else None

    def _growth_victim(self, requester: Request) -> Optional[Request]:
        others = [r for r in self.active.values() if r is not requester]
        order = self._victim_order(others)
        return order[0] if order else None

    def preempt(self, victim: Request) -> None:
        """Evict ``victim``'s blocks and requeue it for recompute."""
        assert victim.slot in self.active and self.active[victim.slot] is victim
        del self.active[victim.slot]
        self._free_slots.append(victim.slot)
        self.alloc.evict(victim.rid)
        victim.slot = -1
        victim.prefilled = 0
        victim.state = WAITING
        victim.preemptions += 1
        self.preemption_count += 1
        self.waiting.appendleft(victim)   # submit_seq keeps its FIFO place
        if self.on_preempt is not None:
            self.on_preempt(victim)

    def ensure_capacity(self, req: Request, n_tokens: int) -> None:
        """Guarantee ``req`` owns blocks for ``n_tokens`` context tokens,
        evicting victims under arena pressure.  The victim order excludes
        the requester, so the loop strictly shrinks the active set and
        terminates; if the requester alone exceeds the arena we raise."""
        while not self.alloc.allocate(req.rid, n_tokens):
            victim = self._growth_victim(req)
            if victim is None:
                raise ArenaExhausted(
                    f"request {req.rid} needs "
                    f"{self.alloc.blocks_for_tokens(n_tokens)} blocks; arena "
                    f"has {self.alloc.num_blocks - 1} usable")
            self.preempt(victim)

    # ---- per-step work selection ------------------------------------------ #
    def next_prefill(self) -> Optional[Tuple[Request, int, int]]:
        """One (request, start, n_tokens) prompt chunk for this step, or
        None.  Strongest class / oldest admission goes first."""
        pending = [r for r in self.active.values() if r.needs_prefill]
        if not pending:
            return None
        req = min(pending, key=lambda r: (r.priority, r.admit_seq))
        start = req.prefilled
        n = min(self.cfg.prefill_chunk, req.prefill_len - start)
        return req, start, n

    def decode_batch(self) -> List[Request]:
        return [r for r in self.active.values() if r.state == DECODE]

    # ---- completion ------------------------------------------------------- #
    def finish(self, req: Request) -> None:
        assert req.slot in self.active and self.active[req.slot] is req
        del self.active[req.slot]
        self._free_slots.append(req.slot)
        self.alloc.free(req.rid)
        req.slot = -1
        req.state = FINISHED
        self.finished_count += 1

    # ---- introspection ---------------------------------------------------- #
    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.active)

    def stats(self) -> Dict[str, int]:
        return {
            "queue_depth": len(self.waiting),
            "active": len(self.active),
            "free_slots": len(self._free_slots),
            "blocks_in_use": self.alloc.blocks_in_use,
            "blocks_free": self.alloc.free_blocks,
            "preemptions": self.preemption_count,
            "finished": self.finished_count,
        }
