"""Request queue + continuous-batching scheduler.

Pure-host control plane for ``serving/engine.py``: admission, the
prefill/decode split with chunked prefill, and SLO-class preemption.  The
scheduler owns request state and drives the :class:`PagedKVAllocator`; it
never touches jax, so every policy below is unit-testable on CPU in
microseconds.

Scheduling policy (see README § Serving):

* **Admission** is continuous: whenever a decode slot and enough arena
  blocks are free, the best waiting request — ordered by (SLO priority,
  submit order) — is admitted.  Head-of-line blocking on an arena-full
  condition is deliberate: skipping ahead would starve long prompts.
* **Chunked prefill**: one prompt chunk (``prefill_chunk`` tokens) is
  processed per engine step, so a long prompt never stalls the decode
  batch for more than one chunk's latency.
* **Preemption** frees a victim's blocks (eviction) and requeues it for
  *recompute* — on resume the prompt + generated-so-far is re-prefilled,
  which under greedy decoding continues the identical token stream.
  Victim order is weakest SLO class first, then youngest admission, and
  never the requester — so the oldest admitted request always progresses
  and the eviction loop terminates.
* **Tiering** (engine-installed, optional): reclamation is a ladder, least
  destructive rung first — (1) release prefix-cache pins, (2) *spill* the
  victim's KV to host/NVMe before its blocks are reclaimed (recompute
  becomes restore), (3) destructive evict when the spill budget refuses.
  A spilled request's restage is prefetched while it waits and it is
  admitted only once its bytes are resident — unless the engine is
  otherwise idle, when blocking on the restage beats doing nothing.
  ``ArenaExhausted`` still means the requester alone cannot hold its
  window in the *device* arena (host/NVMe cannot substitute for decode
  residency); with tiering on, every other sequence has been spilled —
  not destroyed — first, and the error reports tier occupancy.
"""

import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.serving.kv_cache import ArenaExhausted, PagedKVAllocator

# SLO classes, strongest first; lower number = higher priority.
SLO_PRIORITY = {"realtime": 0, "standard": 1, "batch": 2}

WAITING = "waiting"
PREFILL = "prefill"
DECODE = "decode"
FINISHED = "finished"
EXPIRED = "expired"      # deadline passed; cancelled at a step boundary


class QueueFull(Exception):
    """submit() past ``max_queue`` — shed load at the front door."""


class ShedError(Exception):
    """429-style rejection from the adaptive admission ladder: the engine
    is shedding this request's SLO class until pressure clears.  Distinct
    from :class:`QueueFull` (the static bound) so callers can retry-later
    vs. downshift-class deliberately."""

    def __init__(self, message, slo=None, level=None):
        super().__init__(message)
        self.slo = slo
        self.level = level


class DeadlineExceeded(TimeoutError):
    """The request's per-class deadline passed before it finished; it was
    cancelled at a step boundary and its blocks freed."""


# Adaptive admission ladder rungs, mildest first.  ``brownout`` degrades
# (cap max_new_tokens, pause prefix-cache inserts); the shed rungs reject
# outright, weakest SLO class first — realtime is never ladder-shed.
SHED_LEVELS = ("ok", "brownout", "shed_batch", "shed_standard")


class AdmissionController:
    """Pure-host shed ladder over two pressure signals: the TTFT burn
    state (the PR 13 ``SLOMonitor`` state machine for the
    ``serve_ttft_ms`` rule) and the oldest-waiting queue age vs. the
    configured watermark.  Escalation is immediate; de-escalation steps
    one rung down only after ``shed_recovery_steps`` consecutive calm
    evaluations — hysteresis, so the ladder doesn't flap at the boundary.
    """

    def __init__(self, cfg):
        self.cfg = cfg
        # config is static for the controller's lifetime — coerce once so
        # the per-step evaluate()/cap path stays free of conversion calls
        self._watermark_s = float(cfg.queue_age_watermark_ms or 0.0) / 1e3
        self._recovery_steps = max(int(cfg.shed_recovery_steps), 1)
        self._brownout_cap = int(cfg.brownout_max_new_tokens or 0)
        self.level = 0                  # index into SHED_LEVELS
        self._calm = 0
        self.shed_counts: Dict[str, int] = {}

    @property
    def level_name(self) -> str:
        return SHED_LEVELS[self.level]

    @property
    def brownout(self) -> bool:
        return self.level >= 1

    def evaluate(self, queue_age_s: float, ttft_state: str = "ok") -> int:
        """Advance the ladder from the current signals; returns the new
        level.  ``ttft_state`` is an SLOMonitor rule state
        (``ok``/``burn_slow``/``burn_fast``)."""
        wm = self._watermark_s
        target = 0
        if ttft_state == "burn_slow" or (wm > 0.0 and queue_age_s > wm):
            target = 1
        if ttft_state == "burn_fast" or (wm > 0.0 and queue_age_s > 2 * wm):
            target = 2
        if wm > 0.0 and queue_age_s > 4 * wm:
            target = 3
        if target >= self.level:
            # pressure at (or above) the current rung is not calm — the
            # de-escalation counter restarts
            self.level = target
            self._calm = 0
        else:
            self._calm += 1
            if self._calm >= self._recovery_steps:
                self.level -= 1
                self._calm = 0
        return self.level

    def admit_ok(self, slo: str) -> bool:
        """Whether a request of ``slo`` passes the current rung.  Level 2
        sheds ``batch`` (priority 2); level 3 sheds ``standard`` too;
        ``realtime`` only ever hits the static ``max_queue`` bound."""
        if self.level < 2:
            return True
        prio = SLO_PRIORITY.get(slo, SLO_PRIORITY["standard"])
        floor = 2 if self.level == 2 else 1
        if prio >= floor:
            self.shed_counts[slo] = self.shed_counts.get(slo, 0) + 1
            return False
        return True

    def cap_new_tokens(self, max_new_tokens: int) -> int:
        """Brownout rung: cap the token budget of admitted requests."""
        cap = self._brownout_cap
        if self.brownout and cap > 0:
            return min(max_new_tokens, cap)
        return max_new_tokens


@dataclass
class Request:
    rid: int
    prompt: List[int]
    max_new_tokens: int
    slo: str = "standard"
    arrival: float = 0.0               # host clock, supplied by the engine
    state: str = WAITING
    generated: List[int] = field(default_factory=list)
    prefilled: int = 0                 # context tokens with KV in the arena
    prefill_len: int = 0               # prefill target, set at admission
    slot: int = -1                     # decode-batch slot while active
    submit_seq: int = -1               # FIFO key (stable across preemption)
    admit_seq: int = -1                # youngest-victim key, per admission
    preemptions: int = 0
    spilled: bool = False              # KV sits in the tiered store
    spilled_tokens: int = 0            # context tokens the spill covers
    spills: int = 0
    restages: int = 0
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    deadline_at: Optional[float] = None   # host clock; None = no deadline

    @property
    def priority(self) -> int:
        return SLO_PRIORITY.get(self.slo, SLO_PRIORITY["standard"])

    @property
    def context(self) -> List[int]:
        """Tokens whose KV must exist before the next decode step."""
        return self.prompt + self.generated

    @property
    def needs_prefill(self) -> bool:
        return self.state == PREFILL and self.prefilled < self.prefill_len

    def done(self, eos_token_id: Optional[int]) -> bool:
        if len(self.generated) >= self.max_new_tokens:
            return True
        return (eos_token_id is not None and self.generated
                and self.generated[-1] == eos_token_id)


class ServingScheduler:
    def __init__(self, cfg, allocator: PagedKVAllocator, num_slots: int):
        self.cfg = cfg
        self.alloc = allocator
        self.num_slots = int(num_slots)
        self.waiting: deque = deque()
        self.active: Dict[int, Request] = {}      # slot -> request
        self._free_slots: List[int] = list(range(self.num_slots - 1, -1, -1))
        self._submit_counter = itertools.count()
        self._admit_counter = itertools.count()
        self.preemption_count = 0
        self.finished_count = 0
        self.expired_count = 0
        self.spill_count = 0
        self.restage_count = 0
        # engine hook: called with the victim after each eviction (telemetry)
        self.on_preempt = None
        # engine-installed tiering adapter (duck-typed: spill(req)->tier|None,
        # begin_restage/restage_ready/restage(req), discard(req),
        # describe_tiers()); None = destructive evict+recompute only
        self.tiering = None
        # engine-installed PrefixCache + hit callback(req, blocks)
        self.prefix_cache = None
        self.on_prefix_hit = None

    # ---- intake ----------------------------------------------------------- #
    def submit(self, req: Request) -> Request:
        if len(self.waiting) >= self.cfg.max_queue:
            raise QueueFull(f"waiting queue at max_queue={self.cfg.max_queue}")
        req.submit_seq = next(self._submit_counter)
        req.state = WAITING
        self.waiting.append(req)
        return req

    def _pop_best_waiting(self) -> Optional[Request]:
        """Best admittable waiting request.  A spilled request whose
        restage has not landed is *skipped* (its prefetch is kicked here),
        hiding the NVMe read behind decode of whoever comes next — the one
        deliberate departure from strict head-of-line order.  When nothing
        else is active the best request is taken regardless: blocking on
        its restage beats idling the engine."""
        if not self.waiting:
            return None
        order = sorted(self.waiting, key=lambda r: (r.priority, r.submit_seq))
        best = None
        if self.tiering is None:
            best = order[0]
        else:
            for req in order:
                if req.spilled and not self.tiering.restage_ready(req):
                    self.tiering.begin_restage(req)
                    continue
                best = req
                break
            if best is None and not self.active:
                best = order[0]
        if best is None:
            return None
        self.waiting.remove(best)
        return best

    # ---- admission -------------------------------------------------------- #
    def admit(self) -> List[Request]:
        """Fill free decode slots from the waiting queue.  Returns the
        newly admitted requests (their prefill starts next step)."""
        admitted = []
        while self._free_slots:
            req = self._pop_best_waiting()
            if req is None:
                break
            target = len(req.context)
            prefix_blocks: List[int] = []
            if (self.prefix_cache is not None and not req.spilled
                    and not req.generated
                    and not self.alloc.owned_blocks(req.rid)):
                prefix_blocks = self.prefix_cache.lookup(req.prompt)
                if prefix_blocks:
                    self.alloc.adopt(req.rid, prefix_blocks)
            fits = True
            while not self.alloc.allocate(req.rid, target):
                if self._reclaim_prefix(req, target):
                    continue
                victim = self._admission_victim(req)
                if victim is None:
                    fits = False
                    break
                self.preempt(victim)
            if not fits:
                # Arena full and nothing evictable below this class:
                # head-of-line blocks until decode frees capacity.  Drop
                # adopted prefix refs — the cache keeps its own pins, so
                # the re-attach on the next attempt is just as free.
                if prefix_blocks:
                    self.alloc.free(req.rid)
                self.waiting.appendleft(req)
                return admitted
            req.slot = self._free_slots.pop()
            req.admit_seq = next(self._admit_counter)
            req.prefill_len = target
            if req.spilled:
                self._resume_from_spill(req)
            elif prefix_blocks:
                req.prefilled = len(prefix_blocks) * self.alloc.block_size
                if self.on_prefix_hit is not None:
                    self.on_prefix_hit(req, prefix_blocks)
            else:
                req.prefilled = 0
            req.state = PREFILL
            self.active[req.slot] = req
            admitted.append(req)
        return admitted

    def _reclaim_prefix(self, req: Request, n_tokens: int) -> bool:
        """First rung of the reclamation ladder: release LRU prefix-cache
        pins to cover the shortfall.  Blocks the requester itself adopted
        are not freed by this (it holds its own reference)."""
        if self.prefix_cache is None:
            return False
        need = (self.alloc.blocks_for_tokens(n_tokens)
                - len(self.alloc.owned_blocks(req.rid))
                - self.alloc.free_blocks)
        return need > 0 and self.prefix_cache.release(need) > 0

    def _resume_from_spill(self, req: Request) -> None:
        """Restore a spilled request's KV into its fresh allocation; a
        failed restage (unreadable chunk) falls back to full recompute —
        the pre-tiering path, still token-identical."""
        ok = self.tiering is not None and self.tiering.restage(req)
        if ok:
            req.prefilled = req.spilled_tokens
            req.restages += 1
            self.restage_count += 1
        else:
            req.prefilled = 0
        req.spilled = False
        req.spilled_tokens = 0

    # ---- preemption ------------------------------------------------------- #
    def _victim_order(self, candidates: List[Request]) -> List[Request]:
        # weakest SLO class first, then youngest admission
        return sorted(candidates, key=lambda r: (-r.priority, -r.admit_seq))

    def _admission_victim(self, incoming: Request) -> Optional[Request]:
        if not self.cfg.slo_preemption:
            return None
        weaker = [r for r in self.active.values()
                  if r.priority > incoming.priority]
        order = self._victim_order(weaker)
        return order[0] if order else None

    def _growth_victim(self, requester: Request) -> Optional[Request]:
        others = [r for r in self.active.values() if r is not requester]
        order = self._victim_order(others)
        return order[0] if order else None

    def preempt(self, victim: Request) -> None:
        """Reclaim ``victim``'s blocks and requeue it.  With tiering, the
        spill rung runs first — the victim's written KV is captured to
        host/NVMe so re-admission restores instead of recomputes; a
        refused spill (budget, or nothing written yet) degrades to the
        destructive pre-tiering evict.  ``prefilled`` resets to 0 either
        way: until the restage actually lands, the arena holds nothing
        for this request."""
        assert victim.slot in self.active and self.active[victim.slot] is victim
        del self.active[victim.slot]
        self._free_slots.append(victim.slot)
        tier = None
        if self.tiering is not None and victim.prefilled > 0:
            tier = self.tiering.spill(victim)
        victim.spilled = tier is not None
        victim.spilled_tokens = victim.prefilled if victim.spilled else 0
        if victim.spilled:
            victim.spills += 1
            self.spill_count += 1
        self.alloc.evict(victim.rid)
        victim.slot = -1
        victim.prefilled = 0
        victim.state = WAITING
        victim.preemptions += 1
        self.preemption_count += 1
        self.waiting.appendleft(victim)   # submit_seq keeps its FIFO place
        if self.on_preempt is not None:
            self.on_preempt(victim)

    def ensure_capacity(self, req: Request, n_tokens: int) -> None:
        """Guarantee ``req`` owns blocks for ``n_tokens`` context tokens,
        walking the reclamation ladder under arena pressure.  The victim
        order excludes the requester, so the loop strictly shrinks the
        active set and terminates; if the requester alone exceeds the
        arena we raise — host/NVMe tiers cannot substitute for device
        residency of the decode window, so this holds even when every
        other sequence has been spilled rather than destroyed."""
        while not self.alloc.allocate(req.rid, n_tokens):
            if self._reclaim_prefix(req, n_tokens):
                continue
            victim = self._growth_victim(req)
            if victim is None:
                tiers = ("" if self.tiering is None else
                         f"; tiers: {self.tiering.describe_tiers()}")
                raise ArenaExhausted(
                    f"request {req.rid} needs "
                    f"{self.alloc.blocks_for_tokens(n_tokens)} blocks; arena "
                    f"has {self.alloc.num_blocks - 1} usable{tiers}")
            self.preempt(victim)

    # ---- per-step work selection ------------------------------------------ #
    def next_prefill(self) -> Optional[Tuple[Request, int, int]]:
        """One (request, start, n_tokens) prompt chunk for this step, or
        None.  Strongest class / oldest admission goes first."""
        pending = [r for r in self.active.values() if r.needs_prefill]
        if not pending:
            return None
        req = min(pending, key=lambda r: (r.priority, r.admit_seq))
        start = req.prefilled
        n = min(self.cfg.prefill_chunk, req.prefill_len - start)
        return req, start, n

    def decode_batch(self) -> List[Request]:
        return [r for r in self.active.values() if r.state == DECODE]

    # ---- completion ------------------------------------------------------- #
    def finish(self, req: Request) -> None:
        assert req.slot in self.active and self.active[req.slot] is req
        del self.active[req.slot]
        self._free_slots.append(req.slot)
        self.alloc.free(req.rid)
        req.slot = -1
        req.state = FINISHED
        self.finished_count += 1
        if self.tiering is not None:
            # defensively drop any staged copy (e.g. a restage that fell
            # back to recompute): finished bytes must never be readable
            # under a later epoch of a reused block id
            self.tiering.discard(req)

    # ---- deadlines -------------------------------------------------------- #
    def expired(self, now: float) -> List[Request]:
        """Every request (waiting or active) whose deadline has passed.
        Pure scan — cancellation is a separate step so the engine can book
        the wasted work before the state is torn down."""
        out = [r for r in self.waiting
               if r.deadline_at is not None and now >= r.deadline_at]
        out.extend(r for r in self.active.values()
                   if r.deadline_at is not None and now >= r.deadline_at)
        return out

    def cancel(self, req: Request) -> None:
        """Cancel an expired request at the step boundary: free its slot
        and arena blocks, drop any staged tier copy, mark it EXPIRED.
        ``free``/``discard`` are idempotent, so a request that never owned
        blocks (still waiting) cancels cleanly too."""
        if req.slot >= 0 and self.active.get(req.slot) is req:
            del self.active[req.slot]
            self._free_slots.append(req.slot)
        elif req in self.waiting:
            self.waiting.remove(req)
        self.alloc.free(req.rid)
        if self.tiering is not None:
            self.tiering.discard(req)
        req.slot = -1
        req.spilled = False
        req.spilled_tokens = 0
        req.state = EXPIRED
        self.expired_count += 1

    def oldest_wait_s(self, now: float) -> float:
        """Age of the oldest waiting request — the queue-age pressure
        signal for the admission ladder."""
        if not self.waiting:
            return 0.0
        return max(0.0, now - min(r.arrival for r in self.waiting))

    # ---- wedge recovery --------------------------------------------------- #
    def requeue_for_recovery(self, allocator: PagedKVAllocator
                             ) -> List[Request]:
        """Adopt a freshly rebuilt allocator (the arena was reinitialized
        after a wedged step) and return every in-flight request to the
        waiting queue with ``prefilled=0`` — the preemption recompute
        contract, so greedy decoding resumes token-identical.  Spill
        records of *waiting* requests survive (host/NVMe bytes are
        untouched by an arena rebuild); active requests were resident-only
        and simply recompute.  Returns the requeued requests."""
        self.alloc = allocator
        requeued = sorted(self.active.values(), key=lambda r: r.submit_seq)
        self.active.clear()
        self._free_slots = list(range(self.num_slots - 1, -1, -1))
        for req in reversed(requeued):
            req.slot = -1
            req.prefilled = 0
            req.spilled = False
            req.spilled_tokens = 0
            req.state = WAITING
            self.waiting.appendleft(req)   # submit_seq keeps its FIFO place
        return requeued

    # ---- introspection ---------------------------------------------------- #
    @property
    def has_work(self) -> bool:
        return bool(self.waiting or self.active)

    def stats(self) -> Dict[str, int]:
        return {
            "queue_depth": len(self.waiting),
            "active": len(self.active),
            "free_slots": len(self._free_slots),
            "blocks_in_use": self.alloc.blocks_in_use,
            "blocks_free": self.alloc.free_blocks,
            "preemptions": self.preemption_count,
            "finished": self.finished_count,
            "expired": self.expired_count,
            "spills": self.spill_count,
            "restages": self.restage_count,
        }
