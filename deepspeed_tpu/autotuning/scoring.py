"""Trial scoring: rank candidate configs by the goodput ledger.

The single scoring input is the per-trial ``EFFICIENCY.json`` artifact
(``telemetry/ledger.py:write_efficiency_json`` — conservation-checked
category attribution + ``goodput_frac`` + ``mfu``).  Ranking is
``goodput_frac`` first, ``mfu`` second, mean step time as the
tie-break — so a config that "wins" raw step time by skipping recovery
work, stalling on offload, or burning steps on rollback replay does NOT
look fast: those seconds land in non-productive categories and depress
exactly the fraction being ranked.

A ledger that fails its conservation check is mis-instrumented and is
scored as degraded (``conservation_ok=False``) — the closed loop never
crowns it.

Zero-sync contract: everything here is host-side JSON arithmetic over an
artifact already on disk — nothing in this module may touch a device
value, force a transfer, or import jax (checked by the dslint zero-sync
pass; the module is also loaded standalone by the no-jax report CLI).
"""

import json
import math
from dataclasses import asdict, dataclass
from typing import Optional, Tuple

#: EFFICIENCY.json schema this scorer understands (ledger.SCHEMA_VERSION)
LEDGER_SCHEMA = 1


@dataclass
class TrialScore:
    """The scalarizable view of one trial's ledger."""
    goodput_frac: float
    mfu: Optional[float]
    step_time_s: Optional[float]     # wall / steps — the tie-break only
    wall_s: float
    steps: int
    productive_steps: int
    conservation_ok: bool
    mode: str = "train"

    def as_record(self):
        return asdict(self)

    def rank_key(self) -> Tuple[float, float, float]:
        """Sort key, ascending = better: goodput desc, mfu desc, step
        time asc (unknown step time ranks last among equals)."""
        step = self.step_time_s if self.step_time_s is not None else math.inf
        return (-self.goodput_frac, -(self.mfu or 0.0), step)


def score_from_ledger(led: dict) -> Tuple[Optional[TrialScore], Optional[str]]:
    """A folded/snapshotted ledger dict -> (score, error)."""
    if not isinstance(led, dict) or "categories" not in led:
        return None, "not a ledger document (no categories)"
    try:
        # dslint: ok(zero-sync) — JSON scalars off disk, never traced
        wall = float(led.get("wall_s", 0.0))
        steps = int(led.get("steps", 0))  # dslint: ok(zero-sync) — JSON scalar
        gf = led.get("goodput_frac")
        if gf is None:
            return None, "ledger carries no goodput_frac"
        cons = led.get("conservation") or {}
        return TrialScore(
            goodput_frac=float(gf),  # dslint: ok(zero-sync) — JSON scalar
            # dslint: ok(zero-sync) — JSON scalar off disk, never traced
            mfu=(float(led["mfu"]) if led.get("mfu") is not None else None),
            step_time_s=(wall / steps) if steps > 0 else None,
            wall_s=wall,
            steps=steps,
            # dslint: ok(zero-sync) — JSON scalar off disk, never traced
            productive_steps=int(led.get("productive_steps", 0)),
            # dslint: ok(zero-sync) — JSON verdict flag, never traced
            conservation_ok=bool(cons.get("ok", False)),
            mode=str(led.get("mode", "train"))), None
    except (TypeError, ValueError) as e:
        return None, f"malformed ledger: {e}"


def score_from_efficiency(path: str) -> Tuple[Optional[TrialScore],
                                              Optional[str]]:
    """Read one trial's ``EFFICIENCY.json`` -> (score, error).  Accepts
    the artifact envelope (``{"ledger": {...}}``) or a bare ledger."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return None, f"unreadable EFFICIENCY.json {path}: {e}"
    led = doc.get("ledger") if isinstance(doc, dict) and "ledger" in doc \
        else doc
    return score_from_ledger(led)


def better(a: Optional[TrialScore], b: Optional[TrialScore]) -> bool:
    """Is ``a`` a strictly better trial than ``b``?  ``None`` and
    non-conserving scores never beat anything; anything valid beats
    ``None``."""
    if a is None or not a.conservation_ok:
        return False
    if b is None or not b.conservation_ok:
        return True
    return a.rank_key() < b.rank_key()
