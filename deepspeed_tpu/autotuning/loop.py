"""The closed loop: enumerate → prune analytically → trial → score → emit.

:class:`ClosedLoopAutotuner` drives one tuning run end to end:

1. **Enumerate** the typed search space (``space.py``) into candidate
   patches over the modern knobs.
2. **Prune analytically** with the unified memory model
   (``runtime/memory_model.py``) — the SAME arithmetic the offload
   planner's budget gate enforces at engine init, so a config pruned
   here is one the engine would have refused (or OOMed) anyway.  Pruned
   candidates are recorded with their reason and are provably never
   launched (no trial dir, no subprocess).
3. **Trial** every surviving candidate through the
   :class:`~deepspeed_tpu.autotuning.scheduler.TrialScheduler` — short
   profiled subprocess runs with a hang watchdog; wedged or crashed
   trials score degraded and the search moves on.
4. **Score** each trial from its ``EFFICIENCY.json`` goodput ledger
   (``scoring.py``): goodput_frac first, mfu second, step time as the
   tie-break.  ``tuner_early_stopping`` consecutive non-improving
   trials end the search early; ``tuner_num_trials`` caps it.
5. **Emit** a reviewable ``ds_config_patch.json`` (dotted-path diff
   against the base config + environment fingerprint + provenance) and
   a ``manifest.json`` recording every candidate's fate — the report
   CLI (``tools/autotune_report.py``) and the engine's staleness check
   both consume these artifacts.

Config block (all under ``"autotuning"``)::

    {"search_space": {knob: [values...]},       # space.KNOB_CATALOG names
     "model_info": {"num_params": ..., "n_layer": ..., "block_params": ...},
     "device_memory_bytes": ...,                # analytic pruning budget
     "trial": {"steps": 6, "hidden_dim": 64},   # trial.py workload
     "trial_timeout_s": 600, "tuner_num_trials": 50,
     "tuner_early_stopping": 5, "results_dir": "autotuning_results"}
"""

import copy
import json
import os
from typing import Dict, List, Optional

from deepspeed_tpu.autotuning import scheduler as sched_mod
from deepspeed_tpu.autotuning.fingerprint import (PATCH_BASENAME,
                                                  fingerprint_digest)
from deepspeed_tpu.autotuning.scheduler import (PRUNED, TrialResult,
                                                TrialScheduler)
from deepspeed_tpu.autotuning.scoring import better
from deepspeed_tpu.autotuning.space import (SearchSpace, apply_patch,
                                            patch_diff)
from deepspeed_tpu.runtime import memory_model
from deepspeed_tpu.utils.logging import log_dist

MANIFEST_BASENAME = "manifest.json"
MANIFEST_SCHEMA = 1


class ClosedLoopAutotuner:
    """Telemetry-scored configuration search over the modern knobs."""

    def __init__(self, base_config: Dict,
                 results_dir: Optional[str] = None,
                 scheduler: Optional[TrialScheduler] = None,
                 trial_env: Optional[Dict[str, str]] = None,
                 world: Optional[int] = None,
                 fingerprint: Optional[Dict] = None):
        self.base_config = copy.deepcopy(base_config)
        at = dict(self.base_config.get("autotuning") or {})
        self.at = at
        self.results_dir = str(results_dir or at.get("results_dir")
                               or "autotuning_results")
        self.space = SearchSpace.from_config(at)
        self.model_info = dict(at.get("model_info") or {})
        self.device_memory_bytes = at.get("device_memory_bytes")
        self.num_trials = int(at.get("tuner_num_trials", 50))
        self.early_stopping = int(at.get("tuner_early_stopping", 5))
        self.world = world
        self._fingerprint = fingerprint
        os.makedirs(self.results_dir, exist_ok=True)
        self.scheduler = scheduler or TrialScheduler(
            os.path.join(self.results_dir, "trials"),
            timeout_s=float(at.get("trial_timeout_s", 600.0)),
            env=dict(trial_env or {}))
        self.pruned: List[TrialResult] = []
        self.trials: List[TrialResult] = []
        self.baseline: Optional[TrialResult] = None
        self.verification: Optional[TrialResult] = None
        self.best: Optional[TrialResult] = None

    # -- analytic pruning -------------------------------------------------- #
    def _candidate_world(self, cand) -> int:
        mesh = cand.patch.get("mesh")
        if isinstance(mesh, dict) and mesh:
            w = 1
            for v in mesh.values():
                w *= int(v)
            return max(w, 1)
        if self.world:
            return max(int(self.world), 1)
        mesh = self.base_config.get("mesh")
        if isinstance(mesh, dict) and mesh:
            w = 1
            for v in mesh.values():
                w *= int(v)
            return max(w, 1)
        return 1

    def prune_reason(self, cand) -> Optional[str]:
        """Why this candidate cannot fit — or ``None`` to run it.

        Uses :func:`memory_model.analytic_step_peaks` (stage 3: gathered
        vs layer-window peak, offload tiers honored) and
        :func:`memory_model.stage_state_bytes` (stages < 3) against the
        HBM budget — the exact model ``offload/policy.plan_residency``
        enforces at trial init, so pruning never disagrees with the
        engine's own refusal gate."""
        p = int(self.model_info.get("num_params") or 0)
        budget = int(cand.knobs.get("hbm_budget_bytes") or 0) \
            or int(self.device_memory_bytes or 0)
        if not p or not budget:
            return None          # nothing to prune on: run the trial
        base_zo = dict(self.base_config.get("zero_optimization") or {})
        stage = int(cand.knobs.get("zero_stage", base_zo.get("stage", 0)))
        world = self._candidate_world(cand)
        if stage < 3:
            need = memory_model.stage_state_bytes(p, stage, world)
            if need > budget:
                return (f"stage {stage} state needs {need} B "
                        f"> budget {budget} B (world={world})")
            return None
        offload_param = cand.knobs.get(
            "offload_param", (base_zo.get("offload_param") or {}).get("device"))
        offload_opt = cand.knobs.get(
            "offload_optimizer",
            (base_zo.get("offload_optimizer") or {}).get("device"))
        peaks = memory_model.analytic_step_peaks(
            p, world,
            block_params=int(self.model_info.get("block_params") or 0),
            n_layer=int(self.model_info.get("n_layer") or 0),
            prefetch_depth=int(cand.knobs.get(
                "prefetch_depth", base_zo.get("prefetch_depth", 2))),
            optimizer_tier=("hbm" if not offload_opt else str(offload_opt)))
        windowed = bool(offload_param) and peaks.has_window
        peak = peaks.window_peak_bytes if windowed else peaks.plain_peak_bytes
        if peak > budget:
            kind = "window" if windowed else "gathered"
            return (f"stage 3 {kind} peak {peak} B > budget {budget} B "
                    f"(world={world})")
        return None

    # -- the loop ---------------------------------------------------------- #
    def tune(self, baseline: bool = False) -> Optional[TrialResult]:
        """Run the closed loop; returns the best scored trial (or None).

        ``baseline=True`` first runs the UNPATCHED base config as trial
        ``baseline`` — it anchors the manifest's improvement claim but
        does not compete for best and does not count against
        ``tuner_num_trials`` / early stopping."""
        candidates = self.space.enumerate()
        log_dist(f"autotuning: closed loop over {len(candidates)} candidates "
                 f"(space: {[k.name for k in self.space.knobs]})", ranks=[0])
        if baseline:
            self.baseline = self.scheduler.run_trial(
                "baseline", copy.deepcopy(self.base_config))
        launched = 0
        since_improve = 0
        for cand in candidates:
            reason = self.prune_reason(cand)
            if reason is not None:
                self.pruned.append(TrialResult(
                    name=cand.cid, status=PRUNED, patch=cand.patch,
                    knobs=cand.knobs, prune_reason=reason))
                log_dist(f"autotuning: {cand.cid} pruned analytically "
                         f"({reason})", ranks=[0])
                continue
            if launched >= self.num_trials:
                log_dist(f"autotuning: tuner_num_trials={self.num_trials} "
                         "reached; stopping", ranks=[0])
                break
            cfg = apply_patch(self.base_config, cand.patch)
            res = self.scheduler.run_trial(cand.cid, cfg,
                                           extra_env=cand.env(),
                                           patch=cand.patch,
                                           knobs=cand.knobs)
            self.trials.append(res)
            launched += 1
            if res.scored and (self.best is None
                               or better(res.score,
                                         self.best.score
                                         if self.best else None)):
                self.best = res
                since_improve = 0
            else:
                since_improve += 1
                if (self.early_stopping
                        and since_improve >= self.early_stopping):
                    log_dist(
                        f"autotuning: {since_improve} consecutive trials "
                        "without improvement "
                        f"(tuner_early_stopping={self.early_stopping}); "
                        "stopping", ranks=[0])
                    break
        self.write_artifacts()
        return self.best

    def verify(self) -> Optional[TrialResult]:
        """Re-run the winning config once as trial ``verify`` — the
        emitted patch's improvement claim is itself measured, not
        assumed.  Re-emits the artifacts with the verification row."""
        if self.best is None:
            return None
        cfg = apply_patch(self.base_config, self.best.patch)
        cand_env = {k[len("env."):]: str(v)
                    for k, v in self.best.patch.items()
                    if k.startswith("env.")}
        self.verification = self.scheduler.run_trial(
            "verify", cfg, extra_env=cand_env, patch=self.best.patch,
            knobs=self.best.knobs)
        self.write_artifacts()
        return self.verification

    # -- artifacts --------------------------------------------------------- #
    def fingerprint(self) -> Dict:
        if self._fingerprint is None:
            from deepspeed_tpu.autotuning.fingerprint import (
                environment_fingerprint)
            mesh = self.base_config.get("mesh")
            dims = {k: v for k, v in self.model_info.items()
                    if isinstance(v, (int, float, str))}
            self._fingerprint = environment_fingerprint(
                mesh_shape=mesh if isinstance(mesh, dict) else None,
                model_dims=dims)
        return self._fingerprint

    def manifest(self) -> Dict:
        fp = self.fingerprint()
        return {
            "schema": MANIFEST_SCHEMA,
            "fingerprint": fp,
            "fingerprint_digest": fingerprint_digest(fp),
            "search_space": {k.name: list(k.values)
                             for k in self.space.knobs},
            "counts": {"candidates": len(self.pruned) + len(self.trials),
                       "pruned": len(self.pruned),
                       "run": len(self.trials),
                       "scored": sum(1 for t in self.trials if t.scored),
                       "degraded": sum(1 for t in self.trials
                                       if t.status == sched_mod.DEGRADED)},
            "pruned": [t.as_record() for t in self.pruned],
            "trials": [t.as_record() for t in self.trials],
            "baseline": self.baseline.as_record() if self.baseline else None,
            "verification": (self.verification.as_record()
                             if self.verification else None),
            "best": self.best.as_record() if self.best else None,
        }

    def patch_document(self) -> Optional[Dict]:
        if self.best is None:
            return None
        fp = self.fingerprint()
        return {
            "schema": MANIFEST_SCHEMA,
            "fingerprint": fp,
            "fingerprint_digest": fingerprint_digest(fp),
            "patch": self.best.patch,
            "diff": patch_diff(self.base_config, self.best.patch),
            "score": self.best.score.as_record() if self.best.score else None,
            "provenance": {
                "trial": self.best.name,
                "trial_dir": self.best.trial_dir,
                "manifest": os.path.join(self.results_dir,
                                         MANIFEST_BASENAME),
            },
        }

    def write_artifacts(self) -> Dict[str, str]:
        """Drop ``manifest.json`` (+ ``ds_config_patch.json`` when a
        winner exists) into the results dir; returns the paths."""
        out = {}
        man_path = os.path.join(self.results_dir, MANIFEST_BASENAME)
        with open(man_path, "w") as f:
            json.dump(self.manifest(), f, indent=2, sort_keys=True)
        out["manifest"] = man_path
        doc = self.patch_document()
        if doc is not None:
            patch_path = os.path.join(self.results_dir, PATCH_BASENAME)
            with open(patch_path, "w") as f:
                json.dump(doc, f, indent=2, sort_keys=True)
            out["patch"] = patch_path
            log_dist(f"autotuning: best patch written to {patch_path} "
                     f"(goodput_frac="
                     f"{self.best.score.goodput_frac:.3f})", ranks=[0])
        return out
