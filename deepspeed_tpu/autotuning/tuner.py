"""Experiment tuners: grid, random, and cost-model-guided search.

Reference: ``deepspeed/autotuning/tuner/{base_tuner,index_based_tuner,
model_based_tuner,cost_model}.py``.  The reference's model-based tuner
fits an XGBoost ranker; xgboost is not in the TPU image, so the cost model
here is a ridge regressor over the same flattened-config features — the
role (rank untried configs, try the promising ones first) is identical.
"""

import random
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.autotuning.utils import dict_to_feature, flatten


class RidgeCostModel:
    """Least-squares surrogate: predicts the metric from config features
    (the ``XGBoostCostModel`` slot, ``tuner/cost_model.py:14``)."""

    def __init__(self, l2: float = 1e-3):
        self.l2 = l2
        self.w: Optional[np.ndarray] = None

    def fit(self, xs: List[List[float]], ys: List[float]):
        x = np.asarray(xs, np.float64)
        y = np.asarray(ys, np.float64)
        y_max = max(float(np.max(np.abs(y))), 1e-9)
        y = y / y_max
        self._y_max = y_max
        x = np.concatenate([x, np.ones((len(x), 1))], axis=1)   # bias
        a = x.T @ x + self.l2 * np.eye(x.shape[1])
        self.w = np.linalg.solve(a, x.T @ y)

    def predict(self, xs: List[List[float]]) -> np.ndarray:
        x = np.asarray(xs, np.float64)
        x = np.concatenate([x, np.ones((len(x), 1))], axis=1)
        return x @ self.w * self._y_max


class BaseTuner:
    """Iterate experiments, track the best (reference ``base_tuner.py:13``).

    ``run_fn(exp) -> Optional[float]`` executes one experiment and returns
    the metric value (higher is better; None/exception = failed run).
    """

    def __init__(self, exps: List[Dict], run_fn: Callable[[Dict], Optional[float]],
                 metric: str = "throughput"):
        self.all_exps = list(exps)
        self.rm_exps = list(exps)
        self.run_fn = run_fn
        self.metric = metric
        self.best_exp: Optional[Dict] = None
        self.best_metric_val: float = float("-inf")
        self.records: List[Tuple[Dict, Optional[float]]] = []

    def has_next(self) -> bool:
        return len(self.rm_exps) > 0

    def next_batch(self, sample_size: int = 1) -> List[Dict]:
        raise NotImplementedError

    def update(self):
        """Hook after each batch of results (model refit etc.)."""

    def tune(self, sample_size: int = 1, n_trials: int = 1000,
             early_stopping: Optional[int] = None) -> Tuple[Optional[Dict], float]:
        trials = 0
        since_best = 0
        while self.has_next() and trials < n_trials:
            batch = self.next_batch(sample_size)
            for exp in batch:
                try:
                    val = self.run_fn(exp)
                except Exception:
                    val = None
                self.records.append((exp, val))
                trials += 1
                if val is not None and val > self.best_metric_val:
                    self.best_metric_val = val
                    self.best_exp = exp
                    since_best = 0
                else:
                    since_best += 1
            self.update()
            if early_stopping and since_best >= early_stopping:
                break
        return self.best_exp, self.best_metric_val


class GridSearchTuner(BaseTuner):
    def next_batch(self, sample_size: int = 1) -> List[Dict]:
        batch = self.rm_exps[:sample_size]
        self.rm_exps = self.rm_exps[sample_size:]
        return batch


class RandomTuner(BaseTuner):
    def __init__(self, exps, run_fn, metric: str = "throughput", seed: int = 0):
        super().__init__(exps, run_fn, metric)
        self._rng = random.Random(seed)

    def next_batch(self, sample_size: int = 1) -> List[Dict]:
        k = min(sample_size, len(self.rm_exps))
        batch = self._rng.sample(self.rm_exps, k)
        for b in batch:
            self.rm_exps.remove(b)
        return batch


class ModelBasedTuner(BaseTuner):
    """Cost-model-guided search (reference ``model_based_tuner.py:19``):
    warm up randomly, then repeatedly propose the untried configs the
    surrogate ranks highest."""

    def __init__(self, exps, run_fn, metric: str = "throughput",
                 warmup: int = 3, seed: int = 0):
        super().__init__(exps, run_fn, metric)
        self.warmup = warmup
        self._rng = random.Random(seed)
        self.keys = sorted({k for e in exps for k in flatten(e)})
        self.model = RidgeCostModel()
        self._trained = False

    def _feat(self, exp: Dict) -> List[float]:
        return dict_to_feature(flatten(exp), self.keys)

    def next_batch(self, sample_size: int = 1) -> List[Dict]:
        evaluated = len(self.records)
        if evaluated < self.warmup or not self._trained:
            k = min(sample_size, len(self.rm_exps))
            batch = self._rng.sample(self.rm_exps, k)
        else:
            preds = self.model.predict([self._feat(e) for e in self.rm_exps])
            order = np.argsort(-preds)[:sample_size]
            batch = [self.rm_exps[i] for i in order]
        for b in batch:
            self.rm_exps.remove(b)
        return batch

    def update(self):
        xs = [self._feat(e) for e, v in self.records if v is not None]
        ys = [v for _, v in self.records if v is not None]
        if len(xs) >= 2:
            self.model.fit(xs, ys)
            self._trained = True
