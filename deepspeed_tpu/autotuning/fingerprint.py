"""Retune fingerprint: is an emitted patch still valid for THIS run?

A tuned config is hardware- and model-specific (ZeRO++ and the Frontier
recipe both show the winning quantization bits / partition placement /
micro-batch flip with the pod and the model).  So the closed loop stamps
every emitted ``ds_config_patch.json`` with a fingerprint of the
environment it was tuned on — pod shape (device count, platform, mesh
axes, process count), model dims (``model_info``), and the jax version —
and :func:`check` compares it at engine init: a patch tuned on a
different environment triggers a retune warning (default) or an outright
:class:`StaleTuningError` refusal (``autotuning.stale_policy: refuse``).
"""

import hashlib
import json
import os
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

FINGERPRINT_SCHEMA = 1

#: emitted-patch artifact filename inside a results dir
PATCH_BASENAME = "ds_config_patch.json"


class StaleTuningError(RuntimeError):
    """The applied autotuner patch was tuned on a different environment
    and ``autotuning.stale_policy`` is ``refuse``."""


def environment_fingerprint(mesh_shape: Optional[Dict[str, int]] = None,
                            model_dims: Optional[Dict[str, Any]] = None,
                            extra: Optional[Dict[str, Any]] = None) -> Dict:
    """Fingerprint of the live environment: pod shape, model dims, jax
    version.  ``model_dims`` is whatever the caller can state about the
    model (``num_params`` at minimum); comparison is per present key, so
    a richer producer never invalidates a leaner consumer."""
    import jax
    devices = jax.devices()
    fp = {
        "schema": FINGERPRINT_SCHEMA,
        "pod": {
            "device_count": int(jax.device_count()),
            "process_count": int(jax.process_count()),
            "platform": devices[0].platform if devices else "unknown",
            "mesh_shape": {str(k): int(v)
                           for k, v in (mesh_shape or {}).items()},
        },
        "model": dict(model_dims or {}),
        "jax_version": jax.__version__,
    }
    if extra:
        fp["extra"] = dict(extra)
    return fp


def fingerprint_digest(fp: Dict) -> str:
    """Stable short digest of a fingerprint document."""
    blob = json.dumps(fp, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def compare(stored: Dict, current: Dict) -> List[str]:
    """Mismatch descriptions between a stored and the current
    fingerprint.  Leaf-wise over the keys BOTH sides carry (an absent
    key is unknowable, not stale); empty list = still valid."""
    out: List[str] = []

    def _walk(a, b, path):
        if isinstance(a, dict) and isinstance(b, dict):
            for k in sorted(set(a) & set(b)):
                _walk(a[k], b[k], f"{path}.{k}" if path else str(k))
            return
        if a != b:
            out.append(f"{path}: tuned on {a!r}, now {b!r}")

    stored = {k: v for k, v in (stored or {}).items() if k != "schema"}
    current = {k: v for k, v in (current or {}).items() if k != "schema"}
    _walk(stored, current, "")
    return out


def resolve_patch_path(autotuning_cfg: Dict) -> Optional[str]:
    """The patch artifact a config points at: ``autotuning.patch``
    directly, else ``autotuning.results_dir``/ds_config_patch.json."""
    cfg = autotuning_cfg or {}
    if cfg.get("patch"):
        return str(cfg["patch"])
    if cfg.get("results_dir"):
        return os.path.join(str(cfg["results_dir"]), PATCH_BASENAME)
    return None


def check(patch_doc_or_path,
          current_fp: Dict,
          policy: str = "warn") -> List[str]:
    """Compare a patch artifact's stored fingerprint against the current
    environment.  Returns the mismatch list; ``policy`` is ``off`` (skip),
    ``warn`` (log each mismatch, default) or ``refuse`` (raise
    :class:`StaleTuningError`).  A missing/unreadable artifact is a
    warning, never a refusal — the run simply has nothing to validate."""
    if policy == "off":
        return []
    if isinstance(patch_doc_or_path, str):
        try:
            with open(patch_doc_or_path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning(
                f"autotune.check: cannot read patch artifact "
                f"{patch_doc_or_path}: {e}")
            return []
    else:
        doc = patch_doc_or_path or {}
    stored = doc.get("fingerprint")
    if not isinstance(stored, dict):
        logger.warning("autotune.check: patch artifact carries no "
                       "fingerprint; cannot validate staleness")
        return []
    mismatches = compare(stored, current_fp)
    if not mismatches:
        return []
    detail = "; ".join(mismatches)
    if policy == "refuse":
        raise StaleTuningError(
            "autotuned config is stale — the environment changed since the "
            f"tune ({detail}); re-run the autotuner or set "
            "autotuning.stale_policy to 'warn'")
    logger.warning(
        f"autotune.check: tuned config may be stale ({detail}); consider "
        "re-running the autotuner")
    return mismatches


def check_engine(autotuning_cfg: Dict,
                 mesh_shape: Dict[str, int],
                 params=None,
                 num_params: Optional[int] = None) -> List[str]:
    """The engine-init hook: when the ds_config applies a tuned patch
    (``autotuning.patch`` / ``autotuning.results_dir``), validate its
    fingerprint against the live mesh + model + jax version."""
    path = resolve_patch_path(autotuning_cfg)
    if path is None:
        return []
    if num_params is None and params is not None:
        import jax
        num_params = int(sum(int(x.size) for x in jax.tree.leaves(params)))
    dims = {}
    if num_params is not None:
        dims["num_params"] = int(num_params)
    current = environment_fingerprint(mesh_shape=mesh_shape, model_dims=dims)
    policy = str((autotuning_cfg or {}).get("stale_policy", "warn"))
    return check(path, current, policy=policy)
