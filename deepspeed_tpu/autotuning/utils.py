"""Autotuning helpers: tuning-space enumeration and feature mapping.

Reference: ``deepspeed/autotuning/tuner/utils.py`` (gen_combinations /
flatten / feature mapping) and ``autotuning/utils.py``.
"""

import itertools
from typing import Any, Dict, List


def flatten(d: Dict, parent_key: str = "", sep: str = "_") -> Dict:
    """Nested config dict → flat {joined_key: value}."""
    out = {}
    for k, v in d.items():
        key = f"{parent_key}{sep}{k}" if parent_key else str(k)
        if isinstance(v, dict):
            out.update(flatten(v, key, sep))
        else:
            out[key] = v
    return out


def gen_combinations(space: Dict) -> List[Dict]:
    """Cartesian product of every list-valued entry in a (nested) tuning
    space; scalar entries pass through."""
    keys, value_lists = [], []
    for k, v in space.items():
        if isinstance(v, dict):
            subs = gen_combinations(v)
            keys.append(k)
            value_lists.append(subs)
        else:
            keys.append(k)
            value_lists.append(v if isinstance(v, list) else [v])
    out = []
    for combo in itertools.product(*value_lists):
        out.append(dict(zip(keys, combo)))
    return out


def dict_to_feature(flat: Dict, keys: List[str]) -> List[float]:
    """Numeric feature vector for the cost model (non-numeric → hash-ish)."""
    feat = []
    for k in keys:
        v = flat.get(k, 0)
        if isinstance(v, bool):
            feat.append(float(v))
        elif isinstance(v, (int, float)):
            feat.append(float(v))
        else:
            feat.append(float(abs(hash(str(v))) % 1000) / 1000.0)
    return feat


def set_nested(d: Dict, dotted_key: str, value: Any, sep: str = "."):
    parts = dotted_key.split(sep)
    cur = d
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = value
