"""Autotuner: find the fastest ZeRO stage + micro-batch + knob combination.

Reference: ``deepspeed/autotuning/autotuner.py:42``.  The orchestration is
the same — estimate memory per ZeRO stage to prune infeasible spaces,
enumerate experiment configs from per-stage tuning spaces, let a tuner
(grid / random / model-based) order the runs, record results, and write
the optimal config — with TPU-first memory arithmetic (bf16 model, fp32
masters+Adam moments, stage-wise division over the data-parallel world)
and experiments executed by the ``ResourceManager`` (one subprocess per
experiment; the engine drops ``metrics.json``).
"""

import copy
import json
import os
from typing import Callable, Dict, List, Optional

from deepspeed_tpu.autotuning.tuner import (GridSearchTuner, ModelBasedTuner,
                                            RandomTuner)
from deepspeed_tpu.autotuning.utils import gen_combinations
from deepspeed_tpu.runtime import memory_model
from deepspeed_tpu.utils.logging import log_dist

DEFAULT_MIN_MBS = 1
DEFAULT_TUNER = "gridsearch"
TUNERS = {"gridsearch": GridSearchTuner, "random": RandomTuner,
          "model_based": ModelBasedTuner}


class Autotuner:

    def __init__(self, config: Dict, run_fn: Optional[Callable] = None,
                 resource_manager=None, model_info: Optional[Dict] = None,
                 device_memory_bytes: Optional[int] = None,
                 dp_world: int = 1, results_dir: str = "autotuning_results"):
        """``run_fn(exp_config) -> Optional[float]`` overrides subprocess
        execution (tests / in-process tuning); otherwise experiments go
        through ``resource_manager.run_experiment``."""
        self.user_config = copy.deepcopy(config)
        at = dict(config.get("autotuning", {}))
        self.metric = at.get("metric", "throughput")
        self.tuner_type = at.get("tuner_type", DEFAULT_TUNER)
        self.tuner_early_stopping = at.get("tuner_early_stopping", 5)
        self.tuner_num_trials = at.get("tuner_num_trials", 50)
        self.max_train_batch_size = at.get("max_train_batch_size")
        self.mbs_list = at.get("micro_batch_sizes")           # user override
        self.zero_stages = at.get("zero_stages")              # user override
        self.overwrite = at.get("overwrite", True)
        self.results_dir = results_dir
        self.rm = resource_manager
        self._run_fn = run_fn
        self.model_info = model_info or at.get("model_info") or {}
        self.device_memory_bytes = device_memory_bytes
        self.dp_world = max(int(dp_world), 1)
        self.records: Dict[str, List] = {}
        self.best_exp: Optional[Dict] = None
        self.best_metric_val = float("-inf")
        os.makedirs(results_dir, exist_ok=True)

    # -- memory model (reference get_instantiation_memory_required_per_gpu) #
    def get_instantiation_memory_required_per_device(self, stage: int) -> int:
        """Bytes of parameter+optimizer state per device at a ZeRO stage:
        bf16 params (2P) + fp32 masters (4P) + Adam m/v (8P), with the
        stage's sharding: stage>=1 shards optimizer+masters, stage>=3 also
        params.  Gradients (4P fp32 accumulators, sharded at stage>=2) are
        included; activations are workload-dependent and probed, not
        estimated.  The arithmetic lives in ``runtime/memory_model.py`` —
        the SAME model behind ``offload/policy.py:plan_residency``, so the
        bytes pruned on are the bytes the engine's budget gate enforces."""
        p = int(self.model_info.get("num_params", 0))
        return memory_model.stage_state_bytes(p, stage, self.dp_world)

    def _feasible_stages(self) -> List[int]:
        stages = self.zero_stages or [0, 1, 2, 3]
        if not self.device_memory_bytes or not self.model_info.get("num_params"):
            return list(stages)
        out = []
        for s in stages:
            need = self.get_instantiation_memory_required_per_device(s)
            if need < self.device_memory_bytes:
                out.append(s)
            else:
                log_dist(f"autotuning: ZeRO stage {s} pruned "
                         f"(needs {need >> 20} MiB of {self.device_memory_bytes >> 20})",
                         ranks=[0])
        return out or [max(stages)]

    # -- tuning spaces --------------------------------------------------- #
    def _micro_batch_candidates(self) -> List[int]:
        if self.mbs_list:
            return list(self.mbs_list)
        out, m = [], DEFAULT_MIN_MBS
        limit = self.max_train_batch_size or 64
        while m <= limit:
            out.append(m)
            m *= 2
        return out

    def tuning_space(self, stage: int) -> Dict:
        space = {
            "train_micro_batch_size_per_gpu": self._micro_batch_candidates(),
            "zero_optimization": {"stage": stage},
        }
        if stage >= 3:
            # offload on/off is the big stage-3 lever on TPU (pinned host)
            space["zero_optimization"]["offload_param"] = [
                None, {"device": "cpu"}]
        return space

    def _experiments(self, stage: int) -> List[Dict]:
        exps = []
        for combo in gen_combinations(self.tuning_space(stage)):
            cfg = copy.deepcopy(self.user_config)
            cfg.pop("autotuning", None)
            mbs = combo.pop("train_micro_batch_size_per_gpu")
            cfg["train_micro_batch_size_per_gpu"] = mbs
            gas = cfg.get("gradient_accumulation_steps", 1)
            cfg["train_batch_size"] = mbs * gas * self.dp_world
            zo = dict(cfg.get("zero_optimization", {}))
            for k, v in combo.get("zero_optimization", {}).items():
                if v is not None:
                    zo[k] = v
                else:
                    zo.pop(k, None)
            cfg["zero_optimization"] = zo
            if (self.max_train_batch_size
                    and cfg["train_batch_size"] > self.max_train_batch_size):
                continue
            exps.append(cfg)
        return exps

    # -- execution ------------------------------------------------------- #
    def _run_exp(self, exp_cfg: Dict) -> Optional[float]:
        if self._run_fn is not None:
            return self._run_fn(exp_cfg)
        assert self.rm is not None, "need run_fn or a ResourceManager"
        stage = exp_cfg.get("zero_optimization", {}).get("stage", 0)
        mbs = exp_cfg.get("train_micro_batch_size_per_gpu", 0)
        name = f"z{stage}_mbs{mbs}_{len(self.rm.finished_experiments)}"
        return self.rm.run_experiment(name, exp_cfg)

    def tune(self) -> Optional[Dict]:
        """Search every feasible stage's space; returns the best config."""
        for stage in self._feasible_stages():
            exps = self._experiments(stage)
            if not exps:
                continue
            tuner_cls = TUNERS.get(self.tuner_type, GridSearchTuner)
            tuner = tuner_cls(exps, self._run_exp, metric=self.metric)
            best, val = tuner.tune(sample_size=1,
                                   n_trials=self.tuner_num_trials,
                                   early_stopping=self.tuner_early_stopping)
            self.records[f"z{stage}"] = tuner.records
            log_dist(f"autotuning: stage {stage} best {self.metric}={val}",
                     ranks=[0])
            if best is not None and val > self.best_metric_val:
                self.best_metric_val = val
                self.best_exp = best
        if self.best_exp is not None:
            self.write_optimal_config()
        return self.best_exp

    def write_optimal_config(self):
        path = os.path.join(self.results_dir, "ds_config_optimal.json")
        with open(path, "w") as f:
            json.dump(self.best_exp, f, indent=2)
        summary = os.path.join(self.results_dir, "summary.txt")
        with open(summary, "w") as f:
            f.write(f"best {self.metric}: {self.best_metric_val}\n"
                    f"optimal config: {path}\n")
        log_dist(f"autotuning: optimal config written to {path}", ranks=[0])
