"""Typed search space over the modern config knobs.

The seed-era tuner enumerated two knobs (ZeRO stage, micro-batch).  The
closed loop searches the knobs that actually move goodput on the
PR 1-18 stack — each declared as a :class:`Knob` with its dotted
``ds_config`` path, candidate values, and an optional coherence guard so
the cartesian product never emits configs the engine would reject for
structural (not memory) reasons.  Three path namespaces:

* ``a.b.c``  — nested ``ds_config`` key, applied with ``set_nested``;
* ``env.X``  — an environment variable for the trial subprocess (the
  fused-kernel gates ``DST_PALLAS_*`` are env-scoped, not config keys);
* ``mesh``   — the whole mesh-axes dict (mesh shape is one knob whose
  value is the axis mapping, not six independent knobs that would
  mostly multiply to the wrong device count).

A :class:`Candidate` is the normalized patch (dependent knobs whose
guard is off are dropped, then duplicates collapse), which is also the
provenance unit: the manifest records every candidate's patch verbatim,
and the winning patch is what ``ds_config_patch.json`` carries.
"""

import copy
import itertools
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.autotuning.utils import set_nested

#: trial-subprocess env namespace inside a patch
ENV_PREFIX = "env."


@dataclass(frozen=True)
class Knob:
    """One tunable axis: a name, the config path it patches, and the
    candidate values.  ``only_if`` guards coherence: a dict of
    ``{other_knob_name: allowed values}`` — when violated the knob is
    dropped from the candidate (not the candidate from the space)."""
    name: str
    path: str
    values: Tuple[Any, ...]
    kind: str = "runtime"            # mesh|zero|batch|offload|kernel|serving
    only_if: Optional[Dict[str, Tuple[Any, ...]]] = None

    def guard_ok(self, chosen: Dict[str, Any]) -> bool:
        if not self.only_if:
            return True
        for other, allowed in self.only_if.items():
            if other in chosen and chosen[other] not in allowed:
                return False
        return True


#: the modern knob catalog — every axis the PR 1-18 subsystems expose.
#: ``SearchSpace.from_config`` picks the subset a run actually varies;
#: enumerating the full catalog at once is never the intent.
KNOB_CATALOG: Tuple[Knob, ...] = (
    # mesh shape: the whole axes dict is one value
    Knob("mesh_shape", "mesh", (), kind="mesh"),
    # ZeRO stage + ZeRO++ compression
    Knob("zero_stage", "zero_optimization.stage", (1, 2, 3), kind="zero"),
    Knob("qwz", "zero_optimization.zero_quantized_weights", (False, True),
         kind="zero", only_if={"zero_stage": (3,)}),
    Knob("qwz_bits", "zero_optimization.zero_quantized_weights_bits", (8, 4),
         kind="zero", only_if={"qwz": (True,)}),
    Knob("qgz", "zero_optimization.zero_quantized_gradients", (False, True),
         kind="zero", only_if={"zero_stage": (3,)}),
    Knob("qgz_bits", "zero_optimization.zero_quantized_gradients_bits", (8, 4),
         kind="zero", only_if={"qgz": (True,)}),
    Knob("hpz_partition_size", "zero_optimization.zero_hpz_partition_size",
         (1, 2, 4), kind="zero", only_if={"zero_stage": (3,)}),
    Knob("quant_block_size", "zero_optimization.zero_quantization_block_size",
         (64, 256, 1024), kind="zero"),
    # batch shape
    Knob("micro_batch", "train_micro_batch_size_per_gpu",
         (1, 2, 4, 8, 16), kind="batch"),
    Knob("gas", "gradient_accumulation_steps", (1, 2, 4), kind="batch"),
    # beyond-HBM residency
    Knob("prefetch_depth", "zero_optimization.prefetch_depth", (1, 2, 4),
         kind="offload"),
    Knob("hbm_budget_bytes", "zero_optimization.hbm_budget_bytes", (0,),
         kind="offload"),
    Knob("offload_param", "zero_optimization.offload_param.device",
         (None, "cpu", "nvme"), kind="offload", only_if={"zero_stage": (3,)}),
    Knob("offload_optimizer", "zero_optimization.offload_optimizer.device",
         (None, "cpu", "nvme"), kind="offload", only_if={"zero_stage": (3,)}),
    # fused-kernel gates (env-scoped tri-state: unset = TPU-only default)
    Knob("pallas_ce", "env.DST_PALLAS_CE", ("0", "1"), kind="kernel"),
    Knob("pallas_fused_opt", "env.DST_PALLAS_FUSED_OPT", ("0", "1"),
         kind="kernel"),
    # serving arena / chunked prefill
    Knob("serve_num_blocks", "serving.num_blocks", (128, 256, 512),
         kind="serving"),
    Knob("serve_prefill_chunk", "serving.prefill_chunk", (32, 64, 128),
         kind="serving"),
)

_CATALOG_BY_NAME = {k.name: k for k in KNOB_CATALOG}


class UnknownKnobError(ValueError):
    """A search_space entry names no catalog knob — refuse instead of
    silently tuning nothing."""


@dataclass
class Candidate:
    """One point of the search space: the normalized config patch."""
    cid: str
    patch: Dict[str, Any]            # dotted path -> value (incl. env.*)
    knobs: Dict[str, Any] = field(default_factory=dict)   # name -> value

    def key(self) -> str:
        return json.dumps(self.patch, sort_keys=True, default=str)

    def env(self) -> Dict[str, str]:
        """The env-var slice of the patch (trial subprocess scope)."""
        return {p[len(ENV_PREFIX):]: str(v)
                for p, v in self.patch.items()
                if p.startswith(ENV_PREFIX) and v is not None}

    def config_patch(self) -> Dict[str, Any]:
        """The ds_config slice of the patch (dotted paths)."""
        return {p: v for p, v in self.patch.items()
                if not p.startswith(ENV_PREFIX)}


class SearchSpace:
    """The knob subset one tuning run varies.

    ``knobs`` maps knob name -> value tuple (overriding the catalog's
    candidates); every name must exist in :data:`KNOB_CATALOG` so typos
    fail loudly at construction, not as a silently-constant axis.
    """

    def __init__(self, knobs: Dict[str, Sequence[Any]]):
        self.knobs: List[Knob] = []
        for name, values in knobs.items():
            base = _CATALOG_BY_NAME.get(name)
            if base is None:
                raise UnknownKnobError(
                    f"unknown knob {name!r}; catalog: "
                    f"{sorted(_CATALOG_BY_NAME)}")
            vals = tuple(values) if not isinstance(values, tuple) else values
            if not vals:
                raise UnknownKnobError(f"knob {name!r} has no values")
            self.knobs.append(Knob(base.name, base.path, vals, base.kind,
                                   base.only_if))

    @classmethod
    def from_config(cls, autotuning_cfg: Dict) -> "SearchSpace":
        """Build from the ``autotuning.search_space`` config block; when
        absent, a small default over the highest-leverage knobs."""
        space = (autotuning_cfg or {}).get("search_space")
        if not space:
            space = {"zero_stage": (1, 3), "micro_batch": (1, 4, 16),
                     "qwz": (False, True), "qgz": (False, True),
                     "prefetch_depth": (1, 2)}
        return cls(space)

    def enumerate(self) -> List[Candidate]:
        """Cartesian product over the knob values, coherence-guarded and
        deduplicated (a knob whose guard is off is dropped from the
        patch, so e.g. ``qwz_bits`` never multiplies the qwZ-off half of
        the space)."""
        names = [k.name for k in self.knobs]
        out: List[Candidate] = []
        seen = set()
        for combo in itertools.product(*[k.values for k in self.knobs]):
            chosen = dict(zip(names, combo))
            patch: Dict[str, Any] = {}
            kept: Dict[str, Any] = {}
            for k in self.knobs:
                if not k.guard_ok(chosen):
                    continue
                v = chosen[k.name]
                if v is None:
                    continue             # None = leave the base config's value
                patch[k.path] = v
                kept[k.name] = v
            cand = Candidate(cid=f"c{len(out):04d}", patch=patch, knobs=kept)
            if cand.key() in seen:
                continue
            seen.add(cand.key())
            out.append(cand)
        return out


def apply_patch(base_config: Dict, patch: Dict[str, Any]) -> Dict:
    """Base ds_config + dotted-path patch -> the trial config (deep copy;
    ``env.*`` entries are skipped — they scope to the subprocess, and a
    ``mesh`` whole-dict value replaces the mesh block)."""
    cfg = copy.deepcopy(base_config)
    for path, value in patch.items():
        if path.startswith(ENV_PREFIX):
            continue
        if path == "mesh" and isinstance(value, dict):
            cfg["mesh"] = dict(value)
            continue
        set_nested(cfg, path, value)
    return cfg


def patch_diff(base_config: Dict, patch: Dict[str, Any]) -> Dict[str, Dict]:
    """Reviewable JSON diff: for each patched path, the base config's
    value (``None`` when unset) and the patch's."""
    def _get(cfg, dotted):
        cur = cfg
        for part in dotted.split("."):
            if not isinstance(cur, dict) or part not in cur:
                return None
            cur = cur[part]
        return cur

    diff = {}
    for path, value in sorted(patch.items()):
        if path.startswith(ENV_PREFIX):
            diff[path] = {"from": None, "to": value}
        else:
            diff[path] = {"from": _get(base_config, path), "to": value}
    return diff
