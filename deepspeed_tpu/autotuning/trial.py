"""Default trial runner: one short profiled run of a candidate config.

Launched by :class:`~deepspeed_tpu.autotuning.scheduler.TrialScheduler`
as ``python -m deepspeed_tpu.autotuning.trial``; the candidate's full
ds_config arrives via ``DS_AUTOTUNING_CONFIG`` with telemetry forced on,
so the engine's close drops the ``EFFICIENCY.json`` the loop scores.
The workload is deliberately tiny and synthetic — the trial exists to
exercise the CONFIG (sharding, prefetch, quantized collectives, fused
kernels) under the goodput ledger, not to converge a model:

* ``autotuning.trial.steps`` optimizer steps (default 6) of
  :class:`~deepspeed_tpu.models.simple.SimpleModel` with
  ``autotuning.trial.hidden_dim`` (default 64);
* deterministic data (seeded numpy) so two trials differ only by their
  config;
* the inherited ``DS_FAULT_PLAN`` fires inside the engine exactly as in
  production — a plan that wedges the step leaves the trial hung for
  the scheduler's watchdog to reap, which is the point of the wedged
  e2e.

The legacy ``DS_AUTOTUNING_METRIC_PATH`` contract is honored too: the
runner drops a ``metrics.json`` with raw throughput so the seed-era
``ResourceManager``/``Autotuner`` path can drive this same runner.
"""

import json
import os
import sys
import time


def run_trial(config: dict) -> dict:
    import jax
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models.simple import SimpleModel

    trial_cfg = dict((config.get("autotuning") or {}).get("trial") or {})
    steps = int(trial_cfg.get("steps", 6))
    hidden = int(trial_cfg.get("hidden_dim", 64))
    seed = int(trial_cfg.get("seed", 0))

    cfg = json.loads(json.dumps(config))
    # the candidate patch sets the micro-batch; the global batch is then
    # derived from the live world size (the mesh knob may change it), so
    # a stale train_batch_size from the base config must not conflict
    if "train_micro_batch_size_per_gpu" in cfg:
        cfg.pop("train_batch_size", None)
    cfg.setdefault("optimizer", {"type": "Adam", "params": {"lr": 1e-3}})

    model = SimpleModel(hidden_dim=hidden)
    params = model.init_params(jax.random.key(seed))
    engine, *_ = deepspeed_tpu.initialize(
        model=model, model_parameters=params, config=cfg)

    gas = engine.gradient_accumulation_steps()
    rows = max(engine.train_batch_size() // max(gas, 1), 1)
    rng = np.random.default_rng(seed)
    data = [(rng.standard_normal((rows, hidden)).astype(np.float32),
             np.zeros((rows,), np.int32)) for _ in range(4)]

    t0 = time.monotonic()
    while engine.global_steps < steps:
        x, y = data[engine.micro_steps % len(data)]
        loss = engine.forward(x, y)
        engine.backward(loss)
        engine.step()
    wall = time.monotonic() - t0
    engine.close()

    samples = engine.global_samples
    return {"throughput": (samples / wall) if wall > 0 else 0.0,
            "steps": engine.global_steps, "wall_s": wall}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    cfg_path = argv[0] if argv else os.environ.get("DS_AUTOTUNING_CONFIG")
    if not cfg_path:
        print("trial: no config (pass a path or set DS_AUTOTUNING_CONFIG)",
              file=sys.stderr)
        return 2
    with open(cfg_path) as f:
        config = json.load(f)
    metrics = run_trial(config)
    metric_path = os.environ.get("DS_AUTOTUNING_METRIC_PATH")
    if metric_path:
        from deepspeed_tpu.autotuning.scheduler import write_metrics
        write_metrics(metric_path, metrics)
    print("TRIAL_DONE " + json.dumps(metrics), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
