"""Experiment scheduler: subprocess trials with a reaped lifecycle.

Two layers:

* :class:`TrialScheduler` — the closed loop's executor.  One trial is
  one subprocess in its OWN process group (``start_new_session=True``)
  whose ds_config is written to the trial dir and pointed at by
  ``DS_AUTOTUNING_CONFIG``; the trial's telemetry is forced on so it
  drops the per-trial ``EFFICIENCY.json`` the scorer ranks.  A trial
  that exceeds its deadline is SIGTERMed, grace-waited, SIGKILLed, and
  the whole group swept with ``waitpid(-pgid)`` (the elastic-agent reap
  discipline — launcher grandchildren must not linger as zombies), then
  recorded as **degraded** — a wedged trial never eats the search
  budget, it just loses (PR 14's rung-cancellation discipline applied
  per trial).  Crashed trials (rc != 0) and trials whose ledger fails
  its conservation check are likewise recorded degraded, never silently
  dropped: every launched trial leaves a result row.

* :class:`ResourceManager` — the seed-era interface (command template +
  ``metrics.json`` scalar), kept for scripts that drive their own
  training command; ``run_experiment`` still returns the bare metric.

Thread contract: the scheduler may be driven from a tuner thread while
an observer reads ``status()``; the bookkeeping dicts are guarded by
``_lock`` (dslint lock-discipline checked), and no blocking call — the
child wait, the reap sweep, file I/O — ever runs under it.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from deepspeed_tpu.autotuning.scoring import TrialScore, score_from_efficiency
from deepspeed_tpu.utils.logging import log_dist

METRIC_PATH_ENV = "DS_AUTOTUNING_METRIC_PATH"
CONFIG_PATH_ENV = "DS_AUTOTUNING_CONFIG"

#: trial artifact filenames inside each trial dir
TRIAL_CONFIG = "ds_config.json"
TRIAL_EFFICIENCY = "EFFICIENCY.json"
TRIAL_LOG = "stdout.log"

#: trial result statuses
SCORED = "scored"
DEGRADED = "degraded"
PRUNED = "pruned"          # stamped by the loop, never by the scheduler


def reap_group(proc: subprocess.Popen, grace_s: float = 5.0) -> Optional[int]:
    """Terminate and REAP ``proc``'s whole process group: SIGTERM, grace
    wait, SIGKILL, then a scoped ``waitpid(-pgid)`` sweep so trial
    grandchildren (launcher workers, staging helpers) cannot linger as
    zombies across a long search.  Returns the leader's exit code."""
    rc = proc.poll()
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        pgid = proc.pid
    if rc is None:
        try:
            os.killpg(pgid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            rc = proc.wait(timeout=grace_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(pgid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            rc = proc.wait()
    # sweep the rest of the group (scoped to -pgid: never steal other
    # children of this process)
    while True:
        try:
            pid, _status = os.waitpid(-pgid, os.WNOHANG)
        except ChildProcessError:
            break
        if pid == 0:
            break
    return rc


@dataclass
class TrialResult:
    """One launched (or pruned) trial's provenance row."""
    name: str
    status: str                       # scored | degraded | pruned
    ds_config: Dict = field(default_factory=dict)
    patch: Dict = field(default_factory=dict)
    knobs: Dict = field(default_factory=dict)
    rc: Optional[int] = None
    timed_out: bool = False
    score: Optional[TrialScore] = None
    error: Optional[str] = None
    trial_dir: Optional[str] = None
    efficiency_path: Optional[str] = None
    duration_s: float = 0.0
    prune_reason: Optional[str] = None

    @property
    def scored(self) -> bool:
        return self.status == SCORED and self.score is not None

    def as_record(self) -> Dict:
        rec = {
            "name": self.name,
            "status": self.status,
            "patch": self.patch,
            "knobs": self.knobs,
            "rc": self.rc,
            "timed_out": self.timed_out,
            "score": self.score.as_record() if self.score else None,
            "error": self.error,
            "trial_dir": self.trial_dir,
            "duration_s": round(self.duration_s, 3),
        }
        if self.prune_reason is not None:
            rec["prune_reason"] = self.prune_reason
        return rec


class TrialScheduler:
    """Run scored trials as reaped subprocesses.

    ``cmd`` is the trial command (argv); default is the built-in runner
    ``python -m deepspeed_tpu.autotuning.trial`` which builds an engine
    from the trial's ds_config and steps it.  ``env`` overlays the
    inherited environment for every trial (e.g. ``JAX_PLATFORMS=cpu`` +
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` for the
    virtual-mesh search used in tests/bench).
    """

    def __init__(self, exps_dir: str, cmd: Optional[List[str]] = None,
                 timeout_s: float = 600.0, reap_grace_s: float = 5.0,
                 env: Optional[Dict[str, str]] = None):
        self.exps_dir = exps_dir
        self.cmd = list(cmd) if cmd else [sys.executable, "-m",
                                          "deepspeed_tpu.autotuning.trial"]
        self.timeout_s = float(timeout_s)
        self.reap_grace_s = float(reap_grace_s)
        self.env = dict(env or {})
        self._lock = threading.Lock()
        self._running: Dict[str, int] = {}   # guarded-by: _lock (name->pid)
        self.results: List[TrialResult] = []  # guarded-by: _lock
        os.makedirs(exps_dir, exist_ok=True)

    # -- bookkeeping (observer-safe) ------------------------------------- #
    def status(self) -> Dict[str, int]:
        with self._lock:
            scored = sum(1 for r in self.results if r.scored)
            degraded = sum(1 for r in self.results if r.status == DEGRADED)
            running = len(self._running)
        return {"scored": scored, "degraded": degraded, "running": running}

    def _record(self, result: TrialResult):
        with self._lock:
            self._running.pop(result.name, None)
            self.results.append(result)

    # -- execution -------------------------------------------------------- #
    def trial_dir(self, name: str) -> str:
        d = os.path.join(self.exps_dir, name)
        os.makedirs(d, exist_ok=True)
        return d

    def _prepare_config(self, trial_dir: str, ds_config: Dict) -> Dict:
        """Force the telemetry the scorer needs into the trial config:
        goodput ledger on, EFFICIENCY.json + telemetry JSONL in the
        trial dir (unless the caller already routed them)."""
        cfg = json.loads(json.dumps(ds_config))     # deep, JSON-safe copy
        tele = cfg.setdefault("telemetry", {})
        tele.setdefault("enabled", True)
        tele.setdefault("goodput", True)
        tele.setdefault("jsonl_path", os.path.join(trial_dir,
                                                   "telemetry.jsonl"))
        tele.setdefault("efficiency_json_path",
                        os.path.join(trial_dir, TRIAL_EFFICIENCY))
        return cfg

    def run_trial(self, name: str, ds_config: Dict,
                  extra_env: Optional[Dict[str, str]] = None,
                  patch: Optional[Dict] = None,
                  knobs: Optional[Dict] = None) -> TrialResult:
        """Launch one trial to completion (or reap) and score it."""
        trial_dir = self.trial_dir(name)
        cfg = self._prepare_config(trial_dir, ds_config)
        cfg_path = os.path.join(trial_dir, TRIAL_CONFIG)
        with open(cfg_path, "w") as f:
            json.dump(cfg, f, indent=2, sort_keys=True)
        eff_path = cfg["telemetry"]["efficiency_json_path"]

        env = dict(os.environ)
        env.update(self.env)
        env.update(extra_env or {})
        env[CONFIG_PATH_ENV] = cfg_path
        env[METRIC_PATH_ENV] = os.path.join(trial_dir, "metrics.json")

        result = TrialResult(name=name, status=DEGRADED, ds_config=cfg,
                             patch=dict(patch or {}), knobs=dict(knobs or {}),
                             trial_dir=trial_dir, efficiency_path=eff_path)
        t0 = time.monotonic()
        log_path = os.path.join(trial_dir, TRIAL_LOG)
        with open(log_path, "w") as log_f:
            proc = subprocess.Popen(self.cmd, env=env, stdout=log_f,
                                    stderr=subprocess.STDOUT,
                                    start_new_session=True)
        with self._lock:
            self._running[name] = proc.pid
        try:
            try:
                rc = proc.wait(timeout=self.timeout_s)
            except subprocess.TimeoutExpired:
                # the per-trial hang watchdog fired: reap the whole group
                # and score the trial degraded — it lost, the search didn't
                result.timed_out = True
                result.error = (f"trial exceeded {self.timeout_s:.1f}s "
                                "deadline; process group reaped")
                rc = reap_group(proc, grace_s=self.reap_grace_s)
            result.rc = rc
            result.duration_s = time.monotonic() - t0
            if result.timed_out:
                return result
            if rc != 0:
                result.error = f"trial exited rc={rc} (see {log_path})"
                return result
            score, err = score_from_efficiency(eff_path)
            if score is None:
                result.error = err
                return result
            if not score.conservation_ok:
                result.score = score
                result.error = ("ledger failed its conservation check — "
                                "mis-instrumented run, not scored")
                return result
            result.score = score
            result.status = SCORED
            return result
        finally:
            self._record(result)
            log_dist(f"autotuning: trial {name} {result.status}"
                     + (f" goodput={result.score.goodput_frac:.3f}"
                        if result.score else "")
                     + (f" ({result.error})" if result.error else ""),
                     ranks=[0])


# --------------------------------------------------------------------------- #
# Legacy interface (seed-era): command template + metrics.json scalar.
# --------------------------------------------------------------------------- #


class ResourceManager:
    """Run experiments and collect metric values (seed-era interface).

    ``cmd`` is the training command template (list of argv tokens); each
    experiment gets its own directory with ``ds_config.json`` +
    ``metrics.json`` and the env vars ``DS_AUTOTUNING_CONFIG`` /
    ``DS_AUTOTUNING_METRIC_PATH`` pointing at them.  Timed-out or
    crashed experiments return ``None`` and stay in
    ``finished_experiments`` — same contract as before, now with the
    group reap of :func:`reap_group` instead of an orphaning kill."""

    def __init__(self, exps_dir: str, cmd: Optional[List[str]] = None,
                 metric: str = "throughput", timeout: int = 1800):
        self.exps_dir = exps_dir
        self.cmd = cmd
        self.metric = metric
        self.timeout = timeout
        self.finished_experiments: List[Dict] = []
        os.makedirs(exps_dir, exist_ok=True)

    def experiment_dir(self, name: str) -> str:
        d = os.path.join(self.exps_dir, name)
        os.makedirs(d, exist_ok=True)
        return d

    def run_experiment(self, name: str, ds_config: Dict) -> Optional[float]:
        """Launch one experiment; returns the metric value or None."""
        exp_dir = self.experiment_dir(name)
        cfg_path = os.path.join(exp_dir, TRIAL_CONFIG)
        metric_path = os.path.join(exp_dir, "metrics.json")
        with open(cfg_path, "w") as f:
            json.dump(ds_config, f, indent=2)
        env = dict(os.environ)
        env[CONFIG_PATH_ENV] = cfg_path
        env[METRIC_PATH_ENV] = metric_path
        log_path = os.path.join(exp_dir, TRIAL_LOG)
        assert self.cmd, "ResourceManager needs a training command"
        with open(log_path, "w") as log_f:
            proc = subprocess.Popen(self.cmd, env=env, stdout=log_f,
                                    stderr=subprocess.STDOUT,
                                    start_new_session=True)
            try:
                rc = proc.wait(timeout=self.timeout)
            except subprocess.TimeoutExpired:
                rc = reap_group(proc)
        val = self.parse_results(metric_path)
        self.finished_experiments.append(
            {"name": name, "ds_config": ds_config, "rc": rc,
             self.metric: val, "exp_dir": exp_dir})
        return val if rc == 0 else None

    def parse_results(self, metric_path: str) -> Optional[float]:
        if not os.path.exists(metric_path):
            return None
        try:
            with open(metric_path) as f:
                data = json.load(f)
            return float(data.get(self.metric)) if self.metric in data else None
        except (ValueError, TypeError, OSError):
            return None

    def status(self) -> str:
        ok = sum(1 for e in self.finished_experiments if e[self.metric] is not None)
        return f"{ok}/{len(self.finished_experiments)} experiments succeeded"

    def clear(self):
        self.finished_experiments = []


def write_metrics(path: str, metrics: Dict):
    """Engine-side metric dump (atomic-ish)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(metrics, f)
    os.replace(tmp, path)
