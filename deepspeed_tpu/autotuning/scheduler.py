"""Experiment scheduler / resource manager.

Reference: ``deepspeed/autotuning/scheduler.py`` (``ResourceManager:33``) —
reserves host slots, launches each experiment as a training run with its
mutated DS config, and parses the metric from the experiment's results
file.  TPU redesign: an experiment is one subprocess (per-host spawning is
the `dst` launcher's job, which the command template can invoke); the
engine drops ``metrics.json`` when ``DS_AUTOTUNING_METRIC_PATH`` is set.
"""

import json
import os
import subprocess
import sys
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

METRIC_PATH_ENV = "DS_AUTOTUNING_METRIC_PATH"
CONFIG_PATH_ENV = "DS_AUTOTUNING_CONFIG"


class ResourceManager:
    """Run experiments and collect metric values.

    ``cmd`` is the training command template (list of argv tokens); each
    experiment gets its own directory with ``ds_config.json`` +
    ``metrics.json`` and the env vars ``DS_AUTOTUNING_CONFIG`` /
    ``DS_AUTOTUNING_METRIC_PATH`` pointing at them.  User scripts pass the
    config path into ``deepspeed_tpu.initialize`` (or read it themselves);
    the engine writes the metric file automatically.
    """

    def __init__(self, exps_dir: str, cmd: Optional[List[str]] = None,
                 metric: str = "throughput", timeout: int = 1800):
        self.exps_dir = exps_dir
        self.cmd = cmd
        self.metric = metric
        self.timeout = timeout
        self.finished_experiments: List[Dict] = []
        os.makedirs(exps_dir, exist_ok=True)

    def experiment_dir(self, name: str) -> str:
        d = os.path.join(self.exps_dir, name)
        os.makedirs(d, exist_ok=True)
        return d

    def run_experiment(self, name: str, ds_config: Dict) -> Optional[float]:
        """Launch one experiment; returns the metric value or None."""
        exp_dir = self.experiment_dir(name)
        cfg_path = os.path.join(exp_dir, "ds_config.json")
        metric_path = os.path.join(exp_dir, "metrics.json")
        with open(cfg_path, "w") as f:
            json.dump(ds_config, f, indent=2)
        env = dict(os.environ)
        env[CONFIG_PATH_ENV] = cfg_path
        env[METRIC_PATH_ENV] = metric_path
        log_path = os.path.join(exp_dir, "stdout.log")
        assert self.cmd, "ResourceManager needs a training command"
        try:
            with open(log_path, "w") as log_f:
                proc = subprocess.run(self.cmd, env=env, stdout=log_f,
                                      stderr=subprocess.STDOUT,
                                      timeout=self.timeout)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            rc = -1
        val = self.parse_results(metric_path)
        self.finished_experiments.append(
            {"name": name, "ds_config": ds_config, "rc": rc,
             self.metric: val, "exp_dir": exp_dir})
        return val if rc == 0 else None

    def parse_results(self, metric_path: str) -> Optional[float]:
        if not os.path.exists(metric_path):
            return None
        try:
            with open(metric_path) as f:
                data = json.load(f)
            return float(data.get(self.metric)) if self.metric in data else None
        except (ValueError, TypeError, OSError):
            return None

    def status(self) -> str:
        ok = sum(1 for e in self.finished_experiments if e[self.metric] is not None)
        return f"{ok}/{len(self.finished_experiments)} experiments succeeded"

    def clear(self):
        self.finished_experiments = []


def write_metrics(path: str, metrics: Dict):
    """Engine-side metric dump (atomic-ish)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(metrics, f)
    os.replace(tmp, path)
