from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.autotuning.fingerprint import (StaleTuningError,
                                                  environment_fingerprint)
from deepspeed_tpu.autotuning.fingerprint import check as check_fingerprint
from deepspeed_tpu.autotuning.fingerprint import check_engine
from deepspeed_tpu.autotuning.loop import ClosedLoopAutotuner
from deepspeed_tpu.autotuning.scheduler import (CONFIG_PATH_ENV,
                                                METRIC_PATH_ENV,
                                                ResourceManager,
                                                TrialResult, TrialScheduler,
                                                write_metrics)
from deepspeed_tpu.autotuning.scoring import (TrialScore, better,
                                              score_from_efficiency)
from deepspeed_tpu.autotuning.space import (KNOB_CATALOG, Candidate,
                                            SearchSpace, apply_patch,
                                            patch_diff)
from deepspeed_tpu.autotuning.tuner import (BaseTuner, GridSearchTuner,
                                            ModelBasedTuner, RandomTuner,
                                            RidgeCostModel)

__all__ = ["Autotuner", "ResourceManager", "write_metrics", "BaseTuner",
           "GridSearchTuner", "RandomTuner", "ModelBasedTuner",
           "RidgeCostModel", "METRIC_PATH_ENV", "CONFIG_PATH_ENV",
           "ClosedLoopAutotuner", "TrialScheduler", "TrialResult",
           "TrialScore", "better", "score_from_efficiency",
           "SearchSpace", "Candidate", "KNOB_CATALOG", "apply_patch",
           "patch_diff", "environment_fingerprint", "check_fingerprint",
           "check_engine", "StaleTuningError"]
