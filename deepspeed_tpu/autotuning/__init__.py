from deepspeed_tpu.autotuning.autotuner import Autotuner
from deepspeed_tpu.autotuning.scheduler import (CONFIG_PATH_ENV,
                                                METRIC_PATH_ENV,
                                                ResourceManager, write_metrics)
from deepspeed_tpu.autotuning.tuner import (BaseTuner, GridSearchTuner,
                                            ModelBasedTuner, RandomTuner,
                                            RidgeCostModel)

__all__ = ["Autotuner", "ResourceManager", "write_metrics", "BaseTuner",
           "GridSearchTuner", "RandomTuner", "ModelBasedTuner",
           "RidgeCostModel", "METRIC_PATH_ENV", "CONFIG_PATH_ENV"]
