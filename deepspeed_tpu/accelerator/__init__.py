from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.accelerator.real_accelerator import (get_accelerator,
                                                        is_current_accelerator_supported,
                                                        set_accelerator)
from deepspeed_tpu.accelerator.tpu_accelerator import (CPU_Accelerator,
                                                       TPU_Accelerator)

__all__ = ["DeepSpeedAccelerator", "TPU_Accelerator", "CPU_Accelerator",
           "get_accelerator", "set_accelerator",
           "is_current_accelerator_supported"]
