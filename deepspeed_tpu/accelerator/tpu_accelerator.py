"""TPU implementation of the accelerator interface.

Reference: ``accelerator/cuda_accelerator.py:19`` (``CUDA_Accelerator``).
Everything is backed by jax device APIs; ``synchronize`` drains the async
dispatch queue (the only fence TPU needs), memory stats come from
``device.memory_stats()``, pinned memory is the ``pinned_host`` memory
kind.
"""

from typing import Dict, Optional

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"
        self._seed = 0

    # ---- device identity --------------------------------------------- #
    def _devices(self):
        import jax
        return jax.local_devices()

    def device_name(self, device_index: Optional[int] = None) -> str:
        if device_index is None:
            return self._name
        return f"{self._name}:{device_index}"

    def device(self, device_index: Optional[int] = None):
        devs = self._devices()
        return devs[device_index or 0]

    def device_count(self) -> int:
        import jax
        return jax.local_device_count()

    def current_device(self) -> int:
        return 0

    # ---- synchronization --------------------------------------------- #
    def synchronize(self, device_index: Optional[int] = None):
        import jax
        jax.block_until_ready(jax.device_put(0))

    # ---- RNG ----------------------------------------------------------- #
    def manual_seed(self, seed: int):
        self._seed = int(seed)

    def initial_seed(self) -> int:
        return self._seed

    # ---- memory -------------------------------------------------------- #
    def memory_stats(self, device_index: Optional[int] = None) -> Dict:
        try:
            return dict(self.device(device_index).memory_stats() or {})
        except Exception:
            return {}

    # ---- dtype support ------------------------------------------------- #
    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return True   # storable; bf16 is the native fast path

    # ---- communication / availability ---------------------------------- #
    def communication_backend_name(self) -> str:
        return self._communication_backend_name

    def is_available(self) -> bool:
        try:
            return any(d.platform == "tpu" for d in self._devices())
        except Exception:
            return False

    # ---- pinned host memory ------------------------------------------- #
    def pin_memory(self, array):
        import jax
        sh = getattr(array, "sharding", None)
        if sh is not None:
            return jax.device_put(array, sh.with_memory_kind("pinned_host"))
        return array


class CPU_Accelerator(TPU_Accelerator):
    """CPU fallback (virtual-mesh CI, the reference's CPU_Accelerator)."""

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "gloo"

    def is_available(self) -> bool:
        return True

    def is_bf16_supported(self) -> bool:
        return True

    def is_fp16_supported(self) -> bool:
        return False

    def pin_memory(self, array):
        return array   # host memory already
