"""Accelerator abstraction.

Reference: ``accelerator/abstract_accelerator.py:10``
(``DeepSpeedAccelerator`` — the ~40-method device interface every
device-touching component goes through) and ``real_accelerator.py:37``
(``get_accelerator`` singleton with env override).

TPU redesign: the surface keeps the reference's *capability groups*
(device identity, synchronization, RNG, memory stats, dtype support,
communication backend name, op-builder slot) but drops the CUDA-isms that
have no TPU meaning — streams/events/graphs collapse onto XLA's async
dispatch (``synchronize`` drains it), ``empty_cache`` is a no-op (XLA
owns HBM), pinned memory maps to the ``pinned_host`` memory kind.  Those
methods still exist so reference-shaped code runs; they are honest no-ops
with docstrings saying why.
"""

import abc
from typing import Dict, Optional


class DeepSpeedAccelerator(abc.ABC):

    def __init__(self):
        self._name: Optional[str] = None
        self._communication_backend_name: Optional[str] = None

    # ---- device identity --------------------------------------------- #
    @abc.abstractmethod
    def device_name(self, device_index: Optional[int] = None) -> str: ...

    @abc.abstractmethod
    def device(self, device_index: Optional[int] = None): ...

    @abc.abstractmethod
    def device_count(self) -> int: ...

    @abc.abstractmethod
    def current_device(self) -> int: ...

    def current_device_name(self) -> str:
        return self.device_name(self.current_device())

    def set_device(self, device_index: int):
        """No-op under SPMD: one process drives all local devices (the
        reference's per-rank CUDA device selection has no analogue)."""

    # ---- synchronization --------------------------------------------- #
    @abc.abstractmethod
    def synchronize(self, device_index: Optional[int] = None): ...

    # ---- RNG ----------------------------------------------------------- #
    @abc.abstractmethod
    def manual_seed(self, seed: int): ...

    def manual_seed_all(self, seed: int):
        self.manual_seed(seed)

    @abc.abstractmethod
    def initial_seed(self) -> int: ...

    def random(self):
        import numpy as np
        return np.random

    def get_rng_state(self, device_index=None):
        return self.initial_seed()

    def set_rng_state(self, new_state, device_index=None):
        self.manual_seed(int(new_state))

    # ---- streams / events (XLA: async dispatch, no user streams) ------- #
    def stream(self, stream=None):
        import contextlib
        return contextlib.nullcontext()

    def current_stream(self, device_index=None):
        return None

    def default_stream(self, device_index=None):
        return None

    def Stream(self, *a, **k):
        return None

    def Event(self, *a, **k):
        return None

    # ---- memory -------------------------------------------------------- #
    @abc.abstractmethod
    def memory_stats(self, device_index: Optional[int] = None) -> Dict: ...

    def memory_allocated(self, device_index=None) -> int:
        return int(self.memory_stats(device_index).get("bytes_in_use", 0))

    def max_memory_allocated(self, device_index=None) -> int:
        return int(self.memory_stats(device_index).get("peak_bytes_in_use", 0))

    def memory_reserved(self, device_index=None) -> int:
        return self.memory_allocated(device_index)

    def max_memory_reserved(self, device_index=None) -> int:
        return self.max_memory_allocated(device_index)

    def total_memory(self, device_index=None) -> int:
        return int(self.memory_stats(device_index).get("bytes_limit", 0))

    def available_memory(self, device_index=None) -> int:
        return self.total_memory(device_index) - self.memory_allocated(device_index)

    def empty_cache(self):
        """XLA owns the HBM arena; there is no allocator cache to drop."""

    def reset_peak_memory_stats(self, device_index=None):
        """Peak counters live in the runtime; not resettable from here."""

    memory_cached = memory_reserved
    max_memory_cached = max_memory_reserved
    reset_max_memory_allocated = reset_peak_memory_stats
    reset_max_memory_cached = reset_peak_memory_stats

    # ---- dtype support ------------------------------------------------- #
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool: ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool: ...

    def supported_dtypes(self):
        import jax.numpy as jnp
        out = [jnp.float32]
        if self.is_bf16_supported():
            out.append(jnp.bfloat16)
        if self.is_fp16_supported():
            out.append(jnp.float16)
        return out

    # ---- graphs (→ jit) ------------------------------------------------ #
    def is_triton_supported(self) -> bool:
        return False

    def create_graph(self):
        return None

    def capture_to_graph(self, graph, **kwargs):
        import contextlib
        return contextlib.nullcontext()

    def replay_graph(self, graph):
        """jit replay is implicit — compiled programs are cached."""

    # ---- communication / ops ------------------------------------------ #
    @abc.abstractmethod
    def communication_backend_name(self) -> str: ...

    def is_initialized(self) -> bool:
        return True

    @abc.abstractmethod
    def is_available(self) -> bool: ...

    def op_builder_dir(self) -> str:
        """Op 'building' is Pallas/XLA compilation; there is no extension
        dir, but the slot reports where kernels live."""
        return "deepspeed_tpu.ops"

    def on_accelerator(self, array) -> bool:
        import jax
        return isinstance(array, jax.Array)

    # ---- host/pinned memory ------------------------------------------- #
    @abc.abstractmethod
    def pin_memory(self, array): ...

    def is_pinned(self, array) -> bool:
        try:
            return array.sharding.memory_kind == "pinned_host"
        except AttributeError:
            return False
