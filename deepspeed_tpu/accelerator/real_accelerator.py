"""Accelerator singleton.

Reference: ``accelerator/real_accelerator.py:37`` (``get_accelerator`` /
``set_accelerator`` with the ``DS_ACCELERATOR`` env override and
auto-detection).
"""

import os
from typing import Optional

from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator

_accelerator: Optional[DeepSpeedAccelerator] = None

SUPPORTED = ("tpu", "cpu")


def _detect() -> str:
    name = os.environ.get("DS_ACCELERATOR")
    if name:
        assert name in SUPPORTED, \
            f"DS_ACCELERATOR={name!r} not in {SUPPORTED}"
        return name
    try:
        import jax
        if any(d.platform == "tpu" for d in jax.local_devices()):
            return "tpu"
    except Exception:
        pass
    return "cpu"


def get_accelerator() -> DeepSpeedAccelerator:
    global _accelerator
    if _accelerator is None:
        from deepspeed_tpu.accelerator.tpu_accelerator import (CPU_Accelerator,
                                                               TPU_Accelerator)
        _accelerator = (TPU_Accelerator() if _detect() == "tpu"
                        else CPU_Accelerator())
    return _accelerator


def set_accelerator(accel: DeepSpeedAccelerator):
    global _accelerator
    _accelerator = accel


def is_current_accelerator_supported() -> bool:
    return get_accelerator()._name in SUPPORTED
