"""`dst-ssh` / `dst-elastic` — the reference's `bin/` utility belt.

Reference: ``bin/ds_ssh`` (run a command on every hostfile host via pdsh)
and ``bin/ds_elastic`` (query the elastic batch/GPU solver for a config).
TPU-native differences: ``dst-ssh`` shells out to plain ``ssh`` with a
thread per host (pdsh is rarely present on TPU-VM images; the launcher's
pdsh path remains for pods that have it), and ``dst-elastic`` prints the
same solver results from ``deepspeed_tpu.elasticity``.
"""

import argparse
import json
import os
import shlex
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor


def _host_key_checking_mode(insecure_flag: bool) -> str:
    """``accept-new`` trusts a host's key on first contact but still rejects
    a CHANGED key (the MITM case the old blanket ``no`` waved through).
    The blanket-disable escape hatch stays for ephemeral pools whose hosts
    are re-imaged (and re-keyed) constantly: ``--insecure-host-keys`` or
    ``DST_SSH_INSECURE_HOST_KEYS=1``."""
    if insecure_flag or os.environ.get("DST_SSH_INSECURE_HOST_KEYS", "") in (
            "1", "true", "yes"):
        return "no"
    return "accept-new"


def dst_ssh_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dst-ssh", description="run a command on every hostfile host")
    parser.add_argument("-f", "--hostfile", default="/job/hostfile")
    parser.add_argument("--insecure-host-keys", action="store_true",
                        help="disable host-key verification entirely "
                             "(StrictHostKeyChecking=no); default is "
                             "accept-new. Also via DST_SSH_INSECURE_HOST_KEYS=1")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="command to run on each host")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    from deepspeed_tpu.launcher.runner import fetch_hostfile
    resources = fetch_hostfile(args.hostfile)
    if not resources:
        print(f"no hosts in {args.hostfile}", file=sys.stderr)
        return 1
    cmd = shlex.join(args.command)   # preserve arg quoting remotely
    hkc = _host_key_checking_mode(args.insecure_host_keys)

    def run(host):
        p = subprocess.run(
            ["ssh", "-o", f"StrictHostKeyChecking={hkc}", host, cmd],
            capture_output=True, text=True)
        return host, p.returncode, p.stdout, p.stderr

    rc = 0
    with ThreadPoolExecutor(max_workers=min(32, len(resources))) as pool:
        for host, code, out, err in pool.map(run, resources):
            for line in out.splitlines():
                print(f"{host}: {line}")
            for line in err.splitlines():
                print(f"{host}: {line}", file=sys.stderr)
            rc = rc or code
    return rc


def dst_elastic_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="dst-elastic", description="query the elastic batch solver")
    parser.add_argument("-c", "--config", required=True)
    parser.add_argument("-w", "--world-size", type=int, default=0)
    args = parser.parse_args(argv)
    with open(args.config) as f:
        ds_config = json.load(f)
    from deepspeed_tpu.elasticity import compute_elastic_config
    from deepspeed_tpu.version import __version__
    print("Elasticity config:")
    print(json.dumps(ds_config.get("elasticity", {}), indent=4,
                     sort_keys=True))
    if args.world_size > 0:
        batch, gpus, micro = compute_elastic_config(
            ds_config, target_deepspeed_version=__version__,
            world_size=args.world_size, return_microbatch=True)
        print(f"final_batch_size .... {batch}")
        print(f"valid_gpus .......... {gpus}")
        print(f"micro_batch_size .... {micro}")
    else:
        batch, gpus = compute_elastic_config(
            ds_config, target_deepspeed_version=__version__)
        print(f"final_batch_size .... {batch}")
        print(f"valid_gpus .......... {gpus}")
    return 0
