"""Grouped integer quantize/dequantize ops.

Capability parity with the reference quantizer kernels
(``csrc/quantization/{quantize.cu,fake_quantizer.cu,pt_binding.cpp}`` and
the ``ds_quantizer`` wrapper ``ops/quantizer/quantizer.py:14``): grouped
symmetric/asymmetric int8/int4 quantization with nearest or stochastic
rounding, returning REAL integer payloads + per-group scales (for
storage/wire use — the fake-quant STE path for training lives in
``compression/basic_ops.py``).  Pure jnp: XLA fuses the scale/round/clip
chain; int4 packs two nibbles per int8 byte.
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    data: jax.Array        # int8 payload ([groups, elems] or packed nibbles)
    scale: jax.Array       # [groups, 1] float32
    zero_point: jax.Array  # [groups, 1] float32 (0 for symmetric)
    shape: Tuple[int, ...]
    bits: int
    symmetric: bool


def _group(x, groups: int):
    flat = x.reshape(-1)
    n = flat.shape[0]
    assert n % groups == 0, f"{n} elements not divisible into {groups} groups"
    return flat.reshape(groups, -1)


def quantize(x, bits: int = 8, groups: int = 1, symmetric: bool = True,
             stochastic: bool = False,
             rng: Optional[jax.Array] = None) -> QuantizedTensor:
    assert bits in (4, 8), "int8 and int4 supported"
    g = _group(x.astype(jnp.float32), groups)

    def rnd(v):
        if stochastic:
            assert rng is not None
            return jnp.floor(v + jax.random.uniform(rng, v.shape))
        return jnp.round(v)

    if symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        scale = jnp.maximum(jnp.max(jnp.abs(g), axis=1, keepdims=True) / qmax,
                            1e-12)
        zp = jnp.zeros_like(scale)
        q = jnp.clip(rnd(g / scale), -qmax - 1, qmax)
    else:
        qmax = 2.0 ** bits - 1
        lo = jnp.min(g, axis=1, keepdims=True)
        hi = jnp.max(g, axis=1, keepdims=True)
        scale = jnp.maximum((hi - lo) / qmax, 1e-12)
        zp = lo
        q = jnp.clip(rnd((g - lo) / scale), 0, qmax) - 2.0 ** (bits - 1)

    qi = q.astype(jnp.int8)
    if bits == 4:
        qi = _pack_int4(qi)
    return QuantizedTensor(qi, scale, zp, tuple(x.shape), bits, symmetric)


def dequantize(qt: QuantizedTensor, dtype=jnp.float32) -> jax.Array:
    q = qt.data
    if qt.bits == 4:
        q = _unpack_int4(q)
    qf = q.astype(jnp.float32)
    if not qt.symmetric:
        # shift back from the centered int8 representation
        qf = qf + 2.0 ** (qt.bits - 1)
    out = qf * qt.scale + qt.zero_point
    n = 1
    for s in qt.shape:
        n *= s
    return out.reshape(-1)[:n].reshape(qt.shape).astype(dtype)


def _pack_int4(q: jax.Array) -> jax.Array:
    """[g, n] int8 in [-8, 7] → [g, n/2] int8, two nibbles per byte."""
    g, n = q.shape
    assert n % 2 == 0, "int4 packing needs an even group size"
    u = (q.astype(jnp.int32) & 0xF).reshape(g, n // 2, 2)
    return (u[..., 0] | (u[..., 1] << 4)).astype(jnp.int8)


def _unpack_int4(p: jax.Array) -> jax.Array:
    u = p.astype(jnp.int32) & 0xFF
    lo = (u & 0xF)
    hi = (u >> 4) & 0xF
    both = jnp.stack([lo, hi], axis=-1).reshape(p.shape[0], -1)
    return jnp.where(both >= 8, both - 16, both).astype(jnp.int8)


def quantize_dequantize(x, bits: int = 8, groups: int = 1,
                        symmetric: bool = True, stochastic: bool = False,
                        rng: Optional[jax.Array] = None) -> jax.Array:
    """Round-trip (the ``fake_quantizer.cu`` capability) without STE —
    for inference weight conversion; training uses compression.basic_ops."""
    return dequantize(quantize(x, bits, groups, symmetric, stochastic, rng),
                      dtype=x.dtype)
