"""Fused transformer layer — reference API surface.

Reference: ``deepspeed/ops/transformer/transformer.py`` (``
DeepSpeedTransformerConfig:34`` + ``DeepSpeedTransformerLayer:296``, the
Python face of the ~6.5k-line CUDA training kernel) and the
``stochastic_transformer`` builder variant (``op_builder/
stochastic_transformer.py:22``).

TPU-native: the fused layer IS ``models/bert.bert_block`` under jit —
LN/QKV/attention/GELU/dropout fuse in XLA with the flash-attention Pallas
kernel as the hot op.  This module provides the reference's config+layer
class surface on top of it.  ``stochastic_mode`` (the reference's
speed-for-reproducibility trade) is accepted and is a documented no-op:
TPU/XLA execution is deterministic at full speed, so there is nothing to
trade.
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models.bert import BertConfig, bert_block, _init_block


class DeepSpeedTransformerConfig:
    """Reference ``DeepSpeedTransformerConfig:34`` fields."""

    def __init__(self, batch_size: int = -1, hidden_size: int = -1,
                 intermediate_size: int = -1, heads: int = -1,
                 attn_dropout_ratio: float = 0.0,
                 hidden_dropout_ratio: float = 0.0,
                 num_hidden_layers: int = -1, initializer_range: float = 0.02,
                 layer_norm_eps: float = 1e-12, local_rank: int = -1,
                 seed: int = -1, fp16: bool = False, pre_layer_norm: bool = True,
                 normalize_invertible: bool = False, gelu_checkpoint: bool = False,
                 adjust_init_range: bool = True, attn_dropout_checkpoint: bool = False,
                 stochastic_mode: bool = False, return_tuple: bool = False,
                 training: bool = True):
        self.batch_size = batch_size
        self.hidden_size = hidden_size
        self.intermediate_size = (intermediate_size if intermediate_size > 0
                                  else 4 * hidden_size)
        self.heads = heads
        self.attn_dropout_ratio = attn_dropout_ratio
        self.hidden_dropout_ratio = hidden_dropout_ratio
        self.num_hidden_layers = num_hidden_layers
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.pre_layer_norm = pre_layer_norm
        self.fp16 = fp16
        self.stochastic_mode = stochastic_mode   # no-op: TPU is deterministic
        self.training = training
        self.return_tuple = return_tuple

    @classmethod
    def from_dict(cls, json_object: Dict) -> "DeepSpeedTransformerConfig":
        cfg = cls()
        for k, v in json_object.items():
            setattr(cfg, k, v)
        if cfg.intermediate_size is None or cfg.intermediate_size <= 0:
            cfg.intermediate_size = 4 * cfg.hidden_size   # re-derive default
        return cfg


class DeepSpeedTransformerLayer:
    """Reference ``DeepSpeedTransformerLayer:296``: one fused encoder
    layer with its own parameters; jit-compiled on first call."""

    _layer_id = 0

    def __init__(self, config: DeepSpeedTransformerConfig,
                 initial_weights=None, initial_biases=None, seed: int = 0):
        self.config = config
        self.layer_id = DeepSpeedTransformerLayer._layer_id
        DeepSpeedTransformerLayer._layer_id += 1
        self._bcfg = BertConfig(
            vocab_size=128,  # unused by the block
            hidden_size=config.hidden_size,
            num_hidden_layers=max(config.num_hidden_layers, 1),
            num_attention_heads=config.heads,
            intermediate_size=config.intermediate_size,
            hidden_dropout_prob=config.hidden_dropout_ratio,
            pre_ln=config.pre_layer_norm,
            dtype=jnp.float16 if config.fp16 else jnp.float32,
            ln_eps=config.layer_norm_eps)
        self.params = _init_block(self._bcfg,
                                  jax.random.key(seed + self.layer_id))
        if initial_weights is not None or initial_biases is not None:
            self.load_weights(initial_weights, initial_biases)
        self._fn = None

    def load_weights(self, weights, biases):
        """Install externally-created [qkv, out, fc, proj] weight/bias
        lists (the reference's initial_weights/initial_biases path)."""
        names_w = ["qkv_w", "out_w", "fc_w", "proj_w"]
        names_b = ["qkv_b", "out_b", "fc_b", "proj_b"]
        for n, w in zip(names_w, weights or []):
            self.params[n] = jnp.asarray(w)
        for n, b in zip(names_b, biases or []):
            self.params[n] = jnp.asarray(b)

    def __call__(self, hidden_states, attention_mask=None, rng=None):
        """``attention_mask``: [B, S] keep-mask (1 = attend) or an additive
        bias broadcastable to [B, 1, 1, S], as the reference layer takes."""
        from deepspeed_tpu.ops.attention import get_attention_fn
        if self._fn is None:
            cfg = self._bcfg

            def fn(p, x, r, bias):
                attn = get_attention_fn("auto")
                return bert_block(cfg, p, x, attn, rng=r,
                                  train=self.config.training, attn_bias=bias)

            self._fn = jax.jit(fn, static_argnames=())
        rng = rng if rng is not None else jax.random.key(0)
        bias = None
        if attention_mask is not None:
            m = jnp.asarray(attention_mask, jnp.float32)
            if m.ndim == 2:   # keep-mask → additive
                bias = ((1.0 - m) * -1e30)[:, None, None, :]
            else:
                bias = m
        out = self._fn(self.params, hidden_states, rng, bias)
        return (out,) if self.config.return_tuple else out


def stochastic_transformer_layer(config: DeepSpeedTransformerConfig,
                                 **kwargs) -> DeepSpeedTransformerLayer:
    """Reference ``op_builder/stochastic_transformer.py:22`` variant:
    identical layer with ``stochastic_mode=True`` (documented no-op)."""
    config.stochastic_mode = True
    return DeepSpeedTransformerLayer(config, **kwargs)
