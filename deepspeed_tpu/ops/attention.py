"""Attention ops — registry + reference implementation.

The reference's attention fast paths are CUDA kernels
(``csrc/transformer/softmax_kernels.cu``, inference ``softmax_context`` in
``csrc/transformer/inference/csrc/pt_binding.cpp:1717-1781``).  Here the
fast path is a Pallas TPU flash-attention kernel
(``deepspeed_tpu/ops/pallas/flash_attention.py``) and the reference path is
pure jnp (XLA still fuses it into a handful of kernels); parity tests compare
the two the way ``tests/unit/ops/accelerators/test_accelerator_forward.py``
compares fused CUDA vs HF modeling.

All implementations share one signature::

    fn(q, k, v, *, causal: bool, bias=None) -> out   # [batch, seq, heads, head_dim]

``bias`` is an additive attention-logit bias broadcastable to
``[batch, heads, q, k]`` (ALiBi slopes, relative-position bias).  The
Pallas kernel path handles the un-biased case; biased calls take the jnp
path, which XLA fuses (the reference's alibi similarly lives in its own
softmax kernel variant).
"""

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

import numpy as np


def reference_attention(q, k, v, *, causal: bool = True, bias=None):
    """Pure-jnp multi-head attention, fp32 softmax accumulation."""
    B, S, H, D = q.shape
    scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def flash_attention(q, k, v, *, causal: bool = True, bias=None):
    """Pallas flash attention on TPU; falls back to the reference path on
    other backends (tests run on the CPU mesh) and for biased calls."""
    if bias is not None or not _on_tpu():
        return reference_attention(q, k, v, causal=causal, bias=bias)
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention as fa
    return fa(q, k, v, causal=causal)


def ring_attention(q, k, v, *, causal: bool = True, bias=None):
    """Ring attention over the ``seq`` mesh axis (KV blocks rotated by
    ppermute); see ``deepspeed_tpu/parallel/sequence.py``."""
    assert bias is None, "ring attention does not support logit bias yet"
    from deepspeed_tpu.parallel.sequence import ring_attention as ra
    return ra(q, k, v, causal=causal)


def ulysses_attention(q, k, v, *, causal: bool = True, bias=None):
    """Ulysses-style all-to-all sequence parallel attention; see
    ``deepspeed_tpu/parallel/sequence.py``."""
    assert bias is None, "ulysses attention does not support logit bias yet"
    from deepspeed_tpu.parallel.sequence import ulysses_attention as ua
    return ua(q, k, v, causal=causal, inner=flash_attention)


def alibi_slopes(num_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (BLOOM; geometric sequence from the paper)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if np.log2(num_heads).is_integer():
        return np.asarray(pow2_slopes(num_heads), np.float32)
    closest = 2 ** int(np.floor(np.log2(num_heads)))
    extra = pow2_slopes(2 * closest)[0::2][:num_heads - closest]
    return np.asarray(pow2_slopes(closest) + extra, np.float32)


def alibi_bias(num_heads: int, q_len: int, k_len: int,
               q_offset: int = 0) -> jnp.ndarray:
    """[1, H, q, k] additive ALiBi bias: slope_h * -(q_pos - k_pos)."""
    slopes = jnp.asarray(alibi_slopes(num_heads))
    qpos = q_offset + jnp.arange(q_len)[:, None]
    kpos = jnp.arange(k_len)[None, :]
    dist = (kpos - qpos).astype(jnp.float32)        # <= 0 in the causal past
    return (slopes[:, None, None] * dist)[None]


_REGISTRY = {
    "reference": reference_attention,
    "flash": flash_attention,
    "ring": ring_attention,
    "ulysses": ulysses_attention,
}


def get_attention_fn(impl: str = "auto") -> Callable:
    if impl == "auto":
        impl = "flash"
    assert impl in _REGISTRY, f"unknown attention impl {impl!r}; have {list(_REGISTRY)}"
    return _REGISTRY[impl]


def register_attention(name: str, fn: Callable):
    _REGISTRY[name] = fn
