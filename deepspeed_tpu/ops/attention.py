"""Attention ops — registry + reference implementation.

The reference's attention fast paths are CUDA kernels
(``csrc/transformer/softmax_kernels.cu``, inference ``softmax_context`` in
``csrc/transformer/inference/csrc/pt_binding.cpp:1717-1781``).  Here the
fast path is a Pallas TPU flash-attention kernel
(``deepspeed_tpu/ops/pallas/flash_attention.py``) and the reference path is
pure jnp (XLA still fuses it into a handful of kernels); parity tests compare
the two the way ``tests/unit/ops/accelerators/test_accelerator_forward.py``
compares fused CUDA vs HF modeling.

All implementations share one signature::

    fn(q, k, v, *, causal: bool, bias=None, alibi=None) -> out
    # [batch, seq, heads, head_dim]

``k``/``v`` may carry fewer heads than ``q`` (GQA/MQA, ``H % Hkv == 0``):
the Pallas kernel consumes grouped KV natively (no expansion is ever
materialized on that path); the jnp reference and ring path expand
internally.

``alibi`` takes the per-head ALiBi slope vector [H] — O(H) memory on every
path: the Pallas kernel and the ring body synthesize ``slope * (k_pos -
q_pos)`` from iotas, never materializing an [S, S] bias (the reference
bakes alibi into its softmax kernel the same way,
``csrc/transformer/inference/csrc/softmax.cu``).

``bias`` is a dense additive attention-logit bias broadcastable to
``[batch, heads, q, k]`` (relative-position bias etc.), supported on every
path but inherently O(S^2) — prefer ``alibi`` for ALiBi.  On the kernel
paths both are constants under differentiation.
"""

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

import numpy as np


def expand_kv_heads(q, k, v):
    """Repeat grouped KV heads up to q's head count (jnp paths only; the
    Pallas kernels index grouped KV directly)."""
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv == H:
        return k, v
    assert H % Hkv == 0, f"{H} q heads not a multiple of {Hkv} kv heads"
    return (jnp.repeat(k, H // Hkv, axis=2), jnp.repeat(v, H // Hkv, axis=2))


def canonical_bias(bias):
    """Right-align a logit bias to rank 4 ([B|1, H|1, q, k]); the contract
    admits rank 2/3 ('broadcastable to [B, H, S, S]')."""
    if bias is None:
        return None
    assert bias.ndim <= 4, f"bias rank {bias.ndim} > 4"
    while bias.ndim < 4:
        bias = bias[None]
    return bias


def reference_attention(q, k, v, *, causal: bool = True, bias=None, alibi=None):
    """Pure-jnp multi-head attention, fp32 softmax accumulation (GQA-aware).

    ``bias``/``alibi`` are stop-gradiented: the kernel paths (flash, ring)
    cannot produce an O(S^2) dbias without defeating their memory scaling,
    so the FRAMEWORK-WIDE contract (see ``get_attention_fn``) is that both
    bias forms are constants under differentiation — the reference path
    must agree or a learned bias would silently train only when dispatch
    happened to select it."""
    if bias is not None:
        bias = jax.lax.stop_gradient(bias)
    if alibi is not None:
        alibi = jax.lax.stop_gradient(alibi)
    k, v = expand_kv_heads(q, k, v)
    B, S, H, D = q.shape
    Sk = k.shape[1]
    scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    bias = canonical_bias(bias)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if alibi is not None:
        slopes = jnp.asarray(alibi, jnp.float32)
        dist = (jnp.arange(Sk)[None, :] - jnp.arange(S)[:, None]).astype(jnp.float32)
        logits = logits + slopes[None, :, None, None] * dist[None, None]
    if causal:
        mask = jnp.tril(jnp.ones((S, Sk), bool), k=Sk - S)
        logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def flash_attention(q, k, v, *, causal: bool = True, bias=None, alibi=None):
    """Pallas flash attention on TPU (grouped-KV + bias/alibi native); falls
    back to the reference path on other backends (tests run on the CPU mesh)."""
    if not _on_tpu():
        return reference_attention(q, k, v, causal=causal, bias=bias, alibi=alibi)
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention as fa
    return fa(q, k, v, causal=causal, bias=bias, alibi=alibi)


def ring_attention(q, k, v, *, causal: bool = True, bias=None, alibi=None):
    """Ring attention over the ``seq`` mesh axis (KV blocks rotated by
    ppermute); see ``deepspeed_tpu/parallel/sequence.py``."""
    from deepspeed_tpu.parallel.sequence import ring_attention as ra
    return ra(q, k, v, causal=causal, bias=bias, alibi=alibi)


def ulysses_attention(q, k, v, *, causal: bool = True, bias=None, alibi=None):
    """Ulysses-style all-to-all sequence parallel attention; see
    ``deepspeed_tpu/parallel/sequence.py``."""
    from deepspeed_tpu.parallel.sequence import ulysses_attention as ua
    return ua(q, k, v, causal=causal, bias=bias, alibi=alibi,
              inner=flash_attention)


def alibi_slopes(num_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (BLOOM; geometric sequence from the paper)."""
    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(np.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if np.log2(num_heads).is_integer():
        return np.asarray(pow2_slopes(num_heads), np.float32)
    closest = 2 ** int(np.floor(np.log2(num_heads)))
    extra = pow2_slopes(2 * closest)[0::2][:num_heads - closest]
    return np.asarray(pow2_slopes(closest) + extra, np.float32)


def alibi_bias(num_heads: int, q_len: int, k_len: int,
               q_offset: int = 0) -> jnp.ndarray:
    """[1, H, q, k] additive ALiBi bias: slope_h * -(q_pos - k_pos)."""
    slopes = jnp.asarray(alibi_slopes(num_heads))
    qpos = q_offset + jnp.arange(q_len)[:, None]
    kpos = jnp.arange(k_len)[None, :]
    dist = (kpos - qpos).astype(jnp.float32)        # <= 0 in the causal past
    return (slopes[:, None, None] * dist)[None]


# Below this sequence length XLA's fused dense attention beats the Pallas
# flash kernel on-chip (r5, v5e, bf16-MXU kernels with (256, 512) blocks:
# flash wins from S=512 up — fwd+bwd 0.386ms vs 0.411ms dense at S=512,
# micro 8 — and the gap widens with S while dense goes O(S^2) in memory).
XLA_FUSED_MAX_SEQ = 256


def auto_attention(q, k, v, *, causal: bool = True, bias=None, alibi=None):
    """Dispatch by sequence length: XLA-fused dense attention for short
    sequences, Pallas flash beyond ``XLA_FUSED_MAX_SEQ``."""
    if q.shape[1] <= XLA_FUSED_MAX_SEQ:
        return reference_attention(q, k, v, causal=causal, bias=bias, alibi=alibi)
    return flash_attention(q, k, v, causal=causal, bias=bias, alibi=alibi)


_REGISTRY = {
    "auto": auto_attention,
    "reference": reference_attention,
    "flash": flash_attention,
    "ring": ring_attention,
    "ulysses": ulysses_attention,
}


def get_attention_fn(impl: str = "auto") -> Callable:
    """Resolve an attention impl by name.

    Contract (ALL impls): ``fn(q, k, v, *, causal, bias=None, alibi=None)``
    with [batch, seq, heads, head_dim]; ``bias`` and ``alibi`` are
    CONSTANTS under differentiation on every path (gradients flow to
    q/k/v only) — a learned T5-style bias is not supported, by design:
    its O(S^2) dbias would defeat the flash/ring memory scaling, and the
    jnp reference path stop-gradients to keep dispatch-invariant
    semantics."""
    assert impl in _REGISTRY, f"unknown attention impl {impl!r}; have {list(_REGISTRY)}"
    return _REGISTRY[impl]


def register_attention(name: str, fn: Callable):
    _REGISTRY[name] = fn
