"""Attention ops — registry + reference implementation.

The reference's attention fast paths are CUDA kernels
(``csrc/transformer/softmax_kernels.cu``, inference ``softmax_context`` in
``csrc/transformer/inference/csrc/pt_binding.cpp:1717-1781``).  Here the
fast path is a Pallas TPU flash-attention kernel
(``deepspeed_tpu/ops/pallas/flash_attention.py``) and the reference path is
pure jnp (XLA still fuses it into a handful of kernels); parity tests compare
the two the way ``tests/unit/ops/accelerators/test_accelerator_forward.py``
compares fused CUDA vs HF modeling.

All implementations share one signature::

    fn(q, k, v, *, causal: bool) -> out     # [batch, seq, heads, head_dim]
"""

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

import numpy as np


def reference_attention(q, k, v, *, causal: bool = True):
    """Pure-jnp multi-head attention, fp32 softmax accumulation."""
    B, S, H, D = q.shape
    scale = 1.0 / np.sqrt(D)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def flash_attention(q, k, v, *, causal: bool = True):
    """Pallas flash attention on TPU; falls back to the reference path on
    other backends (tests run on the CPU mesh)."""
    if not _on_tpu():
        return reference_attention(q, k, v, causal=causal)
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention as fa
    return fa(q, k, v, causal=causal)


def ring_attention(q, k, v, *, causal: bool = True):
    """Ring attention over the ``seq`` mesh axis (KV blocks rotated by
    ppermute); see ``deepspeed_tpu/parallel/sequence.py``."""
    from deepspeed_tpu.parallel.sequence import ring_attention as ra
    return ra(q, k, v, causal=causal)


def ulysses_attention(q, k, v, *, causal: bool = True):
    """Ulysses-style all-to-all sequence parallel attention; see
    ``deepspeed_tpu/parallel/sequence.py``."""
    from deepspeed_tpu.parallel.sequence import ulysses_attention as ua
    return ua(q, k, v, causal=causal, inner=flash_attention)


_REGISTRY = {
    "reference": reference_attention,
    "flash": flash_attention,
    "ring": ring_attention,
    "ulysses": ulysses_attention,
}


def get_attention_fn(impl: str = "auto") -> Callable:
    if impl == "auto":
        impl = "flash"
    assert impl in _REGISTRY, f"unknown attention impl {impl!r}; have {list(_REGISTRY)}"
    return _REGISTRY[impl]


def register_attention(name: str, fn: Callable):
    _REGISTRY[name] = fn
