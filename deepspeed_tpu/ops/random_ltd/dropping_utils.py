"""Random-LTD token dropping utilities.

Reference: ``deepspeed/ops/random_ltd/dropping_utils.py`` (wrappers over
the ``csrc/random_ltd`` token_sort / gather_scatter kernels:
``gpt_sample_tokens``/``bert_sample_tokens`` + GatherTokens /
ScatterTokens).  On TPU these are jnp sort/take/scatter — XLA lowers them
natively (SURVEY §2.3) — layered over
``runtime/data_pipeline/data_routing/basic_layer.py``.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.runtime.data_pipeline.data_routing.basic_layer import (
    gather_tokens as _gather, sample_token_indices, scatter_tokens as _scatter)


def gpt_sample_tokens(reserved_length: int, seq_length: int, batch_size: int,
                      layers: int = 1, rng: Optional[jax.Array] = None,
                      attn_mask: Optional[jax.Array] = None
                      ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """[layers, reserved] sorted sample indices (+ sliced causal mask).

    GPT attention masks are positional, so the sliced mask for sorted
    indices is just the causal mask over the subsequence (returned None —
    kernels apply causality positionally)."""
    rng = rng if rng is not None else jax.random.key(0)
    idx = sample_token_indices(rng, seq_length, reserved_length, layers)
    return idx, None


def bert_sample_tokens(reserved_length: int, seq_length: int, batch_size: int,
                       layers: int = 1, rng: Optional[jax.Array] = None,
                       attn_mask: Optional[jax.Array] = None
                       ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Like :func:`gpt_sample_tokens` but also slices a [B, S] key-padding
    mask down to the sampled positions per layer → [layers, B, reserved]."""
    rng = rng if rng is not None else jax.random.key(1)
    idx = sample_token_indices(rng, seq_length, reserved_length, layers)
    if attn_mask is None:
        return idx, None
    sliced = jax.vmap(lambda i: jnp.take(attn_mask, i, axis=1))(idx)
    return idx, sliced


class GatherTokens:
    """Reference autograd-function surface; functionally just a gather."""

    @staticmethod
    def apply(activations, sorted_indices, batch_first: bool = True):
        x = activations if batch_first else activations.swapaxes(0, 1)
        out = _gather(x, sorted_indices)
        return (activations, out if batch_first else out.swapaxes(0, 1))


class ScatterTokens:
    @staticmethod
    def apply(all_activations, layer_activations, sorted_indices,
              batch_first: bool = True):
        x = all_activations if batch_first else all_activations.swapaxes(0, 1)
        sub = layer_activations if batch_first else layer_activations.swapaxes(0, 1)
        out = _scatter(x, sub, sorted_indices)
        return out if batch_first else out.swapaxes(0, 1)
