"""Spatial (diffusers/UNet) fused bias ops.

Reference: ``csrc/spatial/csrc/opt_bias_add.cu`` (bias_add /
bias_add_add / bias_add_bias_add for NHWC activations).  XLA fuses these
elementwise chains into one kernel on TPU; the functions exist so
reference-shaped code keeps its call sites (SURVEY §2.3 maps this row to
"XLA fusion").
"""

import jax.numpy as jnp


def nhwc_bias_add(activation, bias):
    """out = a + bias (bias broadcast over N, H, W)."""
    return activation + bias.reshape((1,) * (activation.ndim - 1) + (-1,))


def nhwc_bias_add_add(activation, bias, other):
    """out = (a + bias) + other."""
    return nhwc_bias_add(activation, bias) + other


def nhwc_bias_add_bias_add(activation, bias, other, other_bias):
    """out = (a + bias) + (other + other_bias)."""
    return nhwc_bias_add(activation, bias) + nhwc_bias_add(other, other_bias)
