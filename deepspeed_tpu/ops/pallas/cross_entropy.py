"""Pallas TPU fused cross-entropy over the unembedding (training loss path).

The XLA path (``models/gpt.py:chunked_cross_entropy``) materializes one
``[rows, V]`` fp32 logits block per chunk plus the one-hot contraction —
at GPT-2 vocab that block is the largest single tensor in the step and
its HBM round-trip is pure bandwidth with no MXU work.  This kernel
streams the vocab dimension in VMEM-resident blocks with the online
(flash-style) softmax recurrence, so neither the ``[N, V]`` logits nor
the one-hot tensor ever exists in HBM: forward emits only the per-row
``nll`` and ``lse`` (two ``[N, 1]`` vectors), and the backward recomputes
each score block from ``(x, head, lse)`` — the exact trade
flash attention makes for the attention scores, applied to the loss.

Parity contract (tested in ``tests/unit/ops/test_pallas_ce.py``): with a
single vocab block the forward performs literally the same op sequence as
``logsumexp`` + one-hot contraction — max, exp-shift, sum, log — so fp32
results are bitwise equal to the reference path; multi-block runs differ
only by the online-softmax rescale rounding (≤ a few ulp).  Masked padded
vocab columns use the same ``-1e9`` sentinel as the reference so the two
paths mask identically.

Env: ``DST_PALLAS_CE`` — ``1``/``on`` force-enables (interpret mode makes
this valid on CPU), ``0``/``off`` disables, unset enables on TPU backends
only.  The wrapper in ``models/gpt.py`` falls back to the reference
implementation whenever :func:`ce_supported` says the shape or mesh
doesn't fit (vocab not a multiple of 128, multi-device mesh — a bare
``pallas_call`` has no SPMD partitioning rule).
"""

import functools
import os
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 spells these ``TPUCompilerParams`` / ``TPUMemorySpace``.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

_ROW_BLOCK = 128          # fp32 sublane-multiple; rows are padded up to it
_VMEM_BLOCK_BYTES = 4 << 20   # budget for one [bv, E] head block in VMEM


def _interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def pallas_ce_enabled() -> bool:
    """Tri-state ``DST_PALLAS_CE``: forced on/off, else on-if-TPU."""
    flag = os.environ.get("DST_PALLAS_CE", "").strip().lower()
    if flag in ("0", "off", "false"):
        return False
    if flag in ("1", "on", "true"):
        return True
    return not _interpret()


def _vocab_block(V: int, E: int) -> Optional[int]:
    for bv in (2048, 1024, 512, 256, 128):
        if V % bv == 0 and bv * max(E, 1) * 4 <= _VMEM_BLOCK_BYTES:
            return bv
    return None


def ce_supported(N: int, E: int, V: int) -> bool:
    """Shape + mesh gate for the fused path.  The kernel handles any row
    count (rows pad to the block) but needs the vocab to tile into lane
    blocks, and runs un-sharded — under a >1-device mesh the vocab is
    tensor-parallel and the reference path (which XLA partitions) wins."""
    if _vocab_block(V, E) is None:
        return False
    from deepspeed_tpu.parallel import mesh as mesh_lib
    if mesh_lib.has_mesh() and not mesh_lib.in_manual_mode():
        if int(np.prod(list(mesh_lib.get_mesh().shape.values()))) > 1:
            return False
    return True


# --------------------------------------------------------------------------- #
# Forward: grid (row blocks, vocab blocks), vocab innermost.  Scratch
# carries the online-softmax state (m, l) plus the label logit across the
# vocab sweep; outputs land on the last vocab step.
# --------------------------------------------------------------------------- #
def _fwd_kernel(x_ref, h_ref, lab_ref, *rest, bn, bv, vocab_size, has_bias):
    if has_bias:
        b_ref, nll_ref, lse_ref, m_s, l_s, ll_s = rest
    else:
        nll_ref, lse_ref, m_s, l_s, ll_s = rest
        b_ref = None
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full((bn, 1), -jnp.inf, jnp.float32)
        l_s[...] = jnp.zeros((bn, 1), jnp.float32)
        ll_s[...] = jnp.zeros((bn, 1), jnp.float32)

    x = x_ref[...]                                       # [bn, E]
    h = h_ref[...]                                       # [bv, E]
    s = jax.lax.dot_general(x, h, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)   # [bn, bv]
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    if has_bias:
        s = s + b_ref[...].astype(jnp.float32)           # [1, bv] broadcast
    if vocab_size is not None:
        # same -1e9 sentinel as the reference path (bitwise-equal masking)
        s = jnp.where(cols < vocab_size, s, -1e9)
    lab = lab_ref[...]                                   # [bn, 1] int32
    m = m_s[...]
    m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    l_new = l_s[...] * alpha + jnp.sum(jnp.exp(s - m_new), axis=1,
                                       keepdims=True)
    ll_new = ll_s[...] + jnp.sum(jnp.where(cols == lab, s, 0.0), axis=1,
                                 keepdims=True)
    m_s[...] = m_new
    l_s[...] = l_new
    ll_s[...] = ll_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _emit():
        lse = m_new + jnp.log(l_new)
        lse_ref[...] = lse
        nll_ref[...] = lse - ll_new


def _fwd_rows(x2, head, head_b, lab2, vocab_size, bn, bv):
    """Per-row (nll, lse) for padded inputs: x2 [Np, E], lab2 [Np, 1]."""
    Np, E = x2.shape
    V = head.shape[0]
    grid = (Np // bn, V // bv)
    in_specs = [
        pl.BlockSpec((bn, E), lambda i, j: (i, 0)),
        pl.BlockSpec((bv, E), lambda i, j: (j, 0)),
        pl.BlockSpec((bn, 1), lambda i, j: (i, 0)),
    ]
    args = [x2, head, lab2]
    if head_b is not None:
        in_specs.append(pl.BlockSpec((1, bv), lambda i, j: (0, j)))
        args.append(head_b.reshape(1, V))
    row_spec = pl.BlockSpec((bn, 1), lambda i, j: (i, 0))
    nll, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, bn=bn, bv=bv, vocab_size=vocab_size,
                          has_bias=head_b is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct((Np, 1), jnp.float32),
                   jax.ShapeDtypeStruct((Np, 1), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((bn, 1), jnp.float32)] * 3,
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args)
    return nll, lse


# --------------------------------------------------------------------------- #
# Backward: two kernels so every output block accumulates over consecutive
# grid steps with the same index (the only legal Pallas accumulation).
# dx grids (rows, vocab) and sums over vocab; dhead grids (vocab, rows)
# and sums over rows.  Both recompute the score block from (x, head, lse).
# --------------------------------------------------------------------------- #
def _score_block(x, h, b_ref, cols, vocab_size):
    s = jax.lax.dot_general(x, h, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
    if b_ref is not None:
        s = s + b_ref[...].astype(jnp.float32)
    if vocab_size is not None:
        s = jnp.where(cols < vocab_size, s, -1e9)
    return s


def _bwd_dx_kernel(x_ref, h_ref, lab_ref, lse_ref, gr_ref, *rest,
                   bn, bv, vocab_size, has_bias):
    if has_bias:
        b_ref, dx_ref = rest
    else:
        (dx_ref,) = rest
        b_ref = None
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dx_ref[...] = jnp.zeros_like(dx_ref)

    cols = j * bv + jax.lax.broadcasted_iota(
        jnp.int32, (x_ref.shape[0], bv), 1)
    s = _score_block(x_ref[...], h_ref[...], b_ref, cols, vocab_size)
    p = jnp.exp(s - lse_ref[...])                         # softmax block
    ds = (p - jnp.where(cols == lab_ref[...], 1.0, 0.0)) * gr_ref[...]
    dx_ref[...] += jax.lax.dot_general(
        ds, h_ref[...].astype(jnp.float32), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _bwd_dh_kernel(x_ref, h_ref, lab_ref, lse_ref, gr_ref, *rest,
                   bn, bv, vocab_size, has_bias):
    if has_bias:
        b_ref, dh_ref, db_ref = rest
    else:
        dh_ref, = rest
        b_ref = db_ref = None
    v = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        dh_ref[...] = jnp.zeros_like(dh_ref)
        if has_bias:
            db_ref[...] = jnp.zeros_like(db_ref)

    cols = v * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    s = _score_block(x_ref[...], h_ref[...], b_ref, cols, vocab_size)
    p = jnp.exp(s - lse_ref[...])
    ds = (p - jnp.where(cols == lab_ref[...], 1.0, 0.0)) * gr_ref[...]
    dh_ref[...] += jax.lax.dot_general(
        ds, x_ref[...].astype(jnp.float32), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if has_bias:
        db_ref[...] += jnp.sum(ds, axis=0, keepdims=True)


def _bwd_rows(x2, head, head_b, lab2, lse, gr, vocab_size, bn, bv):
    Np, E = x2.shape
    V = head.shape[0]
    has_bias = head_b is not None
    kw = dict(bn=bn, bv=bv, vocab_size=vocab_size, has_bias=has_bias)
    row = pl.BlockSpec((bn, 1), lambda i, j: (i, 0))
    common = [
        pl.BlockSpec((bn, E), lambda i, j: (i, 0)),
        pl.BlockSpec((bv, E), lambda i, j: (j, 0)),
        row, row, row,
    ]
    args = [x2, head, lab2, lse, gr]
    bias_args = []
    if has_bias:
        bias_args = [head_b.reshape(1, V)]
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, **kw),
        grid=(Np // bn, V // bv),
        in_specs=common + ([pl.BlockSpec((1, bv), lambda i, j: (0, j))]
                           if has_bias else []),
        out_specs=pl.BlockSpec((bn, E), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((Np, E), jnp.float32),
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args, *bias_args)

    # transposed grid: vocab outer, rows accumulated
    rowT = pl.BlockSpec((bn, 1), lambda v, i: (i, 0))
    commonT = [
        pl.BlockSpec((bn, E), lambda v, i: (i, 0)),
        pl.BlockSpec((bv, E), lambda v, i: (v, 0)),
        rowT, rowT, rowT,
    ]
    out_specs = pl.BlockSpec((bv, E), lambda v, i: (v, 0))
    out_shape = jax.ShapeDtypeStruct((V, E), jnp.float32)
    if has_bias:
        out_specs = [out_specs, pl.BlockSpec((1, bv), lambda v, i: (0, v))]
        out_shape = [out_shape, jax.ShapeDtypeStruct((1, V), jnp.float32)]
    dh = pl.pallas_call(
        functools.partial(_bwd_dh_kernel, **kw),
        grid=(V // bv, Np // bn),
        in_specs=commonT + ([pl.BlockSpec((1, bv), lambda v, i: (0, v))]
                            if has_bias else []),
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args, *bias_args)
    if has_bias:
        dh, db = dh
        return dx, dh, db.reshape(V)
    return dx, dh, None


# --------------------------------------------------------------------------- #
# custom_vjp wrapper (mean NLL over the valid rows)
# --------------------------------------------------------------------------- #
def _pad_rows(x2, lab, N, bn):
    n_pad = (-N) % bn
    if n_pad:
        x2 = jnp.concatenate([x2, jnp.zeros((n_pad, x2.shape[1]), x2.dtype)])
        lab = jnp.concatenate([lab, jnp.zeros((n_pad,), lab.dtype)])
    return x2, lab.reshape(-1, 1).astype(jnp.int32), N + n_pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ce(x2, head, head_b, labels, vocab_size, bn, bv):
    nll, _ = _ce_fwd(x2, head, head_b, labels, vocab_size, bn, bv)
    return nll


def _ce_fwd(x2, head, head_b, labels, vocab_size, bn, bv):
    N = x2.shape[0]
    xp, lp, Np = _pad_rows(x2, labels, N, bn)
    nll, lse = _fwd_rows(xp, head, head_b, lp, vocab_size, bn, bv)
    # mean over the REAL rows only; the slice-then-mean matches the
    # reference's jnp.mean(lse - ll) lowering for bitwise fp32 parity
    loss = jnp.mean(nll[:N, 0])
    return loss, (x2, head, head_b, labels, lse)


def _ce_bwd(vocab_size, bn, bv, res, g):
    x2, head, head_b, labels, lse = res
    N, E = x2.shape
    xp, lp, Np = _pad_rows(x2, labels, N, bn)
    # d(mean)/d(nll_i) = g / N on valid rows, 0 on the padding
    rows = jnp.arange(Np)[:, None]
    gr = jnp.where(rows < N, g / N, 0.0).astype(jnp.float32)
    dx, dh, db = _bwd_rows(xp, head, head_b, lp, lse, gr, vocab_size, bn, bv)
    dx = dx[:N].astype(x2.dtype)
    dh = dh.astype(head.dtype)
    db = None if head_b is None else db.astype(head_b.dtype)
    # labels are integral: their cotangent is the zero-sized float0 tangent
    dlab = np.zeros(labels.shape, jax.dtypes.float0)
    return dx, dh, db, dlab


_ce.defvjp(_ce_fwd, _ce_bwd)


def fused_cross_entropy(x2, head, labels, vocab_size: int,
                        head_b=None) -> jax.Array:
    """Mean next-token NLL without materializing logits.

    x2: [N, E] hidden rows; head: [V, E]; labels: [N] int; ``vocab_size``
    masks padded vocab columns (same ``-1e9`` sentinel as the reference).
    Differentiable in x2/head/head_b via the streaming backward kernels.
    """
    V, E = head.shape
    bv = _vocab_block(V, E)
    if bv is None:
        raise ValueError(f"fused CE unsupported for V={V} (call "
                         "ce_supported() first)")
    mask = vocab_size if V != vocab_size else None
    return _ce(x2, head, head_b, labels, mask, _ROW_BLOCK, bv)
