"""Pallas TPU flash attention (training fast path, forward + backward).

TPU-native replacement for the reference's fused attention CUDA kernels
(``csrc/transformer/softmax_kernels.cu`` and the strided-batch-gemm pipeline
of ``csrc/transformer/ds_transformer_cuda.cpp``).  Online-softmax tiling:
O(S) memory, MXU-shaped [128, head_dim] tiles, fp32 accumulation, bf16
operands.

Capabilities beyond the round-3 kernel:

* **Grouped-query attention** — K/V may carry ``Hkv < H`` heads
  (``H % Hkv == 0``).  The kernel maps query head ``h`` onto KV head
  ``h // (H//Hkv)`` via the BlockSpec index map, so grouped K/V are never
  materialized at full head count (the reference expands on the host;
  round 3 expanded in ``models/gpt.py:_expand_kv`` — both pay HBM for it).
  The backward dK/dV kernel grids over *KV* heads and accumulates the
  group's query heads in-register.

* **In-kernel ALiBi** — ``alibi`` takes the per-head slopes (an [H] vector,
  O(H) memory) and the kernel computes ``slope * (k_pos - q_pos)`` from
  iotas on the VPU: zero HBM traffic for the bias, so BLOOM-style models
  ride the flash path at any sequence length.  The reference bakes alibi
  into its softmax kernel the same way
  (``csrc/transformer/inference/csrc/softmax.cu``).

* **Additive logit bias** — an optional dense ``bias`` operand
  broadcastable to ``[B, H, S, S]`` (relative-position bias and other
  non-ALiBi biases), added to the scaled scores before the online softmax.
  Inherently O(S^2) HBM (the caller materialized it); prefer ``alibi``
  when the bias is ALiBi-shaped.  Both bias forms are CONSTANTS under
  differentiation: gradients flow to q/k/v but not to the bias (a learned
  T5-style bias would need an O(S^2) dbias output that defeats flash
  memory scaling).

Layout convention here is [batch, heads, seq, head_dim]; the public wrapper
(`flash_attention`) takes the framework-wide [batch, seq, heads, head_dim].

Mosaic layout notes (learned the hard way — round 1 shipped an lse output
of shape [B, H, S] with block (1, 1, bq), which Mosaic rejects because the
second-to-last block dim (1) is neither a multiple of the sublane tile nor
equal to H): every operand/result carries the row-statistics (lse, delta)
as [B, H, S, 1] so the trailing two block dims (bq, 1) are (sublane-multiple,
full-dim) — always legal.

SPMD: ``pallas_call`` has no partitioning rule, so the public wrapper runs
the kernel under ``shard_map`` over the batch (data/fsdp/expert) and head
(seq × tensor) mesh axes whenever a global mesh is active.  Putting the
``seq`` axis on the HEAD dim (sequence replicated inside the kernel) makes
the wrapper itself the Ulysses all-to-all: activations arriving
sequence-sharded are re-sharded by jit to head-sharded full-sequence form,
the exact re-shard ``parallel/sequence.py:ulysses_attention`` expresses as
sharding constraints.  Ring attention (O(S/sp) memory) remains the explicit
alternative for sequences too long to replicate per-device.

``interpret=True`` (automatic off-TPU) runs the same kernels through the
Pallas interpreter so CPU CI validates them against the jnp reference — the
analogue of the reference's kernel-vs-HF-modeling parity tests
(``tests/unit/ops/accelerators/test_accelerator_forward.py``).
"""

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

# jax < 0.5 spells these ``TPUCompilerParams`` / ``TPUMemorySpace``.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
_MEMSPACE = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace

_PARALLEL3 = _COMPILER_PARAMS(
    dimension_semantics=("parallel", "parallel", "parallel"))


def _interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


#: (q_shape, reason-class) combos already warned about — the demotion is
#: per-call, the telemetry warning one-shot so a training loop doesn't
#: log once per step
_FALLBACK_WARNED = set()


def _fallback_warn_once(shape, reason: str) -> None:
    key = (tuple(shape), reason.split(":")[0])
    if key in _FALLBACK_WARNED:
        return
    _FALLBACK_WARNED.add(key)
    from deepspeed_tpu.utils.logging import logger
    logger.warning("flash_attention %s: %s — demoting to reference "
                   "attention (further occurrences silenced)", tuple(shape),
                   reason)


def _block_sizes(S: int, bq: Optional[int], bk: Optional[int]):
    """Default blocks: largest divisor of S up to 256 (q) / 512 (k) —
    measured on v5e (r5): (256, 512) beats (128, 128) ~2.3x end-to-end at
    S=512 (fewer online-softmax rescales, larger MXU tiles) and also wins
    at S=1024 over (256, 1024).

    Requested sizes (user/env) are CLAMPED to the largest divisor of S at
    most the request — never asserted on — so an odd S degrades to a
    smaller block or to the reference fallback instead of crashing.  For
    S below the cap this yields the full-S block, which is always a legal
    Mosaic tile (the round-1 ``(1, 1, 128)`` cliff came from divisor
    hunting down to sub-sublane blocks like bq=1 at small prime S)."""
    def fit(req: Optional[int], cap: int) -> int:
        b = min(req or cap, cap, S)
        while S % b:
            b -= 1
        return b
    return fit(bq, 256), fit(bk, 512)


def _blocks_lowerable(S: int, bq: int, bk: int) -> bool:
    """Mosaic tiling: a block's second-to-last dim must be a sublane
    multiple (8 for fp32) or span the full extent.  The last dim is the
    head extent D, which is always the full dim, so only bq/bk gate."""
    return all(b == S or b % 8 == 0 for b in (bq, bk))


def _bias_spec_qrows(bias, bq, S):
    """BlockSpec for a [Bb, Hb, S, S] bias on the (b, h, i)-gridded kernels
    (q-block rows, full-S columns), honoring batch/head broadcast."""
    bsel = (lambda b: b) if bias.shape[0] > 1 else (lambda b: 0)
    hsel = (lambda h: h) if bias.shape[1] > 1 else (lambda h: 0)
    return pl.BlockSpec((1, 1, bq, S), lambda b, h, i: (bsel(b), hsel(h), i, 0))


def _bias_spec_kcols(bias, group, bk, S):
    """BlockSpec for the dKV kernel's (b, h_kv, j) grid: full-S q rows,
    KV-block columns, the query-head group stacked in dim 1 (or broadcast)."""
    bsel = (lambda b: b) if bias.shape[0] > 1 else (lambda b: 0)
    if bias.shape[1] > 1:
        return pl.BlockSpec((1, group, S, bk), lambda b, h, j: (bsel(b), h, 0, j))
    return pl.BlockSpec((1, 1, S, bk), lambda b, h, j: (bsel(b), 0, 0, j))


# --------------------------------------------------------------------------- #
# Forward
# --------------------------------------------------------------------------- #
def _fwd_kernel(*refs, scale, causal, bq, bk, S, has_bias, has_alibi):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    n = 3
    b_ref = refs[n] if has_bias else None
    n += has_bias
    a_ref = refs[n] if has_alibi else None
    n += has_alibi
    o_ref, lse_ref = refs[n:]
    qi = pl.program_id(2)
    # operands stay in their storage dtype (bf16): the MXU runs bf16 x bf16
    # with f32 accumulation (preferred_element_type) at full rate — casting
    # inputs to f32 first would drop matmul throughput ~8x on v5e
    q = q_ref[0, 0]                       # [bq, D]
    D = q.shape[-1]
    slope = a_ref[pl.program_id(1)] if has_alibi else None

    if causal:
        num_kb = pl.cdiv((qi + 1) * bq, bk)
    else:
        num_kb = S // bk

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * bk, bk), :]   # [bk, D]
        v = v_ref[0, 0, pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if has_bias:
            s = s + b_ref[0, 0, :, pl.ds(j * bk, bk)].astype(jnp.float32)
        if causal or has_alibi:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if has_alibi:   # slope * (k_pos - q_pos), computed on the VPU
            s = s + slope * (cols - rows).astype(jnp.float32)
        if causal:
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, a0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)        # [bq, 1]


def _fwd(q, k, v, bias, slopes, *, causal, scale, bq=None, bk=None):
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    group = H // Hkv
    bq, bk = _block_sizes(S, bq, bk)
    grid = (B, H, S // bq)
    kv_spec = pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // group, 0, 0))
    in_specs = [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
        kv_spec, kv_spec,
    ]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(_bias_spec_qrows(bias, bq, S))
        args.append(bias)
    if slopes is not None:
        in_specs.append(pl.BlockSpec(memory_space=_MEMSPACE.SMEM))
        args.append(slopes)
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
                          S=S, has_bias=bias is not None,
                          has_alibi=slopes is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        compiler_params=_PARALLEL3,
        interpret=_interpret(),
    )(*args)
    return o, lse


# --------------------------------------------------------------------------- #
# Backward
# --------------------------------------------------------------------------- #
def _bwd_dq_kernel(*refs, scale, causal, bq, bk, S, has_bias, has_alibi):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    n = 6
    b_ref = refs[n] if has_bias else None
    n += has_bias
    a_ref = refs[n] if has_alibi else None
    n += has_alibi
    dq_ref = refs[n]
    qi = pl.program_id(2)
    q = q_ref[0, 0]                       # storage dtype: bf16 MXU operands
    do = do_ref[0, 0]
    lse = lse_ref[0, 0]                   # [bq, 1]
    delta = delta_ref[0, 0]               # [bq, 1]
    D = q.shape[-1]
    slope = a_ref[pl.program_id(1)] if has_alibi else None

    num_kb = pl.cdiv((qi + 1) * bq, bk) if causal else S // bk

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * bk, bk), :]
        v = v_ref[0, 0, pl.ds(j * bk, bk), :]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if has_bias:
            s = s + b_ref[0, 0, :, pl.ds(j * bk, bk)].astype(jnp.float32)
        if causal or has_alibi:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        if has_alibi:
            s = s + slope * (cols - rows).astype(jnp.float32)
        if causal:
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                                   # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros((bq, D), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, bq, bk, S, group, has_bias,
                    bias_per_head, has_alibi):
    """Grid (B, Hkv, S//bk): one KV block per step, accumulating dK/dV over
    the ``group`` query heads that attend to this KV head."""
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    n = 6
    b_ref = refs[n] if has_bias else None
    n += has_bias
    a_ref = refs[n] if has_alibi else None
    n += has_alibi
    dk_ref, dv_ref = refs[n:]
    ki = pl.program_id(2)
    # program_id must bind at kernel top level (not inside the fori_loop
    # body, where interpret mode can't re-associate it with the grid)
    hk = pl.program_id(1)
    k = k_ref[0, 0]                       # storage dtype: bf16 MXU operands
    v = v_ref[0, 0]
    D = k.shape[-1]
    num_qb = S // bq
    start_qb = (ki * bk) // bq if causal else 0

    dk = jnp.zeros((bk, D), jnp.float32)
    dv = jnp.zeros((bk, D), jnp.float32)
    for g in range(group):      # static unroll over the query-head group
        slope = a_ref[hk * group + g] if has_alibi else None

        def body(i, carry, g=g, slope=slope):
            dk, dv = carry
            q = q_ref[0, g, pl.ds(i * bq, bq), :]
            do = do_ref[0, g, pl.ds(i * bq, bq), :]
            lse = lse_ref[0, g, pl.ds(i * bq, bq), :]       # [bq, 1]
            delta = delta_ref[0, g, pl.ds(i * bq, bq), :]   # [bq, 1]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if has_bias:
                gb = g if bias_per_head else 0
                s = s + b_ref[0, gb, pl.ds(i * bq, bq), :].astype(jnp.float32)
            if causal or has_alibi:
                rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
                cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            if has_alibi:
                s = s + slope * (cols - rows).astype(jnp.float32)
            if causal:
                s = jnp.where(rows >= cols, s, NEG_INF)
            p = jnp.exp(s - lse)                                    # [bq, bk]
            pc = p.astype(do.dtype)
            dv = dv + jax.lax.dot_general(pc, do, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta) * scale).astype(q.dtype)         # [bq, bk]
            dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)
            return dk, dv

        dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, (dk, dv))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def flash_block_bwd(q, k, v, do, lse, delta, bias=None, slopes=None, *,
                    causal, scale, bq=None, bk=None):
    """Backward kernels against an EXTERNAL softmax normalizer: ``lse`` is
    the (global) log-sum-exp [B, H, S, 1] and ``delta = sum(do * o)``
    [B, H, S, 1].  Returns (dq, dk, dv).  This is the flash backward body —
    exposed separately so ring attention (``parallel/sequence.py``) can use
    it per KV hop with the final merged lse, which makes the distributed
    backward exact without storing per-hop probabilities."""
    B, H, S, D = q.shape
    Hkv = k.shape[1]
    group = H // Hkv
    bq_, bk_ = _block_sizes(S, bq, bk)

    qspec = pl.BlockSpec((1, 1, bq_, D), lambda b, h, i: (b, h, i, 0))
    kv_full = pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h // group, 0, 0))
    vec_q = pl.BlockSpec((1, 1, bq_, 1), lambda b, h, i: (b, h, i, 0))

    dq_in = [q, k, v, do, lse, delta]
    dq_specs = [qspec, kv_full, kv_full, qspec, vec_q, vec_q]
    if bias is not None:
        dq_in.append(bias)
        dq_specs.append(_bias_spec_qrows(bias, bq_, S))
    if slopes is not None:
        dq_in.append(slopes)
        dq_specs.append(pl.BlockSpec(memory_space=_MEMSPACE.SMEM))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, bq=bq_,
                          bk=bk_, S=S, has_bias=bias is not None,
                          has_alibi=slopes is not None),
        grid=(B, H, S // bq_),
        in_specs=dq_specs,
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        compiler_params=_PARALLEL3,
        interpret=_interpret(),
    )(*dq_in)

    # dK/dV: grid over KV heads; q/do/lse/delta delivered group-at-a-time
    kspec = pl.BlockSpec((1, 1, bk_, D), lambda b, h, j: (b, h, j, 0))
    q_grp = pl.BlockSpec((1, group, S, D), lambda b, h, j: (b, h, 0, 0))
    vec_grp = pl.BlockSpec((1, group, S, 1), lambda b, h, j: (b, h, 0, 0))
    dkv_in = [q, k, v, do, lse, delta]
    dkv_specs = [q_grp, kspec, kspec, q_grp, vec_grp, vec_grp]
    bias_per_head = bias is not None and bias.shape[1] > 1
    if bias is not None:
        dkv_in.append(bias)
        dkv_specs.append(_bias_spec_kcols(bias, group, bk_, S))
    if slopes is not None:
        dkv_in.append(slopes)
        dkv_specs.append(pl.BlockSpec(memory_space=_MEMSPACE.SMEM))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, bq=bq_,
                          bk=bk_, S=S, group=group, has_bias=bias is not None,
                          bias_per_head=bias_per_head,
                          has_alibi=slopes is not None),
        grid=(B, Hkv, S // bk_),
        in_specs=dkv_specs,
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((B, Hkv, S, D), k.dtype),
                   jax.ShapeDtypeStruct((B, Hkv, S, D), v.dtype)],
        compiler_params=_PARALLEL3,
        interpret=_interpret(),
    )(*dkv_in)
    return dq, dk, dv


# [B, H, S, D] forward returning (o, lse) — the ring-attention hop body.
flash_block_fwd = _fwd


def _bwd(causal, scale, bq, bk, res, do):
    q, k, v, bias, slopes, o, lse = res
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)                     # [B,H,S,1]
    dq, dk, dv = flash_block_bwd(q, k, v, do, lse, delta, bias, slopes,
                                 causal=causal, scale=scale, bq=bq, bk=bk)
    # both bias forms are constants under differentiation (module docstring)
    db = None if bias is None else jnp.zeros_like(bias)
    da = None if slopes is None else jnp.zeros_like(slopes)
    return dq, dk, dv, db, da


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash(q, k, v, bias, slopes, causal, scale, bq, bk):
    o, _ = _fwd(q, k, v, bias, slopes, causal=causal, scale=scale, bq=bq, bk=bk)
    return o


def _flash_fwd(q, k, v, bias, slopes, causal, scale, bq, bk):
    o, lse = _fwd(q, k, v, bias, slopes, causal=causal, scale=scale, bq=bq, bk=bk)
    # named for remat: without these tags every jax.checkpoint policy
    # replays the whole forward kernel in the backward pass just to
    # rebuild (o, lse) — ~25% extra attention time for O(B·S·H·D) memory
    # (profiled r5: two identical fwd custom-calls per step under
    # dots_saveable).  checkpointing.checkpoint_policy() saves these names.
    from jax.ad_checkpoint import checkpoint_name
    o = checkpoint_name(o, "flash_o")
    lse = checkpoint_name(lse, "flash_lse")
    return o, (q, k, v, bias, slopes, o, lse)


_flash.defvjp(_flash_fwd, _bwd)


def _flash_bshd(q, k, v, bias, slopes, causal, scale, bq, bk):
    """[B,S,H,D] wrapper around the [B,H,S,D] kernel (grouped-KV aware)."""
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o = _flash(qt, kt, vt, bias, slopes, causal, scale, bq, bk)
    return o.transpose(0, 2, 1, 3)


def flash_attention(q, k, v, *, causal: bool = True, bias=None, alibi=None,
                    block_q: Optional[int] = None, block_k: Optional[int] = None):
    """[batch, seq, heads, head_dim] flash attention (differentiable).

    ``k``/``v`` may carry fewer heads than ``q`` (GQA/MQA; ``H % Hkv == 0``)
    — the kernel indexes grouped KV directly, no host-side expansion.
    ``alibi`` is the per-head slope vector [H]; the kernel synthesizes the
    ALiBi bias from iotas (O(H) memory).  ``bias`` is a dense additive
    logit bias broadcastable to [B, H, S, S].  Both are constants under
    differentiation.

    Under an active mesh the kernel runs inside ``shard_map`` with batch
    sharded over the data/fsdp/expert axes and heads over seq × tensor
    (sequence-sharded inputs are thereby Ulysses-re-sharded to full-seq,
    split-head form before the kernel — see module docstring)."""
    from deepspeed_tpu.ops.attention import canonical_bias
    block_q = block_q or int(os.environ.get("DST_FLASH_BQ", "0")) or None
    block_k = block_k or int(os.environ.get("DST_FLASH_BK", "0")) or None
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    block_q, block_k = _block_sizes(S, block_q, block_k)
    if not _blocks_lowerable(S, block_q, block_k) or H % Hkv != 0:
        # e.g. S=1000: largest divisor ≤256 is 250 — neither a sublane
        # multiple nor full-S, so the tile can't lower; take the jnp path
        _fallback_warn_once(q.shape, f"blocks ({block_q},{block_k}) for "
                            f"S={S} are not lowerable")
        from deepspeed_tpu.ops.attention import reference_attention
        return reference_attention(q, k, v, causal=causal, bias=bias, alibi=alibi)
    scale = 1.0 / np.sqrt(D)
    bias = canonical_bias(bias)
    if bias is not None:
        bias = jnp.broadcast_to(
            bias, (bias.shape[0], bias.shape[1], S, S)).astype(jnp.float32)
    slopes = None
    if alibi is not None:
        slopes = jnp.asarray(alibi, jnp.float32).reshape(H)

    from deepspeed_tpu.parallel import mesh as mesh_lib
    if mesh_lib.has_mesh() and not mesh_lib.in_manual_mode():
        mesh = mesh_lib.get_mesh()
        batch_div = int(np.prod([mesh.shape[a] for a in mesh_lib.BATCH_AXES]))
        head_div = int(mesh.shape["tensor"] * mesh.shape["seq"])
        if batch_div > 1 or head_div > 1:
            if B % batch_div != 0 or H % head_div != 0 or Hkv % head_div != 0:
                # a bare pallas_call has no SPMD partitioning rule; on shapes
                # the shard_map can't split, use the jnp path XLA can shard
                from deepspeed_tpu.ops.attention import reference_attention
                return reference_attention(q, k, v, causal=causal, bias=bias,
                                           alibi=alibi)
            spec = P(mesh_lib.BATCH_AXES, None, ("seq", "tensor"), None)
            in_specs = [spec, spec, spec]
            args = [q, k, v]
            if bias is not None:
                in_specs.append(P(mesh_lib.BATCH_AXES if bias.shape[0] > 1 else None,
                                  ("seq", "tensor") if bias.shape[1] > 1 else None,
                                  None, None))
                args.append(bias)
            if slopes is not None:
                in_specs.append(P(("seq", "tensor")))
                args.append(slopes)
            nb, ns = bias is not None, slopes is not None

            def inner(q, k, v, *rest):
                b = rest[0] if nb else None
                sl = rest[-1] if ns else None
                return _flash_bshd(q, k, v, b, sl, causal, scale, block_q, block_k)

            from deepspeed_tpu.parallel.mesh import shard_map
            return shard_map(inner, mesh=mesh, in_specs=tuple(in_specs),
                             out_specs=spec, check_vma=False)(*args)
    try:
        return _flash_bshd(q, k, v, bias, slopes, causal, scale,
                           block_q, block_k)
    except Exception as e:  # Mosaic lowering failure → demote, don't wedge
        _fallback_warn_once(q.shape, f"kernel lowering failed: {e}")
        from deepspeed_tpu.ops.attention import reference_attention
        return reference_attention(q, k, v, causal=causal, bias=bias,
                                   alibi=alibi)
