"""Pallas TPU flash attention (training fast path, forward + backward).

TPU-native replacement for the reference's fused attention CUDA kernels
(``csrc/transformer/softmax_kernels.cu`` and the strided-batch-gemm pipeline
of ``csrc/transformer/ds_transformer_cuda.cpp``).  Online-softmax tiling:
O(S) memory, MXU-shaped [128, head_dim] tiles, fp32 accumulation, bf16
operands.

Layout convention here is [batch, heads, seq, head_dim]; the public wrapper
(`flash_attention`) takes the framework-wide [batch, seq, heads, head_dim].

Mosaic layout notes (learned the hard way — round 1 shipped an lse output
of shape [B, H, S] with block (1, 1, bq), which Mosaic rejects because the
second-to-last block dim (1) is neither a multiple of the sublane tile nor
equal to H): every operand/result carries the row-statistics (lse, delta)
as [B, H, S, 1] so the trailing two block dims (bq, 1) are (sublane-multiple,
full-dim) — always legal.

SPMD: ``pallas_call`` has no partitioning rule, so the public wrapper runs
the kernel under ``shard_map`` over the batch (data/fsdp/expert) and head
(seq × tensor) mesh axes whenever a global mesh is active.  Putting the
``seq`` axis on the HEAD dim (sequence replicated inside the kernel) makes
the wrapper itself the Ulysses all-to-all: activations arriving
sequence-sharded are re-sharded by jit to head-sharded full-sequence form,
the exact re-shard ``parallel/sequence.py:ulysses_attention`` expresses as
sharding constraints.  Ring attention (O(S/sp) memory) remains the explicit
alternative for sequences too long to replicate per-device.

``interpret=True`` (automatic off-TPU) runs the same kernels through the
Pallas interpreter so CPU CI validates them against the jnp reference — the
analogue of the reference's kernel-vs-HF-modeling parity tests
(``tests/unit/ops/accelerators/test_accelerator_forward.py``).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

_PARALLEL3 = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "parallel"))


def _interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def _block_sizes(S: int, bq: Optional[int], bk: Optional[int]):
    bq = bq or min(128, S)
    bk = bk or min(128, S)
    assert S % bq == 0 and S % bk == 0, f"seq {S} not divisible by blocks {bq}/{bk}"
    return bq, bk


# --------------------------------------------------------------------------- #
# Forward
# --------------------------------------------------------------------------- #
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, bq, bk, S):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
    D = q.shape[-1]

    if causal:
        num_kb = pl.cdiv((qi + 1) * bq, bk)
    else:
        num_kb = S // bk

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)   # [bk, D]
        v = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                                preferred_element_type=jnp.float32)
        return m_new, l, acc

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    a0 = jnp.zeros((bq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, a0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)        # [bq, 1]


def _fwd(q, k, v, *, causal, scale, bq=None, bk=None):
    B, H, S, D = q.shape
    bq, bk = _block_sizes(S, bq, bk)
    grid = (B, H, S // bq)
    kv_spec = pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h, 0, 0))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, S=S),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            kv_spec, kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        compiler_params=_PARALLEL3,
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------- #
# Backward
# --------------------------------------------------------------------------- #
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, bq, bk, S):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]                   # [bq, 1]
    delta = delta_ref[0, 0]               # [bq, 1]
    D = q.shape[-1]

    num_kb = pl.cdiv((qi + 1) * bq, bk) if causal else S // bk

    def body(j, dq):
        k = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                                   # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        return dq + jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros((bq, D), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, bq, bk, S):
    ki = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)   # [bk, D]
    v = v_ref[0, 0].astype(jnp.float32)
    D = k.shape[-1]
    num_qb = S // bq
    start_qb = (ki * bk) // bq if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(i * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * bq, bq), :]       # [bq, 1]
        delta = delta_ref[0, 0, pl.ds(i * bq, bq), :]   # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)                                    # [bq, bk]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale                           # [bq, bk]
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((bk, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(start_qb, num_qb, body, (z, z))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _bwd(causal, scale, bq, bk, res, do):
    q, k, v, o, lse = res
    B, H, S, D = q.shape
    bq_, bk_ = _block_sizes(S, bq, bk)
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)                     # [B,H,S,1]

    qspec = pl.BlockSpec((1, 1, bq_, D), lambda b, h, i: (b, h, i, 0))
    full = pl.BlockSpec((1, 1, S, D), lambda b, h, i: (b, h, 0, 0))
    vec_q = pl.BlockSpec((1, 1, bq_, 1), lambda b, h, i: (b, h, i, 0))
    vec_full = pl.BlockSpec((1, 1, S, 1), lambda b, h, i: (b, h, 0, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, bq=bq_, bk=bk_, S=S),
        grid=(B, H, S // bq_),
        in_specs=[qspec, full, full, qspec, vec_q, vec_q],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        compiler_params=_PARALLEL3,
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)

    kspec = pl.BlockSpec((1, 1, bk_, D), lambda b, h, j: (b, h, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, bq=bq_, bk=bk_, S=S),
        grid=(B, H, S // bk_),
        in_specs=[full, kspec, kspec, full, vec_full, vec_full],
        out_specs=[kspec, kspec],
        out_shape=[jax.ShapeDtypeStruct((B, H, S, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, S, D), v.dtype)],
        compiler_params=_PARALLEL3,
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, bq, bk):
    o, _ = _fwd(q, k, v, causal=causal, scale=scale, bq=bq, bk=bk)
    return o


def _flash_fwd(q, k, v, causal, scale, bq, bk):
    o, lse = _fwd(q, k, v, causal=causal, scale=scale, bq=bq, bk=bk)
    return o, (q, k, v, o, lse)


_flash.defvjp(_flash_fwd, _bwd)


def _flash_bshd(q, k, v, causal, scale, bq, bk):
    """[B,S,H,D] wrapper around the [B,H,S,D] kernel."""
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o = _flash(qt, kt, vt, causal, scale, bq, bk)
    return o.transpose(0, 2, 1, 3)


def flash_attention(q, k, v, *, causal: bool = True,
                    block_q: Optional[int] = None, block_k: Optional[int] = None):
    """[batch, seq, heads, head_dim] flash attention (differentiable).

    Under an active mesh the kernel runs inside ``shard_map`` with batch
    sharded over the data/fsdp/expert axes and heads over seq × tensor
    (sequence-sharded inputs are thereby Ulysses-re-sharded to full-seq,
    split-head form before the kernel — see module docstring)."""
    B, S, H, D = q.shape
    if S % min(128, S) != 0:
        from deepspeed_tpu.ops.attention import reference_attention
        return reference_attention(q, k, v, causal=causal)
    scale = 1.0 / np.sqrt(D)

    from deepspeed_tpu.parallel import mesh as mesh_lib
    if mesh_lib.has_mesh() and not mesh_lib.in_manual_mode():
        mesh = mesh_lib.get_mesh()
        batch_div = int(np.prod([mesh.shape[a] for a in mesh_lib.BATCH_AXES]))
        head_div = int(mesh.shape["tensor"] * mesh.shape["seq"])
        if batch_div > 1 or head_div > 1:
            if B % batch_div != 0 or H % head_div != 0:
                # a bare pallas_call has no SPMD partitioning rule; on shapes
                # the shard_map can't split, use the jnp path XLA can shard
                from deepspeed_tpu.ops.attention import reference_attention
                return reference_attention(q, k, v, causal=causal)
            spec = P(mesh_lib.BATCH_AXES, None, ("seq", "tensor"), None)
            inner = functools.partial(_flash_bshd, causal=causal, scale=scale,
                                      bq=block_q, bk=block_k)
            return jax.shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec),
                                 out_specs=spec, check_vma=False)(q, k, v)
    return _flash_bshd(q, k, v, causal, scale, block_q, block_k)
