"""Pallas TPU fused Adam/AdamW update (training step hot path).

The unfused step is an optax chain traced per leaf: XLA emits separate
moment-update, bias-correction, decay and axpy loops, each re-reading the
leaf from HBM.  This kernel does the whole update for one leaf block —
param, grad, m, v in, param/m/v out — in a single VMEM pass with the
loss-scale unscale and the clip factor folded in as SMEM scalars, which
is what lets the offload-chunked walk in ``runtime/engine.py`` update
chunk N while chunk N+1's NVMe swap-in is still in flight (the per-leaf
launch has no dependency on the rest of the tree).

Parity contract (``tests/unit/runtime/test_fused_optim.py``): bitwise
equality with the optax chain in fp32 — the kernel performs the exact
optax 0.2.x op sequence (``(1-b)*g + b*m``, safe int32 count increment,
``m/bc1 / (sqrt(n/bc2) + eps)``, decay-after for AdamW, ``-lr`` scale)
with the same scalar promotion, so there is no tolerance to tune.

Supported chains: ``optax.adamw`` (static lr or schedule) and
``optax.adam`` — i.e. the factory's adam/fusedadam/cpuadam/adamw with
``adam_w_mode`` (the default).  Anything else (``add_decayed_weights``
*before* adam = L2 mode, lamb, onebit, client chains) makes
:func:`match_adam_chain` return ``None`` and the engine keeps the optax
path.  Env: ``DST_PALLAS_FUSED_OPT`` — ``1`` forces (interpret mode on
CPU), ``0`` disables, unset enables on TPU.
"""

import functools
import os
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 spells these ``TPUCompilerParams`` / ``TPUMemorySpace``.
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams
_MEMSPACE = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace

_LANE = 128
_SUBLANE = 8
_INT32_MAX = jnp.iinfo(jnp.int32).max


def _interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def fused_opt_enabled() -> bool:
    """Tri-state ``DST_PALLAS_FUSED_OPT``: forced on/off, else on-if-TPU."""
    flag = os.environ.get("DST_PALLAS_FUSED_OPT", "").strip().lower()
    if flag in ("0", "off", "false"):
        return False
    if flag in ("1", "on", "true"):
        return True
    return not _interpret()


# --------------------------------------------------------------------------- #
# Spec + state-shape matching
# --------------------------------------------------------------------------- #
def spec_from_config(name: str, params: Dict[str, Any],
                     lr: Union[float, Callable[[int], float]]
                     ) -> Optional[Dict[str, Any]]:
    """Fusion spec for a ds_config optimizer block, or ``None`` when the
    resulting optax chain isn't a decay-after Adam (the only math this
    kernel implements)."""
    name = (name or "adam").lower()
    if name not in ("adam", "adamw", "fusedadam", "cpuadam"):
        return None
    adam_w = bool(params.get("adam_w_mode", True)) or name == "adamw"
    wd = float(params.get("weight_decay", 0.0))
    if not adam_w and wd:
        return None      # L2 mode: decay feeds the moments; different math
    betas = params.get("betas", (0.9, 0.999))
    return {"b1": float(betas[0]), "b2": float(betas[1]),
            "eps": float(params.get("eps", 1e-8)),
            "wd": wd if adam_w else 0.0, "lr": lr}


def match_adam_chain(opt_state) -> Optional[Tuple[int, Optional[int]]]:
    """``(adam_idx, schedule_idx)`` into the chain's state tuple, or
    ``None`` when the structure isn't optax adam/adamw: exactly one
    ScaleByAdamState, at most one ScaleByScheduleState, all other links
    stateless."""
    if not isinstance(opt_state, tuple) or isinstance(opt_state, jnp.ndarray):
        return None
    adam_idx = sched_idx = None
    for i, s in enumerate(opt_state):
        fields = getattr(s, "_fields", None)
        if fields is None:
            return None
        if "mu" in fields and "nu" in fields and "count" in fields:
            if adam_idx is not None:
                return None
            adam_idx = i
        elif "count" in fields:
            if sched_idx is not None:
                return None
            sched_idx = i
        elif len(fields):
            return None
    if adam_idx is None:
        return None
    return adam_idx, sched_idx


def _safe_int32_increment(count):
    # optax.safe_int32_increment — saturates instead of wrapping
    return jnp.where(count < _INT32_MAX, count + 1, _INT32_MAX)


def step_scalars(spec: Dict[str, Any], count, sched_count=None):
    """(neg_lr, bc1, bc2) for this step, matching optax's promotion: the
    bias corrections are ``1 - b**count_inc`` in f32, the step size is
    ``-1 * lr(count)`` (schedule) or the static ``-lr``."""
    count_inc = _safe_int32_increment(count)
    bc1 = (1.0 - spec["b1"] ** count_inc).astype(jnp.float32)
    bc2 = (1.0 - spec["b2"] ** count_inc).astype(jnp.float32)
    lr = spec["lr"]
    if callable(lr):
        sc = count if sched_count is None else sched_count
        neg_lr = jnp.asarray(-1 * lr(sc), jnp.float32)
    else:
        neg_lr = jnp.asarray(-lr, jnp.float32)
    return neg_lr, bc1, bc2


# --------------------------------------------------------------------------- #
# Kernel: one [rows, 128] leaf block per grid step.  scal (SMEM) =
# [inv, clip_factor, neg_lr, bc1, bc2]; inv/clip fold the loss-scale
# unscale and the grad clip so raw accumulated grads can feed the kernel
# with the exact ``(g*inv)*factor`` op order of the unfused path.
# --------------------------------------------------------------------------- #
def _adam_kernel(scal_ref, p_ref, g_ref, mu_ref, nu_ref,
                 op_ref, omu_ref, onu_ref, *, b1, b2, eps, wd):
    g = (g_ref[...].astype(jnp.float32) * scal_ref[0]) * scal_ref[1]
    mu = (1 - b1) * g + b1 * mu_ref[...]
    nu = (1 - b2) * (g * g) + b2 * nu_ref[...]
    u = (mu / scal_ref[3]) / (jnp.sqrt(nu / scal_ref[4]) + eps)
    if wd:
        u = u + wd * p_ref[...]
    u = scal_ref[2] * u
    p = p_ref[...]
    op_ref[...] = (p + u).astype(op_ref.dtype)
    omu_ref[...] = mu
    onu_ref[...] = nu


def _row_block(rows: int) -> int:
    for br in (2048, 1024, 512, 256, 128, 64, 32, 16, 8):
        if rows % br == 0:
            return br
    return rows


def fused_leaf_update(p, g, mu, nu, scal, *, b1, b2, eps, wd):
    """(new_p, new_mu, new_nu) for one leaf.  ``scal`` is the stacked
    [inv, clip_factor, neg_lr, bc1, bc2] f32 vector; shapes are free —
    the leaf is flattened and padded to (rows, 128) lane tiles (the pad
    region computes zeros and is sliced off)."""
    shape, pdt = p.shape, p.dtype
    n = int(p.size)
    tile = _LANE * _SUBLANE
    n_pad = (-n) % tile
    def flat(a, dt=None):
        a = a.reshape(-1) if a.shape != () else a.reshape(1)
        a = a.astype(dt) if dt is not None else a
        if n_pad:
            a = jnp.concatenate([a, jnp.zeros((n_pad,), a.dtype)])
        return a.reshape(-1, _LANE)
    p2, g2 = flat(p), flat(g)
    mu2, nu2 = flat(mu, jnp.float32), flat(nu, jnp.float32)
    rows = p2.shape[0]
    br = _row_block(rows)
    blk = lambda dt: pl.BlockSpec((br, _LANE), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps, wd=wd),
        grid=(rows // br,),
        in_specs=[pl.BlockSpec(memory_space=_MEMSPACE.SMEM),
                  blk(pdt), blk(g2.dtype), blk(jnp.float32),
                  blk(jnp.float32)],
        out_specs=[blk(pdt), blk(jnp.float32), blk(jnp.float32)],
        out_shape=[jax.ShapeDtypeStruct((rows, _LANE), pdt),
                   jax.ShapeDtypeStruct((rows, _LANE), jnp.float32),
                   jax.ShapeDtypeStruct((rows, _LANE), jnp.float32)],
        compiler_params=_COMPILER_PARAMS(dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(scal.astype(jnp.float32), p2, g2, mu2, nu2)
    def unflat(a, dt):
        return a.reshape(-1)[:n].reshape(shape).astype(dt)
    return (unflat(out[0], pdt), unflat(out[1], mu.dtype),
            unflat(out[2], nu.dtype))


def fused_adam_tree_update(spec: Dict[str, Any], params, opt_state, grads):
    """Drop-in for ``tx.update`` + apply: returns ``(new_params,
    new_opt_state)`` with the update already applied to the params, or
    ``None`` when the state tuple doesn't match the supported chain.
    ``grads`` must already be unscaled/clipped (the engine's in-program
    path) — the kernel's fold scalars are 1 here."""
    m = match_adam_chain(opt_state)
    if m is None:
        return None
    adam_idx, sched_idx = m
    adam = opt_state[adam_idx]
    sched_count = opt_state[sched_idx].count if sched_idx is not None else None
    neg_lr, bc1, bc2 = step_scalars(spec, adam.count, sched_count)
    scal = jnp.stack([jnp.float32(1.0), jnp.float32(1.0), neg_lr, bc1, bc2])
    kw = dict(b1=spec["b1"], b2=spec["b2"], eps=spec["eps"], wd=spec["wd"])
    flat_p, tdef = jax.tree_util.tree_flatten(params)
    flat_g = tdef.flatten_up_to(grads)
    flat_mu = tdef.flatten_up_to(adam.mu)
    flat_nu = tdef.flatten_up_to(adam.nu)
    new_p, new_mu, new_nu = [], [], []
    for p, g, mu, nu in zip(flat_p, flat_g, flat_mu, flat_nu):
        np_, nm, nn = fused_leaf_update(p, g, mu, nu, scal, **kw)
        new_p.append(np_); new_mu.append(nm); new_nu.append(nn)
    new_adam = type(adam)(count=_safe_int32_increment(adam.count),
                          mu=tdef.unflatten(new_mu),
                          nu=tdef.unflatten(new_nu))
    out_state = list(opt_state)
    out_state[adam_idx] = new_adam
    if sched_idx is not None:
        sc = opt_state[sched_idx]
        out_state[sched_idx] = type(sc)(
            count=_safe_int32_increment(sc.count))
    return tdef.unflatten(new_p), tuple(out_state)
