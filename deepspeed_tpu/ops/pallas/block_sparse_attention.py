"""Pallas TPU block-sparse flash attention (forward + backward).

TPU-native replacement for the reference's Triton block-sparse attention
(``deepspeed/ops/sparse_attention/matmul.py`` SDD/DSD kernels and
``softmax.py`` blocked softmax).  Instead of three separate sparse GEMM /
softmax launches stitched together through autograd, the whole sparse
attention is one online-softmax flash kernel whose K-block walk is driven
by a per-(head, q-block) lookup table derived from the sparsity layout —
only blocks present in the layout are ever DMA'd from HBM or multiplied,
so FLOPs *and* HBM traffic scale with layout density.

Design notes:
- The layout (``[H, nb, nb]`` 0/1, from ``ops/sparse_attention/
  sparsity_config.py``) is static host metadata.  From it we build
  row-wise LUTs (for fwd + dq) and column-wise LUTs (for dk/dv), padded to
  the densest row.
- LUT + counts enter via ``pltpu.PrefetchScalarGridSpec`` scalar-prefetch
  so the K/V BlockSpec *index maps* can chase the LUT: grid is
  ``(batch, heads, q-blocks, lut-entries)`` and entry ``j`` DMAs exactly
  the K/V block ``lut[h, qi, j]``.  Padding entries re-fetch the row's
  last valid block and are compute-masked with ``pl.when`` — the DMA is a
  VMEM-resident no-op, never extra HBM traffic.  Per-block memory is
  O(block²), independent of sequence length, so 32k+ sequences fit.
- Online-softmax statistics accumulate in fp32 VMEM scratch across the
  (sequential) innermost grid dimension, exactly like the dense flash
  kernel in ``flash_attention.py``; one layout block maps to one MXU tile,
  which is why layout ``block`` of 64/128 is the fast path.
- Rows whose layout is empty produce zero output and zero gradient (the
  softmax normalizer is clamped; every entry is compute-masked).
- ``causal=True`` additionally applies the elementwise triangular mask on
  diagonal blocks (block-level causality should already be in the layout;
  the flag makes within-block masking exact).
- ``interpret=True`` off-TPU runs the same kernels on CPU for CI parity
  against the masked-dense jnp reference, the analogue of the reference's
  ``tests/unit/ops/sparse_attention/test_sparse_attention.py``.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# jax < 0.5 spells the Pallas compiler-params type ``TPUCompilerParams``.
_SEMANTICS4 = (getattr(pltpu, "CompilerParams", None)
               or pltpu.TPUCompilerParams)(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


# --------------------------------------------------------------------------- #
# Layout → LUT
# --------------------------------------------------------------------------- #
@functools.lru_cache(maxsize=64)
def _luts_cached(layout_bytes: bytes, H: int, nb: int):
    layout = np.frombuffer(layout_bytes, dtype=np.int32).reshape(H, nb, nb)
    return _build_luts(layout)


def _build_luts(layout: np.ndarray):
    """Row and column LUTs from a [H, nb, nb] 0/1 layout.

    Returns (row_lut [H*nb, max_r], row_cnt [H, nb],
             col_lut [H*nb, max_c], col_cnt [H, nb]) as int32 numpy arrays.
    Padding entries repeat the last valid index (their compute is masked);
    fully-empty rows pad with 0.
    """
    H, nb, _ = layout.shape
    row_cnt = layout.sum(axis=2).astype(np.int32)
    col_cnt = layout.sum(axis=1).astype(np.int32)
    max_r = max(int(row_cnt.max()), 1)
    max_c = max(int(col_cnt.max()), 1)
    row_lut = np.zeros((H * nb, max_r), dtype=np.int32)
    col_lut = np.zeros((H * nb, max_c), dtype=np.int32)
    for h in range(H):
        for i in range(nb):
            cols = np.nonzero(layout[h, i])[0]
            row_lut[h * nb + i, :len(cols)] = cols
            if len(cols):
                row_lut[h * nb + i, len(cols):] = cols[-1]
            rows = np.nonzero(layout[h, :, i])[0]
            col_lut[h * nb + i, :len(rows)] = rows
            if len(rows):
                col_lut[h * nb + i, len(rows):] = rows[-1]
    return row_lut, row_cnt, col_lut, col_cnt


def build_luts(layout: np.ndarray):
    layout = np.ascontiguousarray(np.asarray(layout, dtype=np.int32))
    H, nb, _ = layout.shape
    return _luts_cached(layout.tobytes(), H, nb)


def _lut_block(nb):
    """Index map chasing the LUT: entry j selects K/V (or Q/dO) block
    ``lut[h*nb + i, j]``.  Scalar-prefetch refs arrive as trailing args."""
    def index_map(b, h, i, j, cnt_ref, lut_ref):
        return b, h, lut_ref[h * nb + i, j], 0
    return index_map


# --------------------------------------------------------------------------- #
# Forward
# --------------------------------------------------------------------------- #
def _fwd_kernel(cnt_ref, lut_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_s, l_s, acc_s, *, scale, causal, bs, nb):
    h, qi, j = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    n = cnt_ref[h, qi]

    @pl.when(j == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(j < n)
    def _step():
        col = lut_ref[h * nb + qi, j]
        q = q_ref[0, 0].astype(jnp.float32)          # [bs, D]
        k = k_ref[0, 0].astype(jnp.float32)          # [bs, D] (LUT-selected)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * bs + jax.lax.broadcasted_iota(jnp.int32, (bs, bs), 0)
            cols = col * bs + jax.lax.broadcasted_iota(jnp.int32, (bs, bs), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_s[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        # exp(NEG_INF - NEG_INF) = 1 would fabricate mass on rows whose
        # every entry is causally masked — zero them explicitly
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        alpha = jnp.exp(m_prev - m_new)
        m_s[...] = m_new
        l_s[...] = l_s[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_s[...] = acc_s[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(3) - 1)
    def _finish():
        l_safe = jnp.maximum(l_s[...], 1e-30)        # empty rows → zero output
        o_ref[0, 0] = (acc_s[...] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = m_s[...] + jnp.log(l_safe)


def _fwd(q, k, v, row_lut, row_cnt, *, scale, causal, bs):
    B, H, S, D = q.shape
    nb = S // bs
    max_nnz = row_lut.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nb, max_nnz),
        in_specs=[
            pl.BlockSpec((1, 1, bs, D), lambda b, h, i, j, *_: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bs, D), _lut_block(nb)),
            pl.BlockSpec((1, 1, bs, D), _lut_block(nb)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bs, D), lambda b, h, i, j, *_: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bs, 1), lambda b, h, i, j, *_: (b, h, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bs, 1), jnp.float32),
            pltpu.VMEM((bs, 1), jnp.float32),
            pltpu.VMEM((bs, D), jnp.float32),
        ],
    )
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bs=bs, nb=nb),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        compiler_params=_SEMANTICS4,
        interpret=_interpret(),
    )(row_cnt, row_lut, q, k, v)
    return o, lse


# --------------------------------------------------------------------------- #
# Backward
# --------------------------------------------------------------------------- #
def _dq_kernel(cnt_ref, lut_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref, dq_s, *, scale, causal, bs, nb):
    h, qi, j = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    n = cnt_ref[h, qi]

    @pl.when(j == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    @pl.when(j < n)
    def _step():
        col = lut_ref[h * nb + qi, j]
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * bs + jax.lax.broadcasted_iota(jnp.int32, (bs, bs), 0)
            cols = col * bs + jax.lax.broadcasted_iota(jnp.int32, (bs, bs), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_s[...] = dq_s[...] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(3) - 1)
    def _finish():
        dq_ref[0, 0] = dq_s[...].astype(dq_ref.dtype)


def _dkv_kernel(cnt_ref, lut_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dk_s, dv_s, *, scale, causal, bs, nb):
    h, ki, j = pl.program_id(1), pl.program_id(2), pl.program_id(3)
    n = cnt_ref[h, ki]

    @pl.when(j == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    @pl.when(j < n)
    def _step():
        row = lut_ref[h * nb + ki, j]
        q = q_ref[0, 0].astype(jnp.float32)          # [bs, D] (LUT-selected)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = row * bs + jax.lax.broadcasted_iota(jnp.int32, (bs, bs), 0)
            cols = ki * bs + jax.lax.broadcasted_iota(jnp.int32, (bs, bs), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.where(s <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dv_s[...] = dv_s[...] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_s[...] = dk_s[...] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(j == pl.num_programs(3) - 1)
    def _finish():
        dk_ref[0, 0] = dk_s[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[...].astype(dv_ref.dtype)


def _bwd_impl(q, k, v, o, lse, do, luts, *, scale, causal, bs):
    B, H, S, D = q.shape
    nb = S // bs
    row_lut, row_cnt, col_lut, col_cnt = luts
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)          # [B, H, S, 1]

    own_blk = pl.BlockSpec((1, 1, bs, D), lambda b, h, i, j, *_: (b, h, i, 0))
    own_vec = pl.BlockSpec((1, 1, bs, 1), lambda b, h, i, j, *_: (b, h, i, 0))
    lut_blk = pl.BlockSpec((1, 1, bs, D), _lut_block(nb))
    lut_vec = pl.BlockSpec((1, 1, bs, 1), _lut_block(nb))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, bs=bs, nb=nb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, nb, row_lut.shape[1]),
            in_specs=[own_blk, lut_blk, lut_blk, own_blk, own_vec, own_vec],
            out_specs=own_blk,
            scratch_shapes=[pltpu.VMEM((bs, D), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, D), q.dtype),
        compiler_params=_SEMANTICS4,
        interpret=_interpret(),
    )(row_cnt, row_lut, q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, bs=bs, nb=nb),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, nb, col_lut.shape[1]),
            in_specs=[lut_blk, own_blk, own_blk, lut_blk, lut_vec, lut_vec],
            out_specs=[own_blk, own_blk],
            scratch_shapes=[pltpu.VMEM((bs, D), jnp.float32),
                            pltpu.VMEM((bs, D), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((B, H, S, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, S, D), v.dtype)],
        compiler_params=_SEMANTICS4,
        interpret=_interpret(),
    )(col_cnt, col_lut, q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------- #
# custom_vjp plumbing (layout enters as static hashable bytes)
# --------------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _sparse(q, k, v, layout_key, scale, causal, bs, H_nb):
    o, _ = _fwd(q, k, v, *_row_luts(layout_key, H_nb),
                scale=scale, causal=causal, bs=bs)
    return o


def _row_luts(layout_key, H_nb):
    row_lut, row_cnt, _, _ = _luts_cached(layout_key, *H_nb)
    return row_lut, row_cnt


def _sparse_fwd(q, k, v, layout_key, scale, causal, bs, H_nb):
    o, lse = _fwd(q, k, v, *_row_luts(layout_key, H_nb),
                  scale=scale, causal=causal, bs=bs)
    return o, (q, k, v, o, lse)


def _sparse_bwd(layout_key, scale, causal, bs, H_nb, res, do):
    q, k, v, o, lse = res
    luts = _luts_cached(layout_key, *H_nb)
    return _bwd_impl(q, k, v, o, lse, do, luts, scale=scale, causal=causal, bs=bs)


_sparse.defvjp(_sparse_fwd, _sparse_bwd)


# --------------------------------------------------------------------------- #
# Public API
# --------------------------------------------------------------------------- #
def block_sparse_attention(q, k, v, layout: np.ndarray, *,
                           causal: bool = False,
                           scale: Optional[float] = None):
    """Block-sparse attention over a static sparsity layout (differentiable).

    Args:
      q, k, v: ``[batch, seq, heads, head_dim]`` (framework-wide convention).
      layout: ``[heads, seq//block, seq//block]`` 0/1 numpy array from a
        :class:`~deepspeed_tpu.ops.sparse_attention.SparsityConfig`; the
        block size is inferred as ``seq // layout.shape[-1]``.
      causal: apply the elementwise triangular mask on top of the layout.
      scale: logit scale; defaults to ``1/sqrt(head_dim)``.
    """
    B, S, H, D = q.shape
    layout = np.ascontiguousarray(np.asarray(layout, dtype=np.int32))
    if layout.ndim != 3 or layout.shape[0] != H:
        raise ValueError(f"layout must be [heads={H}, nb, nb], got {layout.shape}")
    nb = layout.shape[-1]
    if S % nb != 0:
        raise ValueError(f"seq {S} not divisible into {nb} layout blocks")
    bs = S // nb
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    o = _sparse(qt, kt, vt, layout.tobytes(), scale, causal, bs, (H, nb))
    return o.transpose(0, 2, 1, 3)


def sparse_reference_attention(q, k, v, layout: np.ndarray, *,
                               causal: bool = False,
                               scale: Optional[float] = None,
                               rpe=None, key_padding_mask=None, attn_mask=None,
                               key_padding_mask_mode: str = "add",
                               attn_mask_mode: str = "mul"):
    """Masked-dense jnp reference (and fully-general fallback path).

    Semantics of the mask/rpe arguments follow the reference Softmax op
    (``deepspeed/ops/sparse_attention/softmax.py``): ``rpe`` is added to the
    logits; masks either add (``'add'``) or multiply-as-keep (``'mul'``, 0 →
    masked).  Layout blocks that are 0 never contribute probability mass.
    """
    B, S, H, D = q.shape
    nb = layout.shape[-1]
    bs = S // nb
    if scale is None:
        scale = 1.0 / float(np.sqrt(D))
    mask = jnp.asarray(np.kron(np.asarray(layout, np.float32),
                               np.ones((bs, bs), np.float32)))  # [H, S, S]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if rpe is not None:
        s = s + rpe.astype(jnp.float32)
    if attn_mask is not None:
        am = attn_mask.astype(jnp.float32)
        s = s + am if attn_mask_mode == "add" else jnp.where(am != 0, s, NEG_INF)
    if key_padding_mask is not None:
        kp = key_padding_mask.astype(jnp.float32)[:, None, None, :]  # [B,1,1,S]
        s = s + kp if key_padding_mask_mode == "add" else jnp.where(kp != 0, s, NEG_INF)
    if causal:
        tri = jnp.tril(jnp.ones((S, S), jnp.float32))
        s = jnp.where(tri != 0, s, NEG_INF)
    s = jnp.where(mask[None] != 0, s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - jnp.maximum(m, NEG_INF / 2))
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.maximum(l, 1e-30)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)
