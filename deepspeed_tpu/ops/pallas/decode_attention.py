"""Pallas TPU decode attention with KV cache (inference fast path).

The reference's decode hot loop is the fused ``softmax_context`` CUDA kernel
(``csrc/transformer/inference/csrc/pt_binding.cpp:1717-1781``) reading a
KV-cache workspace (``inference_context.h``).  Round 1 shipped a plain-jnp
full-cache attention that reads all ``max_len`` positions every step; this
kernel reads ONLY the ``pos + S_q`` valid positions:

* ``pos`` arrives via scalar prefetch; the kernel loop runs a STATIC trip
  count (``T/bk``, known at compile time) and predicates each iteration's
  whole copy+compute block on ``j < ceil((pos+S_q)/bk)`` — invalid cache
  blocks are neither DMA'd nor computed (decode is HBM-bound; at
  pos ≪ max_len this is the whole win).  The earlier revision bounded the
  ``fori_loop`` itself by the data-dependent count, which wedged a v5e on
  first hardware contact; the static bound removes that mechanism, and
  ``start()``/``wait()`` are paired inside the same predicated branch so
  the DMA semaphores stay balanced on every control path.
* K/V stay in HBM (``MemorySpace.ANY``); each valid block is staged into a
  VMEM scratch buffer with an explicit ``make_async_copy`` keyed by the
  dynamic block index.
* Online softmax in fp32 registers, exactly like the training flash kernel.

Layouts: q ``[B, S_q, H, D]`` (S_q = 1 for decode, small for chunked
prefill), cache ``[B, T, H, D]``.  Tested against the jnp reference via the
interpreter on CPU and on hardware by ``tools/decode_bench.py``.
"""

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

# jax < 0.5 spells the Pallas memory-space enum ``TPUMemorySpace``.
_MEMSPACE = getattr(pltpu, "MemorySpace", None) or pltpu.TPUMemorySpace


def _interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def pallas_decode_enabled() -> bool:
    """Default-on policy for the fused decode kernel (README § Pallas decode
    kernel status): ON where supported (TPU hardware), with
    ``DST_PALLAS_DECODE=0`` as the opt-out; ``DST_PALLAS_DECODE=1`` forces
    it on everywhere (including the CPU interpreter, for parity tests).
    On CPU the default stays the lax/jnp fallback — the interpreter is
    orders of magnitude slower than the fused einsum it would replace."""
    env = os.environ.get("DST_PALLAS_DECODE")
    if env == "0":
        return False
    if env == "1":
        return True
    return not _interpret()


def _paged_kernel_enabled() -> bool:
    """Same policy for the paged (block-table) kernel; independent opt-out
    so the serving path can be steered separately (DST_PALLAS_PAGED)."""
    env = os.environ.get("DST_PALLAS_PAGED")
    if env == "0":
        return False
    if env == "1":
        return True
    return not _interpret()


def _decode_kernel(pos_ref, q_ref, k_hbm, v_hbm, o_ref, k_buf, v_buf,
                   sem_k, sem_v, *, scale, bk, Sq, H, nk_max):
    """Grid (B,): ONE [bk, H, D] DMA per cache block serves every head
    (batched dot_general over the head dim) — the per-(b, h) grid of the
    round-4 kernel both re-streamed the cache H times and sliced the
    tiled H dim to 1, which Mosaic rejects on hardware.

    The loop bound is STATIC (``nk_max = T // bk``): the round-5 kernel
    bounded the fori_loop by the data-dependent live-block count, and that
    dynamically-bounded DMA sequence wedged a v5e on first hardware
    contact.  Here every iteration instead predicates its copy+compute
    block on ``j < nk`` via ``lax.cond`` — dead blocks cost no HBM traffic
    and no MXU work, and both DMAs start AND wait inside the same branch,
    so semaphores stay balanced whichever way the predicate resolves."""
    b = pl.program_id(0)
    pos = pos_ref[0]
    q = q_ref[0]                                  # [Sq, H, D], storage dtype
    nk = (pos + Sq + bk - 1) // bk                # live (DMA'd) block count

    def live(j, carry):
        m, l, acc = carry                         # [H,Sq,1] [H,Sq,1] [H,Sq,D]
        cp_k = pltpu.make_async_copy(k_hbm.at[b, pl.ds(j * bk, bk), :, :],
                                     k_buf, sem_k)
        cp_v = pltpu.make_async_copy(v_hbm.at[b, pl.ds(j * bk, bk), :, :],
                                     v_buf, sem_v)
        cp_k.start()
        cp_v.start()
        cp_k.wait()
        cp_v.wait()
        k = k_buf[...]                            # [bk, H, D]
        v = v_buf[...]
        # batch over H (axis 1 of both operands), contract D: [H, Sq, bk];
        # bf16 MXU operands with fp32 accumulation
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((1,), (1,))),
                                preferred_element_type=jnp.float32) * scale
        rows = jax.lax.broadcasted_iota(jnp.int32, (Sq, bk), 0)
        cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (Sq, bk), 1)
        s = jnp.where((cols <= pos + rows)[None], s, NEG_INF)   # causal
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # batch H (p axis 0 / v axis 1), contract bk: [H, Sq, D]
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    def body(j, carry):
        return jax.lax.cond(j < nk, lambda c: live(j, c), lambda c: c, carry)

    D = q.shape[-1]
    m0 = jnp.full((H, Sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((H, Sq, 1), jnp.float32)
    a0 = jnp.zeros((H, Sq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk_max, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)             # [H, Sq, D]
    o_ref[0] = out.transpose(1, 0, 2).astype(o_ref.dtype)


def _decode_call(q, ck, cv, pos, *, bk):
    """q [B,Sq,H,D], cache [B,T,H,D], pos scalar → out [B,Sq,H,D]."""
    B, Sq, H, D = q.shape
    scale = 1.0 / np.sqrt(D)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Sq, H, D), lambda b, pos_ref: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=_MEMSPACE.ANY),
            pl.BlockSpec(memory_space=_MEMSPACE.ANY),
        ],
        out_specs=pl.BlockSpec((1, Sq, H, D), lambda b, pos_ref: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((bk, H, D), ck.dtype),
            pltpu.VMEM((bk, H, D), cv.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, bk=bk, Sq=Sq, H=H,
                          nk_max=ck.shape[1] // bk),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Sq, H, D), q.dtype),
        interpret=_interpret(),
    )(jnp.asarray(pos, jnp.int32).reshape(1), q, ck, cv)
    return out


def decode_attention_reference(q, ck, cv, pos):
    """Plain-jnp full-cache decode attention (the round-1 path; kept as the
    parity reference and the fallback for unsupported shapes/backends)."""
    B, Sq, H, D = q.shape
    T = ck.shape[1]
    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   ck.astype(jnp.float32)) * scale
    kpos = jax.lax.broadcasted_iota(jnp.int32, (Sq, T), 1)
    qpos = pos + jax.lax.broadcasted_iota(jnp.int32, (Sq, T), 0)
    s = jnp.where((kpos <= qpos)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), cv)


# --------------------------------------------------------------------------- #
# Paged (block-table) decode attention — the serving-engine fast path.
#
# The KV cache is a global arena of fixed-size blocks ([NB, BS, Hkv, D] per
# layer); a sequence's logical positions map to physical blocks through its
# block-table row.  Queries for row ``b`` sit at global positions
# ``lengths[b] + arange(S_q)`` and attend causally to the gathered cache —
# the serving-side analogue of ZeRO-Infinity's memory virtualization:
# logical sequence memory decoupled from physical HBM placement.
# --------------------------------------------------------------------------- #
def paged_attention_reference(q, k_pages, v_pages, block_tables, lengths,
                              bias=None):
    """jnp paged attention (parity reference and CPU/default path).

    q ``[B, Sq, H, D]``; pages ``[NB, BS, Hkv, D]`` (block 0 is the shared
    trash block); ``block_tables`` ``[B, MB]`` int32 physical block ids in
    logical order; ``lengths`` ``[B]`` int32 — tokens already in the cache
    for each row, i.e. the global position of the row's first query.
    ``bias``: optional additive ``[B, H, Sq, T]`` logit bias (ALiBi),
    T = MB * BS.  GQA-aware: grouped against the un-expanded Hkv pages.
    """
    B, Sq, H, D = q.shape
    NB, BS, Hkv, _ = k_pages.shape
    MB = block_tables.shape[1]
    T = MB * BS
    G = H // Hkv
    scale = 1.0 / np.sqrt(D)
    # gather [B, MB, BS, Hkv, D] -> [B, T, Hkv, D]: the T dim is the
    # sequence's LOGICAL positions 0..T-1 (tables are logically ordered)
    ck = k_pages[block_tables].reshape(B, T, Hkv, D)
    cv = v_pages[block_tables].reshape(B, T, Hkv, D)
    qg = q.reshape(B, Sq, Hkv, G, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg.astype(jnp.float32),
                   ck.astype(jnp.float32)) * scale      # [B, Hkv, G, Sq, T]
    if bias is not None:
        s = s + bias.astype(jnp.float32).reshape(
            bias.shape[0], Hkv, G, *bias.shape[2:])
    kpos = jax.lax.broadcasted_iota(jnp.int32, (Sq, T), 1)[None]
    qpos = (lengths[:, None, None]
            + jax.lax.broadcasted_iota(jnp.int32, (Sq, T), 0)[None])
    mask = kpos <= qpos                                 # [B, Sq, T]
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p.astype(q.dtype), cv)
    return out.reshape(B, Sq, H, D)


def _paged_kernel(len_ref, tbl_ref, q_ref, k_hbm, v_hbm, o_ref, k_buf, v_buf,
                  sem_k, sem_v, *, scale, bs, Sq, H, MB):
    """Grid (B,): per row, DMA ONLY the ``ceil((len+Sq)/bs)`` live physical
    blocks through the block table (scalar-prefetched, so the dynamic block
    index is known before the DMA is issued) — the same one-copy-serves-
    every-head layout as ``_decode_kernel``.

    Like ``_decode_kernel``, the loop bound is STATIC (``MB``, the block
    table's row width) and liveness is a per-iteration ``lax.cond``
    predicate — no dynamically-bounded DMA sequence, and ``j`` can never
    reach ``MB``, so the table read ``tbl_ref[b*MB + j]`` is in-bounds by
    construction even when a padded prefill chunk pushes
    ``len + Sq`` past ``MB * bs`` (the causal mask already discards the
    padded tail's scores)."""
    b = pl.program_id(0)
    seq_len = len_ref[b]
    q = q_ref[0]                                  # [Sq, H, D]
    nk = (seq_len + Sq + bs - 1) // bs            # live (DMA'd) block count

    def live(j, carry):
        m, l, acc = carry
        phys = tbl_ref[b * MB + j]                # logical block j -> physical
        cp_k = pltpu.make_async_copy(k_hbm.at[phys], k_buf, sem_k)
        cp_v = pltpu.make_async_copy(v_hbm.at[phys], v_buf, sem_v)
        cp_k.start()
        cp_v.start()
        cp_k.wait()
        cp_v.wait()
        k = k_buf[...]                            # [bs, H, D]
        v = v_buf[...]
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((1,), (1,))),
                                preferred_element_type=jnp.float32) * scale
        rows = jax.lax.broadcasted_iota(jnp.int32, (Sq, bs), 0)
        cols = j * bs + jax.lax.broadcasted_iota(jnp.int32, (Sq, bs), 1)
        s = jnp.where((cols <= seq_len + rows)[None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    def body(j, carry):
        return jax.lax.cond(j < nk, lambda c: live(j, c), lambda c: c, carry)

    D = q.shape[-1]
    m0 = jnp.full((H, Sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((H, Sq, 1), jnp.float32)
    a0 = jnp.zeros((H, Sq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, MB, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)
    o_ref[0] = out.transpose(1, 0, 2).astype(o_ref.dtype)


def _paged_call(q, k_pages, v_pages, block_tables, lengths):
    B, Sq, H, D = q.shape
    NB, BS, Hkv, _ = k_pages.shape
    MB = block_tables.shape[1]
    scale = 1.0 / np.sqrt(D)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                    # lengths, flat block tables
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Sq, H, D), lambda b, len_ref, tbl_ref: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=_MEMSPACE.ANY),
            pl.BlockSpec(memory_space=_MEMSPACE.ANY),
        ],
        out_specs=pl.BlockSpec((1, Sq, H, D),
                               lambda b, len_ref, tbl_ref: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((BS, H, D), k_pages.dtype),
            pltpu.VMEM((BS, H, D), v_pages.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_kernel, scale=scale, bs=BS, Sq=Sq, H=H, MB=MB),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Sq, H, D), q.dtype),
        interpret=_interpret(),
    )(jnp.asarray(lengths, jnp.int32),
      jnp.asarray(block_tables, jnp.int32).reshape(-1),
      q, k_pages, v_pages)


def paged_attention(q, k_pages, v_pages, block_tables, lengths, bias=None):
    """Block-table KV attention for the serving engine; dispatches to the
    paged Pallas kernel where supported (TPU, MHA, no bias — DST_PALLAS_PAGED
    overrides), else the jnp gather reference.  Sharded meshes fall back to
    the reference path (the gather partitions cleanly under SPMD; the kernel
    does not shard the global block arena)."""
    B, Sq, H, D = q.shape
    Hkv = k_pages.shape[2]
    if (bias is not None or Hkv != H or D % 8 != 0
            or not _paged_kernel_enabled()):
        return paged_attention_reference(q, k_pages, v_pages, block_tables,
                                         lengths, bias=bias)
    from deepspeed_tpu.parallel import mesh as mesh_lib
    if mesh_lib.has_mesh():
        mesh = mesh_lib.get_mesh()
        batch_div = int(np.prod([mesh.shape[a] for a in mesh_lib.BATCH_AXES]))
        if batch_div > 1 or int(mesh.shape["tensor"]) > 1:
            return paged_attention_reference(q, k_pages, v_pages, block_tables,
                                             lengths, bias=bias)
    return _paged_call(q, k_pages, v_pages, block_tables, lengths)


def decode_attention(q, ck, cv, pos, *, block_k: Optional[int] = None):
    """KV-cache attention for prefill/decode; dispatches to the Pallas
    kernel when shapes allow, under shard_map when a mesh is active
    (batch over data/fsdp/expert, heads over tensor — decode never shards
    the cache length)."""
    B, Sq, H, D = q.shape
    T = ck.shape[1]
    bk = block_k or min(128, T)
    if T % bk != 0 or D % 8 != 0:
        return decode_attention_reference(q, ck, cv, pos)

    from deepspeed_tpu.parallel import mesh as mesh_lib
    call = functools.partial(_decode_call, bk=bk)
    if mesh_lib.has_mesh():
        mesh = mesh_lib.get_mesh()
        batch_div = int(np.prod([mesh.shape[a] for a in mesh_lib.BATCH_AXES]))
        tp = int(mesh.shape["tensor"])
        if batch_div > 1 or tp > 1:
            if B % batch_div != 0 or H % tp != 0:
                return decode_attention_reference(q, ck, cv, pos)
            qspec = P(mesh_lib.BATCH_AXES, None, "tensor", None)
            return mesh_lib.shard_map(
                call, mesh=mesh,
                in_specs=(qspec, qspec, qspec, P()),
                out_specs=qspec, check_vma=False)(q, ck, cv, pos)
    return call(q, ck, cv, pos)
