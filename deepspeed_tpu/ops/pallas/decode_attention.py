"""Pallas TPU decode attention with KV cache (inference fast path).

The reference's decode hot loop is the fused ``softmax_context`` CUDA kernel
(``csrc/transformer/inference/csrc/pt_binding.cpp:1717-1781``) reading a
KV-cache workspace (``inference_context.h``).  Round 1 shipped a plain-jnp
full-cache attention that reads all ``max_len`` positions every step; this
kernel reads ONLY the ``pos + S_q`` valid positions:

* ``pos`` arrives via scalar prefetch, and the kernel loop has a
  *data-dependent* trip count ``ceil((pos+S_q)/bk)`` — invalid cache blocks
  are neither DMA'd nor computed (decode is HBM-bound; at pos ≪ max_len
  this is the whole win).
* K/V stay in HBM (``MemorySpace.ANY``); each valid block is staged into a
  VMEM scratch buffer with an explicit ``make_async_copy`` keyed by the
  dynamic block index.
* Online softmax in fp32 registers, exactly like the training flash kernel.

Layouts: q ``[B, S_q, H, D]`` (S_q = 1 for decode, small for chunked
prefill), cache ``[B, T, H, D]``.  Tested against the jnp reference via the
interpreter on CPU and on hardware by ``tools/decode_bench.py``.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _interpret() -> bool:
    try:
        return jax.devices()[0].platform != "tpu"
    except Exception:
        return True


def _decode_kernel(pos_ref, q_ref, k_hbm, v_hbm, o_ref, k_buf, v_buf,
                   sem_k, sem_v, *, scale, bk, Sq, H):
    """Grid (B,): ONE [bk, H, D] DMA per cache block serves every head
    (batched dot_general over the head dim) — the per-(b, h) grid of the
    round-4 kernel both re-streamed the cache H times and sliced the
    tiled H dim to 1, which Mosaic rejects on hardware."""
    b = pl.program_id(0)
    pos = pos_ref[0]
    q = q_ref[0]                                  # [Sq, H, D], storage dtype
    nk = (pos + Sq + bk - 1) // bk                # data-dependent trip count

    def body(j, carry):
        m, l, acc = carry                         # [H,Sq,1] [H,Sq,1] [H,Sq,D]
        cp_k = pltpu.make_async_copy(k_hbm.at[b, pl.ds(j * bk, bk), :, :],
                                     k_buf, sem_k)
        cp_v = pltpu.make_async_copy(v_hbm.at[b, pl.ds(j * bk, bk), :, :],
                                     v_buf, sem_v)
        cp_k.start()
        cp_v.start()
        cp_k.wait()
        cp_v.wait()
        k = k_buf[...]                            # [bk, H, D]
        v = v_buf[...]
        # batch over H (axis 1 of both operands), contract D: [H, Sq, bk];
        # bf16 MXU operands with fp32 accumulation
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((1,), (1,))),
                                preferred_element_type=jnp.float32) * scale
        rows = jax.lax.broadcasted_iota(jnp.int32, (Sq, bk), 0)
        cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (Sq, bk), 1)
        s = jnp.where((cols <= pos + rows)[None], s, NEG_INF)   # causal
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # batch H (p axis 0 / v axis 1), contract bk: [H, Sq, D]
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    D = q.shape[-1]
    m0 = jnp.full((H, Sq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((H, Sq, 1), jnp.float32)
    a0 = jnp.zeros((H, Sq, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, a0))
    out = acc / jnp.maximum(l, 1e-30)             # [H, Sq, D]
    o_ref[0] = out.transpose(1, 0, 2).astype(o_ref.dtype)


def _decode_call(q, ck, cv, pos, *, bk):
    """q [B,Sq,H,D], cache [B,T,H,D], pos scalar → out [B,Sq,H,D]."""
    B, Sq, H, D = q.shape
    scale = 1.0 / np.sqrt(D)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Sq, H, D), lambda b, pos_ref: (b, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY),
        ],
        out_specs=pl.BlockSpec((1, Sq, H, D), lambda b, pos_ref: (b, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((bk, H, D), ck.dtype),
            pltpu.VMEM((bk, H, D), cv.dtype),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, bk=bk, Sq=Sq, H=H),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Sq, H, D), q.dtype),
        interpret=_interpret(),
    )(jnp.asarray(pos, jnp.int32).reshape(1), q, ck, cv)
    return out


def decode_attention_reference(q, ck, cv, pos):
    """Plain-jnp full-cache decode attention (the round-1 path; kept as the
    parity reference and the fallback for unsupported shapes/backends)."""
    B, Sq, H, D = q.shape
    T = ck.shape[1]
    scale = 1.0 / np.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   ck.astype(jnp.float32)) * scale
    kpos = jax.lax.broadcasted_iota(jnp.int32, (Sq, T), 1)
    qpos = pos + jax.lax.broadcasted_iota(jnp.int32, (Sq, T), 0)
    s = jnp.where((kpos <= qpos)[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(q.dtype), cv)


def decode_attention(q, ck, cv, pos, *, block_k: Optional[int] = None):
    """KV-cache attention for prefill/decode; dispatches to the Pallas
    kernel when shapes allow, under shard_map when a mesh is active
    (batch over data/fsdp/expert, heads over tensor — decode never shards
    the cache length)."""
    B, Sq, H, D = q.shape
    T = ck.shape[1]
    bk = block_k or min(128, T)
    if T % bk != 0 or D % 8 != 0:
        return decode_attention_reference(q, ck, cv, pos)

    from deepspeed_tpu.parallel import mesh as mesh_lib
    call = functools.partial(_decode_call, bk=bk)
    if mesh_lib.has_mesh():
        mesh = mesh_lib.get_mesh()
        batch_div = int(np.prod([mesh.shape[a] for a in mesh_lib.BATCH_AXES]))
        tp = int(mesh.shape["tensor"])
        if batch_div > 1 or tp > 1:
            if B % batch_div != 0 or H % tp != 0:
                return decode_attention_reference(q, ck, cv, pos)
            qspec = P(mesh_lib.BATCH_AXES, None, "tensor", None)
            return jax.shard_map(
                call, mesh=mesh,
                in_specs=(qspec, qspec, qspec, P()),
                out_specs=qspec, check_vma=False)(q, ck, cv, pos)
    return call(q, ck, cv, pos)
