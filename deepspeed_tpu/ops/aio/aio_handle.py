"""Python binding for the native async file-I/O engine.

Reference surface: ``deepspeed/ops/op_builder/async_io.py`` (builder) +
``csrc/aio/py_lib/deepspeed_py_aio_handle.cpp`` (``aio_handle`` with
``pread/pwrite/async_pread/async_pwrite/wait``).  The native engine is
``csrc/aio/dst_aio.cpp`` in this repo, compiled on first use with g++
into a cached shared object and driven through ctypes (no pybind11 in
the toolchain).  Buffers are numpy arrays (pinned-host staging is the
caller's concern — see runtime/swap_tensor/).
"""

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))
_SRC = os.path.join(_REPO_ROOT, "csrc", "aio", "dst_aio.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_SO = os.path.join(_BUILD_DIR, "libdst_aio.so")

_lib = None
_lib_lock = threading.Lock()


class AsyncIOBuilder:
    """JIT build of the native engine (reference ``OpBuilder.jit_load``)."""

    NAME = "async_io"

    def is_compatible(self) -> bool:
        from shutil import which
        return which("g++") is not None and os.path.exists(_SRC)

    def load(self):
        return _load_lib()

    @staticmethod
    def so_path() -> str:
        return _SO


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if (not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                   "-pthread", _SRC, "-o", _SO + ".tmp"]
            subprocess.run(cmd, check=True, capture_output=True)
            os.replace(_SO + ".tmp", _SO)
        lib = ctypes.CDLL(_SO)
        lib.dst_aio_create.restype = ctypes.c_void_p
        lib.dst_aio_create.argtypes = [ctypes.c_int, ctypes.c_long, ctypes.c_int]
        lib.dst_aio_destroy.argtypes = [ctypes.c_void_p]
        for fn in (lib.dst_aio_submit_read, lib.dst_aio_submit_write):
            fn.restype = ctypes.c_long
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_long, ctypes.c_long]
        lib.dst_aio_wait.restype = ctypes.c_int
        lib.dst_aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_long]
        for fn in (lib.dst_aio_sync_pread, lib.dst_aio_sync_pwrite):
            fn.restype = ctypes.c_int
            fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                           ctypes.c_long, ctypes.c_long]
        _lib = lib
        return _lib


def _buf(arr: np.ndarray):
    assert arr.flags["C_CONTIGUOUS"], "aio buffers must be contiguous"
    return arr.ctypes.data_as(ctypes.c_void_p)


class AIOHandle:
    """The ``aio_handle`` equivalent: sync + async reads/writes of numpy
    buffers against files, with ``wait`` joining async requests."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 32,
                 single_submit: bool = False, overlap_events: bool = True,
                 num_threads: int = 4, use_o_direct: bool = False):
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.single_submit = single_submit
        self.overlap_events = overlap_events
        self.num_threads = num_threads
        lib = _load_lib()
        self._lib = lib
        block = 0 if single_submit else block_size
        self._h = lib.dst_aio_create(num_threads, block, int(use_o_direct))
        self._pending = set()

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self.wait()
                self._lib.dst_aio_destroy(self._h)
                self._h = None
        except Exception:
            pass

    # ---- sync ---------------------------------------------------------- #
    def pread(self, buffer: np.ndarray, path: str, offset: int = 0):
        rc = self._lib.dst_aio_sync_pread(self._h, path.encode(), _buf(buffer),
                                          buffer.nbytes, offset)
        if rc != 0:
            raise OSError(rc, f"aio pread {path!r} failed", path)

    def pwrite(self, buffer: np.ndarray, path: str, offset: int = 0):
        rc = self._lib.dst_aio_sync_pwrite(self._h, path.encode(), _buf(buffer),
                                           buffer.nbytes, offset)
        if rc != 0:
            raise OSError(rc, f"aio pwrite {path!r} failed", path)

    # ---- async --------------------------------------------------------- #
    def async_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        rid = self._lib.dst_aio_submit_read(self._h, path.encode(), _buf(buffer),
                                            buffer.nbytes, offset)
        self._pending.add(rid)
        return rid

    def async_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        rid = self._lib.dst_aio_submit_write(self._h, path.encode(), _buf(buffer),
                                             buffer.nbytes, offset)
        self._pending.add(rid)
        return rid

    def wait(self, request_id: Optional[int] = None) -> int:
        """Join one request (or all); returns the number joined."""
        ids = ([request_id] if request_id is not None
               else sorted(self._pending))
        joined = 0
        for rid in ids:
            rc = self._lib.dst_aio_wait(self._h, rid)
            self._pending.discard(rid)
            if rc != 0:
                raise OSError(rc, f"aio request {rid} failed")
            joined += 1
        return joined
