from deepspeed_tpu.ops.aio.aio_handle import AsyncIOBuilder, AIOHandle

__all__ = ["AIOHandle", "AsyncIOBuilder"]
