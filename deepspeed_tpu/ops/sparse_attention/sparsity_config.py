"""Block-sparsity layout configurations for sparse self-attention.

Capability parity with the reference's sparsity pattern classes
(``deepspeed/ops/sparse_attention/sparsity_config.py``): Dense, Fixed
(Sparse-Transformer style), Variable, BigBird, BSLongformer and
LocalSlidingWindow patterns, each producing a per-head block-level layout
``[num_heads, num_blocks, num_blocks]`` (1 = block computed, 0 = skipped).

TPU-first differences from the reference:

- Layouts are plain ``numpy`` int32 arrays built with vectorized index
  arithmetic (no per-element Python loops, no torch): the layout is static
  host-side metadata that parameterizes the Pallas kernel grid, never a
  device tensor.
- ``block`` defaults to 64 (not 16). The Pallas kernel tiles one layout
  block onto the MXU per step, so lane-dim-friendly blocks (64/128) are the
  fast path; any block size remains correct.
- Randomized patterns take a ``seed``. Every host builds the identical
  layout from the seed, which replaces the reference's rank-0 layout
  broadcast (``sparse_self_attention.py:get_layout``) — there is no
  layout synchronization step in SPMD.
- Random sampling in unidirectional mode never selects future blocks
  (the reference's Variable pattern samples the full row range even in
  causal mode; here causality always bounds the sample range).
"""

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base class: shared block/head bookkeeping for all sparsity patterns."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False, seed: int = 0):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1
        self.seed = seed

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"sequence length {seq_len} must be divisible by block {self.block}")
        nb = seq_len // self.block
        return np.zeros((self.num_heads, nb, nb), dtype=np.int32)

    def propagate_first_head(self, layout: np.ndarray) -> np.ndarray:
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    # ------------------------------------------------------------------ #
    # shared vectorized pattern pieces
    # ------------------------------------------------------------------ #
    @staticmethod
    def _block_grid(nb: int):
        r = np.arange(nb)
        return r[:, None], r[None, :]

    def _rng(self, head: int) -> np.random.Generator:
        return np.random.default_rng((self.seed, head))


class DenseSparsityConfig(SparsityConfig):
    """All blocks active — dense attention expressed in the sparse format
    (kept for comparison, as the reference does)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformer 'fixed' pattern: block-diagonal local windows of
    ``num_local_blocks``, plus ``num_global_blocks`` columns per window
    (taken from the tail of each window) attended globally.  Heads may
    rotate which blocks of the window are global via
    ``num_different_global_patterns``."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1, seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head, seed)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(
                f"num_local_blocks {num_local_blocks} must be divisible by "
                f"num_global_blocks {num_global_blocks}")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"unknown attention type {attention!r}")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires bidirectional")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError(
                "multiple global patterns require different_layout_per_head=True")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError(
                f"num_different_global_patterns {num_different_global_patterns} "
                f"exceeds {num_local_blocks}//{num_global_blocks}")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def _local(self, nb: int) -> np.ndarray:
        R, C = self._block_grid(nb)
        same_window = (R // self.num_local_blocks) == (C // self.num_local_blocks)
        if self.attention == "unidirectional":
            return same_window & (C <= R)
        return same_window

    def _global_starts(self, h: int, nb: int) -> List[int]:
        L, G = self.num_local_blocks, self.num_global_blocks
        first = L - (1 + h % self.num_different_global_patterns) * G
        full_end = nb - nb % L
        starts = list(range(first, full_end, L))
        if full_end < nb:  # short tail window: clamp its global block in range
            starts.append(min(full_end + first, nb - G))
        return starts

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        for h in range(self.num_layout_heads):
            head = self._local(nb).astype(np.int32)
            for s in self._global_starts(h, nb):
                first_row = 0 if self.attention == "bidirectional" else s
                head[first_row:, s:s + self.num_global_blocks] = 1
                if self.horizontal_global_attention:
                    head[s:s + self.num_global_blocks, :] = 1
            layout[h] = head
        return self.propagate_first_head(layout)


class VariableSparsityConfig(SparsityConfig):
    """Generalized fixed pattern: per-window sizes from
    ``local_window_blocks`` (last entry repeats), explicit global block
    indices (optionally ranges via ``global_block_end_indices``), and
    ``num_random_blocks`` random blocks per row."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False, seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head, seed)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"unknown attention type {attention!r}")
        if horizontal_global_attention and attention != "bidirectional":
            raise ValueError("horizontal global attention requires bidirectional")
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks or [4])
        self.global_block_indices = list(global_block_indices or [0])
        self.global_block_end_indices = (
            None if global_block_end_indices is None else list(global_block_end_indices))
        if self.global_block_end_indices is not None:
            if len(self.global_block_indices) != len(self.global_block_end_indices):
                raise ValueError("global start/end index lists differ in length")
            for s, e in zip(self.global_block_indices, self.global_block_end_indices):
                if s >= e:
                    raise ValueError(f"global block range [{s}, {e}) is empty")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def _window_bounds(self, nb: int) -> np.ndarray:
        """[nb, 2] start/end window bounds per block row."""
        bounds = np.zeros((nb, 2), dtype=np.int64)
        start = 0
        sizes = self.local_window_blocks
        i = 0
        while start < nb:
            size = sizes[min(i, len(sizes) - 1)]
            end = min(start + size, nb)
            bounds[start:end, 0] = start
            bounds[start:end, 1] = end
            start = end
            i += 1
        return bounds

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        R, C = self._block_grid(nb)
        bounds = self._window_bounds(nb)
        local = (C >= bounds[:, 0:1]) & (C < bounds[:, 1:2])
        if self.attention == "unidirectional":
            local &= C <= R
        for h in range(self.num_layout_heads):
            head = local.astype(np.int32)
            rng = self._rng(h)
            if self.num_random_blocks:
                for row in range(nb):
                    limit = nb if self.attention == "bidirectional" else row + 1
                    k = min(self.num_random_blocks, limit)
                    head[row, rng.choice(limit, size=k, replace=False)] = 1
            starts = self.global_block_indices
            ends = (self.global_block_end_indices
                    or [s + 1 for s in self.global_block_indices])
            for s, e in zip(starts, ends):
                if s >= nb:
                    continue
                e = min(e, nb)
                if self.horizontal_global_attention:
                    head[s:e, :] = 1
                first_row = 0 if self.attention == "bidirectional" else s
                head[first_row:, s:e] = 1
            if self.attention == "unidirectional":
                head = np.tril(head)
            layout[h] = head
        return self.propagate_first_head(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird ITC pattern: sliding window + random blocks + the first
    ``num_global_blocks`` rows/columns global."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1, num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, attention: str = "bidirectional",
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head, seed)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError(f"unknown attention type {attention!r}")
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        for name, n in (("random", self.num_random_blocks),
                        ("sliding window", self.num_sliding_window_blocks),
                        ("global", self.num_global_blocks)):
            if nb < n:
                raise ValueError(f"{name} blocks {n} exceed row blocks {nb}")
        R, C = self._block_grid(nb)
        w = self.num_sliding_window_blocks // 2
        sliding = np.abs(R - C) <= w
        g = self.num_global_blocks
        for h in range(self.num_layout_heads):
            head = sliding.astype(np.int32)
            rng = self._rng(h)
            for row in range(nb):
                limit = nb if self.attention == "bidirectional" else row + 1
                k = min(self.num_random_blocks, limit)
                head[row, rng.choice(limit, size=k, replace=False)] = 1
            head[:g, :] = 1
            head[:, :g] = 1
            if self.attention == "unidirectional":
                head = np.tril(head)
            layout[h] = head
        return self.propagate_first_head(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Block-sparse Longformer: sliding window + global attention at the
    given block indices (or index ranges)."""

    def __init__(self, num_heads: int, block: int = 64,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional", seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head, seed)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices or [0])
        self.global_block_end_indices = (
            None if global_block_end_indices is None else list(global_block_end_indices))
        if self.global_block_end_indices is not None:
            if len(self.global_block_indices) != len(self.global_block_end_indices):
                raise ValueError("global start/end index lists differ in length")
            for s, e in zip(self.global_block_indices, self.global_block_end_indices):
                if s >= e:
                    raise ValueError(f"global block range [{s}, {e}) is empty")
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError(
                f"sliding window {self.num_sliding_window_blocks} exceeds {nb} blocks")
        R, C = self._block_grid(nb)
        w = self.num_sliding_window_blocks // 2
        sliding = np.abs(R - C) <= w
        head = sliding.astype(np.int32)
        starts = self.global_block_indices
        ends = (self.global_block_end_indices
                or [s + 1 for s in self.global_block_indices])
        for s, e in zip(starts, ends):
            if s >= nb:
                continue
            e = min(e, nb)
            head[s:e, :] = 1
            head[:, s:e] = 1
        if self.attention == "unidirectional":
            head = np.tril(head)
        layout[:] = head
        return layout


MODE_TO_CONFIG = {}  # populated after all classes are defined (end of module)


def validate_sparsity_mode(mode: str) -> str:
    if mode not in MODE_TO_CONFIG:
        raise NotImplementedError(
            f"sparsity mode {mode!r} not implemented; "
            f"choose from {sorted(MODE_TO_CONFIG)}")
    return mode


def sparsity_config_from_dict(cfg: dict, num_heads: int) -> "SparsityConfig":
    """JSON ``sparse_attention`` block → SparsityConfig instance.

    Mirrors the reference's mode dispatch in ``runtime/config.py``
    (``get_sparse_attention``): ``{"mode": "bigbird", "block": 64, ...}``.
    Unknown keys raise (typo protection), unknown modes raise
    NotImplementedError like the reference.
    """
    cfg = dict(cfg)
    mode = validate_sparsity_mode(cfg.pop("mode", "fixed"))
    return MODE_TO_CONFIG[mode](num_heads=num_heads, **cfg)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Purely-local sliding window attention."""

    def __init__(self, num_heads: int, block: int = 64,
                 num_sliding_window_blocks: int = 3,
                 attention: str = "unidirectional", seed: int = 0):
        super().__init__(num_heads, block, seed=seed)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        if nb < self.num_sliding_window_blocks:
            raise ValueError(
                f"sliding window {self.num_sliding_window_blocks} exceeds {nb} blocks")
        R, C = self._block_grid(nb)
        w = self.num_sliding_window_blocks // 2
        head = (R - C <= w) & (C - R <= (w if self.attention == "bidirectional" else 0))
        layout[:] = head.astype(np.int32)
        return layout


MODE_TO_CONFIG.update({
    "dense": DenseSparsityConfig,
    "fixed": FixedSparsityConfig,
    "variable": VariableSparsityConfig,
    "bigbird": BigBirdSparsityConfig,
    "bslongformer": BSLongformerSparsityConfig,
    "local_sliding_window": LocalSlidingWindowSparsityConfig,
})
