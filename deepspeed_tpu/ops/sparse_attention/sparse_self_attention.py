"""Sparse self-attention module over a block-sparsity layout.

Capability parity with the reference ``SparseSelfAttention``
(``deepspeed/ops/sparse_attention/sparse_self_attention.py``): a layer that
owns a master layout built from a :class:`SparsityConfig` for
``max_seq_length`` and applies block-sparse scaled-dot-product attention at
any layout-aligned sequence length, with optional relative position
embedding, key-padding mask and attention mask (each in 'add' or 'mul'
mode).

TPU-first differences:
- No layout broadcast: layouts are deterministic host metadata (seeded
  RNG), identical on every process by construction.
- The fast path is the Pallas LUT kernel
  (:func:`~deepspeed_tpu.ops.pallas.block_sparse_attention.block_sparse_attention`);
  calls carrying rpe/masks use the fully-general masked-dense path, which
  XLA shards like any einsum.  Both are differentiable.
- Tensors are ``[batch, seq, heads, head_dim]`` (framework convention),
  not the reference's ``[batch, heads, seq, head_dim]``.
"""

from typing import Optional

import numpy as np

from deepspeed_tpu.ops.pallas.block_sparse_attention import (
    block_sparse_attention, sparse_reference_attention)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    FixedSparsityConfig, SparsityConfig)


class SparseSelfAttention:
    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add",
                 attn_mask_mode: str = "mul",
                 max_seq_length: int = 2048):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        if key_padding_mask_mode not in ("add", "mul"):
            raise ValueError(f"bad key_padding_mask_mode {key_padding_mask_mode!r}")
        if attn_mask_mode not in ("add", "mul"):
            raise ValueError(f"bad attn_mask_mode {attn_mask_mode!r}")
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self.master_layout = self.sparsity_config.make_layout(max_seq_length)
        self._layout_cache = {}
        self._warned_dense_fallback = False

    def get_layout(self, seq_len: int) -> np.ndarray:
        """Top-left sub-layout covering ``seq_len`` tokens."""
        block = self.sparsity_config.block
        if seq_len % block != 0:
            raise ValueError(
                f"sequence length {seq_len} must be divisible by block {block}")
        if seq_len > self.max_seq_length:
            raise ValueError(
                f"sequence length {seq_len} exceeds max_seq_length "
                f"{self.max_seq_length}")
        if seq_len not in self._layout_cache:
            nb = seq_len // block
            self._layout_cache[seq_len] = np.ascontiguousarray(
                self.master_layout[:, :nb, :nb])
        return self._layout_cache[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None,
                 attn_mask=None):
        """Apply sparse attention.  Inputs are [batch, seq, heads, head_dim]."""
        if query.shape != key.shape or key.shape != value.shape:
            raise ValueError("q/k/v must share shape (self-attention)")
        S = query.shape[1]
        layout = self.get_layout(S)
        causal = getattr(self.sparsity_config, "attention", None) == "unidirectional"
        if rpe is None and key_padding_mask is None and attn_mask is None:
            return block_sparse_attention(query, key, value, layout, causal=causal)
        if not self._warned_dense_fallback:
            self._warned_dense_fallback = True
            import logging

            from deepspeed_tpu.utils.logging import log_dist
            log_dist(
                "SparseSelfAttention: rpe/key_padding_mask/attn_mask take the "
                "masked-dense path (O(S²) memory) — avoid masks at long "
                "sequence lengths or bake them into the layout",
                ranks=[0], level=logging.WARNING)
        return sparse_reference_attention(
            query, key, value, layout, causal=causal, rpe=rpe,
            key_padding_mask=key_padding_mask, attn_mask=attn_mask,
            key_padding_mask_mode=self.key_padding_mask_mode,
            attn_mask_mode=self.attn_mask_mode)
