from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    SparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    VariableSparsityConfig,
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    LocalSlidingWindowSparsityConfig,
)
from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
    SparseSelfAttention,
)
from deepspeed_tpu.ops.pallas.block_sparse_attention import (
    block_sparse_attention,
    sparse_reference_attention,
)

__all__ = [
    "SparsityConfig",
    "DenseSparsityConfig",
    "FixedSparsityConfig",
    "VariableSparsityConfig",
    "BigBirdSparsityConfig",
    "BSLongformerSparsityConfig",
    "LocalSlidingWindowSparsityConfig",
    "SparseSelfAttention",
    "block_sparse_attention",
    "sparse_reference_attention",
]
