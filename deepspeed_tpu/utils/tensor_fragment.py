"""Master-parameter fragment access.

Reference: ``deepspeed/utils/tensor_fragment.py`` (``fragment_address`` /
``tensor_fragment`` mapping + the safe getters ``safe_get_full_fp32_param``
``:91-124`` and ``load_hp_checkpoint_state``): in the reference, fp32
masters live flattened inside ZeRO partitions and fragments map each
low-precision param to its slice.

TPU recast: masters are the engine's param pytree itself, sharded by
NamedSharding — the "fragment" of a parameter is its local addressable
shard, and the "full" view is an all-gathered host array.  The safe
getters keep the reference names so training scripts port unchanged;
addressing is by leaf path string (``'blocks/qkv_w'``) instead of a
module attribute.
"""

from typing import Any, Dict, Optional

import jax
import numpy as np


def _leaf_by_path(tree, path: str):
    cur = tree
    for part in path.split("/"):
        if isinstance(cur, (list, tuple)):
            cur = cur[int(part)]
        else:
            cur = cur[part]
    return cur


def _set_leaf_by_path(tree, path: str, value):
    parts = path.split("/")
    def rec(node, i):
        key = parts[i]
        if isinstance(node, dict):
            if i == len(parts) - 1:
                return {**node, key: value}
            return {**node, key: rec(node[key], i + 1)}
        raise TypeError(f"cannot set into {type(node)}")
    return rec(tree, 0)


# --------------------------------------------------------------------------- #
# Safe getters (reference tensor_fragment.py:91-124 surface)
# --------------------------------------------------------------------------- #
def safe_get_full_fp32_param(engine, path: str) -> np.ndarray:
    """Gathered fp32 master value of one parameter."""
    leaf = _leaf_by_path(engine.state.params, path)
    return np.asarray(jax.device_get(leaf), np.float32)


def safe_set_full_fp32_param(engine, path: str, value) -> None:
    """Overwrite one master parameter (re-sharded onto its placement)."""
    old = _leaf_by_path(engine.state.params, path)
    new = jax.device_put(np.asarray(value, np.float32).reshape(old.shape),
                         old.sharding)
    engine.state.params = _set_leaf_by_path(engine.state.params, path, new)
    engine._invalidate_loss_programs() if hasattr(engine, "_invalidate_loss_programs") else None


def safe_get_full_optimizer_state(engine, path: str, state_name: str) -> np.ndarray:
    """Gathered optimizer state ('mu'/'nu'/'exp_avg'...) for one param."""
    alias = {"exp_avg": "mu", "exp_avg_sq": "nu"}
    state_name = alias.get(state_name, state_name)
    opt = (engine._opt_state_view() if hasattr(engine, "_opt_state_view")
           else engine.state.opt_state)
    for part in _iter_state_parts(opt):
        if hasattr(part, state_name):
            return np.asarray(jax.device_get(
                _leaf_by_path(getattr(part, state_name), path)))
        if isinstance(part, dict) and state_name in part:
            return np.asarray(jax.device_get(
                _leaf_by_path(part[state_name], path)))
    raise KeyError(f"optimizer state {state_name!r} not found")


def _iter_state_parts(opt):
    yield opt                      # NamedTuple states match on themselves
    if isinstance(opt, (list, tuple)):
        for p in opt:
            yield from _iter_state_parts(p)


def safe_get_full_grad(engine, path: str) -> Optional[np.ndarray]:
    """Gathered accumulated gradient (None outside an accumulation window)."""
    if engine.state.grad_acc is None:
        return None
    return np.asarray(jax.device_get(
        _leaf_by_path(engine.state.grad_acc, path)))


# --------------------------------------------------------------------------- #
# Fragment (shard) views — the reference's per-rank partition access
# --------------------------------------------------------------------------- #
def get_hp_fragment(engine, path: str) -> np.ndarray:
    """This process's local shard of a master parameter (the reference's
    per-rank flat fragment)."""
    leaf = _leaf_by_path(engine.state.params, path)
    shards = [s for s in leaf.addressable_shards]
    return np.asarray(shards[0].data) if shards else np.empty((0,))


def fragment_address(engine, path: str) -> Dict[str, Any]:
    """Shard placement metadata (the reference's ``fragment_address``:
    start/numel inside the flat partition; here index + sharding spec)."""
    leaf = _leaf_by_path(engine.state.params, path)
    sh = leaf.sharding
    first = leaf.addressable_shards[0] if leaf.addressable_shards else None
    return {
        "global_shape": tuple(leaf.shape),
        "spec": getattr(sh, "spec", None),
        "index": getattr(first, "index", None),
        "numel": int(np.prod(first.data.shape)) if first is not None else 0,
    }
