"""Wall-clock and throughput timers.

TPU-native analogue of the reference's ``deepspeed/utils/timer.py``:
``SynchronizedWallClockTimer`` (reference ``utils/timer.py:33``) and
``ThroughputTimer`` (reference ``utils/timer.py:137``).  Device
synchronization is a ``jax.block_until_ready`` on a trivial computation (or a
caller-supplied array) instead of CUDA events — on TPU all dispatch is async
through the same stream, so draining it is an exact fence.
"""

import time

from deepspeed_tpu.utils.logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
BACKWARD_INNER_MICRO_TIMER = "bwd_inner_microstep"
BACKWARD_INNER_GLOBAL_TIMER = "bwd_inner"
BACKWARD_REDUCE_MICRO_TIMER = "bwd_allreduce_microstep"
BACKWARD_REDUCE_GLOBAL_TIMER = "bwd_allreduce"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"

try:
    import psutil
    PSUTIL_AVAILABLE = True
except ImportError:
    PSUTIL_AVAILABLE = False


def _sync_device():
    import jax
    # Draining dispatch: put a token op and block.  jax has no global
    # "synchronize" API; blocking on a trivial device computation after all
    # enqueued work is an effective fence on TPU's in-order stream.
    jax.block_until_ready(jax.device_put(0))


class _IntervalTimer:
    """One named timer: accumulates start→stop intervals.

    Total/count accumulators (not a list of records) — the engine reads
    these every ``steps_per_print`` and a record list would grow without
    bound over a long run.
    """

    __slots__ = ("name", "_begin", "_running", "_total_s", "_count")

    def __init__(self, name: str):
        self.name = name
        self._begin = 0.0
        self._running = False
        self._total_s = 0.0
        self._count = 0

    def start(self, sync: bool = False):
        if self._running:
            raise RuntimeError(
                f"timer {self.name!r} is running; stop() it before start()")
        if sync:
            _sync_device()
        self._begin = time.time()
        self._running = True

    def stop(self, reset: bool = False, record: bool = True, sync: bool = True):
        if not self._running:
            raise RuntimeError(f"timer {self.name!r} stopped while not running")
        if sync:
            _sync_device()
        self._running = False
        if record:
            self._total_s += time.time() - self._begin
            self._count += 1
        if reset:
            self.reset()

    def reset(self):
        self._running = False
        self._total_s = 0.0
        self._count = 0

    def elapsed(self, reset: bool = True) -> float:
        """Accumulated milliseconds; a running interval is folded in and the
        timer keeps running."""
        was_running = self._running
        if was_running:
            self.stop(sync=False)
        ms = self._total_s * 1000.0
        if reset:
            self.reset()
        if was_running:
            self.start()
        return ms

    def mean(self) -> float:
        """Mean interval in milliseconds."""
        return (self._total_s / self._count) * 1000.0 if self._count else 0.0


class SynchronizedWallClockTimer:
    """Registry of named interval timers, optionally fencing the device."""

    # engine code does `timers.Timer` in a couple of spots; keep the alias
    Timer = _IntervalTimer

    def __init__(self):
        self.timers = {}

    def get_timers(self):
        return self.timers

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _IntervalTimer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage():
        import jax
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
        except Exception:
            stats = {}
        alloc = stats.get("bytes_in_use", 0) / (1024**3)
        peak = stats.get("peak_bytes_in_use", 0) / (1024**3)
        return f"DeviceMem Allocated {round(alloc, 2)} GB Max {round(peak, 2)} GB"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        log_dist(string, ranks=ranks or [0])

    def get_mean(self, names, normalizer=1.0, reset=True):
        assert normalizer > 0.0
        means = {}
        for name in names:
            if name in self.timers:
                means[name] = self.timers[name].mean() / normalizer
                if reset:
                    self.timers[name].reset()
        return means


class NoopTimer:
    """Placeholder with the SynchronizedWallClockTimer interface."""

    class Timer:

        def start(self, **kwargs):
            ...

        def reset(self):
            ...

        def stop(self, **kwargs):
            ...

        def elapsed(self, **kwargs):
            return 0

        def mean(self):
            return 0

    def __call__(self, name):
        return self.Timer()

    def get_timers(self):
        return {}

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        ...

    def get_mean(self, names, normalizer=1.0, reset=True):
        ...


class ThroughputTimer:
    """Samples/sec tracker (the role of reference ``utils/timer.py:137``).

    TPU-native design point: never fence the device on a per-step basis.
    Dispatch is fully asynchronous (and on tunneled runtimes a device sync
    costs a network round-trip), so a per-step start/stop sync — the
    reference's CUDA-event pattern — serializes the pipeline and *is itself*
    the bottleneck.  Instead, steps are only counted between report
    boundaries; the device is drained once per ``steps_per_output`` window
    and throughput is window_samples / window_time.

    ``batch_size`` is the *global* train batch per step.
    """

    def __init__(self, batch_size, start_step=2, steps_per_output=50,
                 monitor_memory=False, logging_fn=None):
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.steps_per_output = max(1, steps_per_output)
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or log_dist
        self.started = False
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        # measurement window (between device drains)
        self._window_start: float = 0.0
        self._window_step0 = 0
        self._last_stop: float = 0.0
        self._excluded = 0.0   # host time between stop() and the next start()
        # lifetime accumulation over *measured* windows only
        self.total_elapsed_time = 0.0
        self._measured_steps = 0

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def start(self):
        self.started = True
        if self._window_start == 0.0 and self.global_step_count >= self.start_step:
            _sync_device()
            self._window_start = time.time()
            self._window_step0 = self.global_step_count
            self._excluded = 0.0
        elif self._last_stop > 0.0:
            # host-side time spent outside train steps (eval, data loading,
            # checkpointing) is not training throughput; device-async work
            # from those calls may still bleed in, but host stalls dominate
            self._excluded += time.time() - self._last_stop
            self._last_stop = 0.0

    def _close_window(self, report_speed):
        _sync_device()
        now = time.time()
        window = self.global_step_count - self._window_step0
        duration = max(now - self._window_start - self._excluded, 1e-9)
        self.total_elapsed_time += duration
        self._measured_steps += window
        if report_speed and window > 0:
            self.logging(
                f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                f"global_step={self.global_step_count}, RunningAvgSamplesPerSec="
                f"{self.avg_samples_per_sec():.3f}, CurrSamplesPerSec="
                f"{self.batch_size * window / duration:.3f}")
        self._window_start = now
        self._window_step0 = self.global_step_count
        self._excluded = 0.0
        # the drain above is window compute time, not an out-of-step gap;
        # clearing _last_stop keeps the next start() from excluding it
        self._last_stop = 0.0

    def stop(self, global_step=False, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if not global_step:
            return
        self.global_step_count += 1
        self._last_stop = time.time()
        if (self._window_start > 0.0
                and self.global_step_count - self._window_step0 >= self.steps_per_output):
            self._close_window(report_speed)

    def avg_samples_per_sec(self):
        if (self._measured_steps == 0 and self._window_start > 0.0
                and self.global_step_count > self._window_step0):
            # run shorter than one report window: close it now so short
            # trainings still report a measured value
            self._close_window(report_speed=False)
        if self._measured_steps > 0 and self.total_elapsed_time > 0:
            return self.batch_size * self._measured_steps / self.total_elapsed_time
        return 0.0


def trim_mean(data, trim_percent):
    """Mean with the tails trimmed (used by comms logging summaries)."""
    assert 0.0 <= trim_percent <= 1.0
    n = len(data)
    if n == 0:
        return 0
    data = sorted(data)
    k = int(round(n * trim_percent))
    return sum(data[k:n - k]) / max(1, n - 2 * k)
