"""Collective-op logging.

Reference: ``deepspeed/utils/comms_logging.py:CommsLogger:61`` — every comm
op appends (op_name, bytes, latency); ``log_all`` prints a summary table.
On TPU individual collective latency is not observable from Python (ops fuse
into XLA programs), so the logger records op counts + bytes at trace time
and per-*step* wall time; algorithmic bandwidth is reported per step.

``log_all`` reports, per (op, size) bucket: count, total bytes, and — when
latencies were recorded (the onebit host path does) — the trimmed-mean
latency and the algorithmic bandwidth ``size / latency``.  ``summary()``
returns the same fold as a structured dict for the telemetry hub
(``comm_summary`` records), and ``total_bytes()``/``total_ops()`` are the
cheap cumulative counters the hub snapshots per step.
"""

from deepspeed_tpu.utils.logging import log_dist


def get_caller_func(frame_depth=3):
    import sys
    frame = sys._getframe(frame_depth)
    return frame.f_code.co_name


def convert_size(size_bytes: int) -> str:
    import math
    if size_bytes == 0:
        return "0B"
    names = ("B", "KB", "MB", "GB", "TB")
    i = min(int(math.floor(math.log(size_bytes, 1024))), len(names) - 1)
    p = math.pow(1024, i)
    return f"{round(size_bytes / p, 2)} {names[i]}"


class CommsLogger:

    def __init__(self, comms_config=None):
        self.comms_dict = {}
        self.verbose = getattr(comms_config, "verbose", False)
        self.debug = getattr(comms_config, "debug", False)
        self.prof_ops = list(getattr(comms_config, "prof_ops", []) or [])
        self.prof_all = getattr(comms_config, "prof_all", True)
        self.enabled = getattr(comms_config, "enabled", True)
        # running totals: O(1) reads for the telemetry hub's per-step
        # snapshots (walking comms_dict per step would be O(ops))
        self._total_bytes = 0
        self._total_ops = 0
        # compressed ops: wire bytes go in comms_dict/_total_bytes like any
        # op; the pre-compression (logical) volume folds here per op name
        self._logical = {}
        self._total_logical = 0

    def append(self, record_name: str, msg_size: int, latency: float = 0.0,
               logical_size=None):
        """Record one op of ``msg_size`` bytes on the wire.  For compressed
        collectives ``logical_size`` is what the uncompressed op would have
        moved — the summary derives realized compression ratios from it."""
        if not self.enabled:
            return
        if not self.prof_all and record_name not in self.prof_ops:
            return
        entry = self.comms_dict.setdefault(record_name, {})
        stats = entry.setdefault(msg_size, [0, []])
        stats[0] += 1
        if latency:
            stats[1].append(latency)
        self._total_bytes += int(msg_size)
        self._total_ops += 1
        if logical_size is not None:
            self._logical[record_name] = (self._logical.get(record_name, 0)
                                          + int(logical_size))
            self._total_logical += int(logical_size)
        if self.verbose:
            log_dist(f"comm op: {record_name} | msg size: {convert_size(msg_size)}", ranks=[0])

    def total_bytes(self) -> int:
        return self._total_bytes

    def total_ops(self) -> int:
        return self._total_ops

    def summary(self) -> dict:
        """Structured fold of everything recorded so far — the payload of a
        telemetry ``comm_summary`` record and the data behind ``log_all``."""
        from deepspeed_tpu.utils.timer import trim_mean
        ops = {}
        for record_name, entry in sorted(self.comms_dict.items()):
            buckets = []
            for msg_size, (count, lats) in sorted(entry.items()):
                b = {"msg_size": int(msg_size),
                     "count": int(count),
                     "total_bytes": int(msg_size) * int(count)}
                if lats:
                    # trimmed mean: compile-step outliers would otherwise
                    # dominate the reported latency/bandwidth
                    lat = trim_mean(lats, 0.1)
                    b["latency_ms"] = lat * 1000.0
                    b["algbw_gbps"] = (msg_size / max(lat, 1e-12)) / 1e9
                buckets.append(b)
            ops[record_name] = {
                "buckets": buckets,
                "total_bytes": sum(b["total_bytes"] for b in buckets),
                "count": sum(b["count"] for b in buckets),
            }
            if record_name in self._logical:
                logical = self._logical[record_name]
                wire = ops[record_name]["total_bytes"]
                ops[record_name]["logical_bytes"] = int(logical)
                ops[record_name]["compression_ratio"] = (
                    logical / wire if wire else 0.0)
        return {"ops": ops, "total_bytes": self._total_bytes,
                "total_logical_bytes": self._total_logical,
                "total_ops": self._total_ops}

    def log_all(self, print_log=True, hub=None, step=None):
        """Print/return the summary table; with ``hub`` also emit the
        structured fold as a ``comm_summary`` telemetry record."""
        s = self.summary()
        lines = [f"{'Comm. Op':<20}{'Message Size':<16}{'Count':<8}"
                 f"{'Total Bytes':<14}{'Avg Lat(ms)':<13}{'algbw(GB/s)':<12}"]
        for record_name, entry in s["ops"].items():
            lines.append(record_name)
            for b in entry["buckets"]:
                lat = f"{b['latency_ms']:.3f}" if "latency_ms" in b else "-"
                bw = f"{b['algbw_gbps']:.3f}" if "algbw_gbps" in b else "-"
                lines.append(f"{'':<20}{convert_size(b['msg_size']):<16}"
                             f"{b['count']:<8}"
                             f"{convert_size(b['total_bytes']):<14}"
                             f"{lat:<13}{bw:<12}")
        lines.append(f"TOTAL: {convert_size(s['total_bytes'])} over "
                     f"{s['total_ops']} ops")
        summary = "\n".join(lines)
        if print_log:
            log_dist("\n" + summary, ranks=[0])
        if hub is not None:
            hub.emit("comm_summary", s, step=step)
        return summary
