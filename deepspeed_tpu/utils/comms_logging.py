"""Collective-op logging.

Reference: ``deepspeed/utils/comms_logging.py:CommsLogger:61`` — every comm
op appends (op_name, bytes, latency); ``log_all`` prints a summary table.
On TPU individual collective latency is not observable from Python (ops fuse
into XLA programs), so the logger records op counts + bytes at trace time
and per-*step* wall time; algorithmic bandwidth is reported per step.
"""

from deepspeed_tpu.utils.logging import log_dist


def get_caller_func(frame_depth=3):
    import sys
    frame = sys._getframe(frame_depth)
    return frame.f_code.co_name


def convert_size(size_bytes: int) -> str:
    import math
    if size_bytes == 0:
        return "0B"
    names = ("B", "KB", "MB", "GB", "TB")
    i = min(int(math.floor(math.log(size_bytes, 1024))), len(names) - 1)
    p = math.pow(1024, i)
    return f"{round(size_bytes / p, 2)} {names[i]}"


class CommsLogger:

    def __init__(self, comms_config=None):
        self.comms_dict = {}
        self.verbose = getattr(comms_config, "verbose", False)
        self.debug = getattr(comms_config, "debug", False)
        self.prof_ops = list(getattr(comms_config, "prof_ops", []) or [])
        self.prof_all = getattr(comms_config, "prof_all", True)
        self.enabled = getattr(comms_config, "enabled", True)

    def append(self, record_name: str, msg_size: int, latency: float = 0.0):
        if not self.enabled:
            return
        if not self.prof_all and record_name not in self.prof_ops:
            return
        entry = self.comms_dict.setdefault(record_name, {})
        stats = entry.setdefault(msg_size, [0, []])
        stats[0] += 1
        if latency:
            stats[1].append(latency)
        if self.verbose:
            log_dist(f"comm op: {record_name} | msg size: {convert_size(msg_size)}", ranks=[0])

    def log_all(self, print_log=True):
        lines = [f"{'Comm. Op':<20}{'Message Size':<20}{'Count':<10}"]
        for record_name, entry in sorted(self.comms_dict.items()):
            lines.append(record_name)
            for msg_size, (count, _lat) in sorted(entry.items()):
                lines.append(f"{'':<20}{convert_size(msg_size):<20}{count:<10}")
        summary = "\n".join(lines)
        if print_log:
            log_dist("\n" + summary, ranks=[0])
        return summary
