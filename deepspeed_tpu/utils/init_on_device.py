"""Abstract ("meta"-device) parameter initialization.

Reference: ``deepspeed/utils/init_on_device.py:12`` (``OnDevice``: a
context that redirects tensor construction onto a target/meta device so
huge models can be described without materializing).

JAX recast: abstract construction IS a first-class operation —
``jax.eval_shape`` runs any init function with zero FLOPs and zero bytes,
returning a ShapeDtypeStruct pytree.  ``OnDevice(device='meta')`` wraps
that; with a real device it materializes via ``jax.jit`` with
``out_shardings`` so parameters are born sharded (the zero.Init
construction path uses the same mechanism,
``runtime/zero/partition_parameters.py``).
"""

import contextlib
from typing import Any, Callable, Optional

import jax


class OnDevice:
    """``with OnDevice(dtype, device="meta"): params = init(...)`` — usable
    either as a context manager exposing :meth:`init` or directly as a
    callable wrapper."""

    _active: Optional["OnDevice"] = None

    def __init__(self, dtype=None, device: str = "meta", enabled: bool = True):
        self.dtype = dtype
        self.device = device
        self.enabled = enabled

    def __enter__(self):
        OnDevice._active = self if self.enabled else None
        return self

    def __exit__(self, *exc):
        OnDevice._active = None
        return False

    # ------------------------------------------------------------------ #
    def init(self, init_fn: Callable, *args, shardings=None, **kwargs) -> Any:
        """Run ``init_fn`` under this context's device policy."""
        if not self.enabled:
            return init_fn(*args, **kwargs)
        if self.device == "meta":
            out = jax.eval_shape(init_fn, *args, **kwargs)
        else:
            jit_kwargs = {"out_shardings": shardings} if shardings is not None else {}
            out = jax.jit(init_fn, **jit_kwargs)(*args, **kwargs)
        if self.dtype is not None:
            cast = (lambda s: jax.ShapeDtypeStruct(s.shape, self.dtype)
                    if isinstance(s, jax.ShapeDtypeStruct)
                    else s.astype(self.dtype))
            out = jax.tree.map(cast, out)
        return out

    def __call__(self, init_fn: Callable) -> Callable:
        def wrapped(*args, **kwargs):
            return self.init(init_fn, *args, **kwargs)
        return wrapped


def abstract_init(init_fn: Callable, *args, **kwargs):
    """Shorthand: ShapeDtypeStruct pytree of ``init_fn``'s output."""
    return jax.eval_shape(init_fn, *args, **kwargs)
