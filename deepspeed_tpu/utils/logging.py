"""Rank-aware logging.

TPU-native analogue of the reference's ``deepspeed/utils/logging.py``:
``logger`` is a package-level logger, ``log_dist(msg, ranks)`` only logs on
the listed process indices (reference: ``log_dist`` filters on
``deepspeed.comm.get_rank()``).  Here "rank" is ``jax.process_index()``.
"""

import functools
import logging
import os
import sys

LOG_LEVEL_DEFAULT = logging.INFO

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class LoggerFactory:

    @staticmethod
    def create_logger(name=None, level=LOG_LEVEL_DEFAULT):
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(filename)s:%(lineno)d:%(funcName)s] %(message)s")
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = LoggerFactory.create_logger(
    name="deepspeed_tpu",
    level=log_levels.get(os.environ.get("DS_TPU_LOG_LEVEL", "info"), logging.INFO))


@functools.lru_cache(None)
def _process_index():
    # Deferred so that importing utils does not force jax backend init.
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log ``message`` only on the given process indices (default: all).

    ``ranks=[-1]`` or ``None`` means every process; otherwise only processes
    whose ``jax.process_index()`` is listed emit the record.
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_json_dist(message, ranks=None, path=None):
    """Dump ``message`` (a dict) as JSON to ``path`` on the listed ranks."""
    import json
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        message["rank"] = my_rank
        with open(path, "w") as f:
            json.dump(message, f)


def get_current_level():
    return logger.getEffectiveLevel()


def should_log_le(max_log_level_str):
    """True when the logger's level is <= the named level (reference
    ``utils/logging.py:should_log_le``)."""
    if not isinstance(max_log_level_str, str):
        raise ValueError("max_log_level_str must be a string")
    max_log_level_str = max_log_level_str.lower()
    if max_log_level_str not in log_levels:
        raise ValueError(f"{max_log_level_str} is not one of the `logging` levels")
    return get_current_level() <= log_levels[max_log_level_str]


def warning_once(msg):
    _warn_cache_once(msg)


@functools.lru_cache(None)
def _warn_cache_once(msg):
    logger.warning(msg)
