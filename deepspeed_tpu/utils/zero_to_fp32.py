#!/usr/bin/env python
"""Offline checkpoint → consolidated fp32 weights converter.

Reference: ``deepspeed/utils/zero_to_fp32.py`` (the standalone script
copied into every checkpoint directory, ``engine.py:3125``): merge the
per-rank ZeRO partitions of a saved checkpoint into one full fp32 state
dict without needing the training cluster.

TPU storage is one sharded orbax tree per tag, so "consolidation" is a
plain host restore (tensorstore reassembles shards); this tool exists for
the same workflow — grab full weights from a training checkpoint on any
machine:

    python zero_to_fp32.py <checkpoint_dir> <output_file> [--tag TAG]

Output: ``.npz`` of flat-named fp32 arrays (and ``.pt`` when torch is
importable and the output path ends with .pt).
"""

import argparse
import os
import re
import sys


def resolve_tag(checkpoint_dir: str, tag=None) -> str:
    """The tag to read: explicit > the 'latest' pointer > newest dir by
    NATURAL sort (global_step10 beats global_step9 — a plain lexicographic
    sort gets that backwards)."""
    if tag is not None:
        return str(tag)
    latest = os.path.join(checkpoint_dir, "latest")
    if os.path.isfile(latest):
        with open(latest) as f:
            return f.read().strip()
    tags = [d for d in os.listdir(checkpoint_dir)
            if os.path.isdir(os.path.join(checkpoint_dir, d))]
    if not tags:
        raise FileNotFoundError(f"no checkpoints under {checkpoint_dir}")
    natural = lambda s: [int(p) if p.isdigit() else p
                         for p in re.split(r"(\d+)", s)]
    return max(tags, key=natural)


def flatten_tree(tree) -> dict:
    """{dotted_name: leaf} for a nested dict/list tree — the ONE naming
    scheme shared by export (values = arrays) and inspect (values =
    orbax ArrayMetadata)."""
    out = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{prefix}{k}.")
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, f"{prefix}{i}.")
        else:
            out[prefix[:-1]] = node

    walk(tree, "")
    return out


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str,
                                             tag: str = None) -> dict:
    """Full fp32 {flat_name: np.ndarray} from a saved checkpoint."""
    import numpy as np
    import orbax.checkpoint as ocp

    tag = resolve_tag(checkpoint_dir, tag)
    state_path = os.path.join(checkpoint_dir, str(tag), "state")
    assert os.path.isdir(state_path), f"no checkpoint state at {state_path}"

    restored = ocp.PyTreeCheckpointer().restore(state_path)
    return {name: np.asarray(leaf, np.float32)
            for name, leaf in flatten_tree(restored["params"]).items()}


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str,
                                               output_file: str,
                                               tag: str = None):
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    if output_file.endswith(".pt"):
        try:
            import torch
            torch.save({k: torch.from_numpy(v.copy()) for k, v in sd.items()},
                       output_file)
            print(f"saved {len(sd)} tensors to {output_file} (torch)")
            return
        except ImportError:
            output_file += ".npz"
    import numpy as np
    np.savez(output_file if output_file.endswith(".npz")
             else output_file + ".npz", **sd)
    print(f"saved {len(sd)} arrays to {output_file}")


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("checkpoint_dir")
    parser.add_argument("output_file")
    parser.add_argument("--tag", default=None)
    args = parser.parse_args()
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir,
                                               args.output_file, args.tag)


if __name__ == "__main__":
    sys.exit(main())
