"""Blockwise quantization core shared by every compressed collective.

ZeRO++ (arxiv 2306.10209) compresses collective payloads with *blockwise*
quantization: each block of ``block_size`` contiguous elements carries its
own fp32 scale + zero-point, so one outlier only degrades its block instead
of the whole tensor.  This module owns that math plus the error-feedback
state machinery, so the three ZeRO++ collectives (``qwz``/``qgz``/``hpz``)
and the older 1-bit compensated allreduce
(``runtime/comm/compressed.py``) all quantize through one code path.

Everything here is a pure jit-safe function: shapes, bit widths and block
sizes are static, values are traced.  4-bit payloads are nibble-packed into
uint8 so the array that actually crosses the wire has the advertised size —
the comms logger and ``tools/comm_audit.py`` account real bytes, not
"conceptual" ones.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

SCALE_BYTES = 4   # fp32 per-block scale
ZERO_BYTES = 4    # fp32 per-block zero-point


class QuantizedBlocks(NamedTuple):
    """A blockwise-quantized tensor: the three arrays a compressed
    collective moves.  Static metadata (bits, block_size, original length)
    is the caller's — inside jit it must be python-level anyway."""
    data: jax.Array    # uint8 [..., nb, block] (8-bit) or [..., nb, block//2] (4-bit packed)
    scale: jax.Array   # f32 [..., nb]
    zero: jax.Array    # f32 [..., nb]  (block minimum — asymmetric zero-point)


def n_blocks(m: int, block_size: int) -> int:
    return -(-m // block_size)


def quantized_nbytes(m: int, bits: int = 8, block_size: int = 256) -> int:
    """Wire bytes of ``quantize_blockwise`` applied to m elements: packed
    payload + per-block scale/zero-point.  The accounting counterpart the
    engine and bench use for logical-vs-wire reporting."""
    nb = n_blocks(m, block_size)
    payload = nb * block_size * bits // 8
    return payload + nb * (SCALE_BYTES + ZERO_BYTES)


def _check(bits: int, block_size: int):
    assert bits in (4, 8), f"bits must be 4 or 8, got {bits}"
    assert block_size > 0 and block_size % 2 == 0, (
        f"block_size must be positive and even (4-bit packing), got {block_size}")


def _pack4(q):
    """Two 4-bit codes per byte (low nibble first)."""
    return (q[..., ::2] | (q[..., 1::2] << 4)).astype(jnp.uint8)


def _unpack4(p):
    lo = p & jnp.uint8(0x0F)
    hi = (p >> 4) & jnp.uint8(0x0F)
    return jnp.stack([lo, hi], axis=-1).reshape(*p.shape[:-1], p.shape[-1] * 2)


def quantize_blockwise(x, bits: int = 8, block_size: int = 256) -> QuantizedBlocks:
    """Quantize along the LAST axis in independent ``block_size`` blocks
    with a per-block fp32 scale + zero-point (asymmetric uint codes).

    The last block is edge-padded — padding repeats the final element so it
    cannot widen the block's value range (a zero pad would inflate the
    quantization step of every tail block of an all-positive tensor).
    Leading axes are batch axes: each row quantizes independently, which is
    what lets ``qgz`` all-to-all per-peer rows without blocks straddling
    peer boundaries.
    """
    _check(bits, block_size)
    x = jnp.asarray(x)
    m = x.shape[-1]
    nb = n_blocks(m, block_size)
    pad = nb * block_size - m
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)], mode="edge")
    y = x.reshape(*x.shape[:-1], nb, block_size).astype(jnp.float32)
    mn = y.min(axis=-1)
    mx = y.max(axis=-1)
    qmax = (1 << bits) - 1
    # constant block → scale 1, every code 0, dequant returns mn exactly
    scale = jnp.where(mx > mn, (mx - mn) / qmax, 1.0)
    q = jnp.clip(jnp.round((y - mn[..., None]) / scale[..., None]), 0, qmax)
    q = q.astype(jnp.uint8)
    if bits == 4:
        q = _pack4(q)
    return QuantizedBlocks(q, scale, mn)


def dequantize_blockwise(q: QuantizedBlocks, m: int, bits: int = 8,
                         dtype=jnp.float32) -> jax.Array:
    """Invert ``quantize_blockwise``: (..., nb, block) codes → (..., m)."""
    _check(bits, q.data.shape[-1] * (2 if bits == 4 else 1))
    codes = _unpack4(q.data) if bits == 4 else q.data
    y = codes.astype(jnp.float32) * q.scale[..., None] + q.zero[..., None]
    y = y.reshape(*y.shape[:-2], y.shape[-2] * y.shape[-1])
    return y[..., :m].astype(dtype)


def quantization_error_bound(x: np.ndarray, bits: int, block_size: int) -> np.ndarray:
    """Per-element worst-case round-trip error: half a quantization step of
    the element's block.  Host-side helper for tests/analysis."""
    x = np.asarray(x, np.float32)
    m = x.shape[-1]
    nb = n_blocks(m, block_size)
    pad = nb * block_size - m
    if pad:
        x = np.concatenate([x, np.repeat(x[..., -1:], pad, axis=-1)], axis=-1)
    y = x.reshape(*x.shape[:-1], nb, block_size)
    step = (y.max(-1) - y.min(-1)) / ((1 << bits) - 1)
    bound = np.repeat(step[..., None], block_size, axis=-1)
    return bound.reshape(*bound.shape[:-2], nb * block_size)[..., :m] / 2 + 1e-6


# --------------------------------------------------------------------------- #
# Error feedback — the residual-compensation pattern every lossy exchange
# shares.  The state SHAPE is the 1-bit path's ``CompressionState`` (that
# path now imports it from here); blockwise users carry the same two flat
# buffers.
# --------------------------------------------------------------------------- #
class CompressionState(NamedTuple):
    """Per-device error-feedback buffers (flat, padded)."""
    worker_error: jax.Array   # [n_padded]          local quantization residual
    server_error: jax.Array   # [n_padded / world]  residual of the served chunk


def padded_size(n: int, world: int) -> int:
    return -(-n // world) * world


def init_compression_state(n: int, world: int) -> Tuple[np.ndarray, np.ndarray]:
    """Zero-initialized (worker_error, server_error) for a flat size n."""
    np_ = padded_size(n, world)
    return (np.zeros((np_,), np.float32), np.zeros((np_ // world,), np.float32))


def zeroed_compression_state(state):
    """Zeros shaped/placed like ``state`` — the coherent reset after a
    parameter rollback.  Error feedback is a residual of the *trajectory*:
    once the parameters jump back to an older checkpoint, the carried
    residuals belong to updates that never happened and re-injecting them
    corrupts the replayed run (see the stale-EF regression test)."""
    def z(e):
        zero = jnp.zeros(e.shape, e.dtype)
        sharding = getattr(e, "sharding", None)
        if isinstance(e, jax.Array) and sharding is not None:
            return jax.device_put(zero, sharding)
        return np.zeros(e.shape, e.dtype)
    if isinstance(state, CompressionState):
        return CompressionState(z(state.worker_error), z(state.server_error))
    return tuple(z(e) for e in state)


def ef_compensate(x, residual):
    """Fold the carried residual into the value about to be compressed."""
    return x + residual


def ef_residual(compensated, decompressed):
    """What the lossy representation failed to carry — next call's residual."""
    return compensated - decompressed


def sign_scale(x):
    """The 1-bit compressor: elementwise sign + one fp32 scale
    (``||x|| / sqrt(n)``) — reference ``NcclBackend.compressed_allreduce``
    worker/server compression."""
    scale = jnp.linalg.norm(x) / jnp.sqrt(jnp.asarray(x.size, jnp.float32))
    sign = jnp.where(x >= 0, 1, -1).astype(jnp.int8)
    return sign, scale


def ef_quantize(x, residual, bits: int = 8,
                block_size: int = 256) -> Tuple[QuantizedBlocks, jax.Array]:
    """Blockwise quantization with error feedback: compress
    ``x + residual``, return (codes, new_residual).  Repeated application
    with a persistent residual makes the time-average of the decompressed
    stream converge to the true value even at 4 bits."""
    compensated = ef_compensate(x, residual)
    q = quantize_blockwise(compensated, bits=bits, block_size=block_size)
    deq = dequantize_blockwise(q, compensated.shape[-1], bits=bits)
    return q, ef_residual(compensated, deq)
