"""Layered ZeRO-3 — per-block gather/reduce-scatter inside the layer scan.

The bulk stage-3 step (``engine._build_cc_step``) all-gathers the entire
parameter tree before the first matmul and reduce-scatters every gradient
after the last one: zero overlap, peak memory = the full unsharded tree.
This module provides the pieces that express T3's fused track-and-trigger
(arXiv 2401.16677) as *program structure* instead:

* the stacked per-block params (``params["blocks"]``, leading dim = layer)
  flow through ``lax.scan`` **still sharded**;
* the scan carry holds a ring of ``prefetch_depth`` already-gathered block
  slices — iteration *i* computes with ring head *i* while issuing the
  gather for block ``i + depth`` (double buffering for ``depth=1``), so
  XLA's async collective start/done pairs hide under block *i*'s matmuls;
* each slice gather is a ``jax.custom_vjp`` whose backward rule is the
  hierarchical (optionally quantized) reduce-scatter of that block's
  gradient — the scan transpose then reduce-scatters block *i*'s grads as
  soon as its backward slice completes, instead of holding all of them.

The per-leaf forward/backward rules preserve the ZeRO++ wire formats
(qwZ quantized gather, qgZ hierarchical reduce-scatter, hpZ fast-axis
regather of a persisted secondary shard) bit-for-bit against the bulk
path: quantization blocks never straddle a layer boundary as long as the
per-layer shard is a multiple of the quantization block size, and every
other op involved (cast, psum_scatter, stripe merge) is elementwise in
the layer dim.

Models discover the layered mode through a threading-local context (the
``mesh.manual_sharding`` pattern): the engine wraps the loss call in
``block_prefetch_scope(pf)`` and the model's scan branch asks
``current_prefetch()`` — no signature plumbing, and models traced outside
the scope keep their exact current program.
"""

import contextlib
import threading
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.compression import hpz as hpz_mod
from deepspeed_tpu.comm.compression import qgz, qwz

try:  # jax >= 0.4.x keeps this private; absence just disables staging
    from jax._src.sharding_impls import TransferToMemoryKind as _Transfer
except ImportError:  # pragma: no cover - older/newer jax layouts
    _Transfer = None

_scope = threading.local()


def _stage_to_device(x):
    """Per-slice host→HBM stage for offloaded (``pinned_host``) block
    leaves — the device half of the offload prefetch ring.

    Issued inside the slice-gather ``custom_vjp`` *impl*, so it rides the
    same double-buffered ring as the collective: the transfer for block
    ``i + depth`` is in flight while block ``i`` computes, and the
    backward rule is untouched (cotangents stay in device memory with the
    gradient accumulator).  Whole-tree host→device transfers inside the
    scan body are exactly what ``tools/check_overlap_structure.py`` lints
    against; this per-slice form is the sanctioned site.  On backends
    without memory-kind support (CPU tests) the transfer is an identity,
    keeping layered-vs-bulk parity bitwise.
    """
    if _Transfer is None:
        return x
    try:
        return jax.device_put(x, _Transfer("device"))
    except Exception:
        return x


@contextlib.contextmanager
def block_prefetch_scope(pf: "LayeredPrefetch"):
    """Announce the layered step to model code traced inside (trace-time
    only — wrap the loss-function call, like ``mesh.manual_sharding``)."""
    prev = getattr(_scope, "pf", None)
    _scope.pf = pf
    try:
        yield
    finally:
        _scope.pf = prev


def current_prefetch() -> Optional["LayeredPrefetch"]:
    """The active :class:`LayeredPrefetch`, or None outside a layered step
    (models then keep their stock scan over pre-gathered params)."""
    return getattr(_scope, "pf", None)


def _slice_tree(tree, i):
    """Layer ``i``'s slice of a stacked (leading-dim = layer) pytree."""
    return jax.tree.map(
        lambda a: lax.dynamic_index_in_dim(a, i, axis=0, keepdims=False), tree)


# --------------------------------------------------------------------------- #
# Per-leaf slice gathers (custom_vjp: fwd = gather, bwd = reduce-scatter)
# --------------------------------------------------------------------------- #
def _reduce_slice(ct, d, axes, qg_bits, block):
    """The backward rule shared by every sharded-leaf gather: block *i*'s
    gradient cotangent reduce-scattered back to the ZeRO layout the moment
    the scan transpose produces it — same call the bulk ``reduce_grads``
    makes on the stacked gradient (elementwise in the layer dim)."""
    return qgz.hierarchical_reduce_scatter(ct, d, axes, bits=qg_bits,
                                           block_size=block, mean=True)


def _replicated_gather(group, stage=False):
    """Replicated leaf (below the shard threshold): identity forward,
    gradient-mean backward — the bulk path's ``pmean`` per leaf."""
    def impl(x):
        return _stage_to_device(x) if stage else x

    @jax.custom_vjp
    def gather(x):
        return impl(x)

    def fwd(x):
        return impl(x), None

    def bwd(_, ct):
        return (lax.pmean(ct, group),)

    gather.defvjp(fwd, bwd)
    return gather


def _sharded_gather(d, axes, group, qw_bits, qg_bits, block, stage=False):
    """Sharded leaf, primary-shard gather: exact tiled all-gather, or the
    qwZ blockwise-quantized wire format when ``qw_bits`` is set.  With
    ``stage`` the host-resident shard slice is moved into device memory
    first, so the wire carries device-side bytes."""
    if qw_bits is not None:
        def impl(x):
            if stage:
                x = _stage_to_device(x)
            return qwz.quantized_all_gather(x, axes, dim=d, bits=qw_bits,
                                            block_size=block)
    else:
        def impl(x):
            if stage:
                x = _stage_to_device(x)
            return lax.all_gather(x, group, axis=d, tiled=True)

    @jax.custom_vjp
    def gather(x):
        return impl(x)

    def fwd(x):
        return impl(x), None

    def bwd(_, ct):
        return (_reduce_slice(ct, d, axes, qg_bits, block),)

    gather.defvjp(fwd, bwd)
    return gather


def _hpz_gather(d, axes, sizes, group, qg_bits, block, reuse, stage=False):
    """hpZ leaf: forward regathers the persisted secondary shard over the
    fast axis only (both refresh and reuse — the refresh-path full tensor
    *is* the fast regather of the just-built secondary, see
    ``hpz.hierarchical_gather``); backward reduce-scatters into the
    *primary* layout and sends a zero cotangent to the secondary.

    Replicated leaves (``d is None``) keep the bulk asymmetry: refresh
    computes with the exact fp32 primary, reuse with the secondary-dtype
    round trip.
    """
    if d is None:
        def impl(p, s):
            out = s.astype(jnp.float32) if reuse else p
            return _stage_to_device(out) if stage else out

        def bwd(s, ct):
            return lax.pmean(ct, group), jnp.zeros_like(s)
    else:
        def impl(p, s):
            # the hpZ secondary shard is the gathered-from copy: under
            # offload it is the host-resident one, staged per slice
            if stage:
                s = _stage_to_device(s)
            return hpz_mod.fast_regather(s, d, axes[1], w_slow=sizes[0])

        def bwd(s, ct):
            return (_reduce_slice(ct, d, axes, qg_bits, block),
                    jnp.zeros_like(s))

    @jax.custom_vjp
    def gather(p, s):
        return impl(p, s)

    def fwd(p, s):
        return impl(p, s), s

    gather.defvjp(fwd, bwd)
    return gather


# --------------------------------------------------------------------------- #
# The prefetch object the engine hands to the model
# --------------------------------------------------------------------------- #
class LayeredPrefetch:
    """Per-slice gather plan for one layered step.

    ``plan`` is a pytree matching ONE block slice, each leaf the dim its
    shard occupies in the slice (stacked dim minus the layer dim) or None
    for replicated leaves.  ``gather_block(blocks, i)`` slices layer ``i``
    out of the stacked (sharded) blocks tree, gathers every leaf through
    its custom-vjp rule and casts to the compute dtype — producing exactly
    the block-params tree the model's scan body already consumes.
    """

    def __init__(self, plan, cc: dict, compute_dtype,
                 hpz: bool = False, reuse: bool = False,
                 depth: int = 1, offload: bool = False):
        axes, sizes = cc["axes"], cc["sizes"]
        group = axes if len(axes) > 1 else axes[0]
        qw, qg, block = cc["qw_bits"], cc["qg_bits"], cc["block"]
        self.hpz = hpz
        self.depth = max(1, int(depth))
        self.compute_dtype = compute_dtype
        self.offload = bool(offload)

        def leaf_fn(d):
            if hpz:
                return _hpz_gather(d, axes, sizes, group, qg, block, reuse,
                                   stage=self.offload)
            if d is None:
                return _replicated_gather(group, stage=self.offload)
            return _sharded_gather(d, axes, group, qw, qg, block,
                                   stage=self.offload)

        # callables are pytree leaves: the fns tree mirrors one block slice
        self.fns = jax.tree.map(leaf_fn, plan,
                                is_leaf=lambda x: x is None or isinstance(x, int))

    def clamped_depth(self, n_layer: int) -> int:
        """Never prefetch past the last block: with ``depth >= n_layer``
        the ring would just re-gather block L-1 (clamped index) with zero
        cotangents — wire for nothing."""
        return max(1, min(self.depth, max(1, n_layer - 1)))

    def gather_block(self, blocks, i):
        """Gather layer ``i``: slice → per-leaf custom-vjp gather → cast.

        ``blocks`` is the tree the engine placed at ``params["blocks"]``:
        the sharded stacked leaves, or ``{"p": primary, "s": secondary}``
        under hpZ.  The cast to the compute dtype happens *outside* the
        custom-vjp boundary so its transpose (cotangent back to fp32) sits
        exactly where the bulk path's whole-tree cast puts it.
        """
        if self.hpz:
            p = _slice_tree(blocks["p"], i)
            s = _slice_tree(blocks["s"], i)
            out = jax.tree.map(lambda fn, a, b: fn(a, b), self.fns, p, s)
        else:
            sl = _slice_tree(blocks, i)
            out = jax.tree.map(lambda fn, a: fn(a), self.fns, sl)
        return jax.tree.map(lambda a: a.astype(self.compute_dtype), out)
