"""hpZ — hierarchical partitioning / secondary weight sharding (ZeRO++ §4.2).

ZeRO-3 shards each parameter across the FULL data-parallel world, so every
forward *and* backward all-gather crosses the slow inter-host axis.  hpZ
trades memory for bandwidth: after the one unavoidable slow-axis hop, each
host keeps a *secondary shard* — the parameter partitioned only over the
fast intra-host axis, in a compact dtype (bf16 by default).  Re-gathers
within the same parameter-freshness window (micro-steps of one gradient
accumulation boundary) then touch only the fast axis.

Two entry points mirror the two programs the engine builds:

* ``hierarchical_gather``  — the refresh path: slow-axis hop (quantized when
  qwZ is on, else a ``secondary_dtype`` cast) + fast-axis regather.  Returns
  the full tensor AND the secondary shard to persist.
* ``fast_regather``        — the reuse path: fast-axis all-gather of a
  persisted secondary shard.  No slow-axis traffic at all.

Layout: a dim sharded over ``(slow, fast)`` major→minor has global chunk
index ``i_slow·W_fast + i_fast``.  The slow gather therefore concatenates
W_slow *interleaved stripes*, and the fast regather must merge its W_fast
members one level *inside* the slow grouping — the (W_slow, W_fast, chunk)
moveaxis below, not a plain leading-dim merge.
"""

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.parallel import mesh as mesh_lib

from deepspeed_tpu.comm.compression import core, qwz


def fast_regather(secondary: jax.Array, dim: int, fast_axis: str,
                  w_slow: int, out_dtype=jnp.float32) -> jax.Array:
    """All-gather a persisted secondary shard over the fast axis only.

    ``secondary``'s ``dim`` holds ``w_slow`` stripes of this device's fast
    chunk back to back; each gathered member must slot in at position
    (slow_stripe, member) of the full dim.
    """
    w_fast = mesh_lib.manual_axis_size(fast_axis)
    parts = lax.all_gather(secondary.astype(out_dtype), fast_axis,
                           axis=0, tiled=False)      # [Wf, ..., Ws*g, ...]
    shape = parts.shape
    g = shape[1 + dim] // w_slow
    parts = parts.reshape(shape[:1 + dim] + (w_slow, g) + shape[2 + dim:])
    parts = jnp.moveaxis(parts, 0, 1 + dim)          # [..., Ws, Wf, g, ...]
    return parts.reshape(shape[1:1 + dim] + (w_slow * w_fast * g,)
                         + shape[2 + dim:])


def slow_gather_secondary(x: jax.Array, dim: int, axes: Sequence[str],
                          quantize_bits: Optional[int] = None,
                          block_size: int = 256,
                          secondary_dtype=jnp.bfloat16) -> jax.Array:
    """Just the slow-axis hop: gather this device's primary shard into the
    fast-axis-only secondary shard (dim becomes W_slow interleaved stripes
    of the local chunk, in ``secondary_dtype``).

    Shared by :func:`hierarchical_gather` (bulk refresh) and the layered
    step's standalone secondary-refresh program, which builds the stacked
    secondary once per parameter-freshness window while the per-block
    ``fast_regather`` runs inside the scan (``compression/layered.py``).
    The slow hop treats every other dim — including a leading stacked
    layer dim — as batch, so a slice of the stacked secondary equals the
    secondary of the slice.
    """
    from deepspeed_tpu.comm.comm import compressed_op_span

    slow = axes[0]
    w_slow = mesh_lib.manual_axis_size(slow)
    m = x.size
    if quantize_bits is not None:
        return qwz.quantized_all_gather(
            x, (slow,), dim=dim, bits=quantize_bits, block_size=block_size,
            out_dtype=secondary_dtype)
    wire = qwz.logical_bytes(m, w_slow, jnp.dtype(secondary_dtype).itemsize)
    with compressed_op_span(
            "hpz_secondary_gather",
            logical_bytes=qwz.logical_bytes(m, w_slow),
            wire_bytes=wire, group=(slow,)):
        return qwz.merge_at_dim(
            lax.all_gather(x.astype(secondary_dtype), slow,
                           axis=0, tiled=False), dim)


def hierarchical_gather(x: jax.Array, dim: int, axes: Sequence[str],
                        quantize_bits: Optional[int] = None,
                        block_size: int = 256,
                        secondary_dtype=jnp.bfloat16,
                        out_dtype=jnp.float32,
                        checkpoint_fast: bool = True
                        ) -> Tuple[jax.Array, jax.Array]:
    """Gather ``x`` (the primary shard, dim partitioned over ``axes``
    major→minor = (slow, fast)) into the full tensor, returning
    ``(full, secondary)`` where ``secondary`` is the fast-axis-only shard
    to persist for ``fast_regather``.

    The slow hop uses qwZ quantization when ``quantize_bits`` is set,
    otherwise a plain all-gather of the ``secondary_dtype`` cast (still a
    2x wire saving vs fp32).  The fast regather is wrapped in
    ``jax.checkpoint`` so the full weights are rematerialized rather than
    saved for backward — hpZ's memory story depends on only the secondary
    shard being live between fwd and bwd.
    """
    from deepspeed_tpu.comm.comm import compressed_op_span

    slow, fast = axes
    w_slow = mesh_lib.manual_axis_size(slow)

    # dim now Ws*g: the fast-axis shard of the full dim
    secondary = slow_gather_secondary(x, dim, axes, quantize_bits=quantize_bits,
                                      block_size=block_size,
                                      secondary_dtype=secondary_dtype)

    def _fast(sec):
        w_fast = mesh_lib.manual_axis_size(fast)
        with compressed_op_span(
                "hpz_fast_all_gather",
                logical_bytes=qwz.logical_bytes(
                    sec.size, w_fast, jnp.dtype(secondary_dtype).itemsize),
                wire_bytes=qwz.logical_bytes(
                    sec.size, w_fast, jnp.dtype(secondary_dtype).itemsize),
                group=(fast,)):
            return fast_regather(sec, dim, fast, w_slow, out_dtype=out_dtype)

    if checkpoint_fast:
        _fast = jax.checkpoint(_fast)
    return _fast(secondary), secondary


# --------------------------------------------------------------------------- #
# Byte accounting (per device, receive-side)
# --------------------------------------------------------------------------- #
def refresh_wire_bytes(shard_elems: int, w_slow: int, w_fast: int,
                       quantize_bits: Optional[int] = None,
                       block_size: int = 256,
                       secondary_itemsize: int = 2) -> int:
    """Slow hop (quantized or secondary-dtype cast) + fast regather."""
    if quantize_bits is not None:
        slow = qwz.wire_bytes(shard_elems, w_slow, quantize_bits, block_size)
    else:
        slow = (w_slow - 1) * shard_elems * secondary_itemsize
    fast = (w_fast - 1) * shard_elems * w_slow * secondary_itemsize
    return slow + fast


def reuse_wire_bytes(shard_elems: int, w_slow: int, w_fast: int,
                     secondary_itemsize: int = 2) -> int:
    """A reuse-path gather: fast axis only, secondary dtype."""
    return (w_fast - 1) * shard_elems * w_slow * secondary_itemsize


def logical_bytes(shard_elems: int, w_slow: int, w_fast: int,
                  itemsize: int = 4) -> int:
    """The flat fp32 all-gather over the full world that standard ZeRO-3
    would run for the same primary shard."""
    world = w_slow * w_fast
    return (world - 1) * shard_elems * itemsize
