"""Compressed-collective subsystem (ZeRO++, arxiv 2306.10209).

Layered on ``comm/comm.py``: blockwise quantization core + three
topology-aware collectives that cut ZeRO-3 wire volume —

* ``qwz``  — quantized weight all-gather
* ``qgz``  — hierarchical quantized gradient reduce-scatter
* ``hpz``  — secondary intra-host weight shard (slow-axis-free regathers)

``layered`` composes the three into per-block slice gathers with
reduce-scatter backward rules for the overlapped stage-3 step (the scan
carries a prefetch ring; collectives hide under block matmuls).
"""

from deepspeed_tpu.comm.compression.core import (  # noqa: F401
    SCALE_BYTES,
    ZERO_BYTES,
    CompressionState,
    QuantizedBlocks,
    dequantize_blockwise,
    ef_compensate,
    ef_quantize,
    ef_residual,
    init_compression_state,
    n_blocks,
    padded_size,
    quantization_error_bound,
    quantize_blockwise,
    quantized_nbytes,
    sign_scale,
)
from deepspeed_tpu.comm.compression.hpz import (  # noqa: F401
    fast_regather,
    hierarchical_gather,
    slow_gather_secondary,
)
from deepspeed_tpu.comm.compression.qgz import (  # noqa: F401
    hierarchical_reduce_scatter,
    quantized_reduce_scatter_1d,
)
from deepspeed_tpu.comm.compression.qwz import (  # noqa: F401
    merge_at_dim,
    quantized_all_gather,
)
