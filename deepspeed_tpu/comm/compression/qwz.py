"""qwZ — quantized weight all-gather (ZeRO++ §4.1).

ZeRO-3 all-gathers every parameter shard at its use site; qwZ sends the
shard as blockwise uint8 codes + per-block scales instead of full-precision
elements, cutting all-gather wire volume ~4x (fp32 compute) / ~2x (bf16).
Receivers dequantize locally — lossy for the forward weights only, which is
the paper's tolerance argument (gradients w.r.t. the *dequantized* weights
stay consistent because the same dequantized values are used everywhere).

Call inside ``shard_map``.  ``axes`` is the tuple of mesh axes the shard
dim is partitioned over, MAJOR → MINOR (partition-spec order); the gather
runs minor-axis first so the leading group index of the collected parts is
major-axis-major, i.e. exactly the concatenation order of a tiled
``lax.all_gather`` over the same axes.
"""

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.parallel import mesh as mesh_lib

from deepspeed_tpu.comm.compression import core


def _axes_world(axes: Sequence[str]) -> int:
    w = 1
    for a in axes:
        w *= mesh_lib.manual_axis_size(a)
    return w


def merge_at_dim(parts: jax.Array, dim: int) -> jax.Array:
    """[W, *shard] stacked members → shard concatenated at ``dim``
    (member-major — the tiled all_gather layout)."""
    shape = parts.shape
    out = jnp.moveaxis(parts, 0, dim)
    return out.reshape(shape[1:1 + dim] + (shape[0] * shape[1 + dim],)
                       + shape[2 + dim:])


def quantized_all_gather(x: jax.Array, axes: Sequence[str], dim: int = 0,
                         bits: int = 8, block_size: int = 256,
                         out_dtype=jnp.float32) -> jax.Array:
    """All-gather ``x`` (this device's shard) along ``dim`` over ``axes``
    with a blockwise-quantized wire format.

    Parity contract (see tests): equals
    ``lax.all_gather(x, axes, axis=dim, tiled=True)`` up to the per-block
    quantization error bound — and exactly when shard values sit on their
    block's quantization lattice.
    """
    from deepspeed_tpu.comm.comm import compressed_op_span

    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    shard_shape = x.shape
    m = x.size
    q = core.quantize_blockwise(x.reshape(-1), bits=bits, block_size=block_size)

    world = _axes_world(axes)
    with compressed_op_span(
            "qwz_all_gather",
            logical_bytes=logical_bytes(m, world, jnp.dtype(out_dtype).itemsize),
            wire_bytes=wire_bytes(m, world, bits, block_size),
            group=axes):
        parts = q
        # minor axis first: after the loop the leading group dims read
        # (W_major, ..., W_minor) and flatten to the tiled member order.
        for ax in reversed(axes):
            parts = core.QuantizedBlocks(
                lax.all_gather(parts.data, ax, axis=0, tiled=False),
                lax.all_gather(parts.scale, ax, axis=0, tiled=False),
                lax.all_gather(parts.zero, ax, axis=0, tiled=False))

    def flat_members(a):
        return a.reshape((world,) + a.shape[len(axes):])

    gathered = core.QuantizedBlocks(*(flat_members(a) for a in parts))
    members = core.dequantize_blockwise(gathered, m, bits=bits, dtype=out_dtype)
    return merge_at_dim(members.reshape((world,) + shard_shape), dim)


# --------------------------------------------------------------------------- #
# Byte accounting (per device, receive-side — matches the fp32 ring
# convention the 1-bit path's ``compressed_bytes`` established).
# --------------------------------------------------------------------------- #
def wire_bytes(shard_elems: int, world: int, bits: int = 8,
               block_size: int = 256) -> int:
    """Bytes received per device: (world-1) peers' quantized shards."""
    return (world - 1) * core.quantized_nbytes(shard_elems, bits, block_size)


def logical_bytes(shard_elems: int, world: int, itemsize: int = 4) -> int:
    """What the uncompressed all-gather of the same shards would move."""
    return (world - 1) * shard_elems * itemsize
