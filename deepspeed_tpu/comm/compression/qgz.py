"""qgZ — hierarchical quantized gradient reduce-scatter (ZeRO++ §4.3).

The gradient reduce-scatter is split by mesh topology: reduction along the
FAST (innermost, intra-host ICI) axes stays exact fp32 ``psum_scatter``;
the remaining hop along the SLOW (outermost, inter-host DCN) axis travels
as blockwise-quantized codes through an all-to-all — each slow-axis peer
quantizes the sub-chunk it is about to hand off, the receiver dequantizes
and finishes the sum in fp32.  Unlike a naive "quantize the allreduce"
this never accumulates *in* low precision: every partial sum is fp32, only
the wire format is quantized — the property that lets qgZ skip error
feedback (one rounding per hop, not a compounding series).

On a single-axis mesh (the 8-device CPU test mesh, or a one-host TPU slice
where ZeRO folds all data parallelism into ``fsdp``) there is no fast/slow
split: the whole reduce-scatter is the quantized all-to-all hop.

Layout contract: for a dim partitioned over ``axes`` MAJOR → MINOR, device
(i_0, .., i_k) must end up with chunk index ``i_0·W_1·..·W_k + .. + i_k``
(the partition-spec order).  The dim is therefore viewed as
``(W_0, .., W_k, chunk)`` and each stage scatters its own axis' sub-dim —
stage order cannot produce a transposed layout by construction.
"""

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.parallel import mesh as mesh_lib

from deepspeed_tpu.comm.compression import core


def quantized_reduce_scatter_1d(y: jax.Array, axis: str, pos: int,
                                bits: int = 8, block_size: int = 256) -> jax.Array:
    """Reduce over mesh ``axis`` and scatter ``y``'s dim ``pos`` (whose size
    equals the axis size) with a quantized all-to-all: peer ``j`` receives
    everyone's quantized slice ``j``, dequantizes, and sums in fp32.
    Returns ``y`` with dim ``pos`` reduced to size 1.
    """
    w = mesh_lib.manual_axis_size(axis)
    z = jnp.moveaxis(y, pos, 0)                       # [w, ...rest]
    rest_shape = z.shape[1:]
    m = math.prod(rest_shape) if rest_shape else 1
    z = z.reshape(w, m).astype(jnp.float32)
    q = core.quantize_blockwise(z, bits=bits, block_size=block_size)
    # row j of every peer → peer j (the compressed.py exchange pattern)
    theirs = core.QuantizedBlocks(
        lax.all_to_all(q.data, axis, split_axis=0, concat_axis=0),
        lax.all_to_all(q.scale, axis, split_axis=0, concat_axis=0),
        lax.all_to_all(q.zero, axis, split_axis=0, concat_axis=0))
    mine = core.dequantize_blockwise(theirs, m, bits=bits).sum(axis=0)
    return jnp.moveaxis(mine.reshape((1,) + rest_shape), 0, pos)


def hierarchical_reduce_scatter(g: jax.Array, dim: int, axes: Sequence[str],
                                bits: Optional[int] = 8, block_size: int = 256,
                                mean: bool = True) -> jax.Array:
    """Reduce ``g`` over ``axes`` (major → minor) and keep this device's
    chunk of dim ``dim`` in partition-spec order.

    ``bits=None`` runs the same two-level schedule exactly (fp32 both hops)
    — the apples-to-apples baseline for parity tests and for configs with
    ``zero_quantized_gradients`` off.  ``mean=True`` divides by the total
    reduction world (the data-parallel gradient mean).
    """
    from deepspeed_tpu.comm.comm import compressed_op_span

    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    sizes = [mesh_lib.manual_axis_size(a) for a in axes]
    world = 1
    for s in sizes:
        world *= s
    assert g.shape[dim] % world == 0, (
        f"dim {dim} (size {g.shape[dim]}) not divisible by axes product {world}")
    chunk = g.shape[dim] // world

    with compressed_op_span(
            "qgz_reduce_scatter",
            logical_bytes=logical_bytes(g.size, world),
            wire_bytes=wire_bytes(g.size, sizes, bits, block_size),
            group=axes):
        pre = g.shape[:dim]
        post = g.shape[dim + 1:]
        y = g.reshape(pre + tuple(sizes) + (chunk,) + post).astype(jnp.float32)
        if mean:
            y = y / world
        # fast/minor stages: exact fp32, innermost first
        for i in range(len(axes) - 1, 0, -1):
            y = lax.psum_scatter(y, axes[i], scatter_dimension=len(pre) + i,
                                 tiled=True)
        # slow/major hop: quantized (or exact when bits is None)
        if bits is None:
            y = lax.psum_scatter(y, axes[0], scatter_dimension=len(pre),
                                 tiled=True)
        else:
            y = quantized_reduce_scatter_1d(y, axes[0], len(pre),
                                            bits=bits, block_size=block_size)
    return y.reshape(pre + (chunk,) + post)


# --------------------------------------------------------------------------- #
# Byte accounting (per device, receive-side)
# --------------------------------------------------------------------------- #
def wire_bytes(n: int, axes_sizes: Sequence[int], bits: Optional[int] = 8,
               block_size: int = 256) -> int:
    """Bytes received per device across both levels for an n-element leaf:
    fp32 ring psum_scatter per fast stage, then the quantized all-to-all
    over the slow axis (or fp32 when bits is None)."""
    total = 0
    n_cur = n
    for w in reversed(list(axes_sizes[1:])):
        total += (w - 1) * n_cur // w * 4
        n_cur //= w
    w0 = axes_sizes[0]
    if bits is None:
        total += (w0 - 1) * n_cur // w0 * 4
    else:
        total += (w0 - 1) * core.quantized_nbytes(n_cur // w0, bits, block_size)
    return total


def logical_bytes(n: int, world: int, itemsize: int = 4) -> int:
    """The flat single-level fp32 reduce-scatter the standard ZeRO-3 path
    would run: ring receive of (world-1)/world of the tensor."""
    return (world - 1) * (n // world) * itemsize
