"""Out-of-band recovery control plane: heartbeats, coordinated abort,
and the policy ladder (retry → elastic mesh shrink → full restart).

When a collective wedges or a rank dies, the one channel guaranteed
broken is the device mesh itself — so everything here runs host-side
over a tiny shared-filesystem rendezvous (atomic file creates and
renames; the same durability primitives the checkpoint layer trusts).
No device communication anywhere in this module.

The pieces:

* :class:`RecoveryPolicy` — parsed from the ``ds_config["elasticity"]``
  block (coexists with the elastic batch-solver keys; recovery is gated
  on its own ``recovery_enabled``).  Owns the ladder decision:
  ``next_rung`` maps (attempt, survivors, world) to ``retry`` (transient
  straggler, everyone still alive), ``shrink`` (a rank died and the
  survivor set can rebuild a smaller mesh), or ``restart`` (final rung —
  hand the incident to the elastic agent).

* :class:`FileRendezvous` — the wire format: per-rank membership and
  heartbeat files (atomic replace), a first-writer-wins abort file per
  epoch (atomic ``O_EXCL`` create), per-rank abort acks (the barrier
  that gets every survivor out of the jitted step at the same step
  boundary), and a leader-published recovery plan.

* :class:`RecoveryCoordinator` — the per-rank agent over the
  rendezvous: a background heartbeat thread, liveness detection (pid
  probe for same-host ranks — a SIGKILLed rank is visible in one poll,
  long before its heartbeat ages out), abort signal/ack/await, and
  leader plan election (lowest acked rank decides).

* :class:`RecoveryManager` — the engine-facing ladder state machine:
  incident bookkeeping, ``collective_abort``/``mesh_shrink``/
  ``recovery_*`` telemetry, the ``/recovery`` ops-endpoint payload, the
  ``/healthz`` latch, and the ``comm_recovery`` goodput booking.  The
  engine owns the actual state rebuild (retrace, re-shard, reload) —
  this module only coordinates it.

Exit protocol: ranks leaving for recovery reasons use dedicated exit
codes (:data:`MESH_SHRINK_EXIT_CODE` for survivors excluded by a shrink
plan, :data:`RECOVERY_RESTART_EXIT_CODE` for the final rung) and drop a
coordinator-confirmed marker (:func:`write_recovery_marker`) that the
elastic agent consumes to classify the exit like a preemption —
immediate restart, no restart-budget burn — even when the raw exit was
a SIGKILL (-9).

Standard library only — must import (and work) without jax.
"""

import json
import os
import socket
import threading
import time

SCHEMA_VERSION = 1

#: a survivor excluded by a shrink plan exits with this code
MESH_SHRINK_EXIT_CODE = 114
#: the final ladder rung (coordinated full restart) exits with this code
RECOVERY_RESTART_EXIT_CODE = 113
#: every coordinator-confirmed recovery exit code
RECOVERY_EXIT_CODES = (RECOVERY_RESTART_EXIT_CODE, MESH_SHRINK_EXIT_CODE)

#: env fallbacks for rendezvous identity (the e2e harness sets these)
RENDEZVOUS_DIR_ENV = "DS_RECOVERY_DIR"
RANK_ENV = "DS_RECOVERY_RANK"
WORLD_ENV = "DS_RECOVERY_WORLD"

_MARKER_NAME = "recovery_exit.json"


# --------------------------------------------------------------------------- #
# Policy
# --------------------------------------------------------------------------- #

class RecoveryPolicy:
    """The ``elasticity`` recovery keys, with the ladder decision.

    Keys (all under ``ds_config["elasticity"]``, ignored by the elastic
    batch solver which only reads its own keys):

    ``recovery_enabled``        master gate (default False)
    ``collective_timeout_s``    bounded-collective deadline (30.0)
    ``heartbeat_interval_s``    heartbeat write cadence (0.5)
    ``heartbeat_timeout_s``     heartbeat age ⇒ rank presumed dead (5.0)
    ``max_step_retries``        retry-rung attempts before escalating (2)
    ``retry_backoff_s``         base backoff between retries (0.5)
    ``min_world_size``          smallest mesh a shrink may target (1)
    ``allow_shrink``            enable the shrink rung (True)
    ``allow_restart``           enable the final restart rung (True)
    ``recovery_deadline_s``     end-to-end detect→resume bound (120.0)
    ``rendezvous_dir``          shared dir (or env ``DS_RECOVERY_DIR``)
    """

    def __init__(self, enabled=False, collective_timeout_s=30.0,
                 heartbeat_interval_s=0.5, heartbeat_timeout_s=5.0,
                 max_step_retries=2, retry_backoff_s=0.5, min_world_size=1,
                 allow_shrink=True, allow_restart=True,
                 recovery_deadline_s=120.0, rendezvous_dir=None):
        self.enabled = bool(enabled)
        self.collective_timeout_s = float(collective_timeout_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.max_step_retries = int(max_step_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.min_world_size = int(min_world_size)
        self.allow_shrink = bool(allow_shrink)
        self.allow_restart = bool(allow_restart)
        self.recovery_deadline_s = float(recovery_deadline_s)
        self.rendezvous_dir = rendezvous_dir or os.environ.get(
            RENDEZVOUS_DIR_ENV) or None

    @classmethod
    def from_config(cls, ds_config):
        """Parse the ``elasticity`` block of a ds_config dict (or a
        config object exposing ``elasticity_config``)."""
        if ds_config is None:
            block = {}
        elif isinstance(ds_config, dict):
            block = ds_config.get("elasticity", {}) or {}
        else:
            block = getattr(ds_config, "elasticity_config", {}) or {}
        return cls(
            enabled=block.get("recovery_enabled", False),
            collective_timeout_s=block.get("collective_timeout_s", 30.0),
            heartbeat_interval_s=block.get("heartbeat_interval_s", 0.5),
            heartbeat_timeout_s=block.get("heartbeat_timeout_s", 5.0),
            max_step_retries=block.get("max_step_retries", 2),
            retry_backoff_s=block.get("retry_backoff_s", 0.5),
            min_world_size=block.get("min_world_size", 1),
            allow_shrink=block.get("allow_shrink", True),
            allow_restart=block.get("allow_restart", True),
            recovery_deadline_s=block.get("recovery_deadline_s", 120.0),
            rendezvous_dir=block.get("rendezvous_dir"))

    # -- ladder -------------------------------------------------------------- #

    def shrink_target(self, n_survivors):
        """Largest power-of-two world ≤ the survivor count that stays at
        or above ``min_world_size`` — None when no legal target exists.
        Power-of-two keeps every mesh-axis factorization legal without
        re-solving the axis split here."""
        n = int(n_survivors)
        if n < max(self.min_world_size, 1):
            return None
        target = 1
        while target * 2 <= n:
            target *= 2
        if target < self.min_world_size:
            return None
        return target

    def next_rung(self, attempt, n_survivors, world_size):
        """The ladder decision for one incident iteration.

        * everyone alive + retries left → ``retry`` (transient wedge)
        * ranks missing (or retries exhausted with a legal smaller mesh
          unavailable ruled out) → ``shrink`` when allowed and feasible
        * otherwise → ``restart`` when allowed, else ``fail``
        """
        all_alive = int(n_survivors) >= int(world_size)
        if all_alive and attempt < self.max_step_retries:
            return "retry"
        if not all_alive and self.allow_shrink:
            target = self.shrink_target(n_survivors)
            if target is not None and target < int(world_size):
                return "shrink"
        if self.allow_restart:
            return "restart"
        return "fail"

    def retry_delay_s(self, attempt):
        """Exponential backoff for the retry rung (attempt is 0-based)."""
        return self.retry_backoff_s * (2.0 ** max(int(attempt), 0))

    def to_json(self):
        return {
            "enabled": self.enabled,
            "collective_timeout_s": self.collective_timeout_s,
            "heartbeat_interval_s": self.heartbeat_interval_s,
            "heartbeat_timeout_s": self.heartbeat_timeout_s,
            "max_step_retries": self.max_step_retries,
            "retry_backoff_s": self.retry_backoff_s,
            "min_world_size": self.min_world_size,
            "allow_shrink": self.allow_shrink,
            "allow_restart": self.allow_restart,
            "recovery_deadline_s": self.recovery_deadline_s,
            "rendezvous_dir": self.rendezvous_dir,
        }


def resolve_rank_world(default_world=1):
    """(rank, world) for the coordinator, from the recovery env with the
    launcher envs as fallback — single-process runs resolve to (0, 1)."""
    rank = int(os.environ.get(RANK_ENV, os.environ.get("RANK", "0")) or 0)
    world = int(os.environ.get(
        WORLD_ENV, os.environ.get("WORLD_SIZE", str(default_world)))
        or default_world)
    return rank, max(world, 1)


# --------------------------------------------------------------------------- #
# File rendezvous — the wire format
# --------------------------------------------------------------------------- #

def _write_json_atomic(path, doc):
    tmp = "%s.tmp.%d" % (path, os.getpid())
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class FileRendezvous:
    """Shared-directory rendezvous: every primitive is an atomic file
    create or replace, so partial writes are never observable.  One
    instance per rank; no locks — each rank writes only its own files,
    except the first-writer-wins abort/plan files which use ``O_EXCL``.
    """

    def __init__(self, root, rank, world_size, clock=time.time):
        self.root = str(root)
        self.rank = int(rank)
        self.world_size = int(world_size)
        self._clock = clock
        os.makedirs(os.path.join(self.root, "members"), exist_ok=True)
        os.makedirs(os.path.join(self.root, "hb"), exist_ok=True)

    # -- membership ---------------------------------------------------------- #

    def announce(self):
        _write_json_atomic(
            os.path.join(self.root, "members", "rank_%d.json" % self.rank),
            {"rank": self.rank, "pid": os.getpid(),
             "host": socket.gethostname(), "t": self._clock()})

    def members(self):
        """rank → membership doc for every announced rank."""
        out = {}
        mdir = os.path.join(self.root, "members")
        try:
            names = os.listdir(mdir)
        except OSError:
            return out
        for name in names:
            if not name.startswith("rank_") or not name.endswith(".json"):
                continue
            doc = _read_json(os.path.join(mdir, name))
            if doc is not None:
                out[int(doc["rank"])] = doc
        return out

    # -- heartbeats ----------------------------------------------------------- #

    def heartbeat(self, step=0, epoch=0):
        _write_json_atomic(
            os.path.join(self.root, "hb", "rank_%d.json" % self.rank),
            {"rank": self.rank, "pid": os.getpid(),
             "host": socket.gethostname(), "t": self._clock(),
             "step": int(step), "epoch": int(epoch)})

    def heartbeats(self):
        out = {}
        hdir = os.path.join(self.root, "hb")
        try:
            names = os.listdir(hdir)
        except OSError:
            return out
        for name in names:
            doc = _read_json(os.path.join(hdir, name))
            if doc is not None:
                out[int(doc["rank"])] = doc
        return out

    # -- abort (first writer wins) ------------------------------------------- #

    def signal_abort(self, epoch, payload):
        """Atomically create the epoch's abort file.  Returns
        ``(doc, won)``: the winning doc (ours or the earlier writer's)
        and whether this rank won the race."""
        path = os.path.join(self.root, "abort_%d.json" % int(epoch))
        doc = dict(payload)
        doc.setdefault("epoch", int(epoch))
        doc.setdefault("rank", self.rank)
        doc.setdefault("t", self._clock())
        try:
            fd = os.open(path + ".lock", os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            existing = self._await_file(path)
            return (existing if existing is not None else doc), False
        try:
            _write_json_atomic(path, doc)
        finally:
            os.close(fd)
        return doc, True

    def read_abort(self, epoch):
        return _read_json(
            os.path.join(self.root, "abort_%d.json" % int(epoch)))

    def _await_file(self, path, timeout_s=5.0, poll_s=0.02):
        """The ``.lock`` exists but the doc may still be mid-write on the
        winner — wait briefly for it to land."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            doc = _read_json(path)
            if doc is not None:
                return doc
            time.sleep(poll_s)
        return _read_json(path)

    # -- abort-ack barrier ----------------------------------------------------- #

    def ack_abort(self, epoch, info=None):
        _write_json_atomic(
            os.path.join(self.root,
                         "ack_%d_rank_%d.json" % (int(epoch), self.rank)),
            dict(info or {}, rank=self.rank, epoch=int(epoch),
                 t=self._clock()))

    def acks(self, epoch):
        """Ranks that have acked this epoch's abort."""
        out = set()
        prefix = "ack_%d_rank_" % int(epoch)
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if name.startswith(prefix) and name.endswith(".json"):
                try:
                    out.add(int(name[len(prefix):-len(".json")]))
                except ValueError:
                    pass
        return out

    # -- plan ------------------------------------------------------------------ #

    def publish_plan(self, epoch, plan):
        _write_json_atomic(
            os.path.join(self.root, "plan_%d.json" % int(epoch)), plan)

    def read_plan(self, epoch):
        return _read_json(
            os.path.join(self.root, "plan_%d.json" % int(epoch)))

    # -- quarantine ------------------------------------------------------------- #

    def write_quarantine(self, ranks, detail=None):
        doc = _read_json(os.path.join(self.root, "quarantine.json")) or {
            "schema": SCHEMA_VERSION, "ranks": [], "incidents": []}
        merged = sorted(set(doc.get("ranks", [])) | set(int(r) for r in ranks))
        doc["ranks"] = merged
        if detail:
            doc.setdefault("incidents", []).append(dict(detail))
        _write_json_atomic(os.path.join(self.root, "quarantine.json"), doc)
        return doc

    def read_quarantine(self):
        return _read_json(os.path.join(self.root, "quarantine.json"))


# --------------------------------------------------------------------------- #
# Per-rank coordinator
# --------------------------------------------------------------------------- #

class RecoveryCoordinator:
    """Heartbeat + abort agent for one rank.

    Thread model: a background daemon thread writes heartbeats at the
    policy cadence; all shared mutable state (`_step`, `_epoch`,
    `_world_size`) is guarded by ``_lock`` and copied out before any
    file I/O — the rendezvous writes never run under the lock.
    """

    def __init__(self, rendezvous, policy, clock=time.monotonic):
        self.rdv = rendezvous
        self.policy = policy
        self.rank = rendezvous.rank
        self._clock = clock
        self._lock = threading.Lock()
        self._step = 0                 # guarded-by: _lock
        self._epoch = 0                # guarded-by: _lock
        self._world_size = rendezvous.world_size   # guarded-by: _lock
        self._stop_event = threading.Event()
        self._thread = None

    # -- lifecycle ------------------------------------------------------------- #

    def start(self):
        self.rdv.announce()
        self.heartbeat_now()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._hb_loop, name="ds-tpu-recovery-hb", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop_event.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)

    def _hb_loop(self):
        interval = max(self.policy.heartbeat_interval_s, 0.05)
        while not self._stop_event.wait(interval):
            try:
                self.heartbeat_now()
            except OSError:
                pass    # rendezvous dir raced with teardown; next tick retries

    def _snapshot(self):
        with self._lock:
            return self._step, self._epoch, self._world_size

    def heartbeat_now(self):
        step, epoch, _ = self._snapshot()
        self.rdv.heartbeat(step=step, epoch=epoch)

    # -- state feeds ------------------------------------------------------------ #

    def note_step(self, step):
        with self._lock:
            self._step = int(step)

    @property
    def epoch(self):
        with self._lock:
            return self._epoch

    @property
    def world_size(self):
        with self._lock:
            return self._world_size

    # -- liveness ---------------------------------------------------------------- #

    @staticmethod
    def _pid_alive(pid):
        try:
            os.kill(int(pid), 0)
        except ProcessLookupError:
            return False
        except (OSError, ValueError, TypeError):
            return True     # not ours to probe — fall back to heartbeat age
        # signal-0 succeeds on a zombie: a SIGKILLed rank whose parent
        # has not reaped it yet would probe alive forever.  Where /proc
        # exposes the state, a zombie counts as dead.
        try:
            with open(f"/proc/{int(pid)}/stat") as f:
                stat = f.read()
            return stat.rpartition(")")[2].split()[0] != "Z"
        except (OSError, IndexError):
            return True


    def live_ranks(self, now=None):
        """Ranks currently presumed alive: heartbeat fresh, or (same
        host) pid probe positive.  A SIGKILLed same-host rank fails the
        pid probe immediately — detection does not wait for the
        heartbeat to age out."""
        now = time.time() if now is None else now
        host = socket.gethostname()
        hbs = self.rdv.heartbeats()
        members = self.rdv.members()
        live = set()
        for rank in set(hbs) | set(members):
            doc = hbs.get(rank) or members.get(rank)
            same_host = doc.get("host") == host
            if same_host and not self._pid_alive(doc.get("pid", -1)):
                continue
            age = now - float(doc.get("t", 0.0))
            if same_host or age <= self.policy.heartbeat_timeout_s:
                live.add(rank)
        return sorted(live)

    def dead_ranks(self, now=None):
        """Ranks of the CURRENT mesh that look dead.  Ranks at or above
        the current world size are ignored — their rendezvous files are
        leftovers of a pre-shrink epoch (quarantined or excluded ranks),
        and flagging them would re-open the incident on every boundary."""
        world = self.world_size
        known = set(self.rdv.members()) | set(self.rdv.heartbeats())
        known = {r for r in known if r < world}
        return sorted(known - set(self.live_ranks(now=now)))

    # -- abort protocol ------------------------------------------------------------ #

    def request_abort(self, cause, detail=None):
        """Signal (or join) this epoch's coordinated abort.  First writer
        wins; everyone converges on the same abort doc."""
        step, epoch, _ = self._snapshot()
        doc, won = self.rdv.signal_abort(epoch, {
            "schema": SCHEMA_VERSION, "cause": str(cause),
            "detail": dict(detail or {}), "step": step})
        return doc, won

    def poll_abort(self):
        """The step-boundary check: this epoch's abort doc, or None."""
        return self.rdv.read_abort(self.epoch)

    def abort_barrier(self, deadline_s=None, poll_s=0.05):
        """Ack the abort and wait for every live rank's ack (bounded).
        Returns the sorted acked-rank set — the survivor candidates.
        Ranks that never ack within the deadline (dead or still wedged)
        are simply absent; the ladder decides what that means."""
        step, epoch, _ = self._snapshot()
        self.rdv.ack_abort(epoch, {"step": step})
        bound = (self.policy.recovery_deadline_s / 4.0
                 if deadline_s is None else deadline_s)
        deadline = self._clock() + max(bound, poll_s)
        world = self.world_size
        while self._clock() < deadline:
            acked = self.rdv.acks(epoch)
            live = {r for r in self.live_ranks() if r < world}
            if live and live <= acked:
                break
            time.sleep(poll_s)
        live = {r for r in self.live_ranks() if r < world}
        return sorted(self.rdv.acks(epoch) & live | {self.rank})

    # -- plan ------------------------------------------------------------------------ #

    def is_leader(self, survivors):
        return min(survivors) == self.rank if survivors else True

    def publish_plan(self, plan):
        epoch = self.epoch
        plan = dict(plan, epoch=epoch, leader=self.rank)
        self.rdv.publish_plan(epoch, plan)
        return plan

    def await_plan(self, deadline_s=None, poll_s=0.05):
        epoch = self.epoch
        bound = (self.policy.recovery_deadline_s / 2.0
                 if deadline_s is None else deadline_s)
        deadline = self._clock() + max(bound, poll_s)
        while self._clock() < deadline:
            plan = self.rdv.read_plan(epoch)
            if plan is not None:
                return plan
            time.sleep(poll_s)
        return self.rdv.read_plan(epoch)

    def advance_epoch(self, new_world_size=None):
        """Enter the next coordination epoch (after an incident resolves);
        stale abort/ack/plan files from the old epoch become inert."""
        with self._lock:
            self._epoch += 1
            if new_world_size is not None:
                self._world_size = int(new_world_size)
            epoch = self._epoch
        self.heartbeat_now()
        return epoch


# --------------------------------------------------------------------------- #
# Engine-facing ladder state machine
# --------------------------------------------------------------------------- #

#: /recovery ladder states
LADDER_STATES = ("idle", "aborting", "retry", "shrink", "restart",
                 "recovered", "failed")


class RecoveryManager:
    """Incident bookkeeping + telemetry + ops-plane surface.

    The engine calls :meth:`begin_incident` when a deadline fires (or a
    peer's abort is observed), then reports each rung via
    :meth:`note_rung` and the terminal outcome via :meth:`note_recovered`
    / :meth:`note_failed`.  Everything here is host bookkeeping — safe
    to call from the step boundary.
    """

    def __init__(self, policy, coordinator=None, telemetry=None,
                 ledger=None, clock=time.monotonic):
        self.policy = policy
        self.coordinator = coordinator
        self.telemetry = telemetry
        self.ledger = ledger
        self._clock = clock
        self._lock = threading.Lock()
        self._state = "idle"           # guarded-by: _lock
        self._last_abort = None        # guarded-by: _lock
        self._incidents = 0            # guarded-by: _lock
        self._recoveries = 0           # guarded-by: _lock
        self._failed = False           # guarded-by: _lock
        self._incident_t0 = None       # guarded-by: _lock
        self._incident_booked = 0.0    # guarded-by: _lock
        self._last_recovery_s = None   # guarded-by: _lock
        self._quarantined = []         # guarded-by: _lock
        self._world_size = (coordinator.world_size
                            if coordinator is not None else 1)

    # -- telemetry plumbing ---------------------------------------------------- #

    def _emit(self, kind, payload):
        if self.telemetry is None:
            return
        try:
            self.telemetry.emit(kind, payload)
            self.telemetry.flush()
        except Exception:
            pass

    # -- incident lifecycle ------------------------------------------------------ #

    def begin_incident(self, cause, detail=None, step=None, backdate_s=0.0):
        """An incident opened (deadline expiry, observed peer abort, or
        detected rank death).  Emits ``collective_abort`` and flips the
        ladder out of idle.  ``backdate_s`` shifts the incident clock
        into the past — a deadline expiry means the run was already
        wedged for the whole deadline, and that wait belongs to the
        incident, not to training.  Returns the incident record."""
        with self._lock:
            self._incidents += 1
            self._state = "aborting"
            self._incident_t0 = self._clock() - max(float(backdate_s), 0.0)
            self._incident_booked = 0.0
            incident = {
                "schema": SCHEMA_VERSION,
                "incident": self._incidents,
                "cause": str(cause),
                "detail": dict(detail or {}),
                "step": step,
            }
            self._last_abort = incident
        self._emit("collective_abort", dict(incident))
        return incident

    def note_rung(self, rung, attempt=0, detail=None):
        """One ladder rung is being executed."""
        with self._lock:
            self._state = rung
        payload = {"rung": rung, "attempt": int(attempt),
                   "detail": dict(detail or {})}
        kind = {"retry": "recovery_retry", "shrink": "mesh_shrink",
                "restart": "recovery_restart"}.get(rung, "recovery_rung")
        self._emit(kind, payload)

    def note_quarantined(self, ranks, detail=None):
        with self._lock:
            merged = sorted(set(self._quarantined) | set(int(r)
                                                         for r in ranks))
            self._quarantined = merged
        if self.coordinator is not None:
            try:
                self.coordinator.rdv.write_quarantine(ranks, detail=detail)
            except OSError:
                pass

    def note_world_size(self, world_size):
        with self._lock:
            self._world_size = int(world_size)

    def book_rung_complete(self):
        """Book the ladder time spent so far into the conserved
        ``comm_recovery`` ledger category.  The engine calls this the
        moment a rung finishes rebuilding — BEFORE the step re-runs —
        so the retried step's own wall time books as training, not
        recovery (the ledger attributes spans to whichever category
        advanced the mark last).  Incremental and idempotent across
        repeated rungs of one incident."""
        with self._lock:
            t0 = self._incident_t0
            if t0 is None:
                return 0.0
            elapsed = self._clock() - t0
            dt = max(elapsed - self._incident_booked, 0.0)
            self._incident_booked = elapsed
        if self.ledger is not None and dt > 0.0:
            try:
                self.ledger.note_comm_recovery(dt)
            except Exception:
                pass
        return dt

    def note_recovered(self, rung, detail=None):
        """The incident resolved (the step after the rung succeeded):
        emit ``recovery_resume`` with the end-to-end incident duration.
        Ledger booking happened per-rung via :meth:`book_rung_complete`;
        only if the engine never booked does the whole duration book
        here (fallback — never both)."""
        with self._lock:
            t0, self._incident_t0 = self._incident_t0, None
            booked, self._incident_booked = self._incident_booked, 0.0
            dt = (self._clock() - t0) if t0 is not None else 0.0
            self._state = "recovered"
            self._recoveries += 1
            self._last_recovery_s = dt
        if self.ledger is not None and booked == 0.0 and dt > 0.0:
            try:
                self.ledger.note_comm_recovery(dt)
            except Exception:
                pass
        self._emit("recovery_resume", dict(detail or {}, rung=rung,
                                           recovery_s=dt,
                                           booked_s=booked or dt))
        return dt

    def note_failed(self, reason, detail=None):
        with self._lock:
            t0 = self._incident_t0
            booked = self._incident_booked
            dt = (self._clock() - t0) if t0 is not None else 0.0
            self._state = "failed"
            self._failed = True
        residual = max(dt - booked, 0.0)
        if self.ledger is not None and residual > 0.0:
            try:
                self.ledger.note_comm_recovery(residual)
            except Exception:
                pass
        self._emit("recovery_failed", dict(detail or {}, reason=str(reason),
                                           recovery_s=dt))

    # -- ops-plane surface --------------------------------------------------------- #

    def status(self):
        """The ``/recovery`` endpoint body."""
        with self._lock:
            out = {
                "schema": SCHEMA_VERSION,
                "enabled": self.policy.enabled,
                "ladder_state": self._state,
                "incidents": self._incidents,
                "recoveries": self._recoveries,
                "last_abort": self._last_abort,
                "last_recovery_s": self._last_recovery_s,
                "world_size": self._world_size,
                "quarantined_ranks": list(self._quarantined),
                "policy": self.policy.to_json(),
            }
        if self.coordinator is not None:
            out["epoch"] = self.coordinator.epoch
            out["rank"] = self.coordinator.rank
        return out

    def health_check(self):
        """``/healthz`` contribution: unhealthy while an incident is in
        flight and latched unhealthy after a terminal failure; a
        *recovered* run reports healthy again (on a smaller world — the
        shrink is visible in ``world_size``/``quarantined_ranks``)."""
        with self._lock:
            active = self._state in ("aborting", "retry", "shrink",
                                     "restart")
            return {"ok": not (active or self._failed),
                    "ladder_state": self._state,
                    "incidents": self._incidents,
                    "world_size": self._world_size}


# --------------------------------------------------------------------------- #
# Agent-side recovery-exit markers (satellite S3)
# --------------------------------------------------------------------------- #

def write_recovery_marker(root, cause, epoch=0, extra=None):
    """Drop the coordinator-confirmed marker before a recovery exit so
    the supervising elastic agent classifies the (possibly ``-9``) exit
    like a preemption instead of a crash."""
    doc = dict(extra or {}, schema=SCHEMA_VERSION, cause=str(cause),
               epoch=int(epoch), pid=os.getpid(), t=time.time())
    os.makedirs(str(root), exist_ok=True)
    _write_json_atomic(os.path.join(str(root), _MARKER_NAME), doc)
    return doc


def consume_recovery_marker(root, max_age_s=600.0):
    """Agent side: read-and-consume the marker (one marker excuses one
    worker-group exit).  Returns the marker doc, or None when absent or
    stale."""
    if not root:
        return None
    path = os.path.join(str(root), _MARKER_NAME)
    doc = _read_json(path)
    if doc is None:
        return None
    try:
        os.replace(path, path + ".consumed")
    except OSError:
        return None
    if max_age_s is not None and time.time() - float(doc.get("t", 0)) \
            > max_age_s:
        return None
    return doc
