from deepspeed_tpu.comm.comm import (ReduceOp, all_gather, all_reduce, all_to_all, barrier, broadcast,
                                     broadcast_object_list, compressed_op_span, configure_comms_logger,
                                     get_axis_index, get_axis_size, get_device_count, get_local_rank,
                                     get_rank, get_world_size, init_distributed, is_initialized,
                                     log_summary, ppermute, reduce_scatter, send_recv_next,
                                     send_recv_prev)
from deepspeed_tpu.comm import compression
