"""Bounded collective execution — deadlines instead of infinite hangs.

A dead or wedged peer turns every staged collective into an infinite
host-side wait: the survivors sit inside the XLA dispatch (or inside the
trace that stages it) until the watchdog SIGABRTs the whole job.  This
module puts a configurable deadline on that wait.  ``BoundedCollective``
runs the device-blocking callable on a worker thread; the caller waits
``deadline_s`` and, on expiry, raises :class:`CollectiveTimeout` instead
of hanging — carrying the seq + structure fingerprint of the newest
still-open record in the PR 17 collective monitor, so the exception
names exactly which op died.

Threads cannot be killed in Python, so a timed-out worker is
*abandoned*: it stays parked on its (daemon) thread until the wedged
call returns or the process exits, and the next ``run`` gets a fresh
worker with a fresh queue.  The abandoned count is visible in
:meth:`BoundedCollective.stats` — a run that keeps abandoning workers
is wedging repeatedly and should be escalating up the recovery ladder
(``comm/recovery.py``), not retrying forever.

Granularity: in-program collectives fuse into XLA programs, so a single
staged op cannot be individually bounded — the deadline brackets the
*eager seams* where the host actually blocks (compiled-step dispatch in
the engine, host-level barriers, trace construction).  That is also
where a wedge manifests, so it is the right place to cut.

Standard library only — no jax at import time (the callable being
bounded owns all device interaction).
"""

import os
import queue
import threading
import time

#: env override for the default deadline (seconds); unset/0 disables
DEADLINE_ENV = "DS_COLLECTIVE_TIMEOUT_S"


class CollectiveTimeout(RuntimeError):
    """A bounded collective (or the step program containing it) exceeded
    its deadline.  Carries enough identity to attribute the hang: the
    label of the bounded call, the deadline that expired, and — when a
    collective monitor was attached — the seq + fingerprint of the
    newest still-open collective record on this rank."""

    def __init__(self, message, op=None, deadline_s=None, seq=None,
                 fingerprint=None, axis=None):
        super().__init__(message)
        self.op = op
        self.deadline_s = deadline_s
        self.seq = seq
        self.fingerprint = fingerprint
        self.axis = axis

    def context(self):
        """JSON-ready identity of the hang (telemetry / abort payloads)."""
        return {"op": self.op, "deadline_s": self.deadline_s,
                "seq": self.seq, "fingerprint": self.fingerprint,
                "axis": self.axis}


def default_deadline_s():
    """The env-configured default deadline, or ``None`` when unbounded."""
    raw = os.environ.get(DEADLINE_ENV, "")
    try:
        val = float(raw)
    except (TypeError, ValueError):
        return None
    return val if val > 0.0 else None


class _Job:
    __slots__ = ("fn", "args", "kwargs", "done", "result", "error")

    def __init__(self, fn, args, kwargs):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.done = threading.Event()
        self.result = None
        self.error = None


class BoundedCollective:
    """Run device-blocking work with a deadline on a reusable worker.

    ``monitor`` is a ``CollectiveMonitor`` (or None): on timeout the
    newest open record supplies seq/fingerprint for the exception.
    ``on_timeout`` is an optional callable fired (with the
    :class:`CollectiveTimeout` about to be raised) before raising — the
    recovery manager uses it to release interruptible fault-injection
    wedges so an abandoned worker can drain instead of leaking.
    """

    def __init__(self, deadline_s=None, monitor=None, on_timeout=None,
                 clock=time.monotonic):
        self.deadline_s = deadline_s
        self.monitor = monitor
        self.on_timeout = on_timeout
        self._clock = clock
        self._lock = threading.Lock()
        self._worker = None            # guarded-by: _lock
        self._queue = None             # guarded-by: _lock
        self._generation = 0           # guarded-by: _lock
        self.abandoned = 0             # workers left wedged on a timeout
        self.timeouts = 0
        self.calls = 0

    # -- worker plumbing ---------------------------------------------------- #

    def _worker_main(self, q):
        while True:
            job = q.get()
            if job is None:
                return
            try:
                job.result = job.fn(*job.args, **job.kwargs)
            except BaseException as e:      # propagate to the caller
                job.error = e
            finally:
                job.done.set()

    def _ensure_worker(self):
        # requires-lock: _lock
        if self._worker is None or not self._worker.is_alive():
            self._queue = queue.SimpleQueue()
            self._generation += 1
            self._worker = threading.Thread(
                target=self._worker_main, args=(self._queue,),
                name="ds-tpu-bounded-%d" % self._generation, daemon=True)
            self._worker.start()
        return self._queue

    def _abandon_worker(self):
        # requires-lock: _lock
        self._worker = None
        self._queue = None
        self.abandoned += 1

    # -- timeout context ---------------------------------------------------- #

    def _open_record(self):
        """seq/fp/op of the newest still-open monitor record, if any."""
        mon = self.monitor
        if mon is None:
            return None
        try:
            for rec in reversed(mon.last_records(16)):
                if rec.get("t_exit_us") is None:
                    return rec
        except Exception:
            return None
        return None

    # -- API ----------------------------------------------------------------- #

    def run(self, fn, *args, op="collective", deadline_s=None,
            noun="collective", **kwargs):
        """Execute ``fn(*args, **kwargs)`` under the deadline.

        Resolution order for the bound: explicit ``deadline_s`` argument,
        the instance default, the ``DS_COLLECTIVE_TIMEOUT_S`` env.  With
        no bound configured the call runs inline on the caller thread —
        zero overhead, natural tracebacks, exactly the pre-PR behavior.
        ``noun`` labels the bounded work in the timeout message — the
        serving engine bounds compiled *step* dispatches through the same
        machinery and must not report them as collectives.
        """
        bound = deadline_s
        if bound is None:
            bound = self.deadline_s
        if bound is None:
            bound = default_deadline_s()
        if not bound or bound <= 0.0:
            return fn(*args, **kwargs)

        self.calls += 1
        job = _Job(fn, args, kwargs)
        with self._lock:
            q = self._ensure_worker()
        q.put(job)
        if not job.done.wait(bound):
            with self._lock:
                self._abandon_worker()
            self.timeouts += 1
            rec = self._open_record()
            err = CollectiveTimeout(
                "%s %r exceeded its %.3fs deadline%s" % (
                    noun, op, bound,
                    (" (open seq=%s op=%s fp=%s)" % (
                        rec["seq"], rec["op"], rec["fp"]) if rec else "")),
                op=(rec["op"] if rec else op), deadline_s=float(bound),
                seq=(rec["seq"] if rec else None),
                fingerprint=(rec["fp"] if rec else None),
                axis=(rec["axis"] if rec else None))
            if self.on_timeout is not None:
                try:
                    self.on_timeout(err)
                except Exception:
                    pass
            raise err
        if job.error is not None:
            raise job.error
        return job.result

    def stats(self):
        return {"calls": self.calls, "timeouts": self.timeouts,
                "abandoned": self.abandoned,
                "deadline_s": self.deadline_s}

    def shutdown(self):
        """Stop the idle worker (wedged workers are already abandoned)."""
        with self._lock:
            q, self._queue = self._queue, None
            self._worker = None
        if q is not None:
            q.put(None)
