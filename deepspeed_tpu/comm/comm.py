"""Communication facade — the ``deepspeed.comm`` equivalent.

The reference (``deepspeed/comm/comm.py:214-522``) wraps torch.distributed
with a backend object, op timing, and env-based rendezvous.  TPU-native, the
layer splits in two:

1. **In-program collectives** (the hot path): functions usable inside
   ``jit``/``shard_map`` that lower to XLA collectives over ICI/DCN —
   ``all_reduce``/``all_gather``/``reduce_scatter``/``all_to_all``/
   ``ppermute``/``send_recv``.  "Process groups" are mesh axis names
   (see ``parallel/mesh.py``).  These carry the CommsLogger hooks the
   reference applies via ``@timed_op`` (``comm/comm.py:104-137``).

2. **Host-level control plane**: ``init_distributed`` (wraps
   ``jax.distributed.initialize`` — replaces RANK/MASTER_ADDR plumbing),
   ``barrier``, object broadcast — used by the launcher, checkpointing, and
   tests, never inside a compiled step.

Rank semantics: the reference's "rank" is one GPU == one process.  Here a
*device* index plays that role in collectives, while ``get_rank()`` keeps the
process-index meaning for launcher/checkpoint code (on TPU pods one process
drives several chips).
"""

import os
from contextlib import contextmanager
from dataclasses import dataclass
from enum import Enum
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.telemetry.tracing import get_global_tracer
from deepspeed_tpu.testing.fault_injection import fault_point
from deepspeed_tpu.utils.logging import logger

AxisNames = Union[str, Sequence[str]]


class ReduceOp(Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4
    UNUSED = 5


# --------------------------------------------------------------------------- #
# State (reference: the `cdb` global backend object, comm/comm.py:36)
# --------------------------------------------------------------------------- #
_INITIALIZED = False
_COMMS_LOGGER = None


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(dist_backend: str = "xla",
                     auto_mpi_discovery: bool = True,
                     distributed_port: int = 29500,
                     verbose: bool = True,
                     timeout=None,
                     init_method: Optional[str] = None,
                     dist_init_required: Optional[bool] = None,
                     config=None,
                     rank: int = -1,
                     world_size: int = -1):
    """Initialize the distributed runtime (reference ``comm/comm.py:526``).

    Multi-host: calls ``jax.distributed.initialize`` with coordinator info
    from env (``COORDINATOR_ADDRESS``/``MASTER_ADDR``+port, ``RANK`` or
    ``PROCESS_ID``, ``WORLD_SIZE``/``NUM_PROCESSES``).  Single-host: no-op —
    JAX already sees all local devices.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    num_procs = int(os.environ.get("WORLD_SIZE", os.environ.get("NUM_PROCESSES", "1")))
    if world_size > 0:
        num_procs = world_size
    # NOTE: must not touch jax.process_count()/devices() before
    # jax.distributed.initialize — instantiating the local backend first
    # makes the distributed init fail.  Gate on env instead.
    if num_procs > 1 and not jax.distributed.is_initialized():
        coord = os.environ.get("COORDINATOR_ADDRESS")
        if coord is None:
            master = os.environ.get("MASTER_ADDR", "127.0.0.1")
            port = os.environ.get("MASTER_PORT", str(distributed_port))
            coord = f"{master}:{port}"
        proc_id = rank if rank >= 0 else int(os.environ.get("RANK", os.environ.get("PROCESS_ID", "0")))
        if verbose:
            logger.info(f"Initializing jax.distributed: coordinator={coord} "
                        f"process={proc_id}/{num_procs}")
        jax.distributed.initialize(coordinator_address=coord, num_processes=num_procs,
                                   process_id=proc_id)
    _INITIALIZED = True


def get_rank() -> int:
    return jax.process_index()


def get_world_size(group: Optional[AxisNames] = None) -> int:
    if group is not None:
        from deepspeed_tpu.parallel import mesh as mesh_mod
        if mesh_mod.has_mesh():
            axes = (group,) if isinstance(group, str) else tuple(group)
            n = 1
            for a in axes:
                n *= mesh_mod.axis_size(a)
            return n
    return jax.process_count()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", 0))


def get_device_count() -> int:
    return jax.device_count()


def barrier(group=None):
    """Cross-process barrier (reference ``comm/comm.py:barrier``)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("deepspeed_tpu_barrier")


def broadcast_object_list(objs, src: int = 0, group=None):
    """Host-level object broadcast used for checkpoint tags and shape
    metadata (reference pipeline p2p pickle channel, ``pipe/p2p.py:100``)."""
    if jax.process_count() == 1:
        return objs
    import pickle
    import numpy as np
    from jax.experimental import multihost_utils
    payload = pickle.dumps(objs)
    n = np.array([len(payload)], dtype=np.int32)
    n = multihost_utils.broadcast_one_to_all(n, is_source=get_rank() == src)
    buf = np.frombuffer(payload.ljust(int(n[0]), b"\0"), dtype=np.uint8).copy()
    buf = multihost_utils.broadcast_one_to_all(buf, is_source=get_rank() == src)
    return pickle.loads(buf.tobytes()[:int(n[0])])


# --------------------------------------------------------------------------- #
# CommsLogger + tracer hook — records (op, bytes) at trace time; wall-clock
# timing is attached at the step level since ops fuse inside XLA.
# --------------------------------------------------------------------------- #
@dataclass
class _CommRecord:
    name: str
    bytes: int
    count: int = 1


def configure_comms_logger(comms_logger):
    global _COMMS_LOGGER
    _COMMS_LOGGER = comms_logger


_METRICS_REGISTRY = None
_COLLECTIVE_MONITOR = None


def configure_metrics_registry(registry):
    """Attach the live MetricsRegistry: every staged collective then
    increments ``comm_bytes_total{op=...}`` / ``comm_ops_total{op=...}``.
    Same trace-time semantics as the CommsLogger append in ``_log_op`` —
    counts mark when collectives were *staged* into an XLA program (run
    time shows up in profiler captures, and measured latencies reach the
    registry through the ``comm_summary`` fold)."""
    global _METRICS_REGISTRY
    _METRICS_REGISTRY = registry


def configure_collective_monitor(monitor):
    """Attach the per-rank CollectiveMonitor: every collective through the
    facade then gets a monotonic seq_no + structure fingerprint in the
    monitor's bounded ring, with enter/exit stamps.  Same trace-time
    semantics as the other hooks — records mark when collectives were
    *staged*; eager-boundary callers get true execution brackets."""
    global _COLLECTIVE_MONITOR
    _COLLECTIVE_MONITOR = monitor


@contextmanager
def _log_op(name: str, tensor, group=None):
    """Per-collective instrumentation: appends (op, bytes) to the
    CommsLogger, records a seq/fingerprint entry in the collective
    monitor's ring, and opens a ``comm.<op>`` span tagged
    {op, axis, bytes, seq} on the global tracer — the span's ``seq``
    joins trace timelines to collective records by (rank, seq).  All of
    it fires at *trace* time — the op itself fuses into the XLA program,
    so the span marks when the collective was staged (and, via
    jax.named_scope, names it in device profiles); run time shows up in
    the profiler capture, not here.  Zero-sync: reads only aval metadata
    (size/dtype/shape), never a device value."""
    fault_point("comm.collective", op=name)
    try:
        nbytes = tensor.size * tensor.dtype.itemsize
    except Exception:
        nbytes = 0
    if _COMMS_LOGGER is not None:
        _COMMS_LOGGER.append(name, nbytes)
    if _METRICS_REGISTRY is not None:
        _METRICS_REGISTRY.counter("comm_bytes_total",
                                  {"op": name}).inc(nbytes)
        _METRICS_REGISTRY.counter("comm_ops_total", {"op": name}).inc()
    axis = group if isinstance(group, (str, type(None))) else "+".join(group)
    mon = _COLLECTIVE_MONITOR
    rec = None
    if mon is not None:
        try:
            shape = tuple(tensor.shape)
        except Exception:
            shape = ()
        try:
            rec = mon.begin(name, axis, str(getattr(tensor, "dtype", "?")),
                            shape, nbytes)
        except Exception:
            rec = None
    try:
        tracer = get_global_tracer()
        if tracer is None:
            yield
            return
        span_args = {"op": name, "axis": axis, "bytes": nbytes}
        if rec is not None:
            span_args["seq"] = rec["seq"]
        with tracer.span(f"comm.{name}", **span_args):
            yield
    finally:
        if rec is not None:
            mon.end(rec)


@contextmanager
def compressed_op_span(name: str, logical_bytes: int, wire_bytes: int,
                       group=None):
    """Span hook for compressed collectives (qwZ/qgZ/hpZ) carrying BOTH
    logical and on-wire byte counts so compression ratio is readable
    straight off the trace.  Trace-time only, like ``_log_op`` — but no
    CommsLogger append here: compressed ops run every executed step while
    this context fires once per compile, so the engine accounts per-step
    bytes itself from the same accounting helpers."""
    fault_point("comm.collective", op=name)
    axis = group if isinstance(group, (str, type(None))) else "+".join(group)
    mon = _COLLECTIVE_MONITOR
    rec = None
    if mon is not None:
        try:
            rec = mon.begin(name, axis, "", (), int(wire_bytes))
        except Exception:
            rec = None
    try:
        tracer = get_global_tracer()
        if tracer is None:
            yield
            return
        span_args = {"op": name, "axis": axis,
                     "logical_bytes": int(logical_bytes),
                     "wire_bytes": int(wire_bytes)}
        if rec is not None:
            span_args["seq"] = rec["seq"]
        with tracer.span(f"comm.{name}", **span_args):
            yield
    finally:
        if rec is not None:
            mon.end(rec)


# --------------------------------------------------------------------------- #
# In-program collectives (use inside jit/shard_map; `group` = mesh axis name)
# --------------------------------------------------------------------------- #
def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = "data", **kw):
    """Reduce across a mesh axis (reference ``comm/comm.py:all_reduce:214``
    → here an XLA ``psum``/``pmin``/``pmax`` over ICI)."""
    with _log_op("all_reduce", tensor, group):
        if op in (ReduceOp.SUM, ReduceOp.AVG):
            out = lax.psum(tensor, group)
            if op == ReduceOp.AVG:
                out = out / get_axis_size(group)
            return out
        if op == ReduceOp.MIN:
            return lax.pmin(tensor, group)
        if op == ReduceOp.MAX:
            return lax.pmax(tensor, group)
        if op == ReduceOp.PRODUCT:
            # No pprod primitive; reconstruct from log-magnitude + sign parity
            # so negatives and zeros reduce correctly.
            safe = jnp.where(tensor == 0, jnp.ones_like(tensor), jnp.abs(tensor))
            mag = jnp.exp(lax.psum(jnp.log(safe), group))
            neg = lax.psum((tensor < 0).astype(jnp.int32), group)
            any_zero = lax.pmax((tensor == 0).astype(jnp.int32), group)
            sign = jnp.where(neg % 2 == 1, -1.0, 1.0)
            return jnp.where(any_zero == 1, jnp.zeros_like(mag), sign * mag)
        raise ValueError(f"unsupported reduce op {op}")


def all_gather(tensor, group: AxisNames = "data", axis: int = 0, tiled: bool = True):
    """Gather shards along ``axis`` across a mesh axis (reference
    ``all_gather_into_tensor``, ``comm/comm.py:308``)."""
    with _log_op("all_gather", tensor, group):
        return lax.all_gather(tensor, group, axis=axis, tiled=tiled)


def reduce_scatter(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisNames = "data",
                   scatter_dimension: int = 0):
    """Reduce then scatter along ``scatter_dimension`` (reference
    ``reduce_scatter_tensor``, ``comm/comm.py:239``)."""
    with _log_op("reduce_scatter", tensor, group):
        out = lax.psum_scatter(tensor, group, scatter_dimension=scatter_dimension,
                               tiled=True)
        if op == ReduceOp.AVG:
            out = out / get_axis_size(group)
        return out


def all_to_all(tensor, group: AxisNames = "expert", split_axis: int = 0, concat_axis: int = 0):
    """All-to-all over a mesh axis (reference ``all_to_all_single``; MoE
    dispatch ``moe/sharded_moe.py:_AllToAll:90``)."""
    with _log_op("all_to_all", tensor, group):
        return lax.all_to_all(tensor, group, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def broadcast(tensor, src: int = 0, group: AxisNames = "data"):
    """Broadcast the ``src`` shard's value to all members of the axis."""
    with _log_op("broadcast", tensor, group):
        idx = lax.axis_index(group)
        return lax.psum(jnp.where(idx == src, tensor, jnp.zeros_like(tensor)),
                        group)


def ppermute(tensor, perm, group: AxisNames = "pipe"):
    """Point-to-point ring shift — the pipeline P2P primitive (reference
    ``pipe/p2p.py:50,71``; here one XLA ``ppermute`` over the pipe axis)."""
    with _log_op("ppermute", tensor, group):
        return lax.ppermute(tensor, group, perm)


def send_recv_next(tensor, group: AxisNames = "pipe"):
    """Shift shards to the next rank on the axis (ring forward)."""
    n = get_axis_size(group)
    return ppermute(tensor, [(i, (i + 1) % n) for i in range(n)], group)


def send_recv_prev(tensor, group: AxisNames = "pipe"):
    n = get_axis_size(group)
    return ppermute(tensor, [((i + 1) % n, i) for i in range(n)], group)


def get_axis_size(group: AxisNames) -> int:
    axes = (group,) if isinstance(group, str) else tuple(group)
    n = 1
    for a in axes:
        n *= lax.axis_size(a)
    return n


def get_axis_index(group: str):
    return lax.axis_index(group)


# inference/debug helpers -------------------------------------------------- #
def get_global_rank(group, group_rank):
    return group_rank


def log_summary():
    if _COMMS_LOGGER is not None:
        _COMMS_LOGGER.log_all()
