"""BERT encoder family — the reference's headline benchmark model.

The reference's fused transformer training kernel
(``csrc/transformer/ds_transformer_cuda.cpp`` + the
``DeepSpeedTransformerLayer`` wrapper, ``ops/transformer/transformer.py:296``)
is a BERT-style encoder layer, and its 64-TFLOPS/V100 record
(BASELINE.md) is BERT-large pretraining.  This is the TPU-native encoder:

* classic post-LN blocks (``pre_ln=True`` gives the preln variant the
  reference ships as ``modelingpreln.py``);
* bidirectional Pallas flash attention (``causal=False``);
* ``lax.scan`` over layers, Megatron TP partition specs, ZeRO-composable
  — same machinery as ``models/gpt.py``;
* masked-LM loss with padded-vocab masking (pretraining objective).
"""

import dataclasses
import math
from functools import partial
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from deepspeed_tpu.comm.compression import layered as zero_layered
from deepspeed_tpu.models.gpt import (_activation, _dense_init, _dropout,
                                      layer_norm)
from deepspeed_tpu.parallel import mesh as mesh_lib

Array = jax.Array
_constrain = mesh_lib.constrain


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None
    hidden_dropout_prob: float = 0.0
    pre_ln: bool = False          # reference's modelingpreln variant
    scan_layers: bool = True
    remat: bool = False
    attn_impl: str = "auto"
    dtype: Any = jnp.bfloat16
    ln_eps: float = 1e-12
    activation: str = "gelu"     # HF hidden_act (exact gelu for stock BERT)
    vocab_multiple: int = 128
    # encoder-family variants sharing the fused block (reference serves
    # these via separate containers — distil_bert.py, clip.py):
    causal: bool = False         # CLIP text towers are causal encoders
    embed_layernorm: bool = True   # DistilBERT yes, CLIP no
    final_layernorm: bool = False  # CLIP final_layer_norm (params ln_f_g/b)
    mlm_head: bool = True          # towers without an MLM head skip it

    def __post_init__(self):
        self.padded_vocab = int(math.ceil(
            self.vocab_size / self.vocab_multiple) * self.vocab_multiple)
        assert self.hidden_size % self.num_attention_heads == 0
        self.head_dim = self.hidden_size // self.num_attention_heads
        self.ffn = self.intermediate_size or 4 * self.hidden_size


BERT_PRESETS = {
    "tiny":       dict(vocab_size=512, max_position_embeddings=128,
                       hidden_size=64, num_hidden_layers=2, num_attention_heads=4),
    "bert-base":  dict(hidden_size=768, num_hidden_layers=12, num_attention_heads=12),
    "bert-large": dict(hidden_size=1024, num_hidden_layers=24, num_attention_heads=16),
}


def bert_config(preset: str = "bert-base", **overrides) -> BertConfig:
    kw = dict(BERT_PRESETS[preset])
    kw.update(overrides)
    return BertConfig(**kw)


# --------------------------------------------------------------------------- #
def _init_block(cfg: BertConfig, rng: Array) -> Dict:
    E, I = cfg.hidden_size, cfg.ffn
    ks = jax.random.split(rng, 4)
    scale = 0.02
    return {
        "qkv_w": _dense_init(ks[0], E, (E, 3 * E), scale=scale),
        "qkv_b": jnp.zeros((3 * E,), jnp.float32),
        "out_w": _dense_init(ks[1], E, (E, E), scale=scale),
        "out_b": jnp.zeros((E,), jnp.float32),
        "ln1_g": jnp.ones((E,), jnp.float32),
        "ln1_b": jnp.zeros((E,), jnp.float32),
        "fc_w": _dense_init(ks[2], E, (E, I), scale=scale),
        "fc_b": jnp.zeros((I,), jnp.float32),
        "proj_w": _dense_init(ks[3], I, (I, E), scale=scale),
        "proj_b": jnp.zeros((E,), jnp.float32),
        "ln2_g": jnp.ones((E,), jnp.float32),
        "ln2_b": jnp.zeros((E,), jnp.float32),
    }


def init_bert_params(cfg: BertConfig, rng: Array) -> Dict:
    ks = jax.random.split(rng, 5)
    E, L = cfg.hidden_size, cfg.num_hidden_layers
    if cfg.scan_layers:
        blocks = jax.vmap(partial(_init_block, cfg))(jax.random.split(ks[0], L))
    else:
        blocks = {f"h{i}": _init_block(cfg, k)
                  for i, k in enumerate(jax.random.split(ks[0], L))}
    p = {
        "wte": _dense_init(ks[1], cfg.padded_vocab, (cfg.padded_vocab, E)),
        "wpe": _dense_init(ks[2], cfg.max_position_embeddings,
                           (cfg.max_position_embeddings, E), scale=0.01),
        "blocks": blocks,
    }
    if cfg.type_vocab_size > 0:
        p["wtt"] = _dense_init(ks[3], cfg.type_vocab_size,
                               (cfg.type_vocab_size, E), scale=0.01)
    if cfg.embed_layernorm:
        p["ln_emb_g"] = jnp.ones((E,), jnp.float32)
        p["ln_emb_b"] = jnp.zeros((E,), jnp.float32)
    if cfg.final_layernorm:
        p["ln_f_g"] = jnp.ones((E,), jnp.float32)
        p["ln_f_b"] = jnp.zeros((E,), jnp.float32)
    if cfg.mlm_head:
        # MLM transform head (dense + LN; decoder tied to wte + per-vocab
        # bias, the HF cls.predictions.bias)
        p.update({
            "mlm_w": _dense_init(ks[4], E, (E, E)),
            "mlm_b": jnp.zeros((E,), jnp.float32),
            "ln_mlm_g": jnp.ones((E,), jnp.float32),
            "ln_mlm_b": jnp.zeros((E,), jnp.float32),
            "mlm_decoder_b": jnp.zeros((cfg.padded_vocab,), jnp.float32),
        })
    return p


_BLOCK_SPECS = {
    "qkv_w": PartitionSpec(None, "tensor"), "qkv_b": PartitionSpec("tensor"),
    "out_w": PartitionSpec("tensor", None), "out_b": PartitionSpec(),
    "ln1_g": PartitionSpec(), "ln1_b": PartitionSpec(),
    "fc_w": PartitionSpec(None, "tensor"), "fc_b": PartitionSpec("tensor"),
    "proj_w": PartitionSpec("tensor", None), "proj_b": PartitionSpec(),
    "ln2_g": PartitionSpec(), "ln2_b": PartitionSpec(),
}


def bert_partition_specs(cfg: BertConfig) -> Dict:
    def block_specs(stacked: bool):
        pre = (None,) if stacked else ()
        return {k: PartitionSpec(*pre, *s) for k, s in _BLOCK_SPECS.items()}

    blocks = (block_specs(True) if cfg.scan_layers
              else {f"h{i}": block_specs(False)
                    for i in range(cfg.num_hidden_layers)})
    specs = {
        "wte": PartitionSpec("tensor", None),
        "wpe": PartitionSpec(),
        "blocks": blocks,
    }
    if cfg.type_vocab_size > 0:
        specs["wtt"] = PartitionSpec()
    if cfg.embed_layernorm:
        specs["ln_emb_g"] = PartitionSpec()
        specs["ln_emb_b"] = PartitionSpec()
    if cfg.final_layernorm:
        specs["ln_f_g"] = PartitionSpec()
        specs["ln_f_b"] = PartitionSpec()
    if cfg.mlm_head:
        specs.update({
            "mlm_w": PartitionSpec(), "mlm_b": PartitionSpec(),
            "ln_mlm_g": PartitionSpec(), "ln_mlm_b": PartitionSpec(),
            "mlm_decoder_b": PartitionSpec("tensor"),
        })
    return specs


# --------------------------------------------------------------------------- #
def bert_block(cfg: BertConfig, p: Dict, x: Array,
               attention_fn: Callable, rng: Optional[Array] = None,
               train: bool = False, attn_bias: Optional[Array] = None) -> Array:
    """Post-LN (or pre-LN) bidirectional encoder block."""
    B, S, E = x.shape
    H, D = cfg.num_attention_heads, cfg.head_dim
    dt = x.dtype
    r = (jax.random.split(rng, 2) if rng is not None else (None, None))
    drop = lambda h, k: _dropout(h, cfg.hidden_dropout_prob, k, train)

    def attn(h):
        qkv = h @ p["qkv_w"].astype(dt) + p["qkv_b"].astype(dt)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = _constrain(q.reshape(B, S, H, D), mesh_lib.BATCH_AXES, "seq", "tensor", None)
        k = _constrain(k.reshape(B, S, H, D), mesh_lib.BATCH_AXES, "seq", "tensor", None)
        v = _constrain(v.reshape(B, S, H, D), mesh_lib.BATCH_AXES, "seq", "tensor", None)
        o = attention_fn(q, k, v, causal=cfg.causal,
                         bias=attn_bias).reshape(B, S, E)
        return o @ p["out_w"].astype(dt) + p["out_b"].astype(dt)

    def mlp(h):
        h = h @ p["fc_w"].astype(dt) + p["fc_b"].astype(dt)
        h = _activation(h, cfg.activation)
        return h @ p["proj_w"].astype(dt) + p["proj_b"].astype(dt)

    if cfg.pre_ln:
        x = x + drop(attn(layer_norm(x, p["ln1_g"], p["ln1_b"], eps=cfg.ln_eps)), r[0])
        x = x + drop(mlp(layer_norm(x, p["ln2_g"], p["ln2_b"], eps=cfg.ln_eps)), r[1])
    else:
        x = layer_norm(x + drop(attn(x), r[0]), p["ln1_g"], p["ln1_b"], eps=cfg.ln_eps)
        x = layer_norm(x + drop(mlp(x), r[1]), p["ln2_g"], p["ln2_b"], eps=cfg.ln_eps)
    return _constrain(x, mesh_lib.BATCH_AXES, "seq", None)


def bert_encode(cfg: BertConfig, params: Dict, input_ids: Array,
                token_type_ids: Optional[Array] = None,
                attention_fn: Optional[Callable] = None,
                rng: Optional[Array] = None, train: bool = False,
                attention_mask: Optional[Array] = None) -> Array:
    """Hidden states [B, S, E].  ``attention_mask`` [B, S] (1 = real,
    0 = pad, the HF serving convention) becomes an additive key bias so
    pad tokens never receive attention."""
    B, S = input_ids.shape
    dt = cfg.dtype
    with jax.named_scope("embed"):
        x = params["wte"].astype(dt)[input_ids]
        x = x + params["wpe"].astype(dt)[:S][None]
        if cfg.type_vocab_size > 0:
            tt = (token_type_ids if token_type_ids is not None
                  else jnp.zeros_like(input_ids))
            x = x + params["wtt"].astype(dt)[tt]
        if cfg.embed_layernorm:
            x = layer_norm(x, params["ln_emb_g"], params["ln_emb_b"],
                           eps=cfg.ln_eps)
        x = _dropout(x, cfg.hidden_dropout_prob, rng, train)
        x = _constrain(x, mesh_lib.BATCH_AXES, "seq", None)
    return bert_encoder_stack(cfg, params, x, attention_fn, rng=rng,
                              train=train, attention_mask=attention_mask)


def bert_encoder_stack(cfg: BertConfig, params: Dict, x: Array,
                       attention_fn: Optional[Callable] = None,
                       rng: Optional[Array] = None, train: bool = False,
                       attention_mask: Optional[Array] = None) -> Array:
    """The block stack on pre-embedded hidden states ``x`` [B, S, E] —
    shared by BERT/DistilBERT (token embeddings) and the CLIP towers
    (text embeddings / vision patch embeddings, ``models/clip.py``)."""
    from deepspeed_tpu.ops.attention import get_attention_fn
    attention_fn = attention_fn or get_attention_fn(cfg.attn_impl)
    use_rngs = rng is not None and train
    attn_bias = None
    if attention_mask is not None:
        attn_bias = jnp.where(attention_mask[:, None, None, :] > 0,
                              0.0, -1e30).astype(jnp.float32)
    body = partial(bert_block, cfg, attention_fn=attention_fn, train=train,
                   attn_bias=attn_bias)
    if cfg.remat:
        from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
            checkpoint_policy)
        body = jax.checkpoint(body, policy=checkpoint_policy())
    if cfg.scan_layers:
        L = cfg.num_hidden_layers
        rngs = (jax.random.split(jax.random.fold_in(rng, 7), L) if use_rngs
                else jnp.zeros((L, 2), jnp.uint32))

        pf = zero_layered.current_prefetch()
        if pf is not None:
            # layered ZeRO-3: blocks stay sharded; gather one slice per
            # iteration through the prefetch ring (gather i+depth while i
            # computes) so XLA overlaps the collective with the block matmuls
            blocks = params["blocks"]
            depth = pf.clamped_depth(L)
            ring = tuple(pf.gather_block(blocks, jnp.int32(k))
                         for k in range(depth))
            idxs = jnp.arange(L, dtype=jnp.int32)

            def scan_body(carry, layer):
                x, ring = carry
                nxt = pf.gather_block(blocks, jnp.minimum(layer["i"] + depth,
                                                          L - 1))
                x = body(ring[0], x, rng=layer["r"] if use_rngs else None)
                return (x, ring[1:] + (nxt,)), None
            with jax.named_scope("blocks"):
                (x, _), _ = jax.lax.scan(scan_body, (x, ring),
                                         {"r": rngs, "i": idxs})
        else:
            def scan_body(x, layer):
                p, r = layer
                return body(p, x, rng=r if use_rngs else None), None
            with jax.named_scope("blocks"):
                x, _ = jax.lax.scan(scan_body, x, (params["blocks"], rngs))
    else:
        for i in range(cfg.num_hidden_layers):
            r = jax.random.fold_in(rng, i) if use_rngs else None
            x = body(params["blocks"][f"h{i}"], x, rng=r)
    if cfg.final_layernorm:
        x = layer_norm(x, params["ln_f_g"], params["ln_f_b"], eps=cfg.ln_eps)
    return x


def bert_mlm_logits(cfg: BertConfig, params: Dict, input_ids: Array,
                    token_type_ids: Optional[Array] = None,
                    attention_fn: Optional[Callable] = None,
                    rng: Optional[Array] = None, train: bool = False,
                    attention_mask: Optional[Array] = None) -> Array:
    """Masked-LM logits [B, S, padded_vocab] — the encoder INFERENCE path
    (fixed length, no KV cache; reference
    ``module_inject/containers/bert.py`` / ``ds_bert`` serve the same
    shape).  Decoder is tied to wte with the HF per-vocab bias."""
    x = bert_encode(cfg, params, input_ids, token_type_ids, attention_fn,
                    rng=rng, train=train, attention_mask=attention_mask)
    dt = cfg.dtype
    with jax.named_scope("mlm_head"):
        h = x @ params["mlm_w"].astype(dt) + params["mlm_b"].astype(dt)
        h = _activation(h, cfg.activation)
        h = layer_norm(h, params["ln_mlm_g"], params["ln_mlm_b"], eps=cfg.ln_eps)
        logits = (h @ params["wte"].astype(dt).T).astype(jnp.float32)
        logits = logits + params["mlm_decoder_b"].astype(jnp.float32)
        # padded vocab rows never win
        pad_mask = jnp.arange(cfg.padded_vocab) >= cfg.vocab_size
        logits = jnp.where(pad_mask[None, None], -1e30, logits)
    return _constrain(logits, mesh_lib.BATCH_AXES, "seq", "tensor")


def bert_mlm_loss(cfg: BertConfig, params: Dict, input_ids: Array,
                  labels: Array, token_type_ids: Optional[Array] = None,
                  attention_fn: Optional[Callable] = None,
                  rng: Optional[Array] = None, train: bool = False) -> Array:
    """Masked-LM loss; positions with ``labels == -100`` are ignored
    (HF convention)."""
    logits = bert_mlm_logits(cfg, params, input_ids, token_type_ids,
                             attention_fn, rng=rng, train=train)
    valid = labels != -100
    tgt = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(jnp.where(valid, nll, 0.0)) / denom


class Bert:
    """Engine-compatible model object (callable convention
    ``fn(params, batch, rng, train) -> loss``)."""

    # the encoder scan consumes per-block slices through the layered ZeRO-3
    # prefetch context (engine gates the overlapped step on this attribute)
    supports_layered_zero3 = True

    def __init__(self, cfg: BertConfig):
        self.cfg = cfg

    def __call__(self, params, batch, rng, train, **_ignored):
        if len(batch) == 3:
            input_ids, token_type_ids, labels = batch
        else:
            input_ids, labels = batch
            token_type_ids = None
        return bert_mlm_loss(self.cfg, params, input_ids, labels,
                             token_type_ids, rng=rng, train=train)

    def init_params(self, rng):
        return init_bert_params(self.cfg, rng)

    def partition_specs(self):
        return bert_partition_specs(self.cfg)

    def forward_hidden(self, params, input_ids, token_type_ids=None):
        return bert_encode(self.cfg, params, input_ids, token_type_ids)

    def forward_logits(self, params, input_ids, token_type_ids=None,
                       attention_mask=None):
        """InferenceEngine forward contract (encoder: full-sequence MLM
        logits, no decode loop)."""
        return bert_mlm_logits(self.cfg, params, input_ids, token_type_ids,
                               attention_mask=attention_mask)
