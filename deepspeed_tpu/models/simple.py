"""Simple models for tests and examples.

Analogue of the reference's ``tests/unit/simple_model.py`` (SimpleModel &
friends), kept in the package so examples/bench can share them.  Models
follow the framework convention: ``__call__(*batch, train=...)`` returns the
scalar loss; ``init_params(rng)`` builds the parameter pytree.
"""

from typing import Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


class SimpleModel(nn.Module):
    """Linear stack + cross-entropy, mirroring reference SimpleModel
    (``tests/unit/simple_model.py``: Linear layers + CrossEntropyLoss)."""
    hidden_dim: int
    nlayers: int = 1
    empty_grad: bool = False

    @nn.compact
    def __call__(self, x, y, train: bool = True):
        for _ in range(self.nlayers):
            x = nn.Dense(self.hidden_dim)(x)
        logits = x
        loss = jnp.mean(
            -jnp.sum(jax.nn.log_softmax(logits) * jax.nn.one_hot(y, logits.shape[-1]), axis=-1))
        return loss

    def init_params(self, rng, batch_size: int = 4):
        x = jnp.zeros((batch_size, self.hidden_dim), jnp.float32)
        y = jnp.zeros((batch_size,), jnp.int32)
        return self.init(rng, x, y)["params"]


def random_dataset(total_samples: int, hidden_dim: int, nclasses: Optional[int] = None,
                   seed: int = 0):
    """List-style dataset of (x, y) tuples (reference
    ``simple_model.py:random_dataloader``)."""
    import numpy as np
    rng = np.random.default_rng(seed)
    nclasses = nclasses or hidden_dim
    xs = rng.standard_normal((total_samples, hidden_dim), dtype=np.float32)
    ys = rng.integers(0, nclasses, size=(total_samples,))
    return [(xs[i], ys[i].astype(np.int32)) for i in range(total_samples)]
