"""CIFAR ResNet — BASELINE workload 1 (CIFAR-10 ResNet via initialize()).

A standard pre-activation ResNet in flax, loss-returning per the framework
convention.  Small enough to run on the CPU mesh in CI.
"""

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class ResNetBlock(nn.Module):
    filters: int
    strides: int = 1

    @nn.compact
    def __call__(self, x, train: bool = True):
        residual = x
        y = nn.GroupNorm(num_groups=8)(x)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), strides=(self.strides, self.strides), padding="SAME",
                    use_bias=False)(y)
        y = nn.GroupNorm(num_groups=8)(y)
        y = nn.relu(y)
        y = nn.Conv(self.filters, (3, 3), padding="SAME", use_bias=False)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.filters, (1, 1), strides=(self.strides, self.strides),
                               use_bias=False)(residual)
        return y + residual


class ResNetCIFAR(nn.Module):
    """ResNet-(6n+2) for 32x32 inputs; depth 20 by default."""
    num_classes: int = 10
    depth: int = 20
    width: int = 16

    @nn.compact
    def __call__(self, images, labels, train: bool = True):
        n = (self.depth - 2) // 6
        x = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False)(images)
        for i, (filters, stride) in enumerate([(self.width, 1), (self.width * 2, 2),
                                               (self.width * 4, 2)]):
            for b in range(n):
                x = ResNetBlock(filters, strides=stride if b == 0 else 1)(x, train=train)
        x = nn.GroupNorm(num_groups=8)(x)
        x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        logits = nn.Dense(self.num_classes)(x)
        loss = jnp.mean(-jnp.sum(
            jax.nn.log_softmax(logits) * jax.nn.one_hot(labels, self.num_classes), axis=-1))
        return loss

    def init_params(self, rng, batch_size: int = 2):
        images = jnp.zeros((batch_size, 32, 32, 3), jnp.float32)
        labels = jnp.zeros((batch_size,), jnp.int32)
        return self.init(rng, images, labels)["params"]

    def init_variables(self, rng, batch_size: int = 2):
        images = jnp.zeros((batch_size, 32, 32, 3), jnp.float32)
        labels = jnp.zeros((batch_size,), jnp.int32)
        return self.init(rng, images, labels)
