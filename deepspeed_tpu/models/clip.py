"""CLIP text + vision towers — encoder serving for multimodal pipelines.

Reference: ``deepspeed/module_inject/containers/clip.py`` (HFCLIPLayerPolicy
feeds both CLIP towers through the fused inference transformer for Stable
Diffusion's text encoder) and the ``generic_injection`` path
(``module_inject/replace_module.py:182``).

TPU-native: both towers ARE the fused encoder stack of ``models/bert.py``
(``bert_encoder_stack``) — pre-LN blocks, quick-gelu MLP, flash/XLA
attention, scan-over-layers — parameterized by ``BertConfig``:

* **Text tower**: causal encoder (CLIP trains its text side with a causal
  mask), token + position embeddings, no embedding LN, final LN.  Pooled
  output is the EOS-position hidden state (argmax of ``ids == eos``).
* **Vision tower**: non-overlapping patch embedding — a strided conv in
  the HF module, expressed here as reshape + one MXU matmul (identical
  math: each P x P patch flattens to a row times the [3*P*P, E] kernel) —
  class token, learned position embeddings, pre-LN before the stack, and
  post-LN on the CLS row.

The diffusers UNet/VAE side of the reference's Stable-Diffusion stack is
descoped (see README "Descoped" table): its value is conv-heavy diffusion
serving, which is a different framework's job; the CLIP/text half — what
LLM-side pipelines consume — is fully served here.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from deepspeed_tpu.models.bert import (BertConfig, bert_encoder_stack,
                                       init_bert_params,
                                       bert_partition_specs)
from deepspeed_tpu.models.gpt import layer_norm, _dense_init
from deepspeed_tpu.parallel import mesh as mesh_lib

Array = jax.Array


def clip_text_config(vocab_size=49408, max_position_embeddings=77,
                     hidden_size=512, num_hidden_layers=12,
                     num_attention_heads=8, intermediate_size=2048,
                     ln_eps=1e-5, activation="gelu_quick",
                     **overrides) -> BertConfig:
    """CLIPTextConfig -> the fused encoder's config: causal pre-LN blocks,
    no token-type / embedding-LN, final LN, no MLM head."""
    kw = dict(vocab_size=vocab_size,
              max_position_embeddings=max_position_embeddings,
              hidden_size=hidden_size, num_hidden_layers=num_hidden_layers,
              num_attention_heads=num_attention_heads,
              intermediate_size=intermediate_size, ln_eps=ln_eps,
              activation=activation, pre_ln=True, causal=True,
              type_vocab_size=0, embed_layernorm=False, final_layernorm=True,
              mlm_head=False, vocab_multiple=1)
    kw.update(overrides)
    return BertConfig(**kw)


class CLIPTextEncoder:
    """CLIP text tower (reference container ``clip.py`` HFCLIPLayerPolicy).
    ``forward_logits`` (the InferenceEngine encoder contract) returns the
    final hidden states [B, S, E]."""

    def __init__(self, cfg: BertConfig, eos_token_id: int = 49407):
        self.cfg = cfg
        self.eos_token_id = eos_token_id

    def init_params(self, rng):
        return init_bert_params(self.cfg, rng)

    def partition_specs(self):
        return bert_partition_specs(self.cfg)

    def forward_logits(self, params, input_ids, attention_mask=None):
        cfg = self.cfg
        dt = cfg.dtype
        S = input_ids.shape[1]
        x = params["wte"].astype(dt)[input_ids]
        x = x + params["wpe"].astype(dt)[:S][None]
        x = mesh_lib.constrain(x, mesh_lib.BATCH_AXES, "seq", None)
        return bert_encoder_stack(cfg, params, x,
                                  attention_mask=attention_mask)

    def pooled(self, params, input_ids, attention_mask=None):
        """EOS-position hidden state, matching HF CLIPTextModel
        pooler_output exactly: legacy configs carry ``eos_token_id == 2``
        while the real EOS is the highest token id, so HF pools at
        ``input_ids.argmax(-1)`` for them; otherwise at the FIRST
        occurrence of the configured eos token."""
        h = self.forward_logits(params, input_ids, attention_mask)
        if self.eos_token_id == 2:    # HF's legacy-config special case
            idx = jnp.argmax(input_ids, axis=1)
        else:
            idx = jnp.argmax((input_ids == self.eos_token_id).astype(jnp.int32),
                             axis=1)
        return jax.vmap(lambda row, i: row[i])(h, idx)


@dataclasses.dataclass
class CLIPVisionConfig:
    image_size: int = 224
    patch_size: int = 32
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    ln_eps: float = 1e-5
    activation: str = "gelu_quick"
    dtype: Any = jnp.bfloat16
    scan_layers: bool = True
    attn_impl: str = "auto"

    def __post_init__(self):
        assert self.image_size % self.patch_size == 0
        self.n_patches = (self.image_size // self.patch_size) ** 2
        self.encoder = BertConfig(
            vocab_size=1, hidden_size=self.hidden_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            intermediate_size=self.intermediate_size, ln_eps=self.ln_eps,
            activation=self.activation, pre_ln=True, causal=False,
            type_vocab_size=0, embed_layernorm=False, final_layernorm=False,
            mlm_head=False, vocab_multiple=1, dtype=self.dtype,
            scan_layers=self.scan_layers, attn_impl=self.attn_impl,
            max_position_embeddings=self.n_patches + 1)


class CLIPVisionEncoder:
    """CLIP vision tower: patch-matmul embedding + CLS token + pre/post LN
    around the shared fused encoder stack."""

    def __init__(self, cfg: CLIPVisionConfig):
        self.cfg = cfg

    def init_params(self, rng):
        cfg = self.cfg
        ks = jax.random.split(rng, 3)
        E, P = cfg.hidden_size, cfg.patch_size
        p = init_bert_params(cfg.encoder, ks[0])
        del p["wte"], p["wpe"]    # vision embeds pixels, not ids
        p.update({
            "patch_w": _dense_init(ks[1], 3 * P * P, (3 * P * P, E)),
            "class_emb": jnp.zeros((E,), jnp.float32),
            "pos_emb": _dense_init(ks[2], cfg.n_patches + 1,
                                   (cfg.n_patches + 1, E), scale=0.01),
            "pre_ln_g": jnp.ones((E,), jnp.float32),
            "pre_ln_b": jnp.zeros((E,), jnp.float32),
            "post_ln_g": jnp.ones((E,), jnp.float32),
            "post_ln_b": jnp.zeros((E,), jnp.float32),
        })
        return p

    def partition_specs(self):
        specs = bert_partition_specs(self.cfg.encoder)
        del specs["wte"], specs["wpe"]
        specs.update({
            "patch_w": PartitionSpec(None, "tensor"),
            "class_emb": PartitionSpec(), "pos_emb": PartitionSpec(),
            "pre_ln_g": PartitionSpec(), "pre_ln_b": PartitionSpec(),
            "post_ln_g": PartitionSpec(), "post_ln_b": PartitionSpec(),
        })
        return specs

    def forward_logits(self, params, pixel_values):
        """[B, 3, H, W] float pixels -> final hidden states [B, N+1, E]
        (HF last_hidden_state; ``pooled`` applies the post-LN CLS)."""
        cfg = self.cfg
        dt = cfg.dtype
        P = cfg.patch_size
        B, C, H, W = pixel_values.shape
        g = H // P
        # strided conv as reshape + matmul: [B, N, C*P*P] @ [C*P*P, E].
        # HF's Conv2d kernel is [E, C, P, P]; the policy flattens it in
        # (C, P, P) order, matched by the transpose below.
        x = pixel_values.astype(dt).reshape(B, C, g, P, g, P)
        x = x.transpose(0, 2, 4, 1, 3, 5).reshape(B, g * g, C * P * P)
        x = x @ params["patch_w"].astype(dt)
        cls = jnp.broadcast_to(params["class_emb"].astype(dt), (B, 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1)
        x = x + params["pos_emb"].astype(dt)[None]
        x = layer_norm(x, params["pre_ln_g"], params["pre_ln_b"],
                       eps=cfg.ln_eps)
        x = mesh_lib.constrain(x, mesh_lib.BATCH_AXES, "seq", None)
        return bert_encoder_stack(cfg.encoder, params, x)

    def pooled(self, params, pixel_values):
        h = self.forward_logits(params, pixel_values)
        return layer_norm(h[:, 0], params["post_ln_g"], params["post_ln_b"],
                          eps=self.cfg.ln_eps)
