"""GPT-2 model family — the flagship training model, TPU-first.

The reference has no in-tree GPT model (it wraps Megatron/HF modules); this
framework ships one because the north-star benchmark is GPT-2-1.5B ZeRO-3
(BASELINE.json) and the inference stack (reference
``deepspeed/model_implementations/transformers/ds_gpt.py``) needs a concrete
architecture to fuse.

TPU-first design decisions:

* ``lax.scan`` over layers (``scan_layers=True``): one compiled block body
  regardless of depth — compile time is O(1) in ``n_layer`` and parameters
  carry a leading ``[n_layer, ...]`` dim that the ZeRO ``fsdp`` axis shards
  naturally.
* Megatron-style tensor parallelism is expressed purely as sharding
  metadata (``partition_specs``): QKV/MLP-up are column-parallel
  (output-dim ``tensor``), attn-out/MLP-down row-parallel (input-dim
  ``tensor``), token embedding vocab-parallel.  XLA-SPMD inserts the
  per-layer allreduces that Megatron codes by hand.
* Sequence parallelism: activations are sharding-constrained to
  ``[batch, seq, embd]`` = ``(BATCH_AXES, 'seq', None)`` so a ``seq`` mesh
  axis shards the sequence dim end-to-end; the attention op handles the
  head/seq re-sharding (Ulysses) or ring pipelining (see
  ``deepspeed_tpu/ops/attention.py``).
* ``jax.checkpoint`` (remat) on the block body when ``remat=True`` — the
  analogue of the reference's activation checkpointing
  (``runtime/activation_checkpointing/checkpointing.py:474``).
* bf16 activations / fp32 params by default: the engine keeps fp32 masters
  and casts per-step (``runtime/engine.py``).
"""

import dataclasses
import math
import os
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.comm.compression import layered as zero_layered
from deepspeed_tpu.parallel import mesh as mesh_lib

Array = jax.Array


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    scan_layers: bool = True
    remat: bool = False
    attn_impl: str = "auto"   # 'auto' | 'flash' | 'reference' | 'ring'
    dtype: Any = jnp.bfloat16
    # activation of the MLP: 'gelu_tanh' (GPT-2's gelu_new), 'gelu', 'relu'
    # — lets injected foreign architectures (e.g. OPT) reuse the fused block
    activation: str = "gelu_tanh"
    ln_eps: float = 1e-5
    # separate lm_head matrix (HF tie_word_embeddings=False checkpoints);
    # params then carry an extra "lm_head" [padded_vocab, n_embd] leaf
    untied_head: bool = False
    # random-LTD (data_efficiency.data_routing.random_ltd): tokens kept per
    # block in train mode; None/>=seq disables.  Static per compile — the
    # engine swaps it as the schedule advances (one XLA program per value).
    ltd_keep: Optional[int] = None
    # non-scan path only: which block ids drop tokens (None = all); the
    # homogeneous scan path applies LTD to every block when enabled
    ltd_layers: Optional[Tuple[int, ...]] = None
    # --- architecture family knobs (one fused block serves GPT-2, BLOOM
    # (alibi), and LLaMA-style (rope+rmsnorm+swiglu) — the same strategy as
    # the reference's per-arch ds_* model_implementations variants) ------- #
    position_encoding: str = "learned"   # 'learned' | 'rope' | 'alibi'
    norm: str = "layernorm"              # 'layernorm' | 'rmsnorm'
    mlp_type: str = "standard"           # 'standard' | 'swiglu'
    intermediate_size: Optional[int] = None   # default 4*n_embd
    use_bias: bool = True                # LLaMA-style blocks are bias-free
    rope_theta: float = 10000.0
    # grouped-query attention: number of K/V heads (None = n_head = MHA;
    # 1 = MQA).  The KV cache stores only n_kv_head heads — the GQA win.
    n_kv_head: Optional[int] = None
    # pad vocab to a multiple (MXU-friendly, and divisible by tensor axis)
    vocab_multiple: int = 128
    # block topology: 'sequential' (GPT-2/OPT/LLaMA), 'parallel' (GPT-NeoX
    # use_parallel_residual: x + attn(ln1 x) + mlp(ln2 x)), or
    # 'parallel_single_ln' (GPT-J: one LN feeds both attn and mlp)
    block_type: str = "sequential"
    # rotary variants: partial rotary dims (GPT-J rotary_dim / NeoX
    # rotary_pct) and GPT-J's interleaved (rotate-every-two) pairing
    rope_dim: Optional[int] = None
    rope_interleaved: bool = False
    # untied lm_head bias (GPT-J checkpoints carry one)
    head_bias: bool = False
    # activation fake-quant (compression_training.activation_quantization;
    # reference QuantAct, compression/basic_layer.py:404): bits on the
    # normed inputs of the attention and MLP linears, STE gradients
    activation_quant_bits: Optional[int] = None
    activation_quant_type: str = "symmetric"
    # --- mixture-of-experts (reference deepspeed/moe): >0 replaces every
    # block's MLP with a top-k gated expert bank sharded over the 'expert'
    # mesh axis; the load-balance aux loss is added in gpt_loss ----------- #
    moe_num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_eval_capacity_factor: float = 2.0
    moe_min_capacity: int = 4
    moe_aux_coeff: float = 0.01
    moe_expert_hidden: Optional[int] = None

    def __post_init__(self):
        self.padded_vocab = int(
            math.ceil(self.vocab_size / self.vocab_multiple) * self.vocab_multiple)
        assert self.n_embd % self.n_head == 0
        self.head_dim = self.n_embd // self.n_head
        self.kv_heads = self.n_kv_head or self.n_head
        assert self.n_head % self.kv_heads == 0, \
            f"n_head {self.n_head} not divisible by n_kv_head {self.kv_heads}"
        self.qkv_dim = (self.n_head + 2 * self.kv_heads) * self.head_dim
        self.ffn_dim = self.intermediate_size or 4 * self.n_embd
        assert self.position_encoding in ("learned", "rope", "alibi")
        assert self.block_type in ("sequential", "parallel", "parallel_single_ln")
        assert self.moe_top_k in (1, 2), "top-1 and top-2 gating supported" 
        assert self.norm in ("layernorm", "rmsnorm")
        assert self.mlp_type in ("standard", "swiglu")


# Model zoo (GPT-2 sizes; the 1.5B "xl" is the north-star model).
GPT_PRESETS: Dict[str, Dict] = {
    "tiny":        dict(vocab_size=512, n_positions=128, n_embd=64, n_layer=2, n_head=4),
    "gpt2":        dict(n_embd=768, n_layer=12, n_head=12),
    "gpt2-medium": dict(n_embd=1024, n_layer=24, n_head=16),
    "gpt2-large":  dict(n_embd=1280, n_layer=36, n_head=20),
    "gpt2-xl":     dict(n_embd=1600, n_layer=48, n_head=25),
}


def gpt_config(preset: str = "gpt2", **overrides) -> GPTConfig:
    kw = dict(GPT_PRESETS[preset])
    kw.update(overrides)
    return GPTConfig(**kw)


def llama_config(vocab_size=32000, n_positions=2048, n_embd=512, n_layer=4,
                 n_head=8, intermediate_size=None, **overrides) -> GPTConfig:
    """LLaMA-style family: RoPE + RMSNorm + SwiGLU, bias-free, untied
    head (the reference serves these via its llama containers)."""
    kw = dict(vocab_size=vocab_size, n_positions=n_positions, n_embd=n_embd,
              n_layer=n_layer, n_head=n_head,
              position_encoding="rope", norm="rmsnorm", mlp_type="swiglu",
              use_bias=False, untied_head=True,
              intermediate_size=intermediate_size or int(n_embd * 8 / 3),
              activation="gelu")
    kw.update(overrides)
    return GPTConfig(**kw)


def bloom_config(vocab_size=250880, n_positions=2048, n_embd=512, n_layer=4,
                 n_head=8, **overrides) -> GPTConfig:
    """BLOOM family: ALiBi positions, GELU MLP, tied embeddings
    (reference ``model_implementations/transformers/ds_bloom.py``)."""
    kw = dict(vocab_size=vocab_size, n_positions=n_positions, n_embd=n_embd,
              n_layer=n_layer, n_head=n_head,
              position_encoding="alibi", activation="gelu_tanh")
    kw.update(overrides)
    return GPTConfig(**kw)


# --------------------------------------------------------------------------- #
# Parameter construction / partition specs
# --------------------------------------------------------------------------- #
def _dense_init(rng, fan_in, shape, scale=0.02):
    return (jax.random.normal(rng, shape, jnp.float32) * scale).astype(jnp.float32)


def _init_block(cfg: GPTConfig, rng: Array) -> Dict:
    """One transformer block's params (GPT-2 init: residual projections
    scaled by 1/sqrt(2L))."""
    E, I = cfg.n_embd, cfg.ffn_dim
    fc_out = 2 * I if cfg.mlp_type == "swiglu" else I   # swiglu fuses gate|up
    proj_scale = 0.02 / math.sqrt(2 * cfg.n_layer)
    ks = jax.random.split(rng, 4)
    out = {
        "ln1_g": jnp.ones((E,), jnp.float32),
        "ln1_b": jnp.zeros((E,), jnp.float32),
        "qkv_w": _dense_init(ks[0], E, (E, cfg.qkv_dim)),
        "qkv_b": jnp.zeros((cfg.qkv_dim,), jnp.float32),
        "out_w": _dense_init(ks[1], E, (E, E), scale=proj_scale),
        "out_b": jnp.zeros((E,), jnp.float32),
        "ln2_g": jnp.ones((E,), jnp.float32),
        "ln2_b": jnp.zeros((E,), jnp.float32),
        "fc_w": _dense_init(ks[2], E, (E, fc_out)),
        "fc_b": jnp.zeros((fc_out,), jnp.float32),
        "proj_w": _dense_init(ks[3], I, (I, E), scale=proj_scale),
        "proj_b": jnp.zeros((E,), jnp.float32),
    }
    if cfg.moe_num_experts > 0:
        # the MLP becomes a gated expert bank (reference moe/layer.py:16);
        # dense fc/proj weights are dropped from the pytree
        from deepspeed_tpu.moe.experts import Experts, FFNExpert
        ex = Experts(FFNExpert(E, cfg.moe_expert_hidden or I),
                     cfg.moe_num_experts)
        km = jax.random.split(jax.random.fold_in(rng, 1234), 2)
        for k in ("fc_w", "fc_b", "proj_w", "proj_b"):
            del out[k]
        out["moe"] = {
            "gate": {"wg": _dense_init(km[0], E, (E, cfg.moe_num_experts))},
            "experts": ex.init_params(km[1]),
        }
    return out


def _init_embed(cfg: GPTConfig, rng: Array) -> Dict:
    ks = jax.random.split(rng, 2)
    out = {"wte": _dense_init(ks[0], cfg.padded_vocab, (cfg.padded_vocab, cfg.n_embd))}
    if cfg.position_encoding == "learned":
        out["wpe"] = _dense_init(ks[1], cfg.n_positions,
                                 (cfg.n_positions, cfg.n_embd), scale=0.01)
    return out


def init_gpt_params(cfg: GPTConfig, rng: Array) -> Dict:
    """Parameter pytree.  Block params are stacked ``[n_layer, ...]`` when
    ``scan_layers`` (matching the lax.scan body)."""
    k_embed, k_blocks = jax.random.split(rng)
    E, L = cfg.n_embd, cfg.n_layer

    if cfg.scan_layers:
        blocks = jax.vmap(partial(_init_block, cfg))(jax.random.split(k_blocks, L))
    else:
        blocks = {f"h{i}": _init_block(cfg, k)
                  for i, k in enumerate(jax.random.split(k_blocks, L))}
    embed = _init_embed(cfg, k_embed)
    params = {
        "wte": embed["wte"],
        "blocks": blocks,
        "lnf_g": jnp.ones((E,), jnp.float32),
        "lnf_b": jnp.zeros((E,), jnp.float32),
    }
    if "wpe" in embed:
        params["wpe"] = embed["wpe"]
    if cfg.untied_head:
        params["lm_head"] = _dense_init(
            jax.random.fold_in(k_embed, 2), E, (cfg.padded_vocab, E))
        if cfg.head_bias:
            params["lm_head_b"] = jnp.zeros((cfg.padded_vocab,), jnp.float32)
    return params


_BLOCK_SPECS = {
    # Megatron TP: column-parallel QKV/fc (shard output dim), row-parallel
    # out/proj (shard input dim); biases of column-parallel layers sharded.
    "ln1_g": PartitionSpec(), "ln1_b": PartitionSpec(),
    "qkv_w": PartitionSpec(None, "tensor"), "qkv_b": PartitionSpec("tensor"),
    "out_w": PartitionSpec("tensor", None), "out_b": PartitionSpec(),
    "ln2_g": PartitionSpec(), "ln2_b": PartitionSpec(),
    "fc_w": PartitionSpec(None, "tensor"), "fc_b": PartitionSpec("tensor"),
    "proj_w": PartitionSpec("tensor", None), "proj_b": PartitionSpec(),
}


def gpt_partition_specs(cfg: GPTConfig) -> Dict:
    """Logical (tensor-parallel) PartitionSpecs matching ``init_gpt_params``.

    The ZeRO policy composes the ``fsdp`` axis on top of these
    (``runtime/zero/policy.py:zero_partition_spec``) — stage-3 + TP gives
    2-D sharded weights, the TPU analogue of Megatron+ZeRO.
    """
    def block_specs(stacked: bool):
        pre = (None,) if stacked else ()
        keys = dict(_BLOCK_SPECS)
        if cfg.moe_num_experts > 0:
            for k in ("fc_w", "fc_b", "proj_w", "proj_b"):
                del keys[k]
        specs = {k: PartitionSpec(*pre, *s) for k, s in keys.items()}
        if cfg.moe_num_experts > 0:
            specs["moe"] = {
                "gate": {"wg": PartitionSpec(*pre)},
                "experts": {
                    "wi": PartitionSpec(*pre, "expert", None, "tensor"),
                    "bi": PartitionSpec(*pre, "expert", "tensor"),
                    "wo": PartitionSpec(*pre, "expert", "tensor", None),
                    "bo": PartitionSpec(*pre, "expert", None),
                },
            }
        return specs

    if cfg.scan_layers:
        blocks = block_specs(True)
    else:
        blocks = {f"h{i}": block_specs(False) for i in range(cfg.n_layer)}
    specs = {
        "wte": PartitionSpec("tensor", None),   # vocab-parallel embedding
        "blocks": blocks,
        "lnf_g": PartitionSpec(),
        "lnf_b": PartitionSpec(),
    }
    if cfg.position_encoding == "learned":
        specs["wpe"] = PartitionSpec()
    if cfg.untied_head:
        specs["lm_head"] = PartitionSpec("tensor", None)
        if cfg.head_bias:
            specs["lm_head_b"] = PartitionSpec("tensor")
    return specs


# --------------------------------------------------------------------------- #
# Forward
# --------------------------------------------------------------------------- #
_constrain = mesh_lib.constrain


def _activation(x: Array, kind: str) -> Array:
    if kind == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    if kind == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if kind == "relu":
        return jax.nn.relu(x)
    if kind == "gelu_quick":       # CLIP's quick_gelu: x * sigmoid(1.702x)
        return x * jax.nn.sigmoid(1.702 * x)
    raise ValueError(f"unknown activation {kind!r}")


def rms_norm(x: Array, g: Array, eps: float = 1e-5) -> Array:
    xf = x.astype(jnp.float32)
    xf = xf * jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
    return (xf * g.astype(jnp.float32)).astype(x.dtype)


def _norm(cfg: "GPTConfig", x: Array, g: Array, b: Array) -> Array:
    if cfg.norm == "rmsnorm":
        return rms_norm(x, g, eps=cfg.ln_eps)
    return layer_norm(x, g, b, eps=cfg.ln_eps)


def apply_rope(x: Array, positions: Array, theta: float = 10000.0,
               rope_dim: Optional[int] = None,
               interleaved: bool = False) -> Array:
    """Rotary position embedding on [B, S, H, D].

    Default: LLaMA/NeoX half-split pairing over the full head dim.
    ``rope_dim`` rotates only the first ``rope_dim`` features (GPT-J
    ``rotary_dim``, NeoX ``rotary_pct``); ``interleaved`` uses GPT-J's
    rotate-every-two pairing ((0,1),(2,3),...).  ``positions`` is ``[S]``
    (shared across the batch) or ``[B, S]`` (per-row — the continuous-
    batching decode path, where every slot sits at its own position)."""
    B, S, H, D = x.shape
    rd = rope_dim or D
    xr = x[..., :rd].astype(jnp.float32)
    half = rd // 2
    freqs = (1.0 / theta) ** (jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs   # [(B,) S, half]
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    if angles.ndim == 2:            # [S, half] -> broadcast over batch
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    else:                           # [B, S, half] -> per-row positions
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    if interleaved:
        x1, x2 = xr[..., 0::2], xr[..., 1::2]
        rot = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                        axis=-1).reshape(xr.shape)
    else:
        x1, x2 = xr[..., :half], xr[..., half:]
        rot = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                              axis=-1)
    if rd == D:
        return rot.astype(x.dtype)
    return jnp.concatenate([rot.astype(x.dtype), x[..., rd:]], axis=-1)


def _split_qkv(cfg: "GPTConfig", qkv: Array):
    """[B, S, qkv_dim] → q [B,S,H,D], k/v [B,S,Hkv,D] (GQA-aware)."""
    B, S = qkv.shape[:2]
    H, Hkv, D = cfg.n_head, cfg.kv_heads, cfg.head_dim
    q, k, v = jnp.split(qkv, [H * D, (H + Hkv) * D], axis=-1)
    return (q.reshape(B, S, H, D), k.reshape(B, S, Hkv, D),
            v.reshape(B, S, Hkv, D))


def _wget(p: Dict, key: str, dt) -> Array:
    """Weight fetch that transparently dequantizes int8-injected params
    (``module_inject/quantization.py``; reference GroupQuantizer +
    ``dequantize.cu``) — same model code serves fp and int8 weights."""
    from deepspeed_tpu.module_inject.quantization import (dequantize_weight,
                                                          is_quantized_leaf)
    w = p[key]
    if is_quantized_leaf(w):
        return dequantize_weight(w, dt)
    return w.astype(dt)


def _mlp(cfg: "GPTConfig", p: Dict, h: Array, dt) -> Array:
    up = h @ _wget(p, "fc_w", dt)
    if cfg.use_bias:
        up = up + p["fc_b"].astype(dt)
    if cfg.mlp_type == "swiglu":
        gate, val = jnp.split(up, 2, axis=-1)
        h = jax.nn.silu(gate) * val
    else:
        h = _activation(up, cfg.activation)
    out = h @ _wget(p, "proj_w", dt)
    if cfg.use_bias:
        out = out + p["proj_b"].astype(dt)
    return out


def _ffn(cfg: "GPTConfig", p: Dict, h: Array, dt, rng=None,
         train: bool = False) -> Tuple[Array, Array]:
    """Dense MLP or top-k gated MoE expert bank (reference ``moe/layer.py:16``
    when ``moe_num_experts > 0``).  Returns ``(y, aux_loss)``; the aux loss
    is zero on the dense path."""
    if cfg.moe_num_experts == 0:
        return _mlp(cfg, p, h, dt), jnp.zeros((), jnp.float32)
    from deepspeed_tpu.moe.experts import FFNExpert
    from deepspeed_tpu.moe.sharded_moe import (moe_dispatch_combine,
                                               top1gating, top2gating)
    E = cfg.n_embd
    lead = h.shape[:-1]
    xt = h.reshape(-1, E)
    logits = xt.astype(jnp.float32) @ p["moe"]["gate"]["wg"].astype(jnp.float32)
    cf = cfg.moe_capacity_factor if train else cfg.moe_eval_capacity_factor
    if cfg.moe_top_k == 1:
        l_aux, combine, dispatch, _ = top1gating(
            logits, capacity_factor=cf, min_capacity=cfg.moe_min_capacity,
            noise_rng=rng if train else None)
    else:
        l_aux, combine, dispatch, _ = top2gating(
            logits, capacity_factor=cf, min_capacity=cfg.moe_min_capacity,
            noise_rng=rng if train else None)
    expert = FFNExpert(E, cfg.moe_expert_hidden or cfg.ffn_dim)
    y = moe_dispatch_combine(xt, combine, dispatch, expert,
                             p["moe"]["experts"])
    return y.reshape(*lead, E).astype(dt), l_aux.astype(jnp.float32)


def layer_norm(x: Array, g: Array, b: Array, eps: float = 1e-5) -> Array:
    # fp32 statistics regardless of activation dtype (bf16-safe)
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * g + b).astype(x.dtype)


def _dropout(x: Array, rate: float, rng: Optional[Array], train: bool) -> Array:
    if not train or rate == 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def _maybe_actq(cfg: "GPTConfig", h: Array) -> Array:
    if cfg.activation_quant_bits is None:
        return h
    from deepspeed_tpu.compression.basic_ops import quantize_activation
    return quantize_activation(h, bits=cfg.activation_quant_bits,
                               quant_type=cfg.activation_quant_type)


def gpt_block(cfg: GPTConfig, p: Dict, x: Array, rng: Optional[Array],
              train: bool, attention_fn: Callable) -> Tuple[Array, Array]:
    """One transformer block on ``x: [batch, seq, embd]``.  Returns
    ``(x, moe_aux)``; the aux term is zero for dense blocks."""
    B, S, E = x.shape
    H, D = cfg.n_head, cfg.head_dim
    dt = x.dtype
    r = (jax.random.split(rng, 3) if rng is not None else (None, None, None))

    with jax.named_scope("attn"):
        h = _maybe_actq(cfg, _norm(cfg, x, p["ln1_g"], p["ln1_b"]))
        qkv = h @ _wget(p, "qkv_w", dt)
        if cfg.use_bias:
            qkv = qkv + p["qkv_b"].astype(dt)
        q, k, v = _split_qkv(cfg, qkv)
        if cfg.position_encoding == "rope":
            pos = jnp.arange(S)
            q = apply_rope(q, pos, cfg.rope_theta, cfg.rope_dim, cfg.rope_interleaved)
            k = apply_rope(k, pos, cfg.rope_theta, cfg.rope_dim, cfg.rope_interleaved)
        # grouped K/V go to the attention op as-is: the Pallas kernel (and
        # the GQA-aware jnp reference) consume Hkv < H heads natively, so
        # training saves the K/V-expansion HBM the round-3 path paid here
        # heads sharded over tensor axis (Megatron attention parallelism)
        q = _constrain(q, mesh_lib.BATCH_AXES, "seq", "tensor", None)
        k = _constrain(k, mesh_lib.BATCH_AXES, "seq", "tensor", None)
        v = _constrain(v, mesh_lib.BATCH_AXES, "seq", "tensor", None)
        if cfg.position_encoding == "alibi":
            # slopes-only ALiBi: every attention path synthesizes the bias
            # from iotas (O(H) memory — no [S, S] bias tensor ever exists)
            from deepspeed_tpu.ops.attention import alibi_slopes
            o = attention_fn(q, k, v, causal=True,
                             alibi=jnp.asarray(alibi_slopes(H)))
        else:
            o = attention_fn(q, k, v, causal=True)
        o = o.reshape(B, S, E)
        o = o @ _wget(p, "out_w", dt)
        if cfg.use_bias:
            o = o + p["out_b"].astype(dt)
        o = _dropout(o, cfg.dropout, r[0], train)

    with jax.named_scope("mlp"):
        if cfg.block_type == "sequential":
            x = _constrain(x + o, mesh_lib.BATCH_AXES, "seq", None)
            h2 = _maybe_actq(cfg, _norm(cfg, x, p["ln2_g"], p["ln2_b"]))
            f, moe_aux = _ffn(cfg, p, h2, dt, rng=r[1], train=train)
            x = x + _dropout(f, cfg.dropout, r[2], train)
        elif cfg.block_type == "parallel":
            # GPT-NeoX use_parallel_residual: x + attn(ln1 x) + mlp(ln2 x)
            h2 = _norm(cfg, x, p["ln2_g"], p["ln2_b"])
            f, moe_aux = _ffn(cfg, p, h2, dt, rng=r[1], train=train)
            x = x + o + _dropout(f, cfg.dropout, r[2], train)
        else:   # parallel_single_ln (GPT-J): one LN feeds attn AND mlp
            f, moe_aux = _ffn(cfg, p, h, dt, rng=r[1], train=train)
            x = x + o + _dropout(f, cfg.dropout, r[2], train)
    return _constrain(x, mesh_lib.BATCH_AXES, "seq", None), moe_aux


def gpt_forward(cfg: GPTConfig, params: Dict, input_ids: Array,
                rng: Optional[Array] = None, train: bool = False,
                attention_fn: Optional[Callable] = None,
                pld_theta: Optional[Array] = None,
                return_hidden: bool = False,
                with_aux: bool = False) -> Array:
    """Logits ``[batch, seq, padded_vocab]`` (bf16 compute, fp32 logits).

    ``pld_theta`` enables progressive layer drop (reference
    ``runtime/progressive_layer_drop.py``; engine feeds the annealed theta
    per step): block *i* is kept with probability
    ``1 - (i+1)/L * (1 - theta)`` — deeper blocks drop more, theta→1
    disables dropping.  A dropped block is the identity via ``lax.cond``,
    which TPU executes as a real dynamic branch — dropped blocks skip
    their FLOPs, matching the reference's speedup story.
    """
    from deepspeed_tpu.ops.attention import get_attention_fn
    attention_fn = attention_fn or get_attention_fn(cfg.attn_impl)

    B, S = input_ids.shape
    dt = cfg.dtype
    with jax.named_scope("embed"):
        # Explicit ZeRO-3 gather for the embedding table: under stage 3 the
        # policy shards wte's E dim over fsdp, and a table gather with a
        # sharded E produces E-sharded activations that the partitioner can
        # only reshard to the batch/seq layout by full replication (the
        # "involuntary full rematerialization" warnings of MULTICHIP_r03).
        # Constraining the table to its logical (vocab-parallel, E-whole)
        # spec first makes the gather-at-use all-gather explicit — which is
        # what ZeRO-3 does for every parameter anyway — and the gather then
        # lands batch/seq-sharded directly.
        input_ids = _constrain(input_ids, mesh_lib.BATCH_AXES, "seq")
        wte = _constrain(params["wte"], "tensor", None)
        x = wte.astype(dt)[input_ids]
        x = _constrain(x, mesh_lib.BATCH_AXES, "seq", None)
        if cfg.position_encoding == "learned":
            x = x + params["wpe"].astype(dt)[:S][None]
        x = _dropout(x, cfg.dropout, rng, train)

    body = partial(gpt_block, cfg, train=train, attention_fn=attention_fn)
    if cfg.remat:
        from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
            checkpoint_policy)
        body = jax.checkpoint(body, policy=checkpoint_policy())

    # random-LTD: each block trains on its own sorted random token subset,
    # the rest riding the residual stream (data_pipeline/data_routing)
    ltd_on = (train and rng is not None and cfg.ltd_keep is not None
              and cfg.ltd_keep < S)
    if ltd_on:
        from deepspeed_tpu.runtime.data_pipeline.data_routing.basic_layer import (
            sample_token_indices)
        ltd_idx = sample_token_indices(jax.random.fold_in(rng, 99), S,
                                       cfg.ltd_keep, cfg.n_layer)
    # progressive layer drop: per-block keep flags, progressive with depth
    pld_on = train and rng is not None and pld_theta is not None
    if pld_on:
        depth_frac = jnp.arange(1, cfg.n_layer + 1, dtype=jnp.float32) / cfg.n_layer
        keep_p = 1.0 - depth_frac * (1.0 - pld_theta)
        pld_keep = jax.random.bernoulli(jax.random.fold_in(rng, 55), keep_p)

    zero_aux = jnp.zeros((), jnp.float32)

    def apply_block(p, x, r, idx=None, ltd_this_layer=True):
        if ltd_on and idx is not None and ltd_this_layer:
            sub, aux = body(p, jnp.take(x, idx, axis=1), r)
            return x.at[:, idx].set(sub), aux
        return body(p, x, r)

    aux_total = zero_aux
    if cfg.scan_layers:
        use_rngs = rng is not None and train
        rngs = (jax.random.split(jax.random.fold_in(rng, 7), cfg.n_layer)
                if use_rngs else jnp.zeros((cfg.n_layer, 2), jnp.uint32))
        pf = zero_layered.current_prefetch()
        xs = {"r": rngs}
        if pf is None:
            xs["p"] = params["blocks"]
        else:
            xs["i"] = jnp.arange(cfg.n_layer, dtype=jnp.int32)
        if ltd_on:
            xs["idx"] = ltd_idx
        if pld_on:
            xs["keep"] = pld_keep

        if pf is not None:
            # Layered ZeRO-3: params["blocks"] are still SHARDED here —
            # the carry holds a ring of `depth` already-gathered block
            # slices, and each iteration issues block i+depth's gather
            # (independent of block i's compute, so XLA's async collective
            # start/done hides it under the matmuls) before consuming the
            # ring head.  The gathers' custom-vjp backward reduce-scatters
            # each block's grads as its backward slice completes.
            blocks = params["blocks"]
            depth = pf.clamped_depth(cfg.n_layer)
            ring = tuple(pf.gather_block(blocks, jnp.int32(k))
                         for k in range(depth))

            def scan_body(carry, layer):
                (x, aux_sum), ring = carry
                nxt = pf.gather_block(
                    blocks, jnp.minimum(layer["i"] + depth, cfg.n_layer - 1))
                p = ring[0]
                r = layer["r"] if use_rngs else None
                run = lambda xx: apply_block(p, xx, r, layer.get("idx"))
                if pld_on:
                    x, aux = jax.lax.cond(layer["keep"], run,
                                          lambda xx: (xx, zero_aux), x)
                else:
                    x, aux = run(x)
                return ((x, aux_sum + aux), ring[1:] + (nxt,)), None

            with jax.named_scope("blocks"):
                ((x, aux_total), _), _ = jax.lax.scan(
                    scan_body, ((x, zero_aux), ring), xs)
        else:
            def scan_body(carry, layer):
                x, aux_sum = carry
                r = layer["r"] if use_rngs else None
                run = lambda xx: apply_block(layer["p"], xx, r, layer.get("idx"))
                if pld_on:   # lax.cond: a dropped block really skips its FLOPs
                    x, aux = jax.lax.cond(layer["keep"], run,
                                          lambda xx: (xx, zero_aux), x)
                else:
                    x, aux = run(x)
                return (x, aux_sum + aux), None

            with jax.named_scope("blocks"):
                (x, aux_total), _ = jax.lax.scan(scan_body, (x, zero_aux), xs)
    else:
        for i in range(cfg.n_layer):
            r = jax.random.fold_in(rng, i) if (rng is not None and train) else None
            p = params["blocks"][f"h{i}"]
            ltd_this = cfg.ltd_layers is None or i in cfg.ltd_layers
            run = lambda xx: apply_block(p, xx, r, ltd_idx[i] if ltd_on else None,
                                         ltd_this)
            if pld_on:
                x, aux = jax.lax.cond(pld_keep[i], run,
                                      lambda xx: (xx, zero_aux), x)
            else:
                x, aux = run(x)
            aux_total = aux_total + aux

    with jax.named_scope("head"):
        x = _norm(cfg, x, params["lnf_g"], params["lnf_b"])
        if return_hidden:   # training loss path: chunked CE owns the head
            return (x, aux_total) if with_aux else x
        # tied embedding projection (or the untied lm_head when the source
        # checkpoint has one); vocab-parallel → logits sharded over tensor
        head = params["lm_head"] if cfg.untied_head else params["wte"]
        logits = (x @ head.astype(dt).T).astype(jnp.float32)
        if cfg.head_bias:
            logits = logits + params["lm_head_b"].astype(jnp.float32)
    logits = _constrain(logits, mesh_lib.BATCH_AXES, "seq", "tensor")
    return (logits, aux_total) if with_aux else logits


def _pallas_ce_wanted(N: int, E: int, V: int) -> bool:
    """Route the loss through the fused Pallas CE kernel when enabled
    (``DST_PALLAS_CE``) and the shape/mesh is supported; any failure here
    means the XLA chunked path below — never an error."""
    try:
        from deepspeed_tpu.ops.pallas import cross_entropy as _pce
        return _pce.pallas_ce_enabled() and _pce.ce_supported(N, E, V)
    except Exception:
        return False


def chunked_cross_entropy(x: Array, head: Array, labels: Array,
                          vocab_size: int, n_chunks: int = 0,
                          head_b: Optional[Array] = None) -> Array:
    """Cross-entropy over the unembedding WITHOUT materializing [N, V]
    logits: rows are processed in chunks under ``jax.checkpoint``, so both
    forward and backward hold one [chunk, V] logits block at a time (the
    backward recomputes the chunk's logits and forms softmax-minus-onehot
    in place).  At GPT-2 vocab and micro-batch 16×512 this removes ~5 GiB
    of fp32 logits/softmax temporaries from the training step — the memory
    cliff that capped the round-3 headline bench at micro 16.

    x: [B, S, E] final hidden; head: [V, E]; labels: [B, S].
    ``n_chunks=0`` picks the smallest count keeping a chunk's logits block
    under ~256 MiB.
    """
    B, S, E = x.shape
    V = head.shape[0]
    N = B * S
    if _pallas_ce_wanted(N, E, V):
        from deepspeed_tpu.ops.pallas import cross_entropy as _pce
        return _pce.fused_cross_entropy(x.reshape(N, E), head,
                                        labels.reshape(N), vocab_size,
                                        head_b=head_b)
    if n_chunks <= 0:
        # chunking trades ~1/3 extra head FLOPs (backward recompute) for
        # the [N, V] memory.  Measured on v5e (r5): chunking LOSES while the
        # block fits (micro 8 x 512 x 50k = 823 MiB: 90.6 unchunked vs 84.6
        # chunked TFLOPs end-to-end) — the recompute costs more than the
        # saved traffic — so the default only chunks past ~900 MiB, where
        # capacity (OOM at micro 24+) forces it
        threshold = int(os.environ.get("DST_CE_CHUNK_MIB", "900")) * 2 ** 20
        if N * V * 4 <= threshold:
            n_chunks = 1
        else:
            target_rows = max(1, threshold // (4 * V))
            n_chunks = max(1, -(-N // target_rows))
    # rows are PADDED up to n_chunks * rows (pad rows masked out of the
    # mean) — never a divisor hunt, which degenerates for prime-ish N
    rows = -(-N // n_chunks)
    n_pad = n_chunks * rows - N
    if n_chunks == 1:
        logits = (x.reshape(N, E) @ head.astype(x.dtype).T).astype(jnp.float32)
        if head_b is not None:
            logits = logits + head_b.astype(jnp.float32)
        if V != vocab_size:
            logits = jnp.where(jnp.arange(V)[None] < vocab_size, logits, -1e9)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        ll = jnp.sum(logits * jax.nn.one_hot(labels.reshape(N), V,
                                             dtype=logits.dtype), axis=-1)
        return jnp.mean(lse - ll)
    xf = x.reshape(N, E)
    lf = labels.reshape(N)
    valid = None
    if n_pad:
        xf = jnp.concatenate([xf, jnp.zeros((n_pad, E), xf.dtype)])
        lf = jnp.concatenate([lf, jnp.zeros((n_pad,), lf.dtype)])
        valid = (jnp.arange(n_chunks * rows) < N).reshape(n_chunks, rows)
    xc = xf.reshape(n_chunks, rows, E)
    lc = lf.reshape(n_chunks, rows)
    mask_pad = V != vocab_size

    def chunk(total, xs):
        xch, lch = xs[0], xs[1]
        logits = (xch @ head.astype(xch.dtype).T).astype(jnp.float32)  # [rows, V]
        if head_b is not None:
            logits = logits + head_b.astype(jnp.float32)
        if mask_pad:
            logits = jnp.where(jnp.arange(V)[None] < vocab_size, logits, -1e9)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        # one-hot contraction, not take_along_axis: under TP the logits are
        # vocab-parallel and a gather's vjp (scatter on the sharded dim)
        # provokes pathological SPMD partitioner compiles (same issue as
        # gpt_ce_loss_fn); XLA fuses the one-hot select without
        # materializing it
        ll = jnp.sum(logits * jax.nn.one_hot(lch, V, dtype=logits.dtype),
                     axis=-1)
        nll = lse - ll
        if valid is not None:
            nll = jnp.where(xs[2], nll, 0.0)
        return total + jnp.sum(nll), None

    xs = (xc, lc) if valid is None else (xc, lc, valid)
    total, _ = jax.lax.scan(jax.checkpoint(chunk), jnp.zeros((), jnp.float32),
                            xs)
    return total / N


def gpt_loss(cfg: GPTConfig, params: Dict, input_ids: Array, labels: Array,
             rng: Optional[Array] = None, train: bool = True,
             attention_fn: Optional[Callable] = None,
             pld_theta: Optional[Array] = None) -> Array:
    """Next-token cross-entropy, masking padded vocab entries.  Computed
    chunked over the head projection (no [B, S, V] logits tensor exists)."""
    x, aux = gpt_forward(cfg, params, input_ids, rng, train, attention_fn,
                         pld_theta=pld_theta, return_hidden=True,
                         with_aux=True)
    head = params["lm_head"] if cfg.untied_head else params["wte"]
    with jax.named_scope("cross_entropy"):
        ce = chunked_cross_entropy(x, head, labels, cfg.vocab_size,
                                   head_b=params.get("lm_head_b")
                                   if cfg.head_bias else None)
    if cfg.moe_num_experts > 0:
        # load-balance aux loss (reference l_aux, sharded_moe.py:179)
        ce = ce + cfg.moe_aux_coeff * aux
    return ce


# --------------------------------------------------------------------------- #
# Inference: KV cache + decode step (the analogue of the reference's
# softmax_context kernel + inference_context.h workspace, SURVEY.md §2.3)
# --------------------------------------------------------------------------- #
def init_kv_cache(cfg: GPTConfig, batch: int, max_len: int) -> Dict:
    """Per-layer K/V cache, stacked [L, B, max_len, Hkv, D] (scan-friendly;
    GQA stores only the kv heads).  Sharded: batch over DP, heads over
    tensor."""
    L, H, D = cfg.n_layer, cfg.kv_heads, cfg.head_dim
    shape = (L, batch, max_len, H, D)
    k = jnp.zeros(shape, cfg.dtype)
    v = jnp.zeros(shape, cfg.dtype)
    spec = (None, mesh_lib.BATCH_AXES, None, "tensor", None)
    return {"k": _constrain(k, *spec), "v": _constrain(v, *spec),
            "pos": jnp.zeros((), jnp.int32)}


def _cached_attention(q, ck, cv, pos, bias=None):
    """q: [B, S_q, H, D] attends causally to cache positions <= its own
    global position (query i sits at ``pos + i``).  Static shapes:
    full-cache attention with masking — the standard TPU decode pattern.

    GQA-aware: the cache may carry only ``Hkv`` heads; attention is
    computed GROUPED against the un-expanded cache (no [B, T, H, D]
    materialization — the bandwidth saving is the point of GQA).
    ``bias``: additive [1, H, S_q, T] logit bias (ALiBi)."""
    B, Sq, H, D = q.shape
    T, Hkv = ck.shape[1], ck.shape[2]
    from deepspeed_tpu.ops.pallas.decode_attention import (
        decode_attention, pallas_decode_enabled)
    if bias is None and Hkv == H and pallas_decode_enabled():
        # DEFAULT-ON where supported (graduated from the r5 opt-in): the
        # Pallas decode kernel DMAs only the pos+Sq valid cache blocks and
        # fuses score/softmax/PV — the einsum below is ~45% of per-token
        # decode time.  ``DST_PALLAS_DECODE=0`` opts out; on CPU the lax
        # fallback below stays the default (the interpreter is far slower
        # than the einsum).  See README § Pallas decode kernel status.
        return decode_attention(q, ck, cv, pos)
    G = H // Hkv
    scale = 1.0 / np.sqrt(D)
    qg = q.reshape(B, Sq, Hkv, G, D)
    s = jnp.einsum("bqhgd,bkhd->bhgqk", qg.astype(jnp.float32),
                   ck.astype(jnp.float32)) * scale       # [B, Hkv, G, Sq, T]
    if bias is not None:
        s = s + bias.astype(jnp.float32).reshape(
            bias.shape[0], Hkv, G, *bias.shape[2:])
    kpos = jax.lax.broadcasted_iota(jnp.int32, (Sq, T), 1)
    qpos = pos + jax.lax.broadcasted_iota(jnp.int32, (Sq, T), 0)
    mask = kpos <= qpos
    s = jnp.where(mask[None, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", p.astype(q.dtype), cv)
    return out.reshape(B, Sq, H, D)


def gpt_apply_with_cache(cfg: GPTConfig, params: Dict, input_ids: Array,
                         cache: Dict) -> Tuple[Array, Dict]:
    """Run ``input_ids`` [B, S_new] starting at cache position ``pos``;
    returns (logits [B, S_new, V], updated cache).  Covers both prefill
    (S_new = prompt length) and decode (S_new = 1) — one compiled program
    per S_new."""
    assert cfg.scan_layers, "KV-cache path requires scan_layers"
    B, S = input_ids.shape
    H, D, E = cfg.n_head, cfg.head_dim, cfg.n_embd
    dt = cfg.dtype
    pos = cache["pos"]

    x = params["wte"].astype(dt)[input_ids]
    if cfg.position_encoding == "learned":
        x = x + params["wpe"].astype(dt)[jnp.clip(pos + jnp.arange(S), 0,
                                                  cfg.n_positions - 1)][None]
    x = _constrain(x, mesh_lib.BATCH_AXES, None, None)

    T = cache["k"].shape[2]
    if cfg.position_encoding == "alibi":
        from deepspeed_tpu.ops.attention import alibi_slopes
        slopes = jnp.asarray(alibi_slopes(H))
        kpos = jnp.arange(T)[None, :]
        qpos = (pos + jnp.arange(S))[:, None]
        attn_bias = (slopes[:, None, None]
                     * (kpos - qpos).astype(jnp.float32))[None]
    else:
        attn_bias = None

    def layer(carry, p):
        # the FULL stacked [L, B, T, Hkv, D] cache rides the scan carry and
        # is updated in place per layer — stacked scan outputs (`ys`) would
        # copy the whole cache every decode step (measured: ~40% of decode
        # time went to those copies before this layout)
        x, ck_full, cv_full, li = carry
        h = _norm(cfg, x, p["ln1_g"], p["ln1_b"])
        qkv = h @ _wget(p, "qkv_w", dt)
        if cfg.use_bias:
            qkv = qkv + p["qkv_b"].astype(dt)
        q, k, v = _split_qkv(cfg, qkv)
        if cfg.position_encoding == "rope":
            rpos = pos + jnp.arange(S)
            q = apply_rope(q, rpos, cfg.rope_theta, cfg.rope_dim, cfg.rope_interleaved)
            k = apply_rope(k, rpos, cfg.rope_theta, cfg.rope_dim, cfg.rope_interleaved)
        # the cache stores only kv_heads heads (the GQA memory win);
        # expansion to n_head happens at attention time
        zero = jnp.zeros((), jnp.int32)
        ck_full = jax.lax.dynamic_update_slice(
            ck_full, k.astype(ck_full.dtype)[None], (li, zero, pos, zero, zero))
        cv_full = jax.lax.dynamic_update_slice(
            cv_full, v.astype(cv_full.dtype)[None], (li, zero, pos, zero, zero))
        ck = jax.lax.dynamic_index_in_dim(ck_full, li, 0, keepdims=False)
        cv = jax.lax.dynamic_index_in_dim(cv_full, li, 0, keepdims=False)
        o = _cached_attention(q, ck, cv, pos, bias=attn_bias).reshape(B, S, E)
        o = o @ _wget(p, "out_w", dt)
        if cfg.use_bias:
            o = o + p["out_b"].astype(dt)
        if cfg.block_type == "sequential":
            x = x + o
            h2 = _norm(cfg, x, p["ln2_g"], p["ln2_b"])
            f, _ = _ffn(cfg, p, h2, dt, train=False)
            x = x + f
        elif cfg.block_type == "parallel":
            h2 = _norm(cfg, x, p["ln2_g"], p["ln2_b"])
            f, _ = _ffn(cfg, p, h2, dt, train=False)
            x = x + o + f
        else:   # parallel_single_ln
            f, _ = _ffn(cfg, p, h, dt, train=False)
            x = x + o + f
        return (x, ck_full, cv_full, li + 1), None

    (x, new_k, new_v, _), _ = jax.lax.scan(
        layer, (x, cache["k"], cache["v"], jnp.zeros((), jnp.int32)),
        params["blocks"])
    x = _norm(cfg, x, params["lnf_g"], params["lnf_b"])
    head = params["lm_head"] if cfg.untied_head else params["wte"]
    logits = (x @ head.astype(dt).T).astype(jnp.float32)
    if cfg.head_bias:
        logits = logits + params["lm_head_b"].astype(jnp.float32)
    new_cache = {"k": new_k, "v": new_v, "pos": pos + S}
    return logits, new_cache


def gpt_generate(cfg: GPTConfig, params: Dict, input_ids: Array,
                 max_new_tokens: int, rng: Optional[Array] = None,
                 temperature: float = 0.0, max_len: Optional[int] = None,
                 prompt_len: Optional[Array] = None) -> Array:
    """Greedy (temperature=0) or sampled autoregressive generation.
    The decode loop is one ``lax.scan`` — a single compiled program for all
    steps (the analogue of the reference's CUDA-graph'd generate,
    ``inference/engine.py:500-528``)."""
    B, S = input_ids.shape
    assert S + max_new_tokens <= cfg.n_positions, (
        f"prompt ({S}) + max_new_tokens ({max_new_tokens}) exceeds "
        f"n_positions ({cfg.n_positions}); the KV cache cannot grow past it")
    max_len = max_len or (S + max_new_tokens)
    cache = init_kv_cache(cfg, B, max_len)
    logits, cache = gpt_apply_with_cache(cfg, params, input_ids, cache)
    if prompt_len is None:
        last = logits[:, -1]
    else:
        # bucketed serving: the prompt is right-padded to a bucketed S and
        # ``prompt_len`` (traced) marks the real length — one compiled
        # program covers every prompt length in the bucket.  Causality makes
        # right-padding benign: positions < prompt_len never attend to the
        # pad tail, and decode overwrites the tail's K/V slot-by-slot
        # (step i writes position prompt_len + i before reading it).
        idx = jnp.broadcast_to(jnp.reshape(prompt_len - 1, (1, 1, 1)),
                               (B, 1, logits.shape[-1]))
        last = jnp.take_along_axis(logits, idx, axis=1)[:, 0]
        cache = dict(cache, pos=jnp.asarray(prompt_len, jnp.int32))
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def sample(logits, r):
        if cfg.padded_vocab != cfg.vocab_size:
            vmask = jnp.arange(cfg.padded_vocab) < cfg.vocab_size
            logits = jnp.where(vmask[None], logits, -1e30)
        if temperature and temperature > 0:
            return jax.random.categorical(r, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    def step(carry, r):
        cache, last_logits = carry
        tok = sample(last_logits, r)
        logits, cache = gpt_apply_with_cache(cfg, params, tok[:, None], cache)
        return (cache, logits[:, -1]), tok

    rngs = jax.random.split(rng, max_new_tokens)
    (_, _), toks = jax.lax.scan(step, (cache, last), rngs)
    return jnp.concatenate([input_ids, toks.T], axis=1)


# --------------------------------------------------------------------------- #
# Paged (block-table) serving step — the continuous-batching decode path.
# The KV cache is a global block arena (deepspeed_tpu/serving/kv_cache.py)
# instead of a per-call [B, max_len] tensor: physical blocks are reached
# through each row's block table, so batch composition can change every step
# without recompiling (tables/positions are traced int32 inputs).
# --------------------------------------------------------------------------- #
def gpt_paged_step(cfg: GPTConfig, params: Dict, input_ids: Array,
                   positions: Array, k_pages: Array, v_pages: Array,
                   block_tables: Array, write_blocks: Array,
                   write_offsets: Array) -> Tuple[Array, Array, Array]:
    """One fused step over the paged arena.

    ``input_ids`` [B, S] — S = 1 for decode, a chunk for chunked prefill;
    ``positions`` [B] — per-row global position of the first token (tokens
    already resident in the row's cache); ``k_pages``/``v_pages``
    [L, NB, BS, Hkv, D] — the global arena (block 0 is the trash block);
    ``block_tables`` [B, MB] — logical→physical block map per row;
    ``write_blocks``/``write_offsets`` [B, S] — physical (block, offset)
    each new token's K/V lands in (invalid/padded tokens point at the trash
    block).  Returns (logits [B, S, V] fp32, k_pages, v_pages).
    """
    assert cfg.scan_layers, "paged serving path requires scan_layers"
    from deepspeed_tpu.ops.pallas.decode_attention import paged_attention
    B, S = input_ids.shape
    H, E = cfg.n_head, cfg.n_embd
    MB = block_tables.shape[1]
    BS = k_pages.shape[2]
    T = MB * BS
    dt = cfg.dtype
    pos2d = positions[:, None] + jnp.arange(S)[None]          # [B, S]

    x = params["wte"].astype(dt)[input_ids]
    if cfg.position_encoding == "learned":
        x = x + params["wpe"].astype(dt)[
            jnp.clip(pos2d, 0, cfg.n_positions - 1)]
    x = _constrain(x, mesh_lib.BATCH_AXES, None, None)

    if cfg.position_encoding == "alibi":
        from deepspeed_tpu.ops.attention import alibi_slopes
        slopes = jnp.asarray(alibi_slopes(H))
        kpos = jnp.arange(T)[None, None, None, :]
        qpos = pos2d[:, None, :, None]
        attn_bias = slopes[None, :, None, None] * (
            kpos - qpos).astype(jnp.float32)                  # [B, H, S, T]
    else:
        attn_bias = None

    def layer(carry, p):
        x, kp, vp, li = carry
        h = _norm(cfg, x, p["ln1_g"], p["ln1_b"])
        qkv = h @ _wget(p, "qkv_w", dt)
        if cfg.use_bias:
            qkv = qkv + p["qkv_b"].astype(dt)
        q, k, v = _split_qkv(cfg, qkv)
        if cfg.position_encoding == "rope":
            q = apply_rope(q, pos2d, cfg.rope_theta, cfg.rope_dim,
                           cfg.rope_interleaved)
            k = apply_rope(k, pos2d, cfg.rope_theta, cfg.rope_dim,
                           cfg.rope_interleaved)
        # scatter the new K/V into the arena through the write map; rows
        # that must not write (padding, inactive slots) carry trash-block
        # coordinates, so the scatter itself needs no predication
        kp = kp.at[li, write_blocks, write_offsets].set(k.astype(kp.dtype))
        vp = vp.at[li, write_blocks, write_offsets].set(v.astype(vp.dtype))
        kl = jax.lax.dynamic_index_in_dim(kp, li, 0, keepdims=False)
        vl = jax.lax.dynamic_index_in_dim(vp, li, 0, keepdims=False)
        o = paged_attention(q, kl, vl, block_tables, positions,
                            bias=attn_bias).reshape(B, S, E)
        o = o @ _wget(p, "out_w", dt)
        if cfg.use_bias:
            o = o + p["out_b"].astype(dt)
        if cfg.block_type == "sequential":
            x = x + o
            h2 = _norm(cfg, x, p["ln2_g"], p["ln2_b"])
            f, _ = _ffn(cfg, p, h2, dt, train=False)
            x = x + f
        elif cfg.block_type == "parallel":
            h2 = _norm(cfg, x, p["ln2_g"], p["ln2_b"])
            f, _ = _ffn(cfg, p, h2, dt, train=False)
            x = x + o + f
        else:   # parallel_single_ln
            f, _ = _ffn(cfg, p, h, dt, train=False)
            x = x + o + f
        return (x, kp, vp, li + 1), None

    (x, k_pages, v_pages, _), _ = jax.lax.scan(
        layer, (x, k_pages, v_pages, jnp.zeros((), jnp.int32)),
        params["blocks"])
    x = _norm(cfg, x, params["lnf_g"], params["lnf_b"])
    head = params["lm_head"] if cfg.untied_head else params["wte"]
    logits = (x @ head.astype(dt).T).astype(jnp.float32)
    if cfg.head_bias:
        logits = logits + params["lm_head_b"].astype(jnp.float32)
    return logits, k_pages, v_pages


# --------------------------------------------------------------------------- #
# Pipeline-parallel layer classes (for PipelineModule / PipelineEngine)
# --------------------------------------------------------------------------- #
class GPTEmbedLayer:
    """Token+position embedding as pipeline stage-0 layer."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def init_params(self, rng):
        return _init_embed(self.cfg, rng)

    def partition_specs(self):
        return {"wte": PartitionSpec("tensor", None), "wpe": PartitionSpec()}

    def __call__(self, p, ids, rng=None, train=False):
        dt = self.cfg.dtype
        S = ids.shape[-1]
        x = p["wte"].astype(dt)[ids] + p["wpe"].astype(dt)[:S][None]
        x = _dropout(x, self.cfg.dropout, rng, train)
        return _constrain(x, mesh_lib.BATCH_AXES, "seq", None)


class GPTBlockLayer:
    """One transformer block as a homogeneous pipeline middle layer."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def init_params(self, rng):
        return _init_block(self.cfg, rng)

    def partition_specs(self):
        return dict(_BLOCK_SPECS)

    def __call__(self, p, x, rng=None, train=False):
        from deepspeed_tpu.ops.attention import get_attention_fn
        assert self.cfg.moe_num_experts == 0, (
            "MoE blocks in the pipeline engine are not supported yet — "
            "use the scan (non-pipeline) model for MoE training")
        x, _ = gpt_block(self.cfg, p, x, rng=rng, train=train,
                         attention_fn=get_attention_fn(self.cfg.attn_impl))
        return x


class GPTHeadLayer:
    """Final LN + (untied) unembedding projection."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def init_params(self, rng):
        cfg = self.cfg
        return {"lnf_g": jnp.ones((cfg.n_embd,), jnp.float32),
                "lnf_b": jnp.zeros((cfg.n_embd,), jnp.float32),
                "unembed": _dense_init(rng, cfg.n_embd, (cfg.n_embd, cfg.padded_vocab))}

    def partition_specs(self):
        return {"lnf_g": PartitionSpec(), "lnf_b": PartitionSpec(),
                "unembed": PartitionSpec(None, "tensor")}

    def __call__(self, p, x, rng=None, train=False):
        x = layer_norm(x, p["lnf_g"], p["lnf_b"])
        logits = (x @ p["unembed"].astype(x.dtype)).astype(jnp.float32)
        return _constrain(logits, mesh_lib.BATCH_AXES, "seq", "tensor")


def gpt_ce_loss_fn(cfg: GPTConfig):
    def loss_fn(logits, labels):
        if cfg.padded_vocab != cfg.vocab_size:
            mask = jnp.arange(cfg.padded_vocab) < cfg.vocab_size
            logits = jnp.where(mask[None, None, :], logits, -1e9)
        logp = jax.nn.log_softmax(logits, axis=-1)
        # one-hot contraction, NOT take_along_axis: logits are
        # vocab-parallel (sharded 'tensor'), and the vjp of a gather on a
        # sharded dim (a scatter) sends the SPMD partitioner into a
        # pathological compile inside the 1F1B pipeline's scan; the
        # contraction partitions as a local reduce + psum and XLA fuses
        # the one-hot select without materializing it
        onehot = jax.nn.one_hot(labels, logp.shape[-1], dtype=logp.dtype)
        ll = jnp.sum(logp * onehot, axis=-1)
        return -jnp.mean(ll)
    return loss_fn


class GPTTiedHeadLayer:
    """Final LN + unembedding through the TIED token embedding: the tied
    params arrive as the embed layer's pytree (reference ``TiedLayerSpec``
    reuse-site ``forward_fn``, ``pipe/module.py:76``)."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def init_params(self, rng):
        return {"lnf_g": jnp.ones((self.cfg.n_embd,), jnp.float32),
                "lnf_b": jnp.zeros((self.cfg.n_embd,), jnp.float32)}

    def partition_specs(self):
        return {"lnf_g": PartitionSpec(), "lnf_b": PartitionSpec()}

    def __call__(self, p, x, tied=None, rng=None, train=False):
        x = layer_norm(x, p["lnf_g"], p["lnf_b"])
        logits = (x @ tied["wte"].astype(x.dtype).T).astype(jnp.float32)
        return _constrain(logits, mesh_lib.BATCH_AXES, "seq", "tensor")


def gpt_pipeline_module(cfg: GPTConfig, num_stages: int, tied_embedding: bool = False):
    """Layer-list GPT for the PipelineEngine (the analogue of building a
    Megatron GPT from ``LayerSpec``s, reference ``pipe/module.py:85``).
    ``tied_embedding=True`` shares wte between embed and head via
    ``TiedLayerSpec`` (reference embedding/unembedding tying)."""
    from deepspeed_tpu.runtime.pipe.module import (LayerSpec, PipelineModule,
                                                   TiedLayerSpec)
    blocks = [LayerSpec(GPTBlockLayer, cfg) for _ in range(cfg.n_layer)]
    if tied_embedding:
        specs = ([TiedLayerSpec("embed", GPTEmbedLayer, cfg)] + blocks
                 + [TiedLayerSpec("embed", GPTTiedHeadLayer, cfg)])
    else:
        specs = ([LayerSpec(GPTEmbedLayer, cfg)] + blocks
                 + [LayerSpec(GPTHeadLayer, cfg)])
    return PipelineModule(layers=specs, num_stages=num_stages,
                          loss_fn=gpt_ce_loss_fn(cfg))


class GPT:
    """Engine-compatible model object (``.apply``-free callable convention:
    ``fn(params, batch, rng, train) -> loss``) with ``init_params``."""

    # the scan branch consumes per-block slices through the layered ZeRO-3
    # prefetch context (engine gates the overlapped step on this attribute)
    supports_layered_zero3 = True

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def __call__(self, params, batch, rng, train, pld_theta=None, **_ignored):
        input_ids, labels = batch
        return gpt_loss(self.cfg, params, input_ids, labels, rng, train,
                        pld_theta=pld_theta)

    def init_params(self, rng):
        return init_gpt_params(self.cfg, rng)

    def partition_specs(self):
        return gpt_partition_specs(self.cfg)

    # ---- inference decode protocol (InferenceEngine contract) --------- #
    def init_cache(self, batch: int, max_len: int):
        return init_kv_cache(self.cfg, batch, max_len)

    def apply_with_cache(self, params, input_ids, cache):
        return gpt_apply_with_cache(self.cfg, params, input_ids, cache)

    def forward_logits(self, params, input_ids):
        return gpt_forward(self.cfg, params, input_ids, rng=None, train=False)

    def generate(self, params, input_ids, max_new_tokens, rng=None,
                 temperature: float = 0.0, prompt_len=None):
        return gpt_generate(self.cfg, params, input_ids, max_new_tokens,
                            rng=rng, temperature=temperature,
                            prompt_len=prompt_len)

    def paged_step(self, params, input_ids, positions, k_pages, v_pages,
                   block_tables, write_blocks, write_offsets):
        """Serving-engine protocol: one step over the paged KV arena
        (``deepspeed_tpu/serving/engine.py``)."""
        return gpt_paged_step(self.cfg, params, input_ids, positions,
                              k_pages, v_pages, block_tables,
                              write_blocks, write_offsets)

    def num_params(self) -> int:
        cfg = self.cfg
        E, L, I = cfg.n_embd, cfg.n_layer, cfg.ffn_dim
        fc_out = 2 * I if cfg.mlp_type == "swiglu" else I
        per_block = (E * cfg.qkv_dim + cfg.qkv_dim      # qkv (GQA-sized)
                     + E * E + E                        # attn out
                     + E * fc_out + fc_out              # mlp up (gate|up)
                     + I * E + E                        # mlp down
                     + 4 * E)                           # two norms
        total = cfg.padded_vocab * E + L * per_block + 2 * E
        if cfg.position_encoding == "learned":
            total += cfg.n_positions * E
        if cfg.untied_head:
            total += cfg.padded_vocab * E
        return total

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs/token ≈ 6N + attention term (PaLM appendix B)."""
        cfg = self.cfg
        n = self.num_params()
        attn = 12 * cfg.n_layer * cfg.n_embd * seq_len
        return 6 * n + attn
