"""Megatron-LM GPT checkpoint import — the reference's megatron container.

Reference: ``deepspeed/module_inject/containers/megatron_gpt.py`` (+
``MegatronLayerPolicy``, ``replace_policy.py``): serve a Megatron-LM GPT
checkpoint through the fused inference path.  Megatron checkpoints are NOT
HF models — they are torch state dicts with ``model.language_model...``
names, per-TP-rank shards (merged by ``runtime/state_dict_factory``), and
a version-dependent fused-QKV row ordering:

* ``checkpoint_version`` 1.0: rows ordered ``(num_heads, head_dim, 3)``;
* ``checkpoint_version`` >= 2.0: rows ordered ``(num_heads, 3, head_dim)``
  (the two layouts HF's ``fix_query_key_value_ordering`` handles; both
  are de-interleaved to qkv-major here).

Both are rearranged onto this framework's fused layout
``[E, q_allheads | k_allheads | v_allheads]``.
"""

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from deepspeed_tpu.utils.logging import log_dist


def _qkv_to_fused(w: np.ndarray, b: Optional[np.ndarray], num_heads: int,
                  version: float):
    """Megatron ``query_key_value`` [3E, E] (+ [3E] bias) → fused
    ([E, 3E], [3E]) with q|k|v blocks, head-major inside each block.

    Row orderings by ``checkpoint_version`` (the convention HF's
    ``fix_query_key_value_ordering`` documents and Megatron-LM's own
    loader rewrites): v1.0 rows are ``(num_heads, head_dim, 3)``;
    v2.0+ rows are ``(num_heads, 3, head_dim)``."""
    three_e, E = w.shape
    D = three_e // (3 * num_heads)

    def to_qkv_major(x, trailing):
        if version >= 2.0:                       # (H, 3, D, ...)
            r = x.reshape((num_heads, 3, D) + trailing)
            return np.moveaxis(r, 1, 0)          # (3, H, D, ...)
        r = x.reshape((num_heads, D, 3) + trailing)   # v1: (H, D, 3, ...)
        return np.moveaxis(r, 2, 0)              # (3, H, D, ...)

    wq = to_qkv_major(w, (E,))
    fused_w = np.concatenate([wq[i].reshape(num_heads * D, E)
                              for i in range(3)], axis=0).T   # [E, 3E]
    fused_b = None
    if b is not None:
        bq = to_qkv_major(b, ())
        fused_b = np.concatenate([bq[i].reshape(-1) for i in range(3)])
    return fused_w, fused_b


def _flatten(sd: Dict) -> Dict[str, np.ndarray]:
    """Dot-flatten the (possibly nested) checkpoint dict and strip the
    'model'/'module' wrappers: real Megatron-LM saves are NESTED
    ``{'model': {'language_model': {...}}}`` trees; some trainers save
    flat dot-joined keys.  Non-array leaves (args, rng state, the
    checkpoint_version scalar) are dropped."""
    flat: Dict[str, np.ndarray] = {}

    def walk(node, prefix):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{prefix}{k}.")
            return
        if hasattr(node, "detach"):              # torch tensor
            node = node.detach().cpu().numpy()
        if isinstance(node, np.ndarray):
            flat[prefix[:-1]] = node

    walk(sd, "")
    for prefix in ("module.", "model."):
        if any(k.startswith(prefix) for k in flat):
            flat = {k[len(prefix):]: v for k, v in flat.items()
                    if k.startswith(prefix)}
    return flat


def load_megatron_gpt(state_dict: Union[Dict, Sequence[str]],
                      checkpoint_version: Optional[float] = None,
                      num_heads: Optional[int] = None,
                      n_positions: Optional[int] = None,
                      dtype=None):
    """(GPT model, params) from a Megatron-LM GPT state dict (nested or
    dot-flat), a single checkpoint path, or a list of per-TP-rank paths
    (flat dicts merged via state_dict_factory).

    ``checkpoint_version`` defaults to the checkpoint's own
    ``checkpoint_version`` field when present, else 2.0 (the modern
    ordering).  ``num_heads`` is REQUIRED: Megatron stores no head count
    in-tensor and the fused-QKV de-interleave depends on it."""
    import jax.numpy as jnp

    from deepspeed_tpu.models.gpt import GPT, GPTConfig

    if num_heads is None:
        raise ValueError("load_megatron_gpt needs num_heads= — Megatron "
                         "checkpoints do not encode the head count, and "
                         "the fused-QKV row de-interleave depends on it")
    if not isinstance(state_dict, dict):
        paths = list(state_dict)
        if len(paths) == 1:
            import torch
            state_dict = torch.load(paths[0], map_location="cpu",
                                    weights_only=False)
        else:
            from deepspeed_tpu.runtime.state_dict_factory import SDLoaderFactory
            loader = SDLoaderFactory.get_sd_loader(paths)
            state_dict = loader.load(mp_world_size=1, mp_rank=0)
    if checkpoint_version is None:
        cv = state_dict.get("checkpoint_version") if isinstance(state_dict, dict) else None
        checkpoint_version = float(cv) if cv is not None else 2.0
    sd = _flatten(state_dict)
    lm = "language_model."
    wte = sd[lm + "embedding.word_embeddings.weight"]
    wpe = sd[lm + "embedding.position_embeddings.weight"]
    V, E = wte.shape
    layer_prefix = lm + "transformer.layers."
    layer_ids = sorted({int(k[len(layer_prefix):].split(".")[0])
                        for k in sd if k.startswith(layer_prefix)})
    L = len(layer_ids)
    qkv0 = sd[lm + "transformer.layers.0.attention.query_key_value.weight"]
    H = num_heads
    assert qkv0.shape[0] == 3 * E and qkv0.shape[1] == E, qkv0.shape
    cfg = GPTConfig(vocab_size=V, n_positions=n_positions or wpe.shape[0],
                    n_embd=E, n_layer=L, n_head=H,
                    activation="gelu", vocab_multiple=1)
    if dtype is not None:
        import dataclasses
        cfg = dataclasses.replace(cfg, dtype=dtype)

    blocks: List[Dict[str, np.ndarray]] = []
    for i in layer_ids:
        b = f"{lm}transformer.layers.{i}."
        qkv_w, qkv_b = _qkv_to_fused(
            sd[b + "attention.query_key_value.weight"],
            sd.get(b + "attention.query_key_value.bias"),
            H, checkpoint_version)
        blocks.append({
            "ln1_g": sd[b + "input_layernorm.weight"],
            "ln1_b": sd[b + "input_layernorm.bias"],
            "qkv_w": qkv_w,
            "qkv_b": qkv_b if qkv_b is not None
            else np.zeros(qkv_w.shape[1], np.float32),
            "out_w": sd[b + "attention.dense.weight"].T,
            "out_b": sd[b + "attention.dense.bias"],
            "ln2_g": sd[b + "post_attention_layernorm.weight"],
            "ln2_b": sd[b + "post_attention_layernorm.bias"],
            "fc_w": sd[b + "mlp.dense_h_to_4h.weight"].T,
            "fc_b": sd[b + "mlp.dense_h_to_4h.bias"],
            "proj_w": sd[b + "mlp.dense_4h_to_h.weight"].T,
            "proj_b": sd[b + "mlp.dense_4h_to_h.bias"],
        })
    stacked = {k: np.stack([blk[k] for blk in blocks]) for k in blocks[0]}
    params = {
        "wte": wte,
        "wpe": wpe,
        "blocks": {k: jnp.asarray(v) for k, v in stacked.items()},
        "lnf_g": sd[lm + "transformer.final_layernorm.weight"],
        "lnf_b": sd[lm + "transformer.final_layernorm.bias"],
    }
    params = {k: (jnp.asarray(v) if not isinstance(v, dict) else v)
              for k, v in params.items()}
    log_dist(f"megatron-gpt import: L={L} E={E} H={H} V={V} "
             f"(checkpoint_version={checkpoint_version})", ranks=[0])
    return GPT(cfg), params
