"""replace_module — swap a foreign model for the fused TPU decode path.

Reference: ``deepspeed/module_inject/replace_module.py:274``
(``replace_transformer_layer``): walks the torch module tree replacing HF
blocks with ``DeepSpeedTransformerInference`` modules whose weights are
TP-sliced by ``ReplaceWithTensorSlicing``.  TPU-native version: the whole
model is replaced at once by the in-repo fused GPT implementation (one
``lax.scan`` decode program over stacked layers — the
``model_implementations/transformers/ds_transformer.py`` analogue), with
TP expressed as PartitionSpecs instead of sliced copies; XLA-SPMD slices
the weights when they are device_put.
"""

from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.models.gpt import GPT
from deepspeed_tpu.module_inject.policies import (InjectionPolicy,
                                                  policy_for_model)


def inject_hf_model(hf_model, policy: Optional[InjectionPolicy] = None,
                    dtype=None) -> Tuple[GPT, Dict]:
    """Convert an HF causal-LM into ``(fused GPT model, params)``.

    The returned model implements the InferenceEngine decode protocol
    (``init_cache`` / ``apply_with_cache`` / ``generate``), so
    ``init_inference(hf_model)`` serves it with the single-program scan
    decode path and the Pallas decode-attention kernel.
    """
    policy = policy or policy_for_model(hf_model)
    if policy is None:
        from deepspeed_tpu.module_inject.policies import _POLICIES
        mt = getattr(getattr(hf_model, "config", None), "model_type", None)
        supported = sorted({t for pol in _POLICIES for t in pol.model_types})
        raise ValueError(
            f"no injection policy for model_type={mt!r}; supported: "
            f"{', '.join(supported)} — pass policy= for a custom architecture")
    if hasattr(policy, "build_model"):
        # encoder-family policies construct their own model object (e.g.
        # Bert); decoder policies return (GPTConfig, params) below
        model, params = policy.build_model(hf_model)
        if dtype is not None:
            import dataclasses
            model.cfg = dataclasses.replace(model.cfg, dtype=dtype)
        return model, params
    cfg, params = policy.build(hf_model)
    if dtype is not None:
        import dataclasses
        cfg = dataclasses.replace(cfg, dtype=dtype)
    return GPT(cfg), params


def replace_transformer_layer(model, checkpoint=None, policy=None, dtype=None):
    """Reference-named entry point (``replace_module.py:274``).  Returns the
    fused replacement model + params; ``checkpoint`` is unused (weights come
    from the live model — the TPU path has no meta-tensor load)."""
    return inject_hf_model(model, policy=policy, dtype=dtype)


def is_hf_model(model) -> bool:
    """Duck-typed HF detection (has .config.model_type and .state_dict)."""
    return (hasattr(model, "state_dict")
            and hasattr(getattr(model, "config", None), "model_type"))
