"""Per-architecture injection policies.

Reference: ``deepspeed/module_inject/policy.py:26`` (``DSPolicy`` /
``TransformerPolicy`` ABC) and the per-arch containers under
``module_inject/containers/`` (gpt2.py, opt.py, gptneo.py, ...).  A
reference policy tells ``replace_transformer_layer`` where a given HF
architecture keeps its qkv/attention-out/mlp weights so they can be fused
and sliced.  Here a policy converts the HF state dict into the in-repo
fused GPT layout (``models/gpt.py``): stacked ``[n_layer, ...]`` blocks
with fused ``qkv_w [E, 3E]`` — the layout the single-scan decode program
and the Pallas kernels consume.

All conversions are pure numpy on host (runs once at injection time).
"""

import math
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.gpt import GPTConfig


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().cpu().numpy().astype(np.float32)
    return np.asarray(t, np.float32)


def _pad_vocab(wte: np.ndarray, padded: int) -> np.ndarray:
    v, e = wte.shape
    if v == padded:
        return wte
    out = np.zeros((padded, e), np.float32)
    out[:v] = wte
    return out


def _stack(blocks) -> Dict[str, np.ndarray]:
    """[{k: arr}, ...] per layer -> {k: [L, ...]} scan-stacked."""
    return {k: np.stack([b[k] for b in blocks]) for k in blocks[0]}


_HF_ACTIVATIONS = {"relu": "relu", "gelu": "gelu",
                   "quick_gelu": "gelu_quick",
                   "gelu_new": "gelu_tanh", "gelu_pytorch_tanh": "gelu_tanh"}


def _map_activation(hf_act: str) -> str:
    """HF ``activation_function`` → fused-block activation name."""
    if hf_act not in _HF_ACTIVATIONS:
        raise NotImplementedError(
            f"activation {hf_act!r} not supported by the fused block; "
            f"supported: {sorted(_HF_ACTIVATIONS)}")
    return _HF_ACTIVATIONS[hf_act]


def _untied_head(hf_config, sd: Dict[str, np.ndarray], head_key: str):
    """The distinct lm_head matrix, or None when tied.

    Tied checkpoints (the HF default) project logits through the input
    embedding; untied fine-tunes carry a separate lm_head matrix which
    must be loaded, not silently replaced by wte."""
    if getattr(hf_config, "tie_word_embeddings", True):
        return None
    return sd[head_key]


class InjectionPolicy:
    """ABC: map an HF model to (GPTConfig, fused param pytree)."""

    #: HF ``config.model_type`` values this policy handles
    model_types: Tuple[str, ...] = ()

    @classmethod
    def matches(cls, hf_config) -> bool:
        return getattr(hf_config, "model_type", None) in cls.model_types

    def build(self, hf_model) -> Tuple[GPTConfig, Dict]:
        raise NotImplementedError


class HFGPT2Policy(InjectionPolicy):
    """HF GPT-2 (reference ``module_inject/containers/gpt2.py``).

    HF's Conv1D already stores weights ``[in, out]`` — the fused layout —
    so qkv/fc copy through; only stacking + vocab padding is needed.
    """

    model_types = ("gpt2",)

    def build(self, hf_model):
        hc = hf_model.config
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        head = _untied_head(hc, sd, "lm_head.weight")
        cfg = GPTConfig(vocab_size=hc.vocab_size, n_positions=hc.n_positions,
                        n_embd=hc.n_embd, n_layer=hc.n_layer, n_head=hc.n_head,
                        activation=_map_activation(hc.activation_function),
                        ln_eps=hc.layer_norm_epsilon, untied_head=head is not None)
        pre = "transformer."
        blocks = []
        for i in range(cfg.n_layer):
            b = f"{pre}h.{i}."
            blocks.append({
                "ln1_g": sd[b + "ln_1.weight"], "ln1_b": sd[b + "ln_1.bias"],
                "qkv_w": sd[b + "attn.c_attn.weight"],
                "qkv_b": sd[b + "attn.c_attn.bias"],
                "out_w": sd[b + "attn.c_proj.weight"],
                "out_b": sd[b + "attn.c_proj.bias"],
                "ln2_g": sd[b + "ln_2.weight"], "ln2_b": sd[b + "ln_2.bias"],
                "fc_w": sd[b + "mlp.c_fc.weight"], "fc_b": sd[b + "mlp.c_fc.bias"],
                "proj_w": sd[b + "mlp.c_proj.weight"],
                "proj_b": sd[b + "mlp.c_proj.bias"],
            })
        params = {
            "wte": _pad_vocab(sd[pre + "wte.weight"], cfg.padded_vocab),
            "wpe": sd[pre + "wpe.weight"],
            "blocks": _stack(blocks),
            "lnf_g": sd[pre + "ln_f.weight"],
            "lnf_b": sd[pre + "ln_f.bias"],
        }
        if head is not None:
            params["lm_head"] = _pad_vocab(head, cfg.padded_vocab)
        return cfg, params


class HFOPTPolicy(InjectionPolicy):
    """HF OPT (reference ``module_inject/containers/opt.py``).

    torch ``nn.Linear`` stores ``[out, in]`` → transpose; separate q/k/v
    are fused into ``qkv_w``; positional embeddings drop OPT's offset-2
    rows; per-layer ``final_layer_norm`` is the pre-MLP norm (ln2).
    """

    model_types = ("opt",)

    def build(self, hf_model):
        hc = hf_model.config
        assert getattr(hc, "do_layer_norm_before", True), \
            "post-LN OPT (350m) layout is not supported by the fused block"
        assert hc.word_embed_proj_dim == hc.hidden_size, \
            "OPT word_embed_proj_dim != hidden_size not supported"
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        head = _untied_head(hc, sd, "lm_head.weight")
        cfg = GPTConfig(vocab_size=hc.vocab_size,
                        n_positions=hc.max_position_embeddings,
                        n_embd=hc.hidden_size, n_layer=hc.num_hidden_layers,
                        n_head=hc.num_attention_heads,
                        activation=_map_activation(hc.activation_function),
                        untied_head=head is not None)
        pre = "model.decoder."
        blocks = []
        for i in range(cfg.n_layer):
            b = f"{pre}layers.{i}."
            qkv_w = np.concatenate(
                [sd[b + f"self_attn.{n}_proj.weight"].T for n in ("q", "k", "v")],
                axis=1)
            qkv_b = np.concatenate(
                [sd[b + f"self_attn.{n}_proj.bias"] for n in ("q", "k", "v")])
            blocks.append({
                "ln1_g": sd[b + "self_attn_layer_norm.weight"],
                "ln1_b": sd[b + "self_attn_layer_norm.bias"],
                "qkv_w": qkv_w, "qkv_b": qkv_b,
                "out_w": sd[b + "self_attn.out_proj.weight"].T,
                "out_b": sd[b + "self_attn.out_proj.bias"],
                "ln2_g": sd[b + "final_layer_norm.weight"],
                "ln2_b": sd[b + "final_layer_norm.bias"],
                "fc_w": sd[b + "fc1.weight"].T, "fc_b": sd[b + "fc1.bias"],
                "proj_w": sd[b + "fc2.weight"].T, "proj_b": sd[b + "fc2.bias"],
            })
        params = {
            "wte": _pad_vocab(sd[pre + "embed_tokens.weight"], cfg.padded_vocab),
            # OPT's learned positions carry a +2 offset (pad/bos rows)
            "wpe": sd[pre + "embed_positions.weight"][2:],
            "blocks": _stack(blocks),
            "lnf_g": sd[pre + "final_layer_norm.weight"],
            "lnf_b": sd[pre + "final_layer_norm.bias"],
        }
        if head is not None:
            params["lm_head"] = _pad_vocab(head, cfg.padded_vocab)
        return cfg, params


class HFGPTNeoPolicy(InjectionPolicy):
    """HF GPT-Neo (reference ``module_inject/containers/gptneo.py``).

    q/k/v/out are bias-free separate Linears; GPT-Neo attention is
    UNSCALED (no 1/sqrt(d)) — folded in by pre-multiplying the q weights
    by sqrt(head_dim) so the shared scaled-attention kernel reproduces it.
    Only all-'global' attention configs are supported (local windowing
    would need the block-sparse attention op).
    """

    model_types = ("gpt_neo",)

    def build(self, hf_model):
        hc = hf_model.config
        attn_types = [a for a in getattr(hc, "attention_layers", [])]
        assert all(a == "global" for a in attn_types), (
            "GPT-Neo local attention layers not supported by dense injection; "
            "use the sparse-attention ops")
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        head = _untied_head(hc, sd, "lm_head.weight")
        cfg = GPTConfig(vocab_size=hc.vocab_size,
                        n_positions=hc.max_position_embeddings,
                        n_embd=hc.hidden_size, n_layer=hc.num_layers,
                        n_head=hc.num_heads,
                        activation=_map_activation(hc.activation_function),
                        ln_eps=hc.layer_norm_epsilon,
                        untied_head=head is not None)
        pre = "transformer."
        E = cfg.n_embd
        scale = math.sqrt(cfg.head_dim)
        blocks = []
        for i in range(cfg.n_layer):
            b = f"{pre}h.{i}."
            a = b + "attn.attention."
            qw = sd[a + "q_proj.weight"].T * scale
            kw = sd[a + "k_proj.weight"].T
            vw = sd[a + "v_proj.weight"].T
            blocks.append({
                "ln1_g": sd[b + "ln_1.weight"], "ln1_b": sd[b + "ln_1.bias"],
                "qkv_w": np.concatenate([qw, kw, vw], axis=1),
                "qkv_b": np.zeros((3 * E,), np.float32),
                "out_w": sd[a + "out_proj.weight"].T,
                "out_b": sd[a + "out_proj.bias"],
                "ln2_g": sd[b + "ln_2.weight"], "ln2_b": sd[b + "ln_2.bias"],
                "fc_w": sd[b + "mlp.c_fc.weight"].T, "fc_b": sd[b + "mlp.c_fc.bias"],
                "proj_w": sd[b + "mlp.c_proj.weight"].T,
                "proj_b": sd[b + "mlp.c_proj.bias"],
            })
        params = {
            "wte": _pad_vocab(sd[pre + "wte.weight"], cfg.padded_vocab),
            "wpe": sd[pre + "wpe.weight"],
            "blocks": _stack(blocks),
            "lnf_g": sd[pre + "ln_f.weight"],
            "lnf_b": sd[pre + "ln_f.bias"],
        }
        if head is not None:
            params["lm_head"] = _pad_vocab(head, cfg.padded_vocab)
        return cfg, params


_POLICIES = (HFGPT2Policy, HFOPTPolicy, HFGPTNeoPolicy)


def policy_for_model(hf_model) -> Optional[InjectionPolicy]:
    """Pick the policy for an HF model (reference
    ``replace_module.py`` ``generic_policies`` lookup)."""
    hf_config = getattr(hf_model, "config", None)
    for pol in _POLICIES:
        if hf_config is not None and pol.matches(hf_config):
            return pol()
    return None


class HFBloomPolicy(InjectionPolicy):
    """HF BLOOM (reference ``module_inject/containers/bloom.py``).

    BLOOM stores qkv INTERLEAVED per head ([H, 3, D] on the output dim) —
    de-interleave into the fused [q|k|v] layout; positions are ALiBi (no
    wpe); embeddings go through a dedicated LayerNorm folded in by
    pre-norming wte here is NOT possible, so word_embeddings_layernorm is
    REQUIRED to be foldable: it is applied to the embedding output, which
    equals scaling rows of wte only for LayerNorm without cross-feature
    stats — so we keep it as explicit extra params consumed by... instead
    we fold it by materializing normed embeddings: wte' = LN(wte), exact
    because LN acts row-wise on the embedding table lookup output.
    """

    model_types = ("bloom",)

    def build(self, hf_model):
        hc = hf_model.config
        from deepspeed_tpu.models.gpt import bloom_config
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        head = _untied_head(hc, sd, "lm_head.weight")
        cfg = bloom_config(vocab_size=hc.vocab_size,
                           n_positions=getattr(hc, "seq_length", 2048),
                           n_embd=hc.hidden_size, n_layer=hc.n_layer,
                           n_head=hc.n_head, ln_eps=hc.layer_norm_epsilon,
                           untied_head=head is not None)
        pre = "transformer."
        E, H = cfg.n_embd, cfg.n_head
        D = E // H

        def deinterleave(w):                    # [E, H*3*D] <- [3E(out), E].T
            w = w.T.reshape(E, H, 3, D)
            return jnp_concat([w[:, :, i].reshape(E, E) for i in range(3)])

        def jnp_concat(parts):
            return np.concatenate(parts, axis=1)

        def deinterleave_b(b):
            b = b.reshape(H, 3, D)
            return np.concatenate([b[:, i].reshape(E) for i in range(3)])

        blocks = []
        for i in range(cfg.n_layer):
            b = f"{pre}h.{i}."
            blocks.append({
                "ln1_g": sd[b + "input_layernorm.weight"],
                "ln1_b": sd[b + "input_layernorm.bias"],
                "qkv_w": deinterleave(sd[b + "self_attention.query_key_value.weight"]),
                "qkv_b": deinterleave_b(sd[b + "self_attention.query_key_value.bias"]),
                "out_w": sd[b + "self_attention.dense.weight"].T,
                "out_b": sd[b + "self_attention.dense.bias"],
                "ln2_g": sd[b + "post_attention_layernorm.weight"],
                "ln2_b": sd[b + "post_attention_layernorm.bias"],
                "fc_w": sd[b + "mlp.dense_h_to_4h.weight"].T,
                "fc_b": sd[b + "mlp.dense_h_to_4h.bias"],
                "proj_w": sd[b + "mlp.dense_4h_to_h.weight"].T,
                "proj_b": sd[b + "mlp.dense_4h_to_h.bias"],
            })
        # fold the word-embedding LayerNorm into the table (row-wise exact)
        wte = sd[pre + "word_embeddings.weight"]
        g = sd[pre + "word_embeddings_layernorm.weight"]
        bb = sd[pre + "word_embeddings_layernorm.bias"]
        mu = wte.mean(axis=1, keepdims=True)
        var = wte.var(axis=1, keepdims=True)
        wte_normed = (wte - mu) / np.sqrt(var + hc.layer_norm_epsilon) * g + bb
        params = {
            "wte": _pad_vocab(wte_normed, cfg.padded_vocab),
            "blocks": _stack(blocks),
            "lnf_g": sd[pre + "ln_f.weight"],
            "lnf_b": sd[pre + "ln_f.bias"],
        }
        if head is not None:
            params["lm_head"] = _pad_vocab(head, cfg.padded_vocab)
        else:
            # BLOOM ties the head to the RAW embedding table, which we
            # replaced by the normed one — carry the raw table as the head
            params["lm_head"] = _pad_vocab(wte, cfg.padded_vocab)
            cfg = _with(cfg, untied_head=True)
        return cfg, params


class HFLlamaPolicy(InjectionPolicy):
    """HF LLaMA-family (reference llama containers): separate bias-free
    q/k/v, RoPE, RMSNorm, SwiGLU gate/up fused into fc_w."""

    model_types = ("llama",)

    def build(self, hf_model):
        hc = hf_model.config
        from deepspeed_tpu.models.gpt import llama_config
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        head = _untied_head(hc, sd, "lm_head.weight")
        cfg = llama_config(vocab_size=hc.vocab_size,
                           n_positions=hc.max_position_embeddings,
                           n_embd=hc.hidden_size, n_layer=hc.num_hidden_layers,
                           n_head=hc.num_attention_heads,
                           n_kv_head=getattr(hc, "num_key_value_heads",
                                             hc.num_attention_heads),
                           intermediate_size=hc.intermediate_size,
                           ln_eps=hc.rms_norm_eps,
                           rope_theta=getattr(hc, "rope_theta", 10000.0),
                           untied_head=True)
        pre = "model."
        E = cfg.n_embd
        blocks = []
        for i in range(cfg.n_layer):
            b = f"{pre}layers.{i}."
            # HF llama checkpoints already use the half-split (x1|x2) rope
            # pairing apply_rope implements — weights copy straight through
            qkv_w = np.concatenate(
                [sd[b + f"self_attn.{n}_proj.weight"].T
                 for n in ("q", "k", "v")], axis=1)
            blocks.append({
                "ln1_g": sd[b + "input_layernorm.weight"],
                "ln1_b": np.zeros((E,), np.float32),
                "qkv_w": qkv_w,
                "qkv_b": np.zeros((cfg.qkv_dim,), np.float32),
                "out_w": sd[b + "self_attn.o_proj.weight"].T,
                "out_b": np.zeros((E,), np.float32),
                "ln2_g": sd[b + "post_attention_layernorm.weight"],
                "ln2_b": np.zeros((E,), np.float32),
                "fc_w": np.concatenate([sd[b + "mlp.gate_proj.weight"].T,
                                        sd[b + "mlp.up_proj.weight"].T], axis=1),
                "fc_b": np.zeros((2 * cfg.ffn_dim,), np.float32),
                "proj_w": sd[b + "mlp.down_proj.weight"].T,
                "proj_b": np.zeros((E,), np.float32),
            })
        params = {
            "wte": _pad_vocab(sd[pre + "embed_tokens.weight"], cfg.padded_vocab),
            "blocks": _stack(blocks),
            "lnf_g": sd[pre + "norm.weight"],
            "lnf_b": np.zeros((E,), np.float32),
        }
        params["lm_head"] = _pad_vocab(
            head if head is not None else sd[pre + "embed_tokens.weight"],
            cfg.padded_vocab)
        return cfg, params


class HFGPTJPolicy(InjectionPolicy):
    """HF GPT-J (reference ``module_inject/containers/gptj.py``): partial
    INTERLEAVED rotary (rotate-every-two over ``rotary_dim`` features),
    parallel residual with a SINGLE LayerNorm feeding both attention and
    MLP, bias-free attention projections, untied biased lm_head."""

    model_types = ("gptj",)

    def build(self, hf_model):
        hc = hf_model.config
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        E = hc.n_embd
        cfg = GPTConfig(vocab_size=hc.vocab_size, n_positions=hc.n_positions,
                        n_embd=E, n_layer=hc.n_layer, n_head=hc.n_head,
                        position_encoding="rope",
                        rope_dim=hc.rotary_dim, rope_interleaved=True,
                        block_type="parallel_single_ln",
                        activation=_map_activation(hc.activation_function),
                        ln_eps=hc.layer_norm_epsilon,
                        untied_head=True, head_bias=True)
        blocks = []
        for i in range(cfg.n_layer):
            b = f"transformer.h.{i}."
            qkv_w = np.concatenate(
                [sd[b + f"attn.{n}_proj.weight"].T for n in ("q", "k", "v")],
                axis=1)
            blocks.append({
                "ln1_g": sd[b + "ln_1.weight"], "ln1_b": sd[b + "ln_1.bias"],
                "qkv_w": qkv_w,
                "qkv_b": np.zeros((3 * E,), np.float32),
                "out_w": sd[b + "attn.out_proj.weight"].T,
                "out_b": np.zeros((E,), np.float32),
                # GPT-J has no second LN: identity placeholders (the
                # parallel_single_ln block never reads them)
                "ln2_g": np.ones((E,), np.float32),
                "ln2_b": np.zeros((E,), np.float32),
                "fc_w": sd[b + "mlp.fc_in.weight"].T,
                "fc_b": sd[b + "mlp.fc_in.bias"],
                "proj_w": sd[b + "mlp.fc_out.weight"].T,
                "proj_b": sd[b + "mlp.fc_out.bias"],
            })
        head_b = np.zeros((cfg.padded_vocab,), np.float32)
        head_b[:hc.vocab_size] = sd["lm_head.bias"]
        params = {
            "wte": _pad_vocab(sd["transformer.wte.weight"], cfg.padded_vocab),
            "blocks": _stack(blocks),
            "lnf_g": sd["transformer.ln_f.weight"],
            "lnf_b": sd["transformer.ln_f.bias"],
            "lm_head": _pad_vocab(sd["lm_head.weight"], cfg.padded_vocab),
            "lm_head_b": head_b,
        }
        return cfg, params


class HFGPTNeoXPolicy(InjectionPolicy):
    """HF GPT-NeoX / Pythia (reference ``module_inject/containers/gptneox.py``):
    fused qkv stored HEAD-INTERLEAVED ([nh, 3, hd] rows — de-interleaved
    here), partial half-split rotary (``rotary_pct``), parallel residual
    when ``use_parallel_residual`` (the default)."""

    model_types = ("gpt_neox",)

    def build(self, hf_model):
        hc = hf_model.config
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        E = hc.hidden_size
        nh = hc.num_attention_heads
        hd = E // nh
        cfg = GPTConfig(vocab_size=hc.vocab_size,
                        n_positions=hc.max_position_embeddings,
                        n_embd=E, n_layer=hc.num_hidden_layers, n_head=nh,
                        position_encoding="rope",
                        rope_dim=int(hd * hc.rotary_pct),
                        rope_theta=getattr(hc, "rotary_emb_base", 10000.0),
                        block_type=("parallel" if hc.use_parallel_residual
                                    else "sequential"),
                        activation=_map_activation(hc.hidden_act),
                        ln_eps=hc.layer_norm_eps,
                        intermediate_size=hc.intermediate_size,
                        untied_head=True)

        def deinterleave(w, b):
            # rows are [nh, 3, hd]; ours want [q(nh*hd) | k | v] columns
            w = w.reshape(nh, 3, hd, E)       # [nh, 3, hd, E]
            b = b.reshape(nh, 3, hd)
            qkv_w = np.concatenate(
                [w[:, j].reshape(nh * hd, E).T for j in range(3)], axis=1)
            qkv_b = np.concatenate([b[:, j].reshape(nh * hd) for j in range(3)])
            return qkv_w, qkv_b

        blocks = []
        for i in range(cfg.n_layer):
            b = f"gpt_neox.layers.{i}."
            qkv_w, qkv_b = deinterleave(sd[b + "attention.query_key_value.weight"],
                                        sd[b + "attention.query_key_value.bias"])
            blocks.append({
                "ln1_g": sd[b + "input_layernorm.weight"],
                "ln1_b": sd[b + "input_layernorm.bias"],
                "qkv_w": qkv_w, "qkv_b": qkv_b,
                "out_w": sd[b + "attention.dense.weight"].T,
                "out_b": sd[b + "attention.dense.bias"],
                "ln2_g": sd[b + "post_attention_layernorm.weight"],
                "ln2_b": sd[b + "post_attention_layernorm.bias"],
                "fc_w": sd[b + "mlp.dense_h_to_4h.weight"].T,
                "fc_b": sd[b + "mlp.dense_h_to_4h.bias"],
                "proj_w": sd[b + "mlp.dense_4h_to_h.weight"].T,
                "proj_b": sd[b + "mlp.dense_4h_to_h.bias"],
            })
        params = {
            "wte": _pad_vocab(sd["gpt_neox.embed_in.weight"], cfg.padded_vocab),
            "blocks": _stack(blocks),
            "lnf_g": sd["gpt_neox.final_layer_norm.weight"],
            "lnf_b": sd["gpt_neox.final_layer_norm.bias"],
            "lm_head": _pad_vocab(sd["embed_out.weight"], cfg.padded_vocab),
        }
        return cfg, params


class HFBertPolicy(InjectionPolicy):
    """HF BERT encoder (reference ``module_inject/containers/bert.py`` —
    the first ENCODER injection path).  Maps BertForMaskedLM weights onto
    the fused post-LN encoder (``models/bert.py``); serving is
    fixed-length MLM logits (no KV cache)."""

    model_types = ("bert",)

    def build_model(self, hf_model):
        from deepspeed_tpu.models.bert import Bert, BertConfig
        hc = hf_model.config
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        E = hc.hidden_size
        cfg = BertConfig(vocab_size=hc.vocab_size,
                         max_position_embeddings=hc.max_position_embeddings,
                         type_vocab_size=hc.type_vocab_size,
                         hidden_size=E,
                         num_hidden_layers=hc.num_hidden_layers,
                         num_attention_heads=hc.num_attention_heads,
                         intermediate_size=hc.intermediate_size,
                         ln_eps=hc.layer_norm_eps,
                         activation=_map_activation(hc.hidden_act))
        blocks = []
        for i in range(cfg.num_hidden_layers):
            b = f"bert.encoder.layer.{i}."
            qkv_w = np.concatenate(
                [sd[b + f"attention.self.{n}.weight"].T
                 for n in ("query", "key", "value")], axis=1)
            qkv_b = np.concatenate(
                [sd[b + f"attention.self.{n}.bias"]
                 for n in ("query", "key", "value")])
            blocks.append({
                "qkv_w": qkv_w, "qkv_b": qkv_b,
                "out_w": sd[b + "attention.output.dense.weight"].T,
                "out_b": sd[b + "attention.output.dense.bias"],
                "ln1_g": sd[b + "attention.output.LayerNorm.weight"],
                "ln1_b": sd[b + "attention.output.LayerNorm.bias"],
                "fc_w": sd[b + "intermediate.dense.weight"].T,
                "fc_b": sd[b + "intermediate.dense.bias"],
                "proj_w": sd[b + "output.dense.weight"].T,
                "proj_b": sd[b + "output.dense.bias"],
                "ln2_g": sd[b + "output.LayerNorm.weight"],
                "ln2_b": sd[b + "output.LayerNorm.bias"],
            })
        dec_b = np.zeros((cfg.padded_vocab,), np.float32)
        dec_b[:hc.vocab_size] = sd["cls.predictions.bias"]
        params = {
            "wte": _pad_vocab(sd["bert.embeddings.word_embeddings.weight"],
                              cfg.padded_vocab),
            "wpe": sd["bert.embeddings.position_embeddings.weight"],
            "wtt": sd["bert.embeddings.token_type_embeddings.weight"],
            "ln_emb_g": sd["bert.embeddings.LayerNorm.weight"],
            "ln_emb_b": sd["bert.embeddings.LayerNorm.bias"],
            "blocks": _stack(blocks),
            "mlm_w": sd["cls.predictions.transform.dense.weight"].T,
            "mlm_b": sd["cls.predictions.transform.dense.bias"],
            "ln_mlm_g": sd["cls.predictions.transform.LayerNorm.weight"],
            "ln_mlm_b": sd["cls.predictions.transform.LayerNorm.bias"],
            "mlm_decoder_b": dec_b,
        }
        return Bert(cfg), params


class HFDistilBertPolicy(InjectionPolicy):
    """HF DistilBERT (reference ``module_inject/containers/distil_bert.py``).
    Same fused post-LN encoder as BERT with no token-type embeddings;
    separate q/k/v linears concatenate into the fused qkv."""

    model_types = ("distilbert",)

    def build_model(self, hf_model):
        from deepspeed_tpu.models.bert import Bert, BertConfig
        hc = hf_model.config
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        cfg = BertConfig(vocab_size=hc.vocab_size,
                         max_position_embeddings=hc.max_position_embeddings,
                         type_vocab_size=0,
                         hidden_size=hc.dim,
                         num_hidden_layers=hc.n_layers,
                         num_attention_heads=hc.n_heads,
                         intermediate_size=hc.hidden_dim,
                         ln_eps=1e-12,
                         activation=_map_activation(hc.activation))
        blocks = []
        for i in range(cfg.num_hidden_layers):
            b = f"distilbert.transformer.layer.{i}."
            qkv_w = np.concatenate(
                [sd[b + f"attention.{n}.weight"].T
                 for n in ("q_lin", "k_lin", "v_lin")], axis=1)
            qkv_b = np.concatenate(
                [sd[b + f"attention.{n}.bias"]
                 for n in ("q_lin", "k_lin", "v_lin")])
            blocks.append({
                "qkv_w": qkv_w, "qkv_b": qkv_b,
                "out_w": sd[b + "attention.out_lin.weight"].T,
                "out_b": sd[b + "attention.out_lin.bias"],
                "ln1_g": sd[b + "sa_layer_norm.weight"],
                "ln1_b": sd[b + "sa_layer_norm.bias"],
                "fc_w": sd[b + "ffn.lin1.weight"].T,
                "fc_b": sd[b + "ffn.lin1.bias"],
                "proj_w": sd[b + "ffn.lin2.weight"].T,
                "proj_b": sd[b + "ffn.lin2.bias"],
                "ln2_g": sd[b + "output_layer_norm.weight"],
                "ln2_b": sd[b + "output_layer_norm.bias"],
            })
        dec_b = np.zeros((cfg.padded_vocab,), np.float32)
        dec_b[:hc.vocab_size] = sd["vocab_projector.bias"]
        params = {
            "wte": _pad_vocab(sd["distilbert.embeddings.word_embeddings.weight"],
                              cfg.padded_vocab),
            "wpe": sd["distilbert.embeddings.position_embeddings.weight"],
            "ln_emb_g": sd["distilbert.embeddings.LayerNorm.weight"],
            "ln_emb_b": sd["distilbert.embeddings.LayerNorm.bias"],
            "blocks": _stack(blocks),
            # vocab_transform + vocab_layer_norm + tied vocab_projector map
            # exactly onto the BERT MLM transform head
            "mlm_w": sd["vocab_transform.weight"].T,
            "mlm_b": sd["vocab_transform.bias"],
            "ln_mlm_g": sd["vocab_layer_norm.weight"],
            "ln_mlm_b": sd["vocab_layer_norm.bias"],
            "mlm_decoder_b": dec_b,
        }
        return Bert(cfg), params


def _clip_encoder_blocks(sd: Dict[str, np.ndarray], prefix: str, L: int):
    """CLIP encoder layer -> fused block mapping (shared by both towers):
    layer_norm1/2 are the pre-LNs, self_attn carries separate q/k/v/out."""
    blocks = []
    for i in range(L):
        b = f"{prefix}encoder.layers.{i}."
        qkv_w = np.concatenate(
            [sd[b + f"self_attn.{n}.weight"].T
             for n in ("q_proj", "k_proj", "v_proj")], axis=1)
        qkv_b = np.concatenate(
            [sd[b + f"self_attn.{n}.bias"]
             for n in ("q_proj", "k_proj", "v_proj")])
        blocks.append({
            "qkv_w": qkv_w, "qkv_b": qkv_b,
            "out_w": sd[b + "self_attn.out_proj.weight"].T,
            "out_b": sd[b + "self_attn.out_proj.bias"],
            "ln1_g": sd[b + "layer_norm1.weight"],
            "ln1_b": sd[b + "layer_norm1.bias"],
            "fc_w": sd[b + "mlp.fc1.weight"].T,
            "fc_b": sd[b + "mlp.fc1.bias"],
            "proj_w": sd[b + "mlp.fc2.weight"].T,
            "proj_b": sd[b + "mlp.fc2.bias"],
            "ln2_g": sd[b + "layer_norm2.weight"],
            "ln2_b": sd[b + "layer_norm2.bias"],
        })
    return _stack(blocks)


class HFCLIPTextPolicy(InjectionPolicy):
    """HF CLIPTextModel (reference ``module_inject/containers/clip.py``,
    HFCLIPLayerPolicy — Stable Diffusion's text encoder).  Causal pre-LN
    tower with a final LN; serves last hidden states."""

    model_types = ("clip_text_model",)

    def build_model(self, hf_model):
        from deepspeed_tpu.models.clip import CLIPTextEncoder, clip_text_config
        hc = hf_model.config
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        cfg = clip_text_config(
            vocab_size=hc.vocab_size,
            max_position_embeddings=hc.max_position_embeddings,
            hidden_size=hc.hidden_size,
            num_hidden_layers=hc.num_hidden_layers,
            num_attention_heads=hc.num_attention_heads,
            intermediate_size=hc.intermediate_size,
            ln_eps=hc.layer_norm_eps,
            activation=_map_activation(hc.hidden_act))
        pre = "text_model."
        params = {
            "wte": sd[pre + "embeddings.token_embedding.weight"],
            "wpe": sd[pre + "embeddings.position_embedding.weight"],
            "blocks": _clip_encoder_blocks(sd, pre, cfg.num_hidden_layers),
            "ln_f_g": sd[pre + "final_layer_norm.weight"],
            "ln_f_b": sd[pre + "final_layer_norm.bias"],
        }
        return CLIPTextEncoder(cfg, eos_token_id=hc.eos_token_id), params


class HFCLIPVisionPolicy(InjectionPolicy):
    """HF CLIPVisionModel: the ViT tower, patch conv flattened to one MXU
    matmul (``models/clip.py``)."""

    model_types = ("clip_vision_model",)

    def build_model(self, hf_model):
        from deepspeed_tpu.models.clip import CLIPVisionConfig, CLIPVisionEncoder
        hc = hf_model.config
        sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
        cfg = CLIPVisionConfig(
            image_size=hc.image_size, patch_size=hc.patch_size,
            hidden_size=hc.hidden_size,
            num_hidden_layers=hc.num_hidden_layers,
            num_attention_heads=hc.num_attention_heads,
            intermediate_size=hc.intermediate_size,
            ln_eps=hc.layer_norm_eps,
            activation=_map_activation(hc.hidden_act))
        pre = "vision_model."
        patch = sd[pre + "embeddings.patch_embedding.weight"]  # [E, C, P, P]
        # pre_layrnorm: HF's (sic) attribute name for the pre-encoder LN
        params = {
            "patch_w": patch.reshape(patch.shape[0], -1).T,   # [C*P*P, E]
            "class_emb": sd[pre + "embeddings.class_embedding"],
            "pos_emb": sd[pre + "embeddings.position_embedding.weight"],
            "pre_ln_g": sd[pre + "pre_layrnorm.weight"],
            "pre_ln_b": sd[pre + "pre_layrnorm.bias"],
            "blocks": _clip_encoder_blocks(sd, pre, cfg.num_hidden_layers),
            "post_ln_g": sd[pre + "post_layernorm.weight"],
            "post_ln_b": sd[pre + "post_layernorm.bias"],
        }
        return CLIPVisionEncoder(cfg), params


def _with(cfg, **kw):
    import dataclasses
    return dataclasses.replace(cfg, **kw)


_POLICIES = _POLICIES + (HFBloomPolicy, HFLlamaPolicy, HFGPTJPolicy,
                         HFGPTNeoXPolicy, HFBertPolicy, HFDistilBertPolicy,
                         HFCLIPTextPolicy, HFCLIPVisionPolicy)
