"""module_inject — foreign-model injection for inference.

Reference: ``deepspeed/module_inject/`` (``replace_module.py:274``,
``auto_tp.py:13``, ``policy.py:26``).  The reference walks a torch module
tree and swaps HF transformer blocks for fused CUDA modules; the TPU-native
equivalent converts a foreign model's *weights* into this framework's fused
scan layout (one compiled Pallas/XLA decode program) and derives tensor-
parallel PartitionSpecs for the result:

* :class:`AutoTP` — derives column/row-parallel PartitionSpecs for an
  arbitrary parameter pytree (the ``tp_parser`` analogue).
* policies (:mod:`.policies`) — per-architecture weight-layout converters
  (HF GPT-2, OPT, GPT-Neo) feeding the in-repo fused GPT family.
* :func:`replace_transformer_layer` / :func:`inject_hf_model` — the
  ``replace_module.py`` entry points.
"""

from deepspeed_tpu.module_inject.auto_tp import AutoTP
from deepspeed_tpu.module_inject.megatron import load_megatron_gpt
from deepspeed_tpu.module_inject.policies import (HFGPT2Policy, HFOPTPolicy,
                                                  HFGPTNeoPolicy,
                                                  InjectionPolicy,
                                                  policy_for_model)
from deepspeed_tpu.module_inject.replace_module import (inject_hf_model,
                                                        replace_transformer_layer)
