"""Weight quantization for injected inference models.

The analogue of the reference's ``GroupQuantizer``
(``module_inject/replace_module.py:138``) + the int8 dequant decode kernels
(``csrc/transformer/inference/csrc/dequantize.cu``): transformer block
weights are stored as int8 payloads with per-output-channel fp scales, and
every consumer matmul dequantizes on the fly — XLA fuses the
``int8 → bf16 × scale`` chain into the matmul operand read, so decode (a
memory-bound regime) reads half the HBM bytes per weight.  Triggered by
``dtype="int8"`` on the inference config, exactly like the reference
(``inference/engine.py`` quantizes when ``config.dtype == torch.int8``).

Layout: a quantized leaf replaces the weight array with a dict
``{"q8": int8[..., in, out], "scale": f32[..., 1, out]}`` (leading stacked
layer dims preserved).  ``models/gpt.py:_wget`` dequantizes transparently,
so the same model code serves fp and int8 params.
"""

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

# block weights worth quantizing: the large 2-D matmul operands
# (the reference's GroupQuantizer targets the same qkv/dense/mlp set)
QUANT_KEYS = ("qkv_w", "out_w", "fc_w", "proj_w")


def is_quantized_leaf(x) -> bool:
    return isinstance(x, dict) and "q8" in x and "scale" in x


def quantize_weight(w, bits: int = 8):
    """Per-output-channel symmetric int8: scale over the penultimate
    (input) axis.  ``w``: [..., in, out] float."""
    assert bits == 8, "int8 weight-only quantization (int4 via ops.quantizer)"
    wf = jnp.asarray(w, jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(wf), axis=-2, keepdims=True), 1e-12) / 127.0
    q = jnp.clip(jnp.round(wf / scale), -128, 127).astype(jnp.int8)
    return {"q8": q, "scale": scale.astype(jnp.float32)}


def dequantize_weight(leaf: Dict, dt):
    return (leaf["q8"].astype(dt) * leaf["scale"].astype(dt))


def quantize_block_params(params, keys: Sequence[str] = QUANT_KEYS,
                          bits: int = 8):
    """Quantize the named weight leaves anywhere in a params pytree (dict
    keys matched by name, arbitrary nesting/stacking)."""

    def walk(tree):
        if isinstance(tree, dict):
            out = {}
            for k, v in tree.items():
                if k in keys and hasattr(v, "ndim") and v.ndim >= 2:
                    out[k] = quantize_weight(v, bits)
                else:
                    out[k] = walk(v)
            return out
        return tree

    return walk(params)


def quantize_partition_specs(specs, params, keys: Sequence[str] = QUANT_KEYS):
    """Transform a partition-spec tree in lockstep with
    ``quantize_block_params``: q8 keeps the weight's spec; the [.., 1, out]
    scale keeps only the output-channel sharding."""

    def walk(stree, ptree):
        if isinstance(ptree, dict):
            out = {}
            for k, v in ptree.items():
                s = stree[k] if isinstance(stree, dict) else stree
                if k in keys and hasattr(v, "ndim") and v.ndim >= 2:
                    spec = s if isinstance(s, PartitionSpec) else PartitionSpec()
                    pad = [None] * max(0, v.ndim - len(spec))
                    full = list(spec) + pad
                    scale_spec = PartitionSpec(*(full[:-2] + [None, full[-1]]))
                    out[k] = {"q8": spec, "scale": scale_spec}
                else:
                    out[k] = walk(s, v)
            return out
        return stree

    return walk(specs, params)
