"""AutoTP — automatic tensor-parallel partition-spec derivation.

Reference: ``deepspeed/module_inject/auto_tp.py:13`` (``AutoTP.tp_parser``),
which walks a torch module graph to find Linear layers whose outputs feed a
residual add and marks them row-parallel (slice input dim + all-reduce),
everything else column-parallel.  On TPU the all-reduce is XLA-SPMD's job;
what AutoTP must produce is the *sharding metadata*: a PartitionSpec per
leaf of an arbitrary parameter pytree.

Classification is by leaf path + shape, mirroring the reference's name
patterns (``auto_tp.py`` ``load_policies``/linear-name heuristics):

* 2-D weights whose path matches a row-parallel pattern (attention output
  projection, MLP down projection) shard the *input* (contraction) dim.
* all other 2-D weights shard the *output* dim (column-parallel).
* embeddings (path matches embed patterns) shard the vocab dim.
* 1-D vectors shard iff they are the bias of a column-parallel weight
  (same trailing dim); layer norms stay replicated.
"""

import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import PartitionSpec

# Row-parallel = weight contracted against a TP-sharded activation; the
# union of the reference's per-arch ``policy.py`` attention-output / MLP-down
# names plus this repo's fused layout.
ROW_PARALLEL_PATTERNS = (
    r"out_w$", r"proj_w$",                      # in-repo fused GPT layout
    r"attn[./]c_proj", r"mlp[./]c_proj",        # HF GPT-2
    r"out_proj", r"o_proj", r"dense(\.|/|$)",   # OPT / LLaMA-style / BERT-out
    r"fc2", r"down_proj", r"dense_4h_to_h", r"w2$",
)
EMBEDDING_PATTERNS = (r"wte$", r"embed_tokens", r"word_embeddings", r"wte[./]weight")
REPLICATED_PATTERNS = (r"wpe", r"position_embed", r"ln", r"layernorm", r"layer_norm",
                       r"norm(\.|/|$)")
# biases are named, not shape-inferred: a scan-stacked bias is 2-D ([L, dim])
# and would otherwise be mistaken for a weight matrix
BIAS_PATTERNS = (r"_b$", r"[./]b$", r"bias$")


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _matches(name: str, patterns) -> bool:
    low = name.lower()
    return any(re.search(p, low) for p in patterns)


class AutoTP:
    """Derive tensor-parallel PartitionSpecs for an arbitrary param pytree.

    ``stacked_first_dim=True`` treats the leading dim of >=3-D leaves as a
    scan-stacked layer dim (left unsharded by TP; ZeRO composes ``fsdp``
    there).
    """

    def __init__(self, mp_size: int = 1, axis: str = "tensor",
                 stacked_first_dim: bool = True):
        self.mp_size = mp_size
        self.axis = axis
        self.stacked_first_dim = stacked_first_dim

    # -- the tp_parser analogue ----------------------------------------- #
    def classify(self, name: str, shape: Tuple[int, ...]) -> str:
        """Return one of 'row' | 'column' | 'embedding' | 'bias' | 'replicated'."""
        if _matches(name, EMBEDDING_PATTERNS):
            return "embedding"
        if len(shape) < 1:
            return "replicated"
        if _matches(name, REPLICATED_PATTERNS):
            return "replicated"
        if _matches(name, BIAS_PATTERNS):
            return "bias"      # linked to its weight in a second pass
        core = shape[1:] if (self.stacked_first_dim and len(shape) >= 3) else shape
        if len(core) == 2:
            return "row" if _matches(name, ROW_PARALLEL_PATTERNS) else "column"
        return "replicated"  # unnamed 1-D vectors stay replicated

    def _check(self, name: str, shape: Tuple[int, ...], dim: int,
               spec: PartitionSpec) -> PartitionSpec:
        """Validate the sharded dim divides by mp_size (when declared)."""
        if self.mp_size > 1 and shape[dim] % self.mp_size != 0:
            raise ValueError(
                f"AutoTP: {name} dim {dim} of shape {shape} is not divisible "
                f"by mp_size {self.mp_size}")
        return spec

    def _spec_for(self, name: str, kind: str,
                  shape: Tuple[int, ...]) -> PartitionSpec:
        pre = (None,) if (self.stacked_first_dim and len(shape) >= 3) else ()
        ax = self.axis
        if kind == "embedding":
            if len(shape) - len(pre) != 2:
                return PartitionSpec()
            return self._check(name, shape, -2, PartitionSpec(*pre, ax, None))
        if kind == "row":
            return self._check(name, shape, -2, PartitionSpec(*pre, ax, None))
        if kind == "column":
            return self._check(name, shape, -1, PartitionSpec(*pre, None, ax))
        return PartitionSpec()

    def partition_specs(self, params) -> Any:
        """PartitionSpec pytree matching ``params``.

        Biases are sharded iff a sibling column-parallel weight has the
        same output dim (the reference shards column-parallel biases and
        replicates row-parallel ones, ``replace_module.py``
        ``ReplaceWithTensorSlicing.copy``).
        """
        leaves = jax.tree_util.tree_leaves_with_path(params)
        info = {}
        for path, leaf in leaves:
            name = _path_str(path)
            shape = tuple(np.shape(leaf))
            info[name] = (path, shape, self.classify(name, shape))

        # bias linking: find column-parallel output dims per prefix
        col_dims: Dict[str, set] = {}
        for name, (_, shape, kind) in info.items():
            if kind == "column":
                prefix = name.rsplit("/", 1)[0]
                col_dims.setdefault(prefix, set()).add(shape[-1])

        specs = {}
        for name, (path, shape, kind) in info.items():
            if kind == "bias":
                # column-parallel bias shards with its weight's output dim;
                # row-parallel bias is replicated (added after the implicit
                # all-reduce, exactly the reference's rule)
                prefix = name.rsplit("/", 1)[0]
                if shape[-1] in col_dims.get(prefix, ()):
                    pre = (None,) * (len(shape) - 1)
                    specs[name] = self._check(name, shape, -1,
                                              PartitionSpec(*pre, self.axis))
                else:
                    specs[name] = PartitionSpec()
                continue
            specs[name] = self._spec_for(name, kind, shape)

        # rebuild the pytree structure
        treedef = jax.tree_util.tree_structure(params)
        ordered = [specs[_path_str(path)] for path, _ in leaves]
        return jax.tree_util.tree_unflatten(treedef, ordered)

    # -- reference-compat surface --------------------------------------- #
    @staticmethod
    def tp_parser(params) -> List[str]:
        """List the leaf names AutoTP marks row-parallel (the reference
        returns the linear names needing an all-reduce, ``auto_tp.py:13``)."""
        atp = AutoTP()
        out = []
        for path, leaf in jax.tree_util.tree_leaves_with_path(params):
            name = _path_str(path)
            if atp.classify(name, tuple(np.shape(leaf))) == "row":
                out.append(name)
        return out
