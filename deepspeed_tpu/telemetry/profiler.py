"""Windowed XLA profiler capture.

Config-driven ``jax.profiler.start_trace`` / ``stop_trace`` over a single
``[start_step, end_step)`` window.  The state machine has exactly three
states — idle → active → done — and two invariants the tests pin down:

* a trace **never starts twice** (once done, the window stays done even if
  the step counter wraps or re-enters the window);
* a trace **always stops** — via ``step_end`` once the window closes, or
  via ``close()`` on engine teardown, whichever comes first.  The window
  length is clamped to ``max_window_steps`` so a mis-configured
  ``end_step`` can never leave tracing running unbounded.

``start_fn``/``stop_fn`` are injectable for tests; the defaults wrap
``jax.profiler`` and swallow backend errors (profiling is best-effort
observability, never a reason to kill a training run).
"""

from typing import Callable, Optional

from deepspeed_tpu.utils.logging import logger

IDLE = "idle"
ACTIVE = "active"
DONE = "done"

#: hard ceiling on a capture window — XLA traces are large, and an
#: unbounded trace can fill a host disk in minutes.
MAX_WINDOW_STEPS = 64


def _default_start(log_dir: str):
    import jax
    jax.profiler.start_trace(log_dir)


def _default_stop():
    import jax
    jax.profiler.stop_trace()


class ProfilerWindow:
    """One-shot profiler capture over ``[start_step, end_step)``."""

    def __init__(self, start_step: int, end_step: int, log_dir: str,
                 max_window_steps: int = MAX_WINDOW_STEPS,
                 start_fn: Optional[Callable[[str], None]] = None,
                 stop_fn: Optional[Callable[[], None]] = None):
        self.start_step = int(start_step)
        clamp = self.start_step + max(1, int(max_window_steps))
        self.end_step = min(int(end_step), clamp)
        if int(end_step) > clamp:
            logger.warning(
                f"profiler window [{start_step}, {end_step}) clamped to "
                f"[{self.start_step}, {self.end_step}) "
                f"(max_window_steps={max_window_steps})")
        self.log_dir = log_dir
        self.state = IDLE
        self._start_fn = start_fn or _default_start
        self._stop_fn = stop_fn or _default_stop

    @property
    def active(self) -> bool:
        return self.state == ACTIVE

    def step_begin(self, step: int):
        """Call with the about-to-run step index (pre-increment counter)."""
        if self.state != IDLE:
            return
        if self.start_step <= step < self.end_step:
            try:
                self._start_fn(self.log_dir)
            except Exception as e:
                logger.warning(f"profiler start_trace failed: {e}")
                self.state = DONE
                return
            self.state = ACTIVE
            logger.info(f"profiler trace started at step {step} "
                        f"(window [{self.start_step}, {self.end_step}) "
                        f"-> {self.log_dir})")

    def step_end(self, completed_steps: int):
        """Call with the number of completed steps (post-increment counter)."""
        if self.state == ACTIVE and completed_steps >= self.end_step:
            self._stop()

    def _stop(self):
        try:
            self._stop_fn()
        except Exception as e:
            logger.warning(f"profiler stop_trace failed: {e}")
        finally:
            self.state = DONE
            logger.info(f"profiler trace stopped -> {self.log_dir}")

    def close(self):
        """Teardown hook: stop an in-flight trace no matter where the step
        counter is.  Idempotent."""
        if self.state == ACTIVE:
            self._stop()

    @classmethod
    def from_config(cls, tcfg) -> Optional["ProfilerWindow"]:
        """Build from a ``DeepSpeedTelemetryConfig``; None when disabled."""
        if not tcfg.profiler_start_step and not tcfg.profiler_end_step:
            return None
        start = tcfg.profiler_start_step or 0
        end = tcfg.profiler_end_step or (start + 1)
        if end <= start:
            logger.warning(
                f"profiler window [{start}, {end}) is empty; disabled")
            return None
        return cls(start, end, tcfg.profiler_dir,
                   max_window_steps=tcfg.profiler_max_window_steps)
