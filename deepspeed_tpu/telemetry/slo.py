"""Declarative SLO rules with multiwindow burn-rate alerting.

An :class:`SLORule` names a registry metric, how to read it (histogram
quantile, gauge/counter value, counter ratio, or regression against a
self-captured baseline) and the objective bound.  The
:class:`SLOMonitor` samples each rule at evaluation cadence (the hub's
flush boundary in the live plane, replay order in
``tools/obs_report.py``), keeps a sliding window of violation samples,
and converts them into *error-budget burn rates* — the SRE multiwindow
scheme: with ``budget_frac`` the tolerated violating fraction,

    burn(window) = violating_fraction(window) / budget_frac

a **fast** alert (page) fires when the short window burns at ≥
``fast_burn``× budget, a **slow** alert (ticket) when the long window
sustains ≥ ``slow_burn``×.  Every transition into a burning state emits
an ``slo_burn`` telemetry event; recovery emits ``slo_clear``.  The
:meth:`SLOMonitor.verdict` dict is the machine-readable surface the
``/slo`` endpoint serves and the future autotuner scores against.

Rule grammar (config / ``telemetry.slo_rules`` entries)::

    {"name": "serve_p99_ttft_ms",          # unique rule id
     "metric": "serve_ttft_ms",            # registry metric key
     "op": "p99",                          # p50|p95|p99|value|ratio|regression
     "bound": 500.0,                       # objective (ratio: fraction;
                                           #  regression: factor over baseline)
     "cmp": "le",                          # le: value must stay ≤ bound
     "den": "sum:train_step_time_ms",      # ratio only: denominator ref
     "budget_frac": 0.05,                  # tolerated violating fraction
     "fast_window_s": 60, "slow_window_s": 600,
     "fast_burn": 10.0, "slow_burn": 2.0,
     "min_samples": 3}

Value refs for ``ratio`` operands: ``counter:NAME``, ``gauge:NAME``,
``sum:NAME`` / ``count:NAME`` (histogram), or a bare key searched across
sections.  Host-side logic only — evaluation reads registry snapshots
(already host floats); the zero-sync dslint pass polices ``evaluate``.
"""

import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

try:
    from deepspeed_tpu.telemetry import stats as _stats
except ImportError:     # standalone (spec-loaded by a no-jax CLI)
    import importlib.util as _ilu
    import os as _os
    _spec = _ilu.spec_from_file_location(
        "_ds_tpu_telemetry_stats",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "stats.py"))
    _stats = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_stats)

_QUANTILE_OPS = {"p50": 0.50, "p95": 0.95, "p99": 0.99}


class SLORule:
    """One declarative objective; see module docstring for the grammar."""

    def __init__(self, name: str, metric: str, op: str, bound: float,
                 cmp: str = "le", den: Optional[str] = None,
                 budget_frac: float = 0.05,
                 fast_window_s: float = 60.0, slow_window_s: float = 600.0,
                 fast_burn: float = 10.0, slow_burn: float = 2.0,
                 min_samples: int = 3, baseline_min_count: int = 20):
        if op not in ("value", "ratio", "regression") and op not in _QUANTILE_OPS:
            raise ValueError(f"SLO rule {name}: unknown op {op!r}")
        if cmp not in ("le", "ge"):
            raise ValueError(f"SLO rule {name}: cmp must be 'le' or 'ge'")
        if op == "ratio" and not den:
            raise ValueError(f"SLO rule {name}: ratio op needs a 'den' ref")
        if not (0.0 < float(budget_frac) <= 1.0):
            raise ValueError(f"SLO rule {name}: budget_frac must be in (0, 1]")
        self.name = name
        self.metric = metric
        self.op = op
        self.bound = float(bound)
        self.cmp = cmp
        self.den = den
        self.budget_frac = float(budget_frac)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.min_samples = int(min_samples)
        self.baseline_min_count = int(baseline_min_count)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SLORule":
        known = ("name", "metric", "op", "bound", "cmp", "den", "budget_frac",
                 "fast_window_s", "slow_window_s", "fast_burn", "slow_burn",
                 "min_samples", "baseline_min_count")
        unknown = set(d) - set(known)
        if unknown:
            raise ValueError(f"SLO rule: unknown keys {sorted(unknown)}")
        return cls(**d)

    def to_dict(self) -> Dict[str, Any]:
        d = {"name": self.name, "metric": self.metric, "op": self.op,
             "bound": self.bound, "cmp": self.cmp,
             "budget_frac": self.budget_frac,
             "fast_window_s": self.fast_window_s,
             "slow_window_s": self.slow_window_s,
             "fast_burn": self.fast_burn, "slow_burn": self.slow_burn,
             "min_samples": self.min_samples}
        if self.den:
            d["den"] = self.den
        return d


def default_rules(serve_p99_ttft_ms: float = 2000.0,
                  offload_stall_frac: float = 0.15,
                  step_time_factor: float = 1.5,
                  collective_p99_skew_ms: float = 1000.0) -> List[SLORule]:
    """The stock objectives, with relaxed default bounds (tighten per
    deployment via ``telemetry.slo_rules``).  The collective-skew rule
    bounds the p99 first-vs-last rank arrival gap the collective health
    plane folds into ``collective_skew_ms`` — a chronic straggler burns
    this long before it shows up as a step-time regression."""
    return [
        SLORule("serve_p99_ttft_ms", "serve_ttft_ms", "p99",
                serve_p99_ttft_ms, cmp="le"),
        SLORule("offload_stall_frac", "counter:offload_stall_ms_total",
                "ratio", offload_stall_frac, cmp="le",
                den="sum:train_step_time_ms"),
        SLORule("step_time_regression", "train_step_time_ms", "regression",
                step_time_factor, cmp="le"),
        SLORule("collective_p99_skew_ms", "collective_skew_ms", "p99",
                collective_p99_skew_ms, cmp="le"),
    ]


def _lookup(snapshot: Dict[str, Any], ref: str):
    """Resolve a value ref (see module docstring) against a snapshot."""
    section = None
    name = ref
    if ":" in ref:
        section, name = ref.split(":", 1)
    if section in (None, "counter"):
        ent = (snapshot.get("counters") or {}).get(name)
        if ent is not None:
            return ent["value"]
        if section == "counter":
            return None
    if section in (None, "gauge"):
        ent = (snapshot.get("gauges") or {}).get(name)
        if ent is not None:
            return ent.get("value", ent.get("mean"))
        if section == "gauge":
            return None
    if section in ("sum", "count"):
        ent = (snapshot.get("histograms") or {}).get(name)
        if ent is None:
            return None
        return ent[section]
    return None


class SLOMonitor:
    """Samples rules against registry snapshots and runs the burn-rate
    state machine.  States per rule: ``ok`` → ``burn_slow`` → ``burn_fast``
    (and back).  ``telemetry`` (a TelemetryHub, optional) receives the
    ``slo_burn`` / ``slo_clear`` events; ``clock`` is injectable so tests
    never sleep."""

    def __init__(self, rules: Sequence[SLORule], registry=None,
                 telemetry=None, clock=time.monotonic):
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO rule names in {names}")
        self.registry = registry
        self.telemetry = telemetry
        self._clock = clock
        self._samples: Dict[str, deque] = {r.name: deque() for r in self.rules}
        self._state: Dict[str, str] = {r.name: "ok" for r in self.rules}
        self._last: Dict[str, Dict[str, Any]] = {}
        self._baseline: Dict[str, float] = {}
        self.burn_events = 0

    # -- rule sampling ---------------------------------------------------- #
    def _rule_value(self, rule: SLORule, snapshot: Dict[str, Any]):
        if rule.op in _QUANTILE_OPS:
            h = (snapshot.get("histograms") or {}).get(rule.metric)
            if h is None or not h["count"]:
                return None
            return _stats.quantile_from_buckets(h["bounds"], h["counts"],
                                                _QUANTILE_OPS[rule.op])
        if rule.op == "value":
            return _lookup(snapshot, rule.metric)
        if rule.op == "ratio":
            num = _lookup(snapshot, rule.metric)
            den = _lookup(snapshot, rule.den)
            if num is None or not den:
                return None
            return num / den
        if rule.op == "regression":
            h = (snapshot.get("histograms") or {}).get(rule.metric)
            if h is None or h["count"] < rule.baseline_min_count:
                return None
            p50 = _stats.quantile_from_buckets(h["bounds"], h["counts"], 0.50)
            base = self._baseline.get(rule.name)
            if base is None:
                self._baseline[rule.name] = p50
                return None          # baseline capture sample, never violates
            if not base:
                return None
            return p50 / base        # violated when ratio exceeds the factor
        return None

    @staticmethod
    def _violated(rule: SLORule, value) -> bool:
        if value is None:
            return False
        if rule.cmp == "le":
            return value > rule.bound
        return value < rule.bound

    def _burn(self, rule: SLORule, now: float, window_s: float):
        """(burn rate, samples in window) for one sliding window."""
        cutoff = now - window_s
        n = bad = 0
        for t, v in self._samples[rule.name]:
            if t >= cutoff:
                n += 1
                bad += 1 if v else 0
        if n == 0:
            return 0.0, 0
        return (bad / n) / rule.budget_frac, n

    # -- evaluation ------------------------------------------------------- #
    def evaluate(self, now: Optional[float] = None,
                 snapshot: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Sample every rule once, advance the state machines, emit burn
        events on transitions, return the verdict."""
        if now is None:
            now = self._clock()
        if snapshot is None:
            snapshot = self.registry.snapshot() if self.registry else {}
        for rule in self.rules:
            value = self._rule_value(rule, snapshot)
            violated = self._violated(rule, value)
            win = self._samples[rule.name]
            if value is not None:
                win.append((now, violated))
            cutoff = now - rule.slow_window_s
            while win and win[0][0] < cutoff:
                win.popleft()
            fast_burn, fast_n = self._burn(rule, now, rule.fast_window_s)
            slow_burn, slow_n = self._burn(rule, now, rule.slow_window_s)
            prev = self._state[rule.name]
            state = "ok"
            if fast_n >= rule.min_samples and fast_burn >= rule.fast_burn:
                state = "burn_fast"
            elif slow_n >= rule.min_samples and slow_burn >= rule.slow_burn:
                state = "burn_slow"
            self._state[rule.name] = state
            self._last[rule.name] = {
                "state": state, "value": value, "bound": rule.bound,
                "op": rule.op, "cmp": rule.cmp, "violated": violated,
                "burn_fast": round(fast_burn, 4),
                "burn_slow": round(slow_burn, 4),
                "samples_fast": fast_n, "samples_slow": slow_n,
            }
            if state != prev:
                self._transition(rule, prev, state)
        return self.verdict()

    def _transition(self, rule: SLORule, prev: str, state: str):
        info = self._last[rule.name]
        if state == "ok":
            self._emit("slo_clear", {"rule": rule.name, "from": prev})
            return
        self.burn_events += 1
        severity = "fast" if state == "burn_fast" else "slow"
        self._emit("slo_burn", {
            "rule": rule.name, "severity": severity, "from": prev,
            "value": info["value"], "bound": rule.bound,
            "burn_fast": info["burn_fast"], "burn_slow": info["burn_slow"],
        })

    def _emit(self, kind: str, payload: Dict[str, Any]):
        if self.telemetry is not None:
            try:
                self.telemetry.emit(kind, payload)
            except Exception:
                pass

    # -- machine-readable surface ------------------------------------------ #
    def verdict(self) -> Dict[str, Any]:
        rules = {}
        for rule in self.rules:
            rules[rule.name] = dict(self._last.get(
                rule.name, {"state": "ok", "value": None,
                            "bound": rule.bound, "op": rule.op,
                            "cmp": rule.cmp, "violated": False,
                            "burn_fast": 0.0, "burn_slow": 0.0,
                            "samples_fast": 0, "samples_slow": 0}))
        ok = all(r["state"] == "ok" for r in rules.values())
        burning = sorted(n for n, r in rules.items() if r["state"] != "ok")
        return {"ok": ok, "burning": burning,
                "burn_events": self.burn_events, "rules": rules}

    def state_for_metric(self, metric: str) -> str:
        """Worst current burn state among rules sampling ``metric``
        (``ok`` < ``burn_slow`` < ``burn_fast``) — the serving admission
        ladder reads the TTFT rules this way without re-evaluating."""
        rank = {"ok": 0, "burn_slow": 1, "burn_fast": 2}
        worst = "ok"
        for rule in self.rules:
            if rule.metric.split(":", 1)[-1] != metric:
                continue
            state = self._state.get(rule.name, "ok")
            if rank.get(state, 0) > rank[worst]:
                worst = state
        return worst


def rules_from_config(specs, defaults: bool = True) -> List[SLORule]:
    """Build the rule list from ``telemetry.slo_rules`` config entries —
    a falsy spec list yields the stock :func:`default_rules` (when
    ``defaults``), explicit entries replace them wholesale."""
    if specs:
        return [r if isinstance(r, SLORule) else SLORule.from_dict(dict(r))
                for r in specs]
    return default_rules() if defaults else []
