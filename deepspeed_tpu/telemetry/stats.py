"""Shared statistics primitives for the observability plane.

One home for the percentile / histogram / JSONL-loading math that used to
be copy-pasted across ``tools/serve_report.py``, ``tools/offload_audit.py``
and ``tools/stability_report.py``, now also backing the live
:class:`~deepspeed_tpu.telemetry.metrics.MetricsRegistry`.

Standard library only, no intra-package imports — the offline report CLIs
must keep working in environments without jax, so this module can be
loaded either as ``deepspeed_tpu.telemetry.stats`` or standalone via
``importlib.util.spec_from_file_location``.
"""

import bisect
import json
import os
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

# --------------------------------------------------------------------------- #
# Percentiles (exact, over sorted samples) — the offline-report estimator.
# --------------------------------------------------------------------------- #


def percentile(sorted_vals: Sequence[float], q: float):
    """Nearest-rank percentile over an already-sorted sample list.

    Byte-identical to the former per-tool ``_pct`` helpers: index
    ``int(q * n)`` clamped to the last element, ``None`` on empty input.
    """
    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))]


# --------------------------------------------------------------------------- #
# Fixed-bucket histograms — the live-registry estimator.
# --------------------------------------------------------------------------- #

# Default latency bucket upper bounds (ms): 1 ms → ~2 min, roughly
# exponential.  Chosen so serving TTFT (tens–hundreds of ms) and train
# step times (hundreds–thousands of ms) both land mid-range.
DEFAULT_MS_BUCKETS: Tuple[float, ...] = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 120000.0)


def bucket_index(bounds: Sequence[float], value: float) -> int:
    """Index of the bucket ``value`` falls into: ``bounds[i]`` is the
    inclusive upper bound of bucket ``i``; index ``len(bounds)`` is the
    +Inf overflow bucket."""
    return bisect.bisect_left(bounds, value)


def merge_bucket_counts(a: Sequence[int], b: Sequence[int]) -> List[int]:
    """Element-wise sum of two equal-shape bucket-count vectors.

    Histogram merge is associative and commutative (it is vector
    addition), which is what makes the cross-rank fold order-independent.
    """
    if len(a) != len(b):
        raise ValueError(
            f"histogram bucket mismatch: {len(a)} vs {len(b)} counts")
    return [int(x) + int(y) for x, y in zip(a, b)]


def quantile_from_buckets(bounds: Sequence[float], counts: Sequence[int],
                          q: float) -> Optional[float]:
    """Estimate the ``q``-quantile from fixed-bucket counts.

    Returns the upper bound of the bucket holding the target rank
    (Prometheus ``histogram_quantile``-style, without interpolation —
    conservative for SLO checks since the true value is ≤ the estimate).
    The overflow bucket reports the largest finite bound.
    """
    total = sum(int(c) for c in counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += int(c)
        if cum >= target and c:
            if i < len(bounds):
                return float(bounds[i])
            return float(bounds[-1])  # overflow bucket: clamp to last bound
    return float(bounds[-1])


# --------------------------------------------------------------------------- #
# Telemetry JSONL loading (rotation-aware).
# --------------------------------------------------------------------------- #

_ROT_SUFFIX = re.compile(r"\.(\d+)$")


def rotated_set(path: str) -> List[str]:
    """All files of a possibly-rotated JSONL set, oldest first.

    ``JsonlSink`` rotates ``telemetry.jsonl`` to ``telemetry.jsonl.1``,
    ``.2``, … (ascending = chronological), so the read order is the
    numeric rotations ascending followed by the live file.  A path with
    no rotated siblings returns ``[path]`` — the pre-rotation behavior.
    """
    out = []
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path)
    try:
        names = os.listdir(d)
    except OSError:
        names = []
    rots = []
    for name in names:
        if not name.startswith(base + "."):
            continue
        m = _ROT_SUFFIX.search(name)
        if m and name == f"{base}.{m.group(1)}":
            rots.append((int(m.group(1)), os.path.join(d, name)))
    out.extend(p for _, p in sorted(rots))
    out.append(path)
    return out


def iter_jsonl(path: str):
    """Yield parsed dict records from one JSONL file, tolerating torn
    tail lines (a crashed run).  Raises OSError if unreadable."""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue     # torn tail line from a crashed run
            if isinstance(rec, dict):
                yield rec


def load_records(path: str):
    """→ (records list, error string or None).

    The shared loader behind every offline report CLI: reads the full
    rotated set for ``path`` (oldest rotation first, live file last),
    keeps records carrying a ``kind``, tolerates torn tail lines, and
    rejects inputs with no parseable telemetry records.  For an
    un-rotated file this is behavior-identical to the loaders it
    replaced.
    """
    paths = [p for p in rotated_set(path) if os.path.isfile(p)]
    if not os.path.isfile(path) and not paths:
        return None, f"{path}: not a file"
    records: List[Dict[str, Any]] = []
    try:
        for p in paths:
            for rec in iter_jsonl(p):
                if "kind" in rec:
                    records.append(rec)
    except OSError as e:
        return None, f"unreadable {path}: {e}"
    if not records:
        return None, f"{path}: no telemetry records (wrong file?)"
    return records, None


# --------------------------------------------------------------------------- #
# Uniform report finalization — one output contract for every report CLI.
# --------------------------------------------------------------------------- #

# Version of the uniform CLI envelope (tool/report_schema keys + gates→ok
# convention), independent of each tool's own payload fields.
REPORT_SCHEMA = 1


def finalize_report(tool: str, report: Dict[str, Any],
                    gates: Optional[Dict[str, Any]] = None,
                    json_out: Optional[str] = None) -> int:
    """Stamp, print, optionally persist a report dict; return the exit code.

    The one output path shared by every report CLI (``serve_report``,
    ``offload_audit``, ``stability_report``, ``obs_report``,
    ``goodput_report``, ``bench_trend``): adds the uniform envelope keys
    *into* the report (``tool``, ``report_schema`` — existing top-level
    payload fields stay where tests and downstream autotuners expect
    them), merges ``gates`` under ``report["gates"]`` when given, prints
    the canonical sorted-JSON text, mirrors the *same text* to
    ``json_out`` when set, and returns 0/1 from ``report["ok"]``
    (missing ``ok`` means nothing was gated → 0).
    """
    report.setdefault("tool", tool)
    report.setdefault("report_schema", REPORT_SCHEMA)
    if gates is not None:
        report.setdefault("gates", {}).update(gates)
    text = json.dumps(report, indent=2, sort_keys=True)
    print(text)
    if json_out:
        with open(json_out, "w") as f:
            f.write(text + "\n")
    return 0 if report.get("ok", True) else 1
