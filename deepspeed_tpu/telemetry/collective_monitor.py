"""Collective health plane — per-collective records, cross-rank skew fold,
desync detection, straggler attribution.

Distributed runs fail in ways the step-level observability plane cannot
attribute: one rank arrives late at every all-reduce (a straggler), one
rank never arrives at all (a wedge), or — worst — ranks silently stage
*different* collective sequences and the program deadlocks or corrupts
data with nothing in the logs (the desync failure class The Big Send-off
calls out, arXiv:2504.18658).  This module gives every collective that
crosses the ``deepspeed_tpu.comm`` facade an identity and a clock:

* **Record** — each staged collective gets a per-rank monotonic
  ``seq`` and a structure *fingerprint* (CRC-32 of op|axis|dtype|shape —
  deterministic across processes, unlike Python's salted ``hash``),
  appended to a bounded ring with ``time.monotonic_ns`` enter/exit
  stamps.  The hot path (:meth:`CollectiveMonitor.begin` /
  :meth:`~CollectiveMonitor.end`) is zero-sync by construction — it
  reads only static trace-time metadata (op name, axis name, aval dtype
  and shape), never a device value — and is policed by the dslint
  zero-sync pass.  Collectives fire at *trace* time on the staged path
  (they fuse into XLA programs), so staged records measure when the op
  was staged; eager-boundary calls get true execution brackets.

* **Fold** — :func:`fold_windows` merges per-rank window views into one
  health verdict: per-collective skew (first-vs-last rank arrival at
  each common ``seq``) folded into fixed-bucket histograms (global and
  per-op), an exponentially-weighted per-rank straggler score naming the
  chronically-late rank, and **desync detection** — the first ``seq``
  where any two ranks staged structurally different collectives, with
  both fingerprints and the divergent ranks named in the verdict.

* Three provably-equal fold paths (mirroring the metrics-plane
  ``pack_snapshot``/``fold_packed_over_mesh`` discipline): the host fold
  of in-memory views, the device path (:func:`pack_window` vectors
  gathered through the comm facade by
  :func:`gather_windows_over_mesh`, then the same host fold), and the
  offline path (:func:`fold_window_records` over the per-rank
  ``collective_window`` JSONL records the hub emits at
  ``snapshot_every`` cadence — what ``tools/collective_report.py``
  gates).

Time base: each monitor anchors ``time.monotonic_ns`` against
``time.time`` once at construction and expresses stamps as integer
*microseconds since the unix epoch* — ints survive JSON exactly, and
wall anchoring makes stamps from different processes comparable (same
discipline as ``tracing.py``'s ``clock_sync``).

Standard library only — the module is loaded by file path from the
no-jax ``tools/collective_report.py`` (jax is imported lazily inside the
device-mesh helper only).
"""

import threading
import time
import zlib
from collections import deque

SCHEMA_VERSION = 1

#: skew histogram bucket upper bounds (ms) — sub-millisecond resolution
#: at the bottom (ICI-local skew) up to multi-second stragglers.
DEFAULT_SKEW_BUCKETS_MS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 15000.0, 60000.0)

#: EW smoothing factor for the per-rank straggler score.
DEFAULT_EW_ALPHA = 0.2

#: floats per record row in the packed device vector (see pack_window).
_ROW_WIDTH = 8

try:                                    # package import (runtime)
    from deepspeed_tpu.telemetry import stats as _stats
except ImportError:                     # standalone (spec-loaded by a CLI)
    import importlib.util as _ilu
    import os as _os
    _spec = _ilu.spec_from_file_location(
        "_ds_tpu_telemetry_stats",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "stats.py"))
    _stats = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_stats)


def fingerprint_of(op, axis, dtype, shape):
    """Deterministic 32-bit structure fingerprint of one collective.

    CRC-32 over the canonical ``op|axis|dtype|shape`` string: identical
    across processes and runs (Python ``hash`` is salted per process, so
    it could never be compared across ranks), cheap enough for the
    staged hot path, and sensitive to every structural field — two ranks
    staging the same op over the same axis with different dtypes or
    shapes get different fingerprints, which is exactly the divergence
    the desync detector keys on.
    """
    key = "%s|%s|%s|%s" % (op, axis, dtype, tuple(shape))
    return zlib.crc32(key.encode("utf-8"))


class CollectiveMonitor:
    """Per-rank bounded ring of collective records.

    ``begin`` / ``end`` are the comm-facade hot path (one lock, one
    clock read, one deque append — and **no device access**: dtype and
    shape arrive as already-host metadata).  Everything else is
    fold/ops-plane code that runs at snapshot cadence or on demand.
    """

    def __init__(self, rank=0, capacity=2048, clock_ns=time.monotonic_ns):
        self.rank = int(rank)
        self.capacity = max(1, int(capacity))
        self._clock_ns = clock_ns
        self._ring = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        # wall anchor: monotonic stamps become epoch-comparable microseconds
        self._anchor_unix_us = int(time.time() * 1e6)
        self._anchor_mono_ns = clock_ns()
        self.desync_count = 0
        self.last_desync = None

    # ---- hot path (zero-sync: trace-time metadata only) ---------------- #

    def _now_us(self):
        return self._anchor_unix_us + (
            self._clock_ns() - self._anchor_mono_ns) // 1000

    def begin(self, op, axis, dtype, shape, nbytes):
        """Open one collective record: assign the next ``seq``, stamp the
        enter time, append to the ring.  Appending at *begin* (not end)
        is load-bearing: a collective that wedges and never exits is
        still in the ring when the flight recorder dumps it."""
        rec = {
            "seq": 0,                   # assigned under the lock below
            "op": op,
            "axis": "" if axis is None else str(axis),
            "dtype": str(dtype),
            "shape": shape,
            "bytes": nbytes,
            "fp": 0,
            "t_enter_us": self._now_us(),
            "t_exit_us": None,
        }
        rec["fp"] = fingerprint_of(rec["op"], rec["axis"], rec["dtype"],
                                   rec["shape"])
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
        return rec

    def end(self, rec):
        """Stamp the exit time on an open record."""
        rec["t_exit_us"] = self._now_us()

    # ---- read side ------------------------------------------------------ #

    @property
    def seq(self):
        with self._lock:
            return self._seq

    def last_records(self, n=None):
        """Newest-last JSON-ready view of (up to) the last ``n`` records —
        the flight-recorder section payload."""
        with self._lock:
            recs = list(self._ring)
        if n is not None:
            recs = recs[-int(n):]
        return [_record_to_json(r) for r in recs]

    def window_view(self, max_records=None):
        """This rank's fold input: the current ring window as one
        JSON-ready view (the body of a ``collective_window`` telemetry
        record)."""
        return {
            "schema": SCHEMA_VERSION,
            "rank": self.rank,
            "seq": self.seq,
            "records": self.last_records(max_records),
        }

    # ---- desync bookkeeping (fed by the fold) --------------------------- #

    def note_desync(self, detail):
        """The cross-rank fold detected a fingerprint divergence; latch it
        so ``/healthz`` flips unhealthy and stays there."""
        self.desync_count += 1
        self.last_desync = dict(detail)

    def health_check(self):
        """``/healthz`` contribution: unhealthy once any desync has been
        detected (a desynced program is undefined behavior — there is no
        recovering to ``ok`` within the same incarnation)."""
        out = {"ok": self.desync_count == 0,
               "desync_count": self.desync_count,
               "seq": self.seq}
        if self.last_desync is not None:
            out["first_seq"] = self.last_desync.get("first_seq")
        return out

    def wedged_summary(self):
        """One-line 'what was the last collective' context string for the
        watchdog's stall log — names the op a wedge is stuck in."""
        with self._lock:
            rec = self._ring[-1] if self._ring else None
        if rec is None:
            return "no collectives recorded"
        state = "open" if rec["t_exit_us"] is None else "closed"
        return ("last collective seq=%d op=%s axis=%s dtype=%s shape=%s "
                "bytes=%d (%s)" % (rec["seq"], rec["op"], rec["axis"],
                                   rec["dtype"], tuple(rec["shape"]),
                                   rec["bytes"], state))


def _record_to_json(rec):
    out = dict(rec)
    out["shape"] = [int(d) for d in rec["shape"]]
    out["bytes"] = int(rec["bytes"])
    return out


# --------------------------------------------------------------------------- #
# Cross-rank fold (pure host math — shared by live hub, device parity path,
# and the offline report CLI)
# --------------------------------------------------------------------------- #

def _by_seq(view):
    """seq → record for one rank's view (later records win on repeats)."""
    return {int(r["seq"]): r for r in view.get("records", [])}


def fold_windows(views, skew_bounds=DEFAULT_SKEW_BUCKETS_MS,
                 ew_alpha=DEFAULT_EW_ALPHA, new_after=0):
    """Fold per-rank window views into one collective-health verdict.

    * **Desync**: walking ``seq`` ascending over every seq two or more
      ranks recorded, the first one where fingerprints differ is the
      divergence point; the verdict names it, the divergent ranks, and
      each rank's full fingerprint (op/axis/dtype/shape) — ranks that
      merely *miss* a seq (ring eviction, different window tails) are
      not desynced.
    * **Skew**: over seqs present on *all* ranks, first-vs-last arrival
      (enter stamps) in ms, folded into fixed-bucket histograms
      (global + per-op) with p50/p99 estimates.
    * **Straggler**: per-rank EW average of each rank's arrival offset
      from the earliest rank, walked in seq order; the max-score rank is
      the named straggler.
    * ``new_after``: skew samples with ``seq`` ≤ this are folded into
      the histograms but excluded from ``skew_samples`` — the
      incremental feed the live registry consumes without re-observing
      seqs from a previous fold of an overlapping window.
    """
    views = [v for v in views if v is not None]
    ranks = [int(v.get("rank", i)) for i, v in enumerate(views)]
    by_rank = {r: _by_seq(v) for r, v in zip(ranks, views)}
    n_ranks = len(by_rank)
    all_seqs = sorted({s for recs in by_rank.values() for s in recs})

    # -- desync: first seq where any two ranks disagree structurally ----- #
    desync = {"detected": False}
    for s in all_seqs:
        present = {r: recs[s] for r, recs in by_rank.items() if s in recs}
        if len(present) < 2:
            continue
        fps = {int(rec["fp"]) for rec in present.values()}
        if len(fps) > 1:
            desync = {
                "detected": True,
                "first_seq": s,
                "ranks": sorted(present),
                "fingerprints": {
                    str(r): {"fp": int(rec["fp"]), "op": rec["op"],
                             "axis": rec["axis"], "dtype": rec["dtype"],
                             "shape": [int(d) for d in rec["shape"]]}
                    for r, rec in sorted(present.items())},
            }
            break

    # -- skew + straggler over fully-common seqs -------------------------- #
    bounds = tuple(float(b) for b in skew_bounds)
    counts = [0] * (len(bounds) + 1)
    skew_sum = 0.0
    skew_max = 0.0
    per_op = {}
    samples = []
    scores = {r: 0.0 for r in by_rank}
    last_common = 0
    common = [s for s in all_seqs
              if all(s in recs for recs in by_rank.values())]
    if n_ranks >= 2:
        for s in common:
            enters = {r: int(recs[s]["t_enter_us"])
                      for r, recs in by_rank.items()}
            first = min(enters.values())
            skew_ms = (max(enters.values()) - first) / 1000.0
            counts[_stats.bucket_index(bounds, skew_ms)] += 1
            skew_sum += skew_ms
            skew_max = max(skew_max, skew_ms)
            op = by_rank[min(by_rank)][s]["op"]
            ent = per_op.setdefault(op, {"counts": [0] * (len(bounds) + 1),
                                         "sum_ms": 0.0, "count": 0})
            ent["counts"][_stats.bucket_index(bounds, skew_ms)] += 1
            ent["sum_ms"] += skew_ms
            ent["count"] += 1
            for r in scores:
                dt_ms = (enters[r] - first) / 1000.0
                scores[r] = (1.0 - ew_alpha) * scores[r] + ew_alpha * dt_ms
            if s > new_after:
                samples.append({"seq": s, "op": op,
                                "skew_ms": round(skew_ms, 6)})
            last_common = s

    n_skew = sum(counts)
    for op, ent in per_op.items():
        ent["p50_ms"] = _stats.quantile_from_buckets(bounds, ent["counts"],
                                                     0.50)
        ent["p99_ms"] = _stats.quantile_from_buckets(bounds, ent["counts"],
                                                     0.99)
    straggler_rank = None
    straggler_score = 0.0
    if n_ranks >= 2 and n_skew:
        straggler_rank = max(sorted(scores), key=lambda r: scores[r])
        straggler_score = scores[straggler_rank]

    return {
        "schema": SCHEMA_VERSION,
        "n_ranks": n_ranks,
        "ranks": sorted(by_rank),
        "seq_lo": all_seqs[0] if all_seqs else 0,
        "seq_hi": all_seqs[-1] if all_seqs else 0,
        "common_seqs": len(common),
        "skew": {
            "bounds": list(bounds),
            "counts": counts,
            "count": n_skew,
            "sum_ms": skew_sum,
            "max_ms": skew_max,
            "p50_ms": _stats.quantile_from_buckets(bounds, counts, 0.50),
            "p99_ms": _stats.quantile_from_buckets(bounds, counts, 0.99),
            "last_seq": last_common,
        },
        "per_op_skew": per_op,
        "straggler": {
            "rank": straggler_rank,
            "score_ms": round(straggler_score, 6),
            "scores_ms": {str(r): round(v, 6)
                          for r, v in sorted(scores.items())},
            "ew_alpha": ew_alpha,
        },
        "skew_samples": samples,
        "desync": desync,
    }


def fold_window_records(records, skew_bounds=DEFAULT_SKEW_BUCKETS_MS,
                        ew_alpha=DEFAULT_EW_ALPHA):
    """Offline fold: merge the ``collective_window`` records of a
    telemetry JSONL set (possibly many windows per rank — records merge
    per rank by seq, later windows win) and run :func:`fold_windows`.
    Returns ``None`` when the set carries no window records."""
    merged = {}
    for rec in records:
        if rec.get("kind") != "collective_window":
            continue
        rank = int(rec.get("rank", 0))
        dst = merged.setdefault(rank, {})
        for r in rec.get("records", []):
            dst[int(r["seq"])] = r
    if not merged:
        return None
    views = [{"schema": SCHEMA_VERSION, "rank": rank,
              "records": [dst[s] for s in sorted(dst)]}
             for rank, dst in sorted(merged.items())]
    return fold_windows(views, skew_bounds=skew_bounds, ew_alpha=ew_alpha)


# --------------------------------------------------------------------------- #
# Device fold path — packed vectors gathered through the comm facade
# --------------------------------------------------------------------------- #
#
# Row layout per record (all values exact in float32):
#   [seq, fp_hi, fp_lo, dt_us_hi, dt_us_lo, bytes_hi, bytes_lo, exit_flag]
# fp (32-bit) splits 16/16; dt_us (enter - base, < 2**48 us) and bytes
# split 24/24 — every half stays under 2**24, the float32 exact-integer
# range.  Rows are padded with -1 up to ``width`` records per rank.

def pack_window(view, base_us, width):
    """→ (meta, vector): the fixed-width float row-matrix for one rank's
    view plus the host-side fingerprint dictionary (fp → structure) the
    unpack needs to restore record fields — same split as the metrics
    fold's schema/vector pair."""
    meta = {}
    vec = []
    recs = view.get("records", [])[-int(width):]
    for r in recs:
        fp = int(r["fp"])
        meta[str(fp)] = {"op": r["op"], "axis": r["axis"],
                         "dtype": r["dtype"],
                         "shape": [int(d) for d in r["shape"]]}
        dt = int(r["t_enter_us"]) - int(base_us)
        if not (0 <= dt < 1 << 48):
            raise ValueError(f"enter stamp out of pack range: dt_us={dt}")
        nbytes = min(int(r["bytes"]), (1 << 48) - 1)
        vec.extend([
            float(int(r["seq"])),
            float(fp >> 16), float(fp & 0xFFFF),
            float(dt >> 24), float(dt & 0xFFFFFF),
            float(nbytes >> 24), float(nbytes & 0xFFFFFF),
            1.0 if r.get("t_exit_us") is not None else 0.0,
        ])
    pad = int(width) - len(recs)
    vec.extend([-1.0] * (pad * _ROW_WIDTH))
    return meta, vec


def unpack_window(vector, meta, rank, base_us):
    """Inverse of :func:`pack_window` for one gathered row — rebuilds a
    fold-ready view (exit stamps collapse to a presence flag; the skew
    fold only reads enter stamps)."""
    records = []
    vec = [float(v) for v in vector]
    for i in range(0, len(vec), _ROW_WIDTH):
        row = vec[i:i + _ROW_WIDTH]
        if len(row) < _ROW_WIDTH or row[0] < 0:
            continue
        fp = (int(round(row[1])) << 16) | int(round(row[2]))
        dt = (int(round(row[3])) << 24) | int(round(row[4]))
        nbytes = (int(round(row[5])) << 24) | int(round(row[6]))
        m = meta.get(str(fp)) or {"op": "?", "axis": "", "dtype": "?",
                                  "shape": []}
        records.append({
            "seq": int(round(row[0])),
            "op": m["op"], "axis": m["axis"], "dtype": m["dtype"],
            "shape": list(m["shape"]),
            "bytes": nbytes,
            "fp": fp,
            "t_enter_us": int(base_us) + dt,
            "t_exit_us": 0 if row[7] > 0.5 else None,
        })
    return {"schema": SCHEMA_VERSION, "rank": int(rank), "records": records}


def gather_windows_over_mesh(views, width=None, axis="obs"):
    """Gather per-rank packed windows through the comm facade on a device
    mesh and unpack the rows back into fold-ready views.

    One ``all_gather`` program over the ``axis`` mesh axis (the same
    single-collective discipline as the metrics plane's
    ``fold_packed_over_mesh``), so the parity test proves the device
    path end to end: pack → device gather → unpack → :func:`fold_windows`
    equals the pure host fold of the same views.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.comm import comm as C

    views = list(views)
    if width is None:
        width = max((len(v.get("records", [])) for v in views), default=1)
    enters = [int(r["t_enter_us"]) for v in views
              for r in v.get("records", [])]
    base_us = min(enters) if enters else 0
    metas, vectors, ranks = [], [], []
    for i, v in enumerate(views):
        meta, vec = pack_window(v, base_us, width)
        metas.append(meta)
        vectors.append(vec)
        ranks.append(int(v.get("rank", i)))

    stacked = np.asarray(vectors, dtype=np.float32)
    r, n = stacked.shape
    devices = jax.devices()[:r]
    if len(devices) < r:
        raise ValueError(f"gather needs >= {r} devices, have {len(devices)}")
    mesh = Mesh(np.array(devices), (axis,))

    def _gather(block):          # [1, N] local shard = one rank's vector
        return C.all_gather(block[0], group=axis, axis=0, tiled=False)[None]

    from jax.experimental.shard_map import shard_map
    arr = jax.device_put(stacked, NamedSharding(mesh, P(axis, None)))
    gathered = jax.jit(shard_map(_gather, mesh=mesh, in_specs=P(axis, None),
                                 out_specs=P(axis, None)))(arr)
    # every shard holds the full [R, N] gather; read rank 0's copy
    rows = np.asarray(gathered.addressable_shards[0].data)[0]
    return [unpack_window(rows[i], metas[i], ranks[i], base_us)
            for i in range(r)]


# --------------------------------------------------------------------------- #
# Registry feed (shared by the live MetricsSink handler and offline replay)
# --------------------------------------------------------------------------- #

def feed_registry(registry, health):
    """Publish one fold verdict onto a MetricsRegistry: incremental skew
    observations (``skew_samples`` only — the fold already deduplicates
    against the previous window via ``new_after``), straggler gauges, and
    the per-op staged counts.  The ``dstpu_collective_*`` Prometheus
    series render straight off these."""
    skew = health.get("skew") or {}
    bounds = tuple(skew.get("bounds") or DEFAULT_SKEW_BUCKETS_MS)
    hist = registry.histogram("collective_skew_ms", bounds=bounds,
                              help="first-vs-last rank arrival per "
                                   "collective seq")
    for s in health.get("skew_samples") or []:
        v = s.get("skew_ms")
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            hist.observe(float(v))
            op = str(s.get("op", "?"))
            registry.histogram("collective_skew_ms", {"op": op},
                               bounds=bounds).observe(float(v))
    strag = health.get("straggler") or {}
    for rank, score in (strag.get("scores_ms") or {}).items():
        registry.gauge("collective_straggler_score_ms",
                       {"rank": str(rank)},
                       help="EW arrival-offset score per rank").set(
            float(score))
    if strag.get("rank") is not None:
        registry.gauge("collective_straggler_rank",
                       help="rank with the worst EW straggler score").set(
            float(strag["rank"]))
    registry.gauge("collective_common_seqs",
                   help="seqs present on every rank in the last fold").set(
        float(health.get("common_seqs", 0)))
    desync = health.get("desync") or {}
    if desync.get("detected"):
        registry.gauge("collective_desync_first_seq").set(
            float(desync.get("first_seq", 0)))
