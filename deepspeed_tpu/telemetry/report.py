"""Fold a telemetry JSONL run into a BENCH_*.json-shaped summary.

The fold logic lives here (importable by tests); ``tools/telemetry_report.py``
is a thin CLI over :func:`fold_run`.  Output mirrors the repo's
``BENCH_DETAIL_*.json`` convention: a dict of named entries, each with
``metric``/``value``/``unit`` plus supporting scalars.

Robust-statistics note: steady-state rates use :func:`trim_mean` from
``utils/timer.py`` (drop the top/bottom tail) so compile steps and stragglers
don't skew the headline number.
"""

import json
from typing import Any, Dict, List, Optional

from deepspeed_tpu.telemetry import events
from deepspeed_tpu.utils.timer import trim_mean


class SchemaError(ValueError):
    """JSONL file is missing/has an incompatible schema header."""


def load_records(path: str, strict_schema: bool = True) -> List[Dict[str, Any]]:
    """Parse a telemetry JSONL file, validating the schema version."""
    records = []
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise SchemaError(f"{path}:{lineno}: not JSON: {e}") from e
            records.append(rec)
    if strict_schema:
        versions = {r.get("schema") for r in records if "schema" in r}
        bad = versions - {events.SCHEMA_VERSION}
        if bad:
            raise SchemaError(
                f"{path}: schema version(s) {sorted(bad)} not supported "
                f"(this reader understands {events.SCHEMA_VERSION})")
    return records


def _steps(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [r for r in records if r.get("kind") == events.STEP]


def _vals(recs: List[Dict[str, Any]], field: str) -> List[float]:
    return [float(r[field]) for r in recs
            if isinstance(r.get(field), (int, float))
            and not isinstance(r.get(field), bool)]


def _robust(vals: List[float], trim: float = 0.1) -> Optional[float]:
    if not vals:
        return None
    return trim_mean(vals, trim)


def fold_run(records: List[Dict[str, Any]], label: str = "run",
             skip_steps: int = 1, trim: float = 0.1) -> Dict[str, Any]:
    """Collapse a record stream into a BENCH-shaped summary dict.

    ``skip_steps`` drops the first N step records (compile/warm-up) before
    computing steady-state rates; ``trim`` is the two-sided trim fraction.
    """
    steps = _steps(records)
    steady = steps[skip_steps:] if len(steps) > skip_steps else steps
    out: Dict[str, Any] = {}

    if steps:
        sps = _robust(_vals(steady, "samples_per_sec"), trim)
        step_ms = _robust(_vals(steady, "step_time_ms"), trim)
        losses = _vals(steps, "loss")
        entry: Dict[str, Any] = {
            "metric": f"{label} steady-state throughput "
                      f"({len(steps)} steps, {skip_steps} warm-up dropped)",
            "value": round(sps, 4) if sps is not None else None,
            "unit": "samples/sec",
            "steps": len(steps),
            "step_time_ms": round(step_ms, 4) if step_ms is not None else None,
        }
        if losses:
            entry["loss"] = round(losses[-1], 6)
            entry["loss_first"] = round(losses[0], 6)
        lrs = _vals(steps, "lr")
        if lrs:
            entry["lr_last"] = lrs[-1]
        tflops = _robust(_vals(steady, "tflops_per_chip"), trim)
        if tflops is not None:
            entry["tflops_per_chip"] = round(tflops, 4)
        out["train"] = entry

        comm = sum(_vals(steps, "comm_bytes"))
        peak = max(_vals(steps, "device_peak_bytes") or [0.0])
        out["resources"] = {
            "metric": f"{label} comm volume + device memory watermark",
            "value": round(comm / 1e6, 4),
            "unit": "MB (total collective bytes, trace-time accounting)",
            "device_peak_bytes": int(peak),
            "comm_bytes_total": int(comm),
        }

    infer = [r for r in records if r.get("kind") == events.INFERENCE]
    if infer:
        lat = _robust(_vals(infer, "latency_ms"), trim)
        tps = _robust(_vals(infer, "tokens_per_sec"), trim)
        out["inference"] = {
            "metric": f"{label} serving latency ({len(infer)} requests)",
            "value": round(lat, 4) if lat is not None else None,
            "unit": "ms/request",
            "tokens_per_sec": round(tps, 4) if tps is not None else None,
            "requests": len(infer),
        }

    pipe = [r for r in records if r.get("kind") == events.PIPE]
    if pipe:
        bf = _vals(pipe, "bubble_fraction")
        out["pipeline"] = {
            "metric": f"{label} pipeline bubble fraction "
                      f"({pipe[-1].get('schedule', '?')})",
            "value": round(bf[-1], 6) if bf else None,
            "unit": "fraction of schedule ticks idle",
            "stages": pipe[-1].get("stages"),
            "micro_batches": pipe[-1].get("micro_batches"),
        }

    moe = [r for r in records if r.get("kind") == events.MOE]
    if moe:
        drops = _vals(moe, "drop_fraction")
        out["moe"] = {
            "metric": f"{label} MoE token drop fraction ({len(moe)} gauges)",
            "value": round(_robust(drops) or 0.0, 6),
            "unit": "fraction of routed tokens dropped",
            "drop_fraction_max": round(max(drops), 6) if drops else None,
        }

    comms = [r for r in records if r.get("kind") == events.COMM_SUMMARY]
    if comms:
        last = comms[-1]
        out["comms"] = {
            "metric": f"{label} collective traffic by op",
            "value": round(float(last.get("total_bytes", 0)) / 1e6, 4),
            "unit": "MB",
            "ops": last.get("ops"),
        }

    return out


def fold_file(path: str, label: str = "run", skip_steps: int = 1,
              trim: float = 0.1) -> Dict[str, Any]:
    return fold_run(load_records(path), label=label,
                    skip_steps=skip_steps, trim=trim)
