"""Flight recorder — crash-safe post-mortem dumps for hangs and kills.

When the watchdog fires (or a SIGTERM/SIGABRT lands), this module writes
everything needed to reconstruct "what was every thread doing, and what
was the last telemetry the run produced" to an append-only JSONL file:

* the telemetry ring buffer (already-flushed, host-side records),
* the hub's *pending* records with device arrays replaced by aval
  placeholders — **never forced**: forcing an in-flight ``jax.Array``
  blocks on the device, i.e. on the very hang being diagnosed,
* all currently-open tracer spans plus a tail of completed ones,
* a Python stack for every live thread (``sys._current_frames``).

Crash-safety: the file is opened in append mode, every record is written
as one line and flushed immediately, and the file is fsync'd at the end
— a SIGKILL halfway through still leaves a parseable prefix.  Timing
uses ``time.monotonic_ns`` only (see ``tools/check_monotonic.py``).
"""

import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

_mono_ns = time.monotonic_ns

DUMP_SCHEMA_VERSION = 1


def _hang_safe(value: Any) -> Any:
    """JSON-ready view of a value that must not block: jax.Arrays (and
    anything else exotic) become descriptive placeholders instead of
    being forced to host."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {str(k): _hang_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_hang_safe(v) for v in value]
    aval = getattr(value, "aval", None)
    if aval is not None:  # jax.Array / tracer: do NOT force it
        return f"<unforced {type(value).__name__} {aval}>"
    try:
        import numpy as np
        if isinstance(value, np.generic):
            return value.item()
        if isinstance(value, np.ndarray):
            return value.tolist() if value.size <= 16 else (
                f"<ndarray shape={value.shape} dtype={value.dtype}>")
    except Exception:
        pass
    return f"<{type(value).__name__}>"


def thread_stacks() -> List[Dict[str, Any]]:
    """One entry per live thread: name, ident, daemon flag, and the
    current Python stack (outermost frame first)."""
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        t = names.get(ident)
        out.append({
            "thread_id": ident,
            "name": t.name if t else "<unknown>",
            "daemon": bool(t.daemon) if t else None,
            "stack": [ln.rstrip("\n")
                      for ln in traceback.format_stack(frame)],
        })
    return out


class FlightRecorder:
    """Aggregates hub + tracer state into a post-mortem JSONL dump.

    ``dump(reason)`` is the watchdog's ``on_stall`` payload (via
    :meth:`on_stall`) and is safe to call from signal handlers and
    watchdog threads: no device sync, no allocation beyond the dump
    itself, best-effort on every sub-section.
    """

    def __init__(self, dump_dir: str, rank: int = 0, hub=None, tracer=None,
                 span_tail: int = 256, collective_monitor=None,
                 collective_tail: int = 64):
        self.dump_dir = dump_dir
        self.rank = int(rank)
        self.hub = hub
        self.tracer = tracer
        self.span_tail = int(span_tail)
        self.collective_monitor = collective_monitor
        self.collective_tail = int(collective_tail)
        self._seq = 0
        self._lock = threading.Lock()

    # adapter matching HangWatchdog's on_stall signature
    def on_stall(self, watchdog, stalled_for_s: float, what: str) -> str:
        what = what or "unknown"
        # signal-origin dumps are already fully qualified ("signal:15")
        reason = what if what.startswith("signal:") else f"stall:{what}"
        return self.dump(reason=reason, stalled_for_s=stalled_for_s)

    # -- section builders (each individually best-effort) --------------- #
    def _ring_records(self) -> List[Dict[str, Any]]:
        hub = self.hub
        if hub is None:
            return []
        ring = getattr(hub, "ring", None)   # hub's RingBufferSink, if any
        if ring is None:
            return []
        return [_hang_safe(r) for r in list(ring.records)]

    def _pending_records(self) -> List[Dict[str, Any]]:
        # Unflushed hub records may hold in-flight device values; keep
        # them unforced.
        hub = self.hub
        if hub is None:
            return []
        return [_hang_safe(r) for r in list(getattr(hub, "_pending", []))]

    def _collectives(self) -> Dict[str, Any]:
        # last-N ring records: a wedge dump names the stuck collective —
        # an open record (t_exit_us None) at the tail IS the wedge
        mon = self.collective_monitor
        if mon is None:
            return {"records": [], "seq": 0, "desync_count": 0}
        out = {
            "records": mon.last_records(self.collective_tail),
            "seq": mon.seq,
            "desync_count": mon.desync_count,
        }
        if mon.last_desync is not None:
            out["last_desync"] = _hang_safe(mon.last_desync)
        return out

    def _spans(self) -> Dict[str, Any]:
        tr = self.tracer
        if tr is None:
            return {"open": [], "recent": []}
        return {
            "open": [_hang_safe(r) for r in tr.open_spans()],
            "recent": [_hang_safe(r) for r in tr.snapshot(self.span_tail)],
        }

    def dump(self, reason: str = "manual", stalled_for_s: float = 0.0) -> str:
        """Write one dump (header + sections, one JSON object per line)
        and return its path.  Never raises."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        path = os.path.join(
            self.dump_dir, f"flight_rank{self.rank}_{seq}.jsonl")
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            f = open(path, "a")
        except OSError as e:
            logger.error(f"flight recorder: cannot open {path}: {e}")
            return path

        def emit(section: str, payload):
            try:
                rec = {"section": section, "payload": payload}
                f.write(json.dumps(rec, default=str) + "\n")
                f.flush()
            except Exception as e:
                logger.error(f"flight recorder: section {section} failed: {e}")

        try:
            emit("header", {
                "schema_version": DUMP_SCHEMA_VERSION,
                "rank": self.rank,
                "pid": os.getpid(),
                "reason": reason,
                "stalled_for_s": stalled_for_s,
                "mono_ns": _mono_ns(),
            })
            emit("ring_buffer", self._ring_records())
            emit("pending_records", self._pending_records())
            spans = self._spans()
            emit("open_spans", spans["open"])
            emit("recent_spans", spans["recent"])
            emit("thread_stacks", thread_stacks())
            emit("collectives", self._collectives())
            emit("end", {"complete": True})
        finally:
            try:
                f.flush()
                os.fsync(f.fileno())
            except OSError:
                pass
            f.close()
        logger.error(f"flight recorder: dumped state ({reason}) -> {path}")
        return path


def read_dump(path: str) -> Dict[str, List[Any]]:
    """Parse a dump back into ``{section: [payloads...]}`` — tolerant of
    a truncated final line (the SIGKILL case)."""
    sections: Dict[str, List[Any]] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                break  # truncated tail — keep what we have
            sections.setdefault(rec.get("section", "?"), []).append(
                rec.get("payload"))
    return sections
