"""Hang watchdog — a monotonic heartbeat with a flight-recorder trigger.

Distributed hangs are the worst failure mode a collective-heavy runtime
has: one rank blocks in an all-reduce and every other rank blocks with
it, forever, with nothing in the logs.  The watchdog turns "forever"
into a bounded wait: the engine arms a heartbeat at step/collective
granularity (each tracer span pets it via the tracer's ``heartbeat``
hook), and if no beat lands for ``timeout_s`` the watchdog fires its
``on_stall`` callback — in production the
:class:`~deepspeed_tpu.telemetry.flight_recorder.FlightRecorder` dump —
exactly once per stall.

Clock discipline: everything is ``time.monotonic_ns`` (NTP slews and
wall-clock jumps must not fake or mask a stall); this file is policed by
``tools/check_monotonic.py``.

Signal path: ``install_signal_handlers()`` chains onto SIGTERM/SIGABRT
so that a preemption or libc abort also produces a dump before the
previous handler (or the default action) runs.

Testability: the poll loop is a thin wrapper around the pure
``check(now_ns)`` method; tests drive ``check`` with a fake clock and
never need a real 120 s stall.
"""

import faulthandler  # noqa: F401  (re-exported convenience for dumps to fd)
import os
import signal
import threading
import time
from typing import Callable, Optional

from deepspeed_tpu.utils.logging import logger

_mono_ns = time.monotonic_ns


class HangWatchdog:
    """Heartbeat monitor.  ``arm(what)`` starts/renames the watch,
    ``pet()`` records liveness, ``disarm()`` pauses it (e.g. between
    train_batch calls, where blocking on user code is legitimate).

    ``on_stall(watchdog, stalled_for_s, what)`` fires at most once per
    armed period; re-arming or petting after a fire re-enables it.
    """

    def __init__(self, timeout_s: float = 120.0,
                 on_stall: Optional[Callable] = None,
                 poll_s: float = 0.0,
                 clock: Optional[Callable[[], int]] = None):
        self.timeout_ns = int(float(timeout_s) * 1e9)
        self.on_stall = on_stall
        # default poll: 1/4 of the timeout, clamped to [0.5s, 10s]
        self.poll_s = float(poll_s) if poll_s and poll_s > 0 else (
            min(10.0, max(0.5, float(timeout_s) / 4.0)))
        self._clock = clock or _mono_ns
        self._lock = threading.Lock()
        self._armed = False
        self._fired = False
        self._last_beat_ns = self._clock()
        self._what = ""
        self.stall_count = 0
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._prev_handlers = {}
        #: optional zero-arg callable -> str, logged when a stall fires —
        #: wired to CollectiveMonitor.wedged_summary so the stall log
        #: names the collective the run is stuck in
        self.context_fn: Optional[Callable[[], str]] = None

    # -- heartbeat API (hot path: one clock read under a lock) ---------- #
    def arm(self, what: str = ""):
        """Begin (or re-scope) a watched period, resetting the beat."""
        with self._lock:
            self._armed = True
            self._fired = False
            self._what = what
            self._last_beat_ns = self._clock()

    def pet(self):
        """Record liveness; wired into ``Tracer.heartbeat`` so every
        phase/collective span beats automatically."""
        with self._lock:
            self._last_beat_ns = self._clock()
            self._fired = False

    def disarm(self):
        with self._lock:
            self._armed = False

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._armed

    def heartbeat_age_s(self) -> float:
        """Seconds since the last beat — the ``watchdog_heartbeat_age_s``
        gauge behind ``/healthz``: readable by the obs-server scrape
        thread without perturbing the beat itself."""
        with self._lock:
            last = self._last_beat_ns
        return max(0.0, (self._clock() - last) / 1e9)

    # -- stall detection ------------------------------------------------ #
    def check(self, now_ns: Optional[int] = None) -> bool:
        """Evaluate the stall condition once; returns True iff this call
        fired ``on_stall``.  Pure given ``now_ns`` — the unit tests call
        this directly with a synthetic clock."""
        now = self._clock() if now_ns is None else int(now_ns)
        with self._lock:
            if not self._armed or self._fired:
                return False
            stalled_ns = now - self._last_beat_ns
            if stalled_ns < self.timeout_ns:
                return False
            self._fired = True
            self.stall_count += 1
            what = self._what
        stalled_s = stalled_ns / 1e9
        logger.error(
            f"watchdog: no heartbeat for {stalled_s:.1f}s "
            f"(threshold {self.timeout_ns / 1e9:.1f}s) during '{what}'")
        if self.context_fn is not None:
            try:
                logger.error(f"watchdog: {self.context_fn()}")
            except Exception:
                pass
        if self.on_stall is not None:
            try:
                self.on_stall(self, stalled_s, what)
            except Exception as e:  # a broken dump must not kill the run
                logger.error(f"watchdog: on_stall callback failed: {e}")
        return True

    # -- background poller ---------------------------------------------- #
    def start(self):
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._poll_loop, name="ds-tpu-watchdog", daemon=True)
        self._thread.start()

    def _poll_loop(self):
        while not self._stop_evt.wait(self.poll_s):
            self.check()

    def stop(self):
        self._stop_evt.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2 * self.poll_s + 1.0)
        self.restore_signal_handlers()

    # -- signal chaining ------------------------------------------------- #
    def install_signal_handlers(self, signals=(signal.SIGTERM, signal.SIGABRT)):
        """Dump on termination signals, then chain to the previous
        handler (re-raising under SIG_DFL so the default action still
        happens).  Only callable from the main thread; a no-op failure
        elsewhere is logged, not raised."""
        for sig in signals:
            try:
                self._prev_handlers[sig] = signal.signal(
                    sig, self._handle_signal)
            except (ValueError, OSError) as e:
                logger.warning(
                    f"watchdog: cannot install handler for {sig}: {e}")

    def _handle_signal(self, signum, frame):
        logger.error(f"watchdog: received signal {signum}; dumping state")
        if self.on_stall is not None:
            try:
                self.on_stall(self, 0.0, f"signal:{signum}")
            except Exception as e:
                logger.error(f"watchdog: signal dump failed: {e}")
        prev = self._prev_handlers.get(signum, signal.SIG_DFL)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # restore + re-raise so the default action (terminate) runs
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
        # SIG_IGN: swallow

    def restore_signal_handlers(self):
        for sig, prev in list(self._prev_handlers.items()):
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
            self._prev_handlers.pop(sig, None)
