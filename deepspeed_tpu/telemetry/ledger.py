"""Goodput & efficiency attribution ledger.

``GoodputLedger`` attributes every wall-clock second of a train or serve
run into exhaustive, mutually exclusive categories, with a conservation
invariant: the categories always sum to the measured wall time (the
residual category ``idle_other`` absorbs whatever the instrumented seams
did not claim, clamped at zero).  The categories:

==================== ===================================================
``productive``       step compute that advanced training/serving state
``exposed_comm``     collective time not hidden behind compute (fed from
                     trace-derived measurements when available)
``offload_stall``    blocking beyond-HBM staging waits inside a step
                     (``runtime/engine._emit_offload_telemetry`` deltas;
                     serving restage waits land here too)
``ckpt_stall``       blocking checkpoint save/finalize time
                     (``save_checkpoint`` + finalizer joins)
``rollback_recompute`` steps replayed between a rollback target and the
                     previously reached step (``auto_rollback``)
``quarantine_skip``  step share burned running no-op micro-steps over
                     quarantined batches
``downtime``         preemption/restart gap (elastic-agent ``downtime``
                     events; in-process via :meth:`note_downtime`)
``hang``             watchdog-detected stall time (per-step wall beyond
                     the watchdog threshold)
``comm_recovery``    coordinated collective-recovery time: detect →
                     abort barrier → retry/shrink → resume
                     (``comm/recovery.py`` books it per incident)
``idle_other``       residual: wall - sum(everything above), >= 0
==================== ===================================================

Derived top-line gauges: ``goodput_frac`` (productive / wall), ``mfu``
(productive FLOPs over peak, when FLOPs inputs are wired), and
``lost_work_steps`` (steps whose results a rollback discarded).

The attribution model is mark-based: the ledger keeps a monotonic
``_last_mark``; :meth:`on_step` (the hot path — zero-sync, host floats
only) attributes the span since the last mark, splitting out hang
excess, offload stall, exposed comm, and quarantine share, and crediting
the remainder to ``productive`` — or to ``rollback_recompute`` while the
run is replaying steps at or below the last rollback's ``from_step``.
Out-of-step stalls (:meth:`note_ckpt_stall`, :meth:`note_downtime`,
:meth:`note_quarantine_skip` with a duration) advance the mark by the
same amount so the next step's span never double-counts them —
conservation holds by construction, and :meth:`conservation` proves it.

Cross-rank: when constructed with a ``MetricsRegistry`` the ledger
mirrors each category into ``goodput_seconds_total{category=...}``
counters (SUM-folded by ``pack_snapshot``/``fold_packed_over_mesh``) and
exposes the derived gauges, so ``render_prometheus`` publishes the
``dstpu_goodput_*`` series and ``/goodput`` on the obs server serves
:meth:`snapshot` live.

Offline: :func:`fold_goodput` folds the ``goodput``/``downtime`` records
of a telemetry JSONL set (one cumulative snapshot per attempt — restarts
are separate attempts keyed by ``run_id``) into the same shape, which is
what ``tools/goodput_report.py`` gates and what the per-run
``EFFICIENCY.json`` artifact (:meth:`write_efficiency_json`) snapshots —
the single scoring input the ROADMAP item-2 autotuner consumes.

Standard library only — the module is loaded by file path from the
no-jax report CLIs.
"""

import json
import os
import time

SCHEMA_VERSION = 1

#: exhaustive, mutually exclusive wall-time categories (seconds)
CATEGORIES = (
    "productive",
    "exposed_comm",
    "offload_stall",
    "ckpt_stall",
    "rollback_recompute",
    "quarantine_skip",
    "downtime",
    "hang",
    "comm_recovery",
    "idle_other",
)

#: accumulating categories (everything except the derived residual)
_ACCUMULATED = tuple(c for c in CATEGORIES if c != "idle_other")

#: default per-SLO-class TTFT bounds (ms) for serve goodput; a request's
#: tokens count as delivered-within-bound when its TTFT met its class
DEFAULT_SLO_TTFT_BOUNDS_MS = {
    "interactive": 500.0,
    "standard": 2000.0,
    "batch": 30000.0,
}


class GoodputLedger:
    """Attribute every second of a run into the category taxonomy.

    Parameters
    ----------
    mode : ``"train"`` or ``"serve"`` — stamped on snapshots.
    registry : optional ``MetricsRegistry``; when given the categories
        and gauges are mirrored into ``goodput_*`` metrics.
    clock : monotonic clock (injectable for tests).
    hang_threshold_s : per-step wall beyond this is attributed to
        ``hang`` (wire to the watchdog timeout; 0 disables).
    flops_per_step : number or zero-arg callable -> model FLOPs per
        optimizer step (may return None early in a run).
    peak_flops_per_s : peak sustained FLOPs/s of one chip; with
        ``flops_per_step`` this enables the ``mfu`` gauge.
    run_id : attempt identity carried on every snapshot so the offline
        fold can group records per process incarnation; defaults to
        ``"<pid>-<start-ms>"``.
    """

    def __init__(self, mode="train", registry=None, clock=time.monotonic,
                 hang_threshold_s=0.0, flops_per_step=None,
                 peak_flops_per_s=None, run_id=None):
        self.mode = mode
        self._clock = clock
        self.hang_threshold_s = float(hang_threshold_s)
        self.flops_per_step = flops_per_step
        self.peak_flops_per_s = peak_flops_per_s
        self._start = clock()
        self.start_unix = time.time()
        self.run_id = run_id or "%d-%d" % (os.getpid(),
                                           int(self.start_unix * 1000.0))
        self._last_mark = self._start
        self._cats = {c: 0.0 for c in _ACCUMULATED}
        self.steps = 0
        self.productive_steps = 0
        self.lost_work_steps = 0
        self.rollbacks = 0
        self.quarantine_skips = 0
        self.replay_until = -1          # steps <= this are recompute
        # straggler share: the part of exposed_comm attributable to
        # cross-rank arrival skew (a sub-accounting, NOT a category —
        # conservation is untouched; fed by the collective-health fold)
        self.exposed_comm_straggler_s = 0.0
        #: per-SLO-class TTFT bounds (ms); engines may override per config
        self.slo_ttft_bounds_ms = dict(DEFAULT_SLO_TTFT_BOUNDS_MS)
        self._serve = {}                # slo -> token accounting
        self._c_cat = None
        if registry is not None:
            self._c_cat = {
                c: registry.counter(
                    "goodput_seconds_total", labels={"category": c},
                    help="wall-clock seconds attributed per category")
                for c in _ACCUMULATED}
            self._c_steps = registry.counter(
                "goodput_steps_total", help="optimizer/serve steps accounted")
            registry.gauge("goodput_frac", fn=self._frac,
                           help="productive seconds / wall seconds")
            registry.gauge("goodput_mfu", fn=self._mfu_or_zero,
                           help="model FLOPs utilization over productive wall")
            registry.gauge("goodput_lost_work_steps",
                           fn=lambda: float(self.lost_work_steps),
                           help="steps a rollback discarded")
            registry.gauge("goodput_wall_seconds", fn=self._wall,
                           help="ledger wall clock (this attempt)")
            registry.gauge("goodput_idle_other_seconds", fn=self._idle,
                           help="wall seconds no instrumented seam claimed")
            registry.gauge("goodput_exposed_comm_straggler_frac",
                           fn=self._straggler_frac,
                           help="share of exposed_comm attributable to "
                                "cross-rank arrival skew")

    # ---- hot path ------------------------------------------------------ #

    def _acc(self, category, seconds):
        """Attribute ``seconds`` to one category (dict + mirror counter)."""
        if seconds <= 0.0:
            return
        self._cats[category] += seconds
        if self._c_cat is not None:
            self._c_cat[category].inc(seconds)

    def on_step(self, step, offload_wait_s=0.0, exposed_comm_s=0.0,
                quarantine_frac=0.0, now=None):
        """Attribute the span since the last mark to this step.

        Called once per optimizer step (train) or engine step (serve)
        from the step boundary.  ``offload_wait_s`` / ``exposed_comm_s``
        are the measured stall components of the span (clamped to it);
        ``quarantine_frac`` is the fraction of the step's micro-batches
        skipped over quarantined data.  Steps at or below the last
        rollback's origin are attributed to ``rollback_recompute``.
        """
        if now is None:
            now = self._clock()
        dt = now - self._last_mark
        self._last_mark = now
        if dt < 0.0:
            dt = 0.0
        self.steps += 1
        if self._c_cat is not None:
            self._c_steps.inc(1.0)
        rem = dt
        if self.hang_threshold_s > 0.0 and dt > self.hang_threshold_s:
            hang = dt - self.hang_threshold_s
            self._acc("hang", hang)
            rem -= hang
        stall = min(max(offload_wait_s, 0.0), rem)
        self._acc("offload_stall", stall)
        rem -= stall
        comm = min(max(exposed_comm_s, 0.0), rem)
        self._acc("exposed_comm", comm)
        rem -= comm
        if quarantine_frac > 0.0:
            skip = rem * min(quarantine_frac, 1.0)
            self._acc("quarantine_skip", skip)
            rem -= skip
        if step <= self.replay_until:
            self._acc("rollback_recompute", rem)
        else:
            self._acc("productive", rem)
            self.productive_steps += 1

    # ---- out-of-step seams --------------------------------------------- #

    def mark(self, now=None):
        """Advance the mark without attributing the skipped span (it
        falls to ``idle_other``) — e.g. past setup/compile phases."""
        self._last_mark = now if now is not None else self._clock()

    def _note(self, category, seconds):
        """Attribute an out-of-step stall and advance the mark past it so
        the next step's span does not count it again."""
        s = max(float(seconds), 0.0)
        self._acc(category, s)
        now = self._clock()
        self._last_mark = min(self._last_mark + s, now)

    def note_ckpt_stall(self, seconds):
        """Blocking checkpoint save/finalize time just spent."""
        self._note("ckpt_stall", seconds)

    def note_downtime(self, seconds):
        """Preemption/restart downtime observed in-process (cross-process
        downtime arrives via elastic-agent ``downtime`` events and is
        added by the offline fold)."""
        self._note("downtime", seconds)

    def note_hang(self, seconds):
        """Watchdog-measured stall time (explicit feed)."""
        self._note("hang", seconds)

    def note_comm_recovery(self, seconds):
        """Coordinated collective-recovery time just spent (deadline
        expiry → abort barrier → ladder rung → resume).  Booked by the
        recovery manager per incident; mark-advancing like every
        out-of-step stall, so conservation holds by construction."""
        self._note("comm_recovery", seconds)

    def note_straggler_share(self, seconds):
        """The collective-health fold measured ``seconds`` of cross-rank
        arrival skew: book it as the straggler share of ``exposed_comm``.
        Sub-accounting only — it does not move the mark or any category,
        it explains how much of the already-attributed exposed_comm a
        straggling rank caused."""
        if seconds > 0.0:
            self.exposed_comm_straggler_s += float(seconds)

    def note_quarantine_skip(self, seconds=0.0):
        """A quarantined batch was skipped; ``seconds`` when measured
        out-of-step (in-step share is fed via ``quarantine_frac``)."""
        self.quarantine_skips += 1
        if seconds > 0.0:
            self._note("quarantine_skip", seconds)

    def on_rollback(self, from_step, to_step):
        """A rollback rewound ``from_step`` -> ``to_step``: the steps in
        between are lost work, and their replay is recompute."""
        lost = max(int(from_step) - int(to_step), 0)
        self.lost_work_steps += lost
        self.rollbacks += 1
        if from_step > self.replay_until:
            self.replay_until = int(from_step)

    # ---- serve goodput -------------------------------------------------- #

    def note_serve_request(self, slo, ttft_ms, new_tokens):
        """A request finished: its tokens count as delivered within bound
        when TTFT met the class bound, late otherwise."""
        s = self._serve.setdefault(str(slo), {
            "finished": 0, "tokens_in_bound": 0, "tokens_late": 0,
            "wasted_prefill_tokens": 0})
        s["finished"] += 1
        bound = self.slo_ttft_bounds_ms.get(
            str(slo), DEFAULT_SLO_TTFT_BOUNDS_MS["standard"])
        if ttft_ms is not None and float(ttft_ms) <= bound:
            s["tokens_in_bound"] += int(new_tokens)
        else:
            s["tokens_late"] += int(new_tokens)

    def note_wasted_prefill(self, slo, tokens):
        """An eviction discarded KV that must be re-prefilled: ``tokens``
        of prefill compute were wasted."""
        if tokens <= 0:
            return
        s = self._serve.setdefault(str(slo), {
            "finished": 0, "tokens_in_bound": 0, "tokens_late": 0,
            "wasted_prefill_tokens": 0})
        s["wasted_prefill_tokens"] += int(tokens)

    def note_serve_expired(self, slo, tokens_wasted=0):
        """A request's deadline passed before it finished: count the
        cancellation and book whatever prefill it had accumulated as
        wasted compute."""
        s = self._serve.setdefault(str(slo), {
            "finished": 0, "tokens_in_bound": 0, "tokens_late": 0,
            "wasted_prefill_tokens": 0})
        s["expired"] = s.get("expired", 0) + 1
        if tokens_wasted > 0:
            s["wasted_prefill_tokens"] += int(tokens_wasted)

    # ---- derived views -------------------------------------------------- #

    def _wall(self, now=None):
        return (now if now is not None else self._clock()) - self._start

    def _idle(self, now=None):
        wall = self._wall(now)
        return max(0.0, wall - sum(self._cats.values()))

    def _frac(self, now=None):
        wall = self._wall(now)
        return self._cats["productive"] / wall if wall > 0.0 else 0.0

    def _mfu(self, now=None):
        peak = self.peak_flops_per_s
        flops = self.flops_per_step
        if callable(flops):
            try:
                flops = flops()
            except Exception:
                flops = None
        if not peak or not flops:
            return None
        wall = self._wall(now)
        if wall <= 0.0:
            return None
        return (float(flops) * self.productive_steps) / (wall * float(peak))

    def _mfu_or_zero(self):
        return self._mfu() or 0.0

    def _straggler_frac(self):
        comm = self._cats["exposed_comm"]
        if comm <= 0.0:
            return 0.0
        return min(self.exposed_comm_straggler_s / comm, 1.0)

    def snapshot(self, now=None):
        """Cumulative attribution snapshot (conserves by construction)."""
        if now is None:
            now = self._clock()
        wall = self._wall(now)
        cats = {c: self._cats[c] for c in _ACCUMULATED}
        cats["idle_other"] = max(0.0, wall - sum(cats.values()))
        snap = {
            "schema": SCHEMA_VERSION,
            "mode": self.mode,
            "run_id": self.run_id,
            "start_unix": self.start_unix,
            "wall_s": wall,
            "categories": cats,
            "steps": self.steps,
            "productive_steps": self.productive_steps,
            "lost_work_steps": self.lost_work_steps,
            "rollbacks": self.rollbacks,
            "quarantine_skips": self.quarantine_skips,
            "goodput_frac": self._frac(now),
            "mfu": self._mfu(now),
            "exposed_comm_straggler_s": self.exposed_comm_straggler_s,
            "exposed_comm_straggler_frac": self._straggler_frac(),
        }
        if self._serve:
            snap["serve"] = serve_summary(self._serve)
        snap["conservation"] = conservation(snap)
        return snap

    def conservation(self, snap=None, eps=0.01):
        """Check categories sum to wall within ``eps`` (fractional)."""
        return conservation(snap or self.snapshot(), eps=eps)

    def write_efficiency_json(self, path, snap=None, extra=None):
        """Write the per-run ``EFFICIENCY.json`` artifact — the scoring
        input for the autotuner (ROADMAP item 2).  Atomic replace."""
        doc = {
            "schema": SCHEMA_VERSION,
            "generated_unix": time.time(),
            "source": "live",
            "ledger": snap if snap is not None else self.snapshot(),
        }
        if extra:
            doc.update(extra)
        tmp = "%s.tmp.%d" % (path, os.getpid())
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return doc


# ---- pure folds (shared with tools/goodput_report.py) ------------------- #

def serve_summary(by_slo):
    """Roll per-SLO token accounting up into the serve goodput view."""
    total_in = sum(s["tokens_in_bound"] for s in by_slo.values())
    total_late = sum(s["tokens_late"] for s in by_slo.values())
    total_waste = sum(s["wasted_prefill_tokens"] for s in by_slo.values())
    total_expired = sum(s.get("expired", 0) for s in by_slo.values())
    denom = total_in + total_late + total_waste
    out = {
        "by_slo": {k: dict(v) for k, v in sorted(by_slo.items())},
        "tokens_in_bound": total_in,
        "tokens_late": total_late,
        "wasted_prefill_tokens": total_waste,
        "expired": total_expired,
        "goodput_tokens_frac": (total_in / denom) if denom else None,
    }
    return out


def conservation(snap, eps=0.01):
    """Conservation verdict for one snapshot (or fold) dict: do the
    categories sum to the wall time within ``eps`` of it?"""
    wall = float(snap.get("wall_s", 0.0))
    total = sum(float(v) for v in snap.get("categories", {}).values())
    abs_err = abs(total - wall)
    frac_err = (abs_err / wall) if wall > 0.0 else 0.0
    return {
        "sum_s": total,
        "wall_s": wall,
        "abs_err_s": abs_err,
        "frac_err": frac_err,
        "eps": eps,
        "ok": frac_err <= eps,
    }


def _merge_serve(folded, serve):
    for slo, s in serve.get("by_slo", {}).items():
        dst = folded.setdefault(slo, {
            "finished": 0, "tokens_in_bound": 0, "tokens_late": 0,
            "wasted_prefill_tokens": 0, "expired": 0})
        for key in dst:
            dst[key] += int(s.get(key, 0))


def fold_goodput(records, eps=0.01):
    """Fold the ``goodput``/``downtime`` records of a telemetry JSONL set
    into one run-level report.

    Each process incarnation (attempt) emits cumulative ``goodput``
    snapshots under its own ``run_id`` — the last one per attempt wins.
    Elastic-agent ``downtime`` events measure the gaps BETWEEN attempts,
    so their seconds are added to both the ``downtime`` category and the
    total wall (conservation is preserved).  Returns None when the set
    carries no goodput records.
    """
    last_by_attempt = {}
    order = []
    downtime_s = 0.0
    downtime_events = 0
    for rec in records:
        kind = rec.get("kind")
        if kind == "goodput":
            rid = str(rec.get("run_id", "?"))
            if rid not in last_by_attempt:
                order.append(rid)
            last_by_attempt[rid] = rec
        elif kind == "downtime":
            try:
                downtime_s += float(rec.get("downtime_s", 0.0))
                downtime_events += 1
            except (TypeError, ValueError):
                pass
    if not last_by_attempt:
        return None

    cats = {c: 0.0 for c in CATEGORIES}
    wall = 0.0
    steps = productive_steps = lost = rollbacks = skips = 0
    serve_by_slo = {}
    mfu_vals = []
    mode = None
    straggler_s = 0.0
    for rid in order:
        snap = last_by_attempt[rid]
        wall += float(snap.get("wall_s", 0.0))
        straggler_s += float(snap.get("exposed_comm_straggler_s", 0.0))
        for c, v in snap.get("categories", {}).items():
            if c in cats:
                cats[c] += float(v)
        steps += int(snap.get("steps", 0))
        productive_steps += int(snap.get("productive_steps", 0))
        lost += int(snap.get("lost_work_steps", 0))
        rollbacks += int(snap.get("rollbacks", 0))
        skips += int(snap.get("quarantine_skips", 0))
        if snap.get("mfu") is not None:
            mfu_vals.append(float(snap["mfu"]))
        mode = snap.get("mode", mode)
        if snap.get("serve"):
            _merge_serve(serve_by_slo, snap["serve"])
    cats["downtime"] += downtime_s
    wall += downtime_s

    report = {
        "schema": SCHEMA_VERSION,
        "mode": mode or "train",
        "attempts": len(order),
        "run_ids": order,
        "wall_s": wall,
        "categories": cats,
        "steps": steps,
        "productive_steps": productive_steps,
        "lost_work_steps": lost,
        "rollbacks": rollbacks,
        "quarantine_skips": skips,
        "downtime_events": downtime_events,
        "downtime_event_s": downtime_s,
        "goodput_frac": (cats["productive"] / wall) if wall > 0.0 else 0.0,
        "mfu": (sum(mfu_vals) / len(mfu_vals)) if mfu_vals else None,
        "exposed_comm_straggler_s": straggler_s,
        "exposed_comm_straggler_frac": (
            min(straggler_s / cats["exposed_comm"], 1.0)
            if cats["exposed_comm"] > 0.0 else 0.0),
    }
    if serve_by_slo:
        report["serve"] = serve_summary(serve_by_slo)
    report["conservation"] = conservation(report, eps=eps)
    return report
