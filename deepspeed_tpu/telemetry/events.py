"""Telemetry record schema.

Every record the :class:`~deepspeed_tpu.telemetry.hub.TelemetryHub` emits is
a flat JSON-serializable dict with two reserved keys:

* ``kind`` — the record type (one of :data:`KINDS`);
* ``schema`` — the schema version (:data:`SCHEMA_VERSION`), stamped by the
  hub so a JSONL file is self-describing and ``tools/telemetry_report.py``
  can refuse files it does not understand.

``step`` records additionally guarantee :data:`STEP_REQUIRED_FIELDS` — the
contract the JSONL acceptance test and the report folder both rely on.
Values may be device arrays at emission time; the hub converts them to host
floats at drain boundaries (see the hub's windowed-drain discipline).
"""

from typing import Any, Dict

SCHEMA_VERSION = 1

# record kinds ------------------------------------------------------------- #
STEP = "step"                      # one optimizer step of a training engine
PIPE = "pipe"                      # pipeline schedule stats (bubble fraction)
INFERENCE = "inference_request"    # one generate()/forward() serving request
MOE = "moe_gauge"                  # expert-load / drop-fraction gauges
COMM_SUMMARY = "comm_summary"      # CommsLogger fold (op counts/bytes/bw)
FLOPS_BREAKDOWN = "flops_breakdown"  # one-shot per-module FLOPs cost table
WORKER_EXIT = "worker_exit"        # elastic-agent worker group exit/restart
CKPT_SAVED = "ckpt_saved"          # one durable (committed+verified) checkpoint
CKPT_RETRY = "ckpt_retry"          # transient storage error, save being retried
CKPT_ROLLBACK = "ckpt_rollback"    # corrupt/torn tag skipped at load
PREEMPTION = "preemption"          # preemption notice / final-checkpoint exit
ANOMALY = "anomaly"                # stability sentinel detection (cause code)
LR_BACKOFF = "lr_backoff"          # recovery ladder scaled the LR schedule
AUTO_ROLLBACK = "auto_rollback"    # ladder rolled back to a verified tag
BATCH_QUARANTINED = "batch_quarantined"  # fingerprint quarantined / skipped
EF_RESET = "ef_reset"              # compression error-feedback zeroed at load
SERVE_REQUEST = "serve_request"    # one completed ServingEngine request (TTFT)
SERVE_STEP = "serve_step"          # serving-loop gauges (queue depth, blocks)
SERVE_PREEMPT = "serve_preempt"    # SLO/arena preemption (blocks evicted)
SERVE_SHED = "serve_shed"          # admission-ladder rejection / rung change
SERVE_EXPIRED = "serve_expired"    # request deadline passed; cancelled
SERVE_INCIDENT = "serve_incident"  # wedged serve step -> in-process recovery
KV_SPILL = "kv_spill"              # preempted KV captured to host/NVMe tier
KV_RESTAGE = "kv_restage"          # spilled KV restored on re-admission
PREFIX_HIT = "prefix_hit"          # cached prompt blocks attached copy-free
PROGRAM_CACHE = "program_cache_evict"  # inference per-shape LRU cache eviction
OFFLOAD_STAGED = "offload_staged"  # per-step staging fold (bytes, ring hits)
OFFLOAD_WAIT = "offload_wait"      # blocking stall on a staged read/write
DOWNTIME = "downtime"              # elastic-agent worker_exit -> restart gap
GOODPUT = "goodput"                # cumulative GoodputLedger snapshot
COLLECTIVE_WINDOW = "collective_window"    # one rank's collective-ring window
COLLECTIVE_HEALTH = "collective_health"    # cross-rank skew/straggler fold
COLLECTIVE_DESYNC = "collective_desync"    # fingerprint divergence detected
SCHEMA = "schema"                  # JSONL header record (written by the sink)

KINDS = (STEP, PIPE, INFERENCE, MOE, COMM_SUMMARY, FLOPS_BREAKDOWN,
         WORKER_EXIT, CKPT_SAVED, CKPT_RETRY, CKPT_ROLLBACK, PREEMPTION,
         ANOMALY, LR_BACKOFF, AUTO_ROLLBACK, BATCH_QUARANTINED, EF_RESET,
         SERVE_REQUEST, SERVE_STEP, SERVE_PREEMPT, SERVE_SHED, SERVE_EXPIRED,
         SERVE_INCIDENT, KV_SPILL, KV_RESTAGE,
         PREFIX_HIT, PROGRAM_CACHE, OFFLOAD_STAGED, OFFLOAD_WAIT, DOWNTIME,
         GOODPUT, COLLECTIVE_WINDOW, COLLECTIVE_HEALTH, COLLECTIVE_DESYNC,
         SCHEMA)

# Every `step` record carries at least these keys once drained.
STEP_REQUIRED_FIELDS = (
    "step",
    "loss",
    "lr",
    "step_time_ms",
    "samples_per_sec",
    "comm_bytes",
    "device_peak_bytes",
)


def make_record(kind: str, payload: Dict[str, Any]) -> Dict[str, Any]:
    """Stamp ``kind``/``schema`` onto a payload (payload keys win nothing:
    the reserved keys are overwritten)."""
    rec = dict(payload)
    rec["kind"] = kind
    rec["schema"] = SCHEMA_VERSION
    return rec
