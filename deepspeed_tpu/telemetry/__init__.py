"""deepspeed_tpu.telemetry — structured step events, JSONL sink, windowed
XLA profiler capture, span tracing, and the hang-watchdog flight recorder.
See README.md § Telemetry / § Tracing for config keys and schemas."""

from deepspeed_tpu.telemetry import events
from deepspeed_tpu.telemetry.events import (SCHEMA_VERSION,
                                            STEP_REQUIRED_FIELDS, make_record)
from deepspeed_tpu.telemetry.flight_recorder import FlightRecorder, read_dump
from deepspeed_tpu.telemetry.hub import (JsonlSink, MonitorSink,
                                         RingBufferSink, TelemetryHub,
                                         TelemetrySink)
from deepspeed_tpu.telemetry.profiler import ProfilerWindow
from deepspeed_tpu.telemetry.tracing import (Tracer, get_global_tracer,
                                             maybe_span, set_global_tracer)
from deepspeed_tpu.telemetry.watchdog import HangWatchdog

__all__ = [
    "events",
    "SCHEMA_VERSION",
    "STEP_REQUIRED_FIELDS",
    "make_record",
    "TelemetryHub",
    "TelemetrySink",
    "JsonlSink",
    "RingBufferSink",
    "MonitorSink",
    "ProfilerWindow",
    "Tracer",
    "set_global_tracer",
    "get_global_tracer",
    "maybe_span",
    "HangWatchdog",
    "FlightRecorder",
    "read_dump",
]
