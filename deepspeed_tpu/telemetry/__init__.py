"""deepspeed_tpu.telemetry — structured step events, JSONL sink, windowed
XLA profiler capture.  See README.md § Telemetry for config keys and the
JSONL schema."""

from deepspeed_tpu.telemetry import events
from deepspeed_tpu.telemetry.events import (SCHEMA_VERSION,
                                            STEP_REQUIRED_FIELDS, make_record)
from deepspeed_tpu.telemetry.hub import (JsonlSink, MonitorSink,
                                         RingBufferSink, TelemetryHub,
                                         TelemetrySink)
from deepspeed_tpu.telemetry.profiler import ProfilerWindow

__all__ = [
    "events",
    "SCHEMA_VERSION",
    "STEP_REQUIRED_FIELDS",
    "make_record",
    "TelemetryHub",
    "TelemetrySink",
    "JsonlSink",
    "RingBufferSink",
    "MonitorSink",
    "ProfilerWindow",
]
