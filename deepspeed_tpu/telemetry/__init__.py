"""deepspeed_tpu.telemetry — structured step events, JSONL sink, windowed
XLA profiler capture, span tracing, the hang-watchdog flight recorder, and
the live observability plane (metrics registry, ops HTTP endpoints, SLO
burn-rate monitors).  See README.md § Observability for config keys,
schemas, and the scrape contract."""

from deepspeed_tpu.telemetry import events, stats
from deepspeed_tpu.telemetry.events import (SCHEMA_VERSION,
                                            STEP_REQUIRED_FIELDS, make_record)
from deepspeed_tpu.telemetry.flight_recorder import FlightRecorder, read_dump
from deepspeed_tpu.telemetry.hub import (JsonlSink, MonitorSink,
                                         RingBufferSink, TelemetryHub,
                                         TelemetrySink)
from deepspeed_tpu.telemetry.ledger import (CATEGORIES, GoodputLedger,
                                            fold_goodput)
from deepspeed_tpu.telemetry.metrics import (Counter, Gauge, Histogram,
                                             MetricsRegistry, MetricsSink,
                                             cross_rank_snapshot,
                                             merge_snapshots,
                                             render_prometheus)
from deepspeed_tpu.telemetry.obs_server import ObsServer, watchdog_health_check
from deepspeed_tpu.telemetry.profiler import ProfilerWindow
from deepspeed_tpu.telemetry.slo import (SLOMonitor, SLORule, default_rules,
                                         rules_from_config)
from deepspeed_tpu.telemetry.tracing import (Tracer, get_global_tracer,
                                             maybe_span, set_global_tracer)
from deepspeed_tpu.telemetry.watchdog import HangWatchdog

__all__ = [
    "events",
    "stats",
    "SCHEMA_VERSION",
    "STEP_REQUIRED_FIELDS",
    "make_record",
    "TelemetryHub",
    "TelemetrySink",
    "JsonlSink",
    "RingBufferSink",
    "MonitorSink",
    "ProfilerWindow",
    "Tracer",
    "set_global_tracer",
    "get_global_tracer",
    "maybe_span",
    "HangWatchdog",
    "FlightRecorder",
    "read_dump",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsSink",
    "merge_snapshots",
    "cross_rank_snapshot",
    "render_prometheus",
    "GoodputLedger",
    "CATEGORIES",
    "fold_goodput",
    "ObsServer",
    "watchdog_health_check",
    "SLORule",
    "SLOMonitor",
    "default_rules",
    "rules_from_config",
]
