"""Opt-in HTTP ops endpoints over the live metrics plane.

A stdlib :class:`~http.server.ThreadingHTTPServer` on a daemon thread —
no new dependencies, off by default (``telemetry.ops_server``), bound to
loopback unless configured otherwise.  Endpoints:

* ``GET /metrics`` — Prometheus text exposition of the local registry;
  when a pod-level cross-rank snapshot has been folded, its aggregates
  follow under the ``dstpu_pod_`` prefix.
* ``GET /healthz`` — liveness contract: ``200``/``503`` with a JSON body
  listing every registered check (watchdog heartbeat age vs its arm
  threshold, last-step age, tier occupancy, …).
* ``GET /slo`` — the :class:`~deepspeed_tpu.telemetry.slo.SLOMonitor`
  machine-readable verdict (``200`` when every rule is ``ok``, ``503``
  while any rule is burning).
* ``GET /goodput`` — the live cumulative
  :class:`~deepspeed_tpu.telemetry.ledger.GoodputLedger` snapshot
  (category seconds, goodput fraction, conservation verdict).
* ``GET /collectives`` — the last cross-rank collective-health fold
  (skew p50/p99, straggler rank + per-rank scores, desync verdict) plus
  this rank's newest ring records.
* ``GET /recovery`` — the collective-recovery ladder state
  (``comm/recovery.py:RecoveryManager.status``): current rung, last
  abort cause, current world size, quarantined ranks.  ``503`` while an
  incident is in flight or after a terminal failure.
* ``POST /debug/dump`` (``GET`` accepted for curl ergonomics) — triggers
  a flight-recorder dump and returns its path.

The scrape path only *reads* metric values (one lock per metric), so a
scraper can never stall the training or serving hot path.  Every
request socket carries a read/write timeout (``request_timeout_s``,
default 10s): a scraper that connects and then stalls — mid-request or
mid-response — gets its handler thread back instead of pinning it
forever.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from deepspeed_tpu.telemetry import metrics as _metrics
from deepspeed_tpu.utils.logging import logger


class ObsServer:
    """Lifecycle + routing for the ops endpoints.  ``port=0`` binds an
    ephemeral port (read it back from :attr:`port` after :meth:`start`) —
    the test-friendly default."""

    def __init__(self, registry, host: str = "127.0.0.1", port: int = 0,
                 flight_recorder=None, slo_monitor=None,
                 prefix: str = "dstpu_", request_timeout_s: float = 10.0):
        self.registry = registry
        self.host = host
        self._requested_port = int(port)
        self.flight_recorder = flight_recorder
        self.slo_monitor = slo_monitor
        self.goodput_fn = None     # GoodputLedger.snapshot when wired
        self.collectives_fn = None  # hub.collective_status when wired
        self.recovery_fn = None    # RecoveryManager.status when wired
        self.request_timeout_s = float(request_timeout_s)
        self.prefix = prefix
        self._checks: Dict[str, Callable[[], Dict[str, Any]]] = {}
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- health checks ----------------------------------------------------- #
    def add_health_check(self, name: str,
                         fn: Callable[[], Dict[str, Any]]):
        """Register a liveness check.  ``fn`` returns a JSON-ready dict
        with at least ``{"ok": bool}``; a raising check reports unhealthy
        rather than breaking the endpoint."""
        self._checks[name] = fn

    def health(self) -> Dict[str, Any]:
        checks = {}
        for name, fn in sorted(self._checks.items()):
            try:
                res = dict(fn())
                res.setdefault("ok", False)
            except Exception as e:
                res = {"ok": False, "error": f"{type(e).__name__}: {e}"}
            checks[name] = res
        return {"healthy": all(c["ok"] for c in checks.values()),
                "checks": checks}

    # -- endpoint bodies ---------------------------------------------------- #
    def metrics_text(self) -> str:
        snap = self.registry.snapshot()
        text = _metrics.render_prometheus(snap, prefix=self.prefix)
        pod = self.registry.pod_snapshot
        if pod:
            text += _metrics.render_prometheus(pod, prefix=self.prefix + "pod_",
                                               merged=True)
        return text

    def slo_verdict(self) -> Optional[Dict[str, Any]]:
        if self.slo_monitor is None:
            return None
        return self.slo_monitor.verdict()

    def goodput_snapshot(self) -> Optional[Dict[str, Any]]:
        if self.goodput_fn is None:
            return None
        return self.goodput_fn()

    def collectives_status(self) -> Optional[Dict[str, Any]]:
        if self.collectives_fn is None:
            return None
        return self.collectives_fn()

    def recovery_status(self) -> Optional[Dict[str, Any]]:
        if self.recovery_fn is None:
            return None
        return self.recovery_fn()

    def debug_dump(self) -> Dict[str, Any]:
        if self.flight_recorder is None:
            return {"ok": False, "error": "no flight recorder configured"}
        try:
            path = self.flight_recorder.dump(reason="ops_debug_dump")
            return {"ok": True, "path": path}
        except Exception as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    # -- lifecycle ---------------------------------------------------------- #
    def start(self) -> "ObsServer":
        if self._httpd is not None:
            return self
        server = self

        class Handler(BaseHTTPRequestHandler):
            # per-request read/write deadline: BaseRequestHandler.setup()
            # applies it as the connection's socket timeout, so a stalled
            # scraper times out instead of pinning this handler thread
            timeout = server.request_timeout_s

            def log_message(self, fmt, *args):   # keep stdout clean
                ...

            def handle_timeout(self):
                self.close_connection = True

            def _reply(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    # OSError covers socket.timeout: a reader that stopped
                    # draining mid-response forfeits the rest of the body
                    self.close_connection = True

            def _json(self, code: int, obj):
                self._reply(code, (json.dumps(obj, sort_keys=True) + "\n")
                            .encode(), "application/json")

            def _route(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._reply(200, server.metrics_text().encode(),
                                    "text/plain; version=0.0.4")
                    elif path == "/healthz":
                        h = server.health()
                        self._json(200 if h["healthy"] else 503, h)
                    elif path == "/slo":
                        v = server.slo_verdict()
                        if v is None:
                            self._json(404, {"error": "no SLO monitor"})
                        else:
                            self._json(200 if v["ok"] else 503, v)
                    elif path == "/goodput":
                        g = server.goodput_snapshot()
                        if g is None:
                            self._json(404, {"error": "no goodput ledger"})
                        else:
                            self._json(200, g)
                    elif path == "/collectives":
                        c = server.collectives_status()
                        if c is None:
                            self._json(404,
                                       {"error": "no collective monitor"})
                        else:
                            self._json(200, c)
                    elif path == "/recovery":
                        r = server.recovery_status()
                        if r is None:
                            self._json(404, {"error": "no recovery manager"})
                        else:
                            ok = r.get("ladder_state") in ("idle",
                                                           "recovered")
                            self._json(200 if ok else 503, r)
                    elif path == "/debug/dump":
                        d = server.debug_dump()
                        self._json(200 if d["ok"] else 500, d)
                    else:
                        self._json(404, {"error": f"no route {path}"})
                except Exception as e:   # endpoint bug must not kill thread
                    try:
                        self._json(500,
                                   {"error": f"{type(e).__name__}: {e}"})
                    except Exception:
                        pass

            do_GET = _route
            do_POST = _route

        self._httpd = ThreadingHTTPServer((self.host, self._requested_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="ds-tpu-obs-server", daemon=True)
        self._thread.start()
        logger.info(f"obs server listening on http://{self.host}:{self.port}")
        return self

    @property
    def port(self) -> int:
        if self._httpd is not None:
            return self._httpd.server_address[1]
        return self._requested_port

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self):
        httpd, self._httpd = self._httpd, None
        thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)


def watchdog_health_check(watchdog) -> Callable[[], Dict[str, Any]]:
    """`/healthz` check: unhealthy once the heartbeat age exceeds the
    watchdog's arm threshold — a wedged collective becomes visible from
    outside the process before SIGTERM lands."""
    def _check():
        age = watchdog.heartbeat_age_s()
        threshold = watchdog.timeout_ns / 1e9
        return {"ok": (not watchdog.armed) or age < threshold,
                "armed": watchdog.armed,
                "heartbeat_age_s": round(age, 3),
                "threshold_s": threshold}
    return _check


def collective_desync_health_check(monitor) -> Callable[[], Dict[str, Any]]:
    """`/healthz` check: 503 once the cross-rank fold has detected a
    fingerprint desync — and it stays unhealthy (a desynced program is
    undefined behavior; the only recovery is a restart)."""
    def _check():
        return monitor.health_check()
    return _check
