"""Span-based distributed tracing — the "where inside a step" layer.

The :class:`~deepspeed_tpu.telemetry.hub.TelemetryHub` answers *how fast*
a step was; the :class:`Tracer` answers *where inside the step the time
went*.  Engines and the comm facade open nested spans around phases
(``fwd``/``bwd``/``step``), collectives (``comm.all_reduce``), pipeline
schedule slots, inference prefill/decode, and checkpoint save/load.

Design constraints (shared with the hub):

* **Zero-sync.**  Opening/closing a span is two ``time.monotonic_ns``
  reads and a list append.  Attribute values are stored by reference —
  a still-in-flight ``jax.Array`` attr is never forced until export (and
  the flight recorder deliberately never forces it at all: forcing blocks
  during the very hangs it exists to diagnose).
* **Monotonic clock only for durations.**  Wall-clock time appears in
  exactly one place — the per-tracer clock anchor used by
  ``tools/trace_merge.py`` to align rank timelines — and is statically
  policed by ``tools/check_monotonic.py``.
* **Double-duty annotation.**  ``span()`` also enters ``jax.named_scope``
  so that spans opened around traced code show up in XLA profiles
  (``ProfilerWindow`` captures) under the same names.
* **Bounded memory.**  Completed spans live in a ring (``capacity``);
  overflow increments ``dropped`` instead of growing without bound.

Export is Chrome-trace / Perfetto JSON (``traceEvents`` with complete
``X`` duration events), one file per rank; ``tools/trace_merge.py`` folds
N rank files onto one clock-aligned timeline.
"""

import itertools
import json
import os
import re
import threading
import time
from collections import deque
from contextlib import contextmanager, nullcontext
from typing import Any, Callable, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

#: the only clock spans are timed with (see tools/check_monotonic.py)
_mono_ns = time.monotonic_ns

_SCOPE_SANITIZE = re.compile(r"[^A-Za-z0-9_.-]")


def _named_scope(name: str):
    """``jax.named_scope`` with a sanitized name; inert if jax is absent
    or rejects the name (tracing must never be a reason to crash)."""
    try:
        import jax
        return jax.named_scope(_SCOPE_SANITIZE.sub("_", name) or "span")
    except Exception:
        return nullcontext()


class Tracer:
    """Nested context-manager span recorder with Chrome-trace export.

    ``clock`` is injectable for tests and must be a nanosecond monotonic
    clock.  ``heartbeat`` (optional) is invoked on every span open — the
    hang watchdog registers its ``pet`` here so each phase/collective
    span doubles as a liveness beat.
    """

    def __init__(self, rank: int = 0, capacity: int = 65536,
                 clock: Optional[Callable[[], int]] = None,
                 heartbeat: Optional[Callable[[], None]] = None,
                 use_named_scope: bool = True):
        self.rank = int(rank)
        self.capacity = max(1, int(capacity))
        self._clock = clock or _mono_ns
        self.heartbeat = heartbeat
        self.use_named_scope = use_named_scope
        self.completed = deque(maxlen=self.capacity)
        self.dropped = 0
        self._ids = itertools.count(1)
        self._open: Dict[int, List[Dict[str, Any]]] = {}  # tid -> span stack
        self.epoch_ns = self._clock()
        # The single sanctioned wall-clock read: trace_merge aligns rank
        # timelines by mapping each tracer's monotonic epoch to wall time.
        self.epoch_wall_ns = time.time_ns()  # wall-clock anchor: ok
        self.closed = False

    # -- recording (zero-sync hot path) -------------------------------- #
    def _stack(self) -> List[Dict[str, Any]]:
        tid = threading.get_ident()
        stack = self._open.get(tid)
        if stack is None:
            stack = self._open[tid] = []
        return stack

    def _append(self, rec: Dict[str, Any]):
        if len(self.completed) == self.capacity:
            self.dropped += 1
        self.completed.append(rec)

    @contextmanager
    def span(self, name: str, **args):
        """Open a nested span; attributes are stored by reference (never
        forced here).  Also enters ``jax.named_scope(name)`` so traced
        code inside the span is annotated in XLA profiles."""
        if self.closed:
            yield
            return
        if self.heartbeat is not None:
            self.heartbeat()
        stack = self._stack()
        rec = {
            "sid": next(self._ids),
            "name": name,
            "t0": self._clock(),
            "t1": None,
            "tid": threading.get_ident(),
            "depth": len(stack),
            "parent": stack[-1]["sid"] if stack else 0,
            "args": args or None,
        }
        stack.append(rec)
        scope = _named_scope(name) if self.use_named_scope else nullcontext()
        try:
            with scope:
                yield rec
        finally:
            rec["t1"] = self._clock()
            if stack and stack[-1] is rec:
                stack.pop()
            else:  # defensive: unbalanced exit from another thread/path
                try:
                    stack.remove(rec)
                except ValueError:
                    pass
            self._append(rec)

    def instant(self, name: str, **args):
        """Zero-duration marker (Chrome ``ph: "i"``) — e.g. a collective
        recorded at trace time, where host-side duration is meaningless."""
        if self.closed:
            return
        stack = self._stack()
        self._append({
            "sid": next(self._ids), "name": name, "t0": self._clock(),
            "t1": None, "tid": threading.get_ident(), "depth": len(stack),
            "parent": stack[-1]["sid"] if stack else 0,
            "args": args or None, "instant": True,
        })

    def add_span(self, name: str, t0_ns: int, t1_ns: int,
                 track: Optional[str] = None, **args):
        """Record a retrospective span with explicit timestamps (used for
        synthetic tracks, e.g. the pipeline schedule-slot timeline).
        ``track`` names a virtual thread lane in the exported trace."""
        if self.closed:
            return
        self._append({
            "sid": next(self._ids), "name": name, "t0": int(t0_ns),
            "t1": int(t1_ns), "tid": track or threading.get_ident(),
            "depth": 0, "parent": 0, "args": args or None,
        })

    # -- introspection (flight recorder / tests) ------------------------ #
    def open_spans(self) -> List[Dict[str, Any]]:
        """Snapshot of every currently-open span across all threads (the
        flight recorder dumps these on a stall).  Values are copied
        shallowly; attrs stay unforced."""
        out = []
        for tid, stack in list(self._open.items()):
            for rec in list(stack):
                out.append(dict(rec))
        return out

    def snapshot(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Most-recent completed spans, newest last."""
        recs = list(self.completed)
        return recs if limit is None else recs[-int(limit):]

    # -- export ---------------------------------------------------------- #
    def _args_host(self, args):
        if not args:
            return None
        from deepspeed_tpu.telemetry.hub import _to_host
        try:
            return {k: _to_host(v) for k, v in args.items()}
        except Exception:
            return {k: str(type(v).__name__) for k, v in args.items()}

    def _tid_index(self, tids) -> Dict[Any, int]:
        """Stable small integers per lane: real thread ids first (main
        thread = 0), then named synthetic tracks."""
        ints = sorted(t for t in tids if isinstance(t, int))
        names = sorted(str(t) for t in tids if not isinstance(t, int))
        main = threading.main_thread().ident
        if main in ints:
            ints.remove(main)
            ints.insert(0, main)
        index = {t: i for i, t in enumerate(ints)}
        index.update({n: len(ints) + i for i, n in enumerate(names)})
        return index

    def to_chrome_events(self) -> List[Dict[str, Any]]:
        """Completed spans as Chrome-trace ``traceEvents`` (ts/dur in µs,
        relative to this tracer's monotonic epoch)."""
        recs = self.snapshot()
        tid_of = self._tid_index({r["tid"] for r in recs})
        events: List[Dict[str, Any]] = [
            {"ph": "M", "name": "process_name", "pid": self.rank, "tid": 0,
             "ts": 0, "args": {"name": f"rank {self.rank}"}},
            {"ph": "M", "name": "process_sort_index", "pid": self.rank,
             "tid": 0, "ts": 0, "args": {"sort_index": self.rank}},
        ]
        for tid, i in tid_of.items():
            name = tid if isinstance(tid, str) else (
                "main" if tid == threading.main_thread().ident
                else f"thread-{i}")
            events.append({"ph": "M", "name": "thread_name", "pid": self.rank,
                           "tid": i, "ts": 0, "args": {"name": name}})
        for r in recs:
            ev = {
                "name": r["name"],
                "cat": str(r["name"]).split(".", 1)[0],
                "pid": self.rank,
                "tid": tid_of[r["tid"]],
                "ts": (r["t0"] - self.epoch_ns) / 1e3,
            }
            args = self._args_host(r.get("args"))
            if args:
                ev["args"] = args
            if r.get("instant"):
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                t1 = r["t1"] if r["t1"] is not None else r["t0"]
                ev["dur"] = max((t1 - r["t0"]) / 1e3, 0.0)
            events.append(ev)
        return events

    def export_chrome_trace(self, path: str) -> str:
        """Write this rank's timeline as a Perfetto-loadable JSON object.
        ``metadata.clock_sync`` carries the monotonic→wall anchor that
        ``tools/trace_merge.py`` uses for cross-rank alignment."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        doc = {
            "traceEvents": self.to_chrome_events(),
            "displayTimeUnit": "ms",
            "metadata": {
                "rank": self.rank,
                "dropped_spans": self.dropped,
                "clock_sync": {"mono_ns": self.epoch_ns,
                               "wall_ns": self.epoch_wall_ns},
            },
        }
        with open(path, "w") as f:
            json.dump(doc, f, default=str)
        logger.info(f"tracer: wrote {len(doc['traceEvents'])} events -> {path}")
        return path

    def close(self):
        self.closed = True


# --------------------------------------------------------------------------- #
# Global tracer registry — the instrumentation points (comm facade,
# checkpointing, engines built without an explicit tracer) look here.
# --------------------------------------------------------------------------- #
_GLOBAL_TRACER: Optional[Tracer] = None


def set_global_tracer(tracer: Optional[Tracer]):
    global _GLOBAL_TRACER
    _GLOBAL_TRACER = tracer


def get_global_tracer() -> Optional[Tracer]:
    return _GLOBAL_TRACER


def maybe_span(name: str, **args):
    """A span on the global tracer, or an inert context when tracing is
    off — the one-liner instrumentation points use."""
    t = _GLOBAL_TRACER
    return t.span(name, **args) if t is not None else nullcontext()


# --------------------------------------------------------------------------- #
# ZeRO-3 schedule lanes — the compute/communication overlap record
# --------------------------------------------------------------------------- #
def emit_zero3_schedule(tracer: Tracer, t0_ns: int, t1_ns: int,
                        n_blocks: int, layered: bool, depth: int = 1,
                        offload: bool = False):
    """Emit synthetic ``zero3.comm`` / ``zero3.compute`` lanes describing
    the stage-3 step's dependence structure inside the measured fwd window.

    Host-side spans fire at TRACE time (they nest inside the fwd span and
    observe no device concurrency), so real gather/compute simultaneity is
    invisible to the tracer.  What IS knowable host-side is the schedule
    the program structure admits — the same convention the pipeline
    schedule-slot lanes use.  The bulk step's all-gather strictly precedes
    the first block and its reduce-scatter strictly follows the last
    (overlap fraction ~0); the layered step issues block *i+depth*'s
    gather alongside block *i*'s compute and block *i*'s reduce-scatter
    alongside the backward of block *i+1* (overlap fraction L/(L+2)).

    ``tools/trace_merge.py`` computes the overlap fraction from these
    lanes via interval intersection on ``args.kind``.
    """
    L = max(1, int(n_blocks))
    span = max(1, int(t1_ns) - int(t0_ns))
    slots = L + 2
    dt = span / slots

    def at(i):
        return int(t0_ns + i * dt)

    if layered:
        for i in range(L):
            if offload:
                # the host→HBM stage of slice i rides the same ring slot
                # as its gather (it feeds the gather's wire bytes), hidden
                # under block i-depth's compute like the collective
                tracer.add_span("offload.stage", at(i), at(i + 1),
                                track="offload.stage", kind="comm",
                                block=i, depth=depth)
            tracer.add_span("zero3.gather", at(i), at(i + 1),
                            track="zero3.comm", kind="comm", block=i,
                            depth=depth)
            tracer.add_span("zero3.block", at(i + 1), at(i + 2),
                            track="zero3.compute", kind="compute", block=i)
            tracer.add_span("zero3.reduce_scatter", at(i + 2), at(i + 3),
                            track="zero3.comm", kind="comm", block=i)
    else:
        tracer.add_span("zero3.all_gather", at(0), at(1),
                        track="zero3.comm", kind="comm")
        for i in range(L):
            tracer.add_span("zero3.block", at(i + 1), at(i + 2),
                            track="zero3.compute", kind="compute", block=i)
        tracer.add_span("zero3.reduce_scatter", at(L + 1), at(L + 2),
                        track="zero3.comm", kind="comm")
