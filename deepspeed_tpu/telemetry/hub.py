"""TelemetryHub — the structured event bus every engine emits into.

Design constraints (the point of this module):

* **Telemetry-off costs nothing.**  Engines hold ``telemetry = None`` when
  the config block is absent; no code path below ever runs.
* **Telemetry-on never syncs the device per step.**  ``record_step`` only
  appends a dict whose values may still be in-flight ``jax.Array``s — the
  same windowed-drain discipline as ``ThroughputTimer``.  One device drain
  happens per flush window (default: the engine's report boundary), after
  which every buffered value is a cheap ready-array read.
* **Sinks are pluggable.**  A rank-0 append-only JSONL file (schema-
  versioned, consumed by ``tools/telemetry_report.py``), the existing
  ``MonitorMaster`` writers (TensorBoard/W&B/CSV), and an in-memory ring
  buffer queryable from tests.
"""

import json
import os
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.telemetry import events
from deepspeed_tpu.utils.logging import logger


def _to_host(value: Any) -> Any:
    """JSON-ready host value from a (ready) device array / numpy / scalar."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, dict):
        return {k: _to_host(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_to_host(v) for v in value]
    try:
        arr = np.asarray(value)
    except Exception:
        return str(value)
    if arr.ndim == 0 and arr.dtype.kind == "O":
        return str(value)
    if arr.ndim == 0:
        if arr.dtype.kind == "b":
            return bool(arr)
        if arr.dtype.kind in "iu":
            return int(arr)
        return float(arr)
    return arr.tolist()


# --------------------------------------------------------------------------- #
# Sinks
# --------------------------------------------------------------------------- #
class TelemetrySink:
    """Interface: receives fully-drained (host-value) records."""

    def write(self, records: List[Dict[str, Any]]):
        raise NotImplementedError

    def close(self):
        ...


class JsonlSink(TelemetrySink):
    """Append-only JSONL file, rank-0 only.  The first line of a fresh file
    is a ``schema`` header record so the file is self-describing.

    Size-capped rotation (``max_bytes`` > 0): when the live file passes the
    cap it is renamed to ``path.N`` (N ascending = chronological) and a
    fresh header-bearing file is opened; at most ``keep`` rotated files are
    retained.  Readers go through ``telemetry.stats.load_records``, which
    walks the rotated set transparently."""

    def __init__(self, path: str, rank: int = 0, max_bytes: int = 0,
                 keep: int = 5):
        self.path = path
        self.rank = rank
        self.max_bytes = int(max_bytes or 0)
        self.keep = max(1, int(keep))
        self._fh = None

    def _ensure_open(self):
        if self._fh is not None:
            return
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fresh = not (os.path.exists(self.path) and os.path.getsize(self.path) > 0)
        self._fh = open(self.path, "a")
        if fresh:
            header = events.make_record(events.SCHEMA,
                                        {"version": events.SCHEMA_VERSION,
                                         "created_unix": time.time()})
            self._fh.write(json.dumps(header) + "\n")

    def write(self, records):
        if self.rank != 0 or not records:
            return
        self._ensure_open()
        for rec in records:
            self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        if self.max_bytes and self._fh.tell() >= self.max_bytes:
            self._rotate()

    def _rotate(self):
        from deepspeed_tpu.telemetry import stats as _stats
        self._fh.close()
        self._fh = None
        rotated = [p for p in _stats.rotated_set(self.path)
                   if p != self.path and os.path.exists(p)]
        next_idx = 1
        if rotated:
            next_idx = max(int(p.rsplit(".", 1)[1]) for p in rotated) + 1
        try:
            os.replace(self.path, f"{self.path}.{next_idx}")
        except OSError as e:
            logger.warning(f"telemetry jsonl rotation failed: {e}")
            return
        rotated.append(f"{self.path}.{next_idx}")
        for stale in rotated[:max(0, len(rotated) - self.keep)]:
            try:
                os.remove(stale)
            except OSError:
                pass

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class RingBufferSink(TelemetrySink):
    """Bounded in-memory record buffer — the test/debug query surface."""

    def __init__(self, capacity: int = 1024):
        self.records = deque(maxlen=max(1, capacity))

    def write(self, records):
        self.records.extend(records)

    def of_kind(self, kind: str) -> List[Dict[str, Any]]:
        return [r for r in self.records if r.get("kind") == kind]

    def last(self, kind: Optional[str] = None) -> Optional[Dict[str, Any]]:
        pool = self.records if kind is None else self.of_kind(kind)
        return pool[-1] if pool else None


class MonitorSink(TelemetrySink):
    """Fan step records out to the existing ``MonitorMaster`` writers as
    ``(name, value, step)`` scalar events (reference monitor convention)."""

    # step-record fields forwarded as monitor scalars
    FIELDS = ("loss", "lr", "grad_norm", "step_time_ms", "samples_per_sec",
              "tflops_per_chip", "comm_bytes", "device_peak_bytes")

    def __init__(self, monitor, prefix: str = "Train/Telemetry"):
        self.monitor = monitor
        self.prefix = prefix

    def write(self, records):
        evs = []
        for rec in records:
            if rec.get("kind") != events.STEP:
                continue
            step = rec.get("step", 0)
            for f in self.FIELDS:
                v = rec.get(f)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    evs.append((f"{self.prefix}/{f}", v, step))
        if evs:
            self.monitor.write_events(evs)


# --------------------------------------------------------------------------- #
# Hub
# --------------------------------------------------------------------------- #
class TelemetryHub:
    """Buffers typed records and drains them to sinks at window boundaries.

    ``flush_every`` step records per window (0/None disables auto-flush —
    callers flush at their own report boundary).  Per-step cost is one dict
    append + one ``time.time()``; the single device drain per window happens
    inside :meth:`flush`.
    """

    def __init__(self, sinks: Optional[List[TelemetrySink]] = None,
                 flush_every: int = 50,
                 batch_size: int = 1,
                 device_count: int = 1,
                 lr_fn: Optional[Callable[[int], float]] = None,
                 comms_logger=None,
                 flops_per_step: Optional[Callable[[], float]] = None,
                 sync_fn: Optional[Callable[[], None]] = None,
                 memory_stats_fn: Optional[Callable[[], Dict[str, int]]] = None):
        self.sinks = list(sinks or [])
        self.flush_every = int(flush_every or 0)
        self.batch_size = max(1, int(batch_size))
        self.device_count = max(1, int(device_count))
        self.lr_fn = lr_fn
        self.comms_logger = comms_logger
        self.flops_per_step = flops_per_step
        self._sync_fn = sync_fn
        self._memory_stats_fn = memory_stats_fn
        self._pending: List[Dict[str, Any]] = []
        self._pending_steps = 0
        self._window_t = time.time()     # wall clock of the last drained step
        self._window_comm = 0            # cumulative comm bytes at last record
        self.closed = False
        # live observability plane (wired up by from_config when enabled)
        self.registry = None             # metrics.MetricsRegistry
        self.slo_monitor = None          # slo.SLOMonitor, run at flush boundary
        self.obs_server = None           # obs_server.ObsServer
        self.snapshot_every = 0          # cross-rank fold cadence (steps)
        self._last_snapshot_step = None
        # collective health plane (wired up by from_config when enabled)
        self.collective_monitor = None   # collective_monitor.CollectiveMonitor
        self._collective_fed_seq = 0     # last seq whose skew reached the sink
        self._last_collective_health = None
        self._last_step_mono = None
        self._last_flush_mono = time.monotonic()
        # goodput attribution (wired up by from_config when enabled)
        self.ledger = None               # ledger.GoodputLedger
        self.efficiency_json_path = ""   # per-run EFFICIENCY.json artifact
        self._goodput_final = False

    # -- construction ---------------------------------------------------- #
    @classmethod
    def from_config(cls, tcfg, monitor=None, comms_logger=None,
                    flops_profiler=None, batch_size: int = 1,
                    steps_per_print: Optional[int] = None):
        """Build the hub + sinks from a ``telemetry`` config block
        (``runtime/config.py:DeepSpeedTelemetryConfig``)."""
        import jax
        rank = jax.process_index()
        sinks: List[TelemetrySink] = []
        if tcfg.jsonl_path:
            sinks.append(JsonlSink(
                tcfg.jsonl_path, rank=rank,
                max_bytes=getattr(tcfg, "jsonl_max_bytes", 0),
                keep=getattr(tcfg, "jsonl_keep", 5)))
        if tcfg.ring_buffer_size:
            sinks.append(RingBufferSink(tcfg.ring_buffer_size))
        if monitor is not None:
            sinks.append(MonitorSink(monitor))
        flush_every = tcfg.flush_every or steps_per_print or 50
        flops_fn = None
        if flops_profiler is not None:
            flops_fn = lambda: flops_profiler.flops_per_step  # noqa: E731
        hub = cls(sinks=sinks, flush_every=flush_every, batch_size=batch_size,
                  device_count=jax.device_count(), comms_logger=comms_logger,
                  flops_per_step=flops_fn)
        if getattr(tcfg, "metrics", True):
            from deepspeed_tpu.telemetry import slo as slo_mod
            from deepspeed_tpu.telemetry.metrics import (MetricsRegistry,
                                                         MetricsSink)
            hub.registry = MetricsRegistry()
            hub.add_sink(MetricsSink(hub.registry))
            hub.snapshot_every = int(getattr(tcfg, "snapshot_every", 0) or 0)
            hub.slo_monitor = slo_mod.SLOMonitor(
                slo_mod.rules_from_config(getattr(tcfg, "slo_rules", None)),
                registry=hub.registry, telemetry=hub)
            if getattr(tcfg, "goodput", True):
                from deepspeed_tpu.telemetry.ledger import GoodputLedger
                peak_tflops = float(
                    getattr(tcfg, "goodput_peak_tflops_per_chip", 0.0) or 0.0)
                hub.ledger = GoodputLedger(
                    registry=hub.registry,
                    hang_threshold_s=(
                        float(getattr(tcfg, "watchdog_timeout_s", 0.0))
                        if getattr(tcfg, "watchdog_enabled", False) else 0.0),
                    flops_per_step=flops_fn,
                    peak_flops_per_s=(peak_tflops * 1e12) or None)
                path = getattr(tcfg, "efficiency_json_path", "") or ""
                if not path and tcfg.jsonl_path:
                    path = os.path.join(os.path.dirname(tcfg.jsonl_path),
                                        "EFFICIENCY.json")
                hub.efficiency_json_path = path
            if getattr(tcfg, "collective_monitor", True):
                from deepspeed_tpu.telemetry.collective_monitor import (
                    CollectiveMonitor)
                hub.collective_monitor = CollectiveMonitor(
                    rank=rank,
                    capacity=int(getattr(tcfg, "collective_ring", 2048)
                                 or 2048))
            if getattr(tcfg, "ops_server", False):
                from deepspeed_tpu.telemetry.obs_server import (
                    ObsServer, collective_desync_health_check)
                hub.obs_server = ObsServer(
                    hub.registry,
                    host=getattr(tcfg, "ops_host", "127.0.0.1"),
                    port=getattr(tcfg, "ops_port", 0),
                    slo_monitor=hub.slo_monitor)
                hub.obs_server.add_health_check("telemetry", hub.health_check)
                if hub.ledger is not None:
                    hub.obs_server.goodput_fn = hub.ledger.snapshot
                if hub.collective_monitor is not None:
                    hub.obs_server.collectives_fn = hub.collective_status
                    hub.obs_server.add_health_check(
                        "collective_desync",
                        collective_desync_health_check(
                            hub.collective_monitor))
                hub.obs_server.start()
        return hub

    # -- sink queries (tests) -------------------------------------------- #
    def add_sink(self, sink: TelemetrySink):
        self.sinks.append(sink)

    @property
    def ring(self) -> Optional[RingBufferSink]:
        for s in self.sinks:
            if isinstance(s, RingBufferSink):
                return s
        return None

    # -- emission (zero-sync hot path) ------------------------------------ #
    def _comm_totals(self):
        if self.comms_logger is None:
            return 0, 0
        try:
            return (self.comms_logger.total_bytes(),
                    self.comms_logger.total_ops())
        except Exception:
            return 0, 0

    def record_step(self, step: int, **fields):
        """Buffer one per-step record.  Values may be device arrays; nothing
        here blocks on the device."""
        if self.closed:
            return
        self._last_step_mono = time.monotonic()
        # dslint: ok(zero-sync) — step is the host-side counter, never traced
        rec: Dict[str, Any] = {"step": int(step), "_t": time.time()}
        cbytes, cops = self._comm_totals()
        rec["_comm_bytes_cum"] = cbytes
        rec["_comm_ops_cum"] = cops
        rec.update(fields)
        self._pending.append(events.make_record(events.STEP, rec))
        self._pending_steps += 1
        if self.flush_every and self._pending_steps >= self.flush_every:
            self.flush()

    def emit(self, kind: str, payload: Dict[str, Any], step: Optional[int] = None):
        """Buffer a non-step record (pipe/inference/moe/comm summary)."""
        if self.closed:
            return
        rec = dict(payload)
        if step is not None:
            # dslint: ok(zero-sync) — host-side step counter, never traced
            rec["step"] = int(step)
        self._pending.append(events.make_record(kind, rec))

    # -- drain ------------------------------------------------------------ #
    def _drain_device(self):
        if self._sync_fn is not None:
            self._sync_fn()
            return
        from deepspeed_tpu.utils.timer import _sync_device
        _sync_device()

    def _device_peak_bytes(self) -> int:
        if self._memory_stats_fn is not None:
            stats = self._memory_stats_fn() or {}
        else:
            try:
                import jax
                stats = jax.local_devices()[0].memory_stats() or {}
            except Exception:
                stats = {}
        return int(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)))

    def flush(self):
        """Drain the device once, resolve buffered values to host floats,
        derive windowed rates, and fan records out to every sink."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        self._pending_steps = 0
        # the drain exists to materialize buffered device values in step
        # records; a window of host-side event records (worker_exit, SLO
        # transitions, the closing goodput snapshot) must not pay a sync
        if any(rec.get("kind") == events.STEP for rec in pending):
            self._drain_device()
        peak = self._device_peak_bytes()
        flops = None
        if self.flops_per_step is not None:
            try:
                flops = self.flops_per_step()
            except Exception:
                flops = None

        out: List[Dict[str, Any]] = []
        prev_t = self._window_t
        prev_comm = self._window_comm
        for rec in pending:
            if rec.get("kind") != events.STEP:
                out.append({k: _to_host(v) for k, v in rec.items()})
                continue
            t = rec.pop("_t")
            comm_cum = rec.pop("_comm_bytes_cum", 0)
            ops_cum = rec.pop("_comm_ops_cum", 0)
            dt = max(t - prev_t, 1e-9)
            resolved = {k: _to_host(v) for k, v in rec.items()}
            resolved["step_time_ms"] = dt * 1000.0
            resolved["samples_per_sec"] = self.batch_size / dt
            resolved["comm_bytes"] = max(0, comm_cum - prev_comm)
            resolved["comm_ops"] = ops_cum
            resolved["device_peak_bytes"] = peak
            resolved.setdefault("loss", 0.0)
            if self.lr_fn is not None and "lr" not in resolved:
                try:
                    resolved["lr"] = float(self.lr_fn(resolved["step"]))
                except Exception:
                    resolved["lr"] = 0.0
            resolved.setdefault("lr", 0.0)
            if flops:
                resolved["tflops_per_chip"] = (
                    flops / dt / 1e12 / self.device_count)
            out.append(resolved)
            prev_t = t
            prev_comm = comm_cum
        self._window_t = prev_t
        self._window_comm = prev_comm

        # one cumulative goodput snapshot rides every drain window (the
        # close() finalization emits the authoritative last one itself)
        if self.ledger is not None and not self._goodput_final and not any(
                r.get("kind") == events.GOODPUT for r in out):
            try:
                out.append(events.make_record(events.GOODPUT,
                                              self.ledger.snapshot()))
            except Exception as e:
                logger.warning(f"goodput snapshot failed: {e}")

        for sink in self.sinks:
            try:
                sink.write(out)
            except Exception as e:
                logger.warning(f"telemetry sink {type(sink).__name__} failed: {e}")
        self._last_flush_mono = time.monotonic()
        if self.slo_monitor is not None:
            try:
                self.slo_monitor.evaluate()
            except Exception as e:
                logger.warning(f"SLO evaluation failed: {e}")

    # -- live observability plane ----------------------------------------- #
    def maybe_snapshot(self, step: int):
        """Run the cross-rank metrics fold at the configured step cadence
        (``telemetry.snapshot_every``); rank 0's registry then carries the
        pod-level merged snapshot the ops server serves under
        ``dstpu_pod_``."""
        if not (self.registry is not None and self.snapshot_every):
            return
        last = self._last_snapshot_step
        if last is not None and step - last < self.snapshot_every:
            return
        self._last_snapshot_step = step
        from deepspeed_tpu.telemetry import metrics as metrics_mod
        try:
            metrics_mod.cross_rank_snapshot(self.registry)
        except Exception as e:
            logger.warning(f"cross-rank metrics snapshot failed: {e}")
        if self.collective_monitor is not None:
            try:
                self.collective_fold(step=step)
            except Exception as e:
                logger.warning(f"collective health fold failed: {e}")

    def _gather_collective_views(self, view):
        """Per-rank window views for the fold: multihost gathers packed
        float64 rows (µs-since-epoch stays exact below 2**53) through
        ``process_allgather`` — the same piggyback ride the metrics fold
        takes — and restores record fields from the local fingerprint
        dictionary (fingerprints this rank never staged stay opaque but
        still compare, which is all desync detection needs)."""
        import jax
        if jax.process_count() <= 1:
            return [view]
        import numpy as np
        from jax.experimental import multihost_utils
        width = self.collective_monitor.capacity
        recs = view.get("records", [])[-width:]
        rows = np.full((width, 4), -1.0, dtype=np.float64)
        meta = {}
        for i, r in enumerate(recs):
            rows[i] = (r["seq"], r["fp"], r["t_enter_us"],
                       0.0 if r["t_exit_us"] is None else 1.0)
            meta[int(r["fp"])] = {"op": r["op"], "axis": r["axis"],
                                  "dtype": r["dtype"],
                                  "shape": list(r["shape"])}
        gathered = np.asarray(multihost_utils.process_allgather(rows))
        views = []
        for p in range(gathered.shape[0]):
            records = []
            for row in gathered[p]:
                if row[0] < 0:
                    continue
                fp = int(row[1])
                m = meta.get(fp, {"op": "?", "axis": "", "dtype": "?",
                                  "shape": []})
                records.append(dict(m, seq=int(row[0]), fp=fp, bytes=0,
                                    t_enter_us=int(row[2]),
                                    t_exit_us=0 if row[3] > 0.5 else None))
            views.append({"rank": p, "records": records})
        return views

    def collective_fold(self, step: Optional[int] = None,
                        per_rank_views=None):
        """Fold the per-rank collective windows into one health verdict
        and publish it everywhere: a ``collective_window`` record (this
        rank's ring — the offline fold's input), a ``collective_health``
        record (whose sink handler is the SINGLE feed path for the
        ``dstpu_collective_*`` series), a one-shot ``collective_desync``
        event on first divergence, and the goodput ledger's straggler
        share.  ``per_rank_views`` overrides the gather (tests, virtual
        ranks)."""
        mon = self.collective_monitor
        if mon is None:
            return None
        from deepspeed_tpu.telemetry import collective_monitor as cm
        view = mon.window_view()
        views = per_rank_views
        if views is None:
            views = self._gather_collective_views(view)
        health = cm.fold_windows(views, new_after=self._collective_fed_seq)
        last = (health.get("skew") or {}).get("last_seq", 0)
        if last > self._collective_fed_seq:
            self._collective_fed_seq = last
        self.emit(events.COLLECTIVE_WINDOW, view, step=step)
        self.emit(events.COLLECTIVE_HEALTH, health, step=step)
        desync = health.get("desync") or {}
        if desync.get("detected") and mon.desync_count == 0:
            mon.note_desync(desync)
            logger.error(
                "collective desync detected at seq=%s between ranks %s: %s"
                % (desync.get("first_seq"), desync.get("ranks"),
                   desync.get("fingerprints")))
            self.emit(events.COLLECTIVE_DESYNC, dict(desync), step=step)
        if self.ledger is not None:
            skew_s = sum(float(s.get("skew_ms", 0.0))
                         for s in health.get("skew_samples") or []) / 1e3
            self.ledger.note_straggler_share(skew_s)
        self._last_collective_health = health
        return health

    def collective_status(self) -> Optional[Dict[str, Any]]:
        """``/collectives`` endpoint body: the last fold verdict plus this
        rank's newest ring records."""
        mon = self.collective_monitor
        if mon is None:
            return None
        out = {
            "rank": mon.rank,
            "seq": mon.seq,
            "desync_count": mon.desync_count,
            "health": self._last_collective_health,
            "records": mon.last_records(32),
        }
        if mon.last_desync is not None:
            out["last_desync"] = mon.last_desync
        return out

    def health_check(self) -> Dict[str, Any]:
        """`/healthz` contribution: last-step / last-flush ages.  Always
        ``ok`` on its own (step cadence is workload-defined) — the
        watchdog check is what flips unhealthy on a stall."""
        now = time.monotonic()
        age = None
        if self._last_step_mono is not None:
            age = round(now - self._last_step_mono, 3)
        return {"ok": True, "last_step_age_s": age,
                "last_flush_age_s": round(now - self._last_flush_mono, 3),
                "pending_records": len(self._pending)}

    def close(self):
        if self.closed:
            return
        if self.collective_monitor is not None \
                and self.collective_monitor.seq:
            # final fold: short runs that never hit the snapshot cadence
            # still leave their window + health verdict in the JSONL
            try:
                self.collective_fold()
            except Exception as e:
                logger.warning(f"final collective fold failed: {e}")
        if self.ledger is not None and not self._goodput_final:
            # final cumulative snapshot: the same dict becomes the last
            # `goodput` record in the JSONL AND the EFFICIENCY.json body,
            # so the offline fold and the artifact agree exactly
            self._goodput_final = True
            try:
                snap = self.ledger.snapshot()
                self.emit(events.GOODPUT, snap)
                if self.efficiency_json_path:
                    self.ledger.write_efficiency_json(
                        self.efficiency_json_path, snap=snap)
            except Exception as e:
                logger.warning(f"goodput finalization failed: {e}")
        self.flush()
        if self._pending:        # SLO transition events from the final flush
            self.flush()
        if self.obs_server is not None:
            try:
                self.obs_server.stop()
            except Exception:
                pass
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:
                pass
        self.closed = True
