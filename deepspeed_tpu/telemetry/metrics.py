"""Live metrics plane: thread-safe registry, drain-path sink, cross-rank fold.

The registry is the in-process state behind the ops server's ``/metrics``
page and the SLO monitor.  Three metric families:

* :class:`Counter` — monotone float, ``inc()``.
* :class:`Gauge` — last-write-wins float, ``set()``; or a callable
  evaluated lazily at snapshot time (e.g. ``watchdog_heartbeat_age_s``).
* :class:`Histogram` — fixed-bucket counts with p50/p95/p99 estimation
  (:func:`~deepspeed_tpu.telemetry.stats.quantile_from_buckets`).

Zero-sync discipline: ``inc`` / ``set`` / ``observe`` are hot-path
functions policed by the dslint zero-sync pass — callers hand them host
scalars (wall-clock deltas, drained telemetry values, store statistics);
nothing in here may force a device value.  Each update is one lock
acquire + one float add, cheap enough for per-request serving paths.

Cross-rank aggregation: :func:`pack_snapshot` flattens a snapshot into a
schema + float vector, :func:`fold_packed_over_mesh` reduces stacked
per-rank vectors through the ``deepspeed_tpu.comm`` facade (psum for
counters/histograms, pmin/pmax/psum for gauge min/max/mean) on a device
mesh, and :func:`unpack_folded` rebuilds the pod-level snapshot —
provably equal to the host-side :func:`merge_snapshots` fold of the same
per-rank snapshots (histogram merge is vector addition, hence
associative).
"""

import json
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

try:
    from deepspeed_tpu.telemetry import stats as _stats
except ImportError:     # standalone (spec-loaded by a no-jax CLI)
    import importlib.util as _ilu
    import os as _os
    _spec = _ilu.spec_from_file_location(
        "_ds_tpu_telemetry_stats",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)), "stats.py"))
    _stats = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_stats)

try:
    from deepspeed_tpu.telemetry import collective_monitor as _cm
except ImportError:     # standalone (spec-loaded by a no-jax CLI)
    import importlib.util as _ilu
    import os as _os
    _spec = _ilu.spec_from_file_location(
        "_ds_tpu_telemetry_collective_monitor",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      "collective_monitor.py"))
    _cm = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_cm)

DEFAULT_MS_BUCKETS = _stats.DEFAULT_MS_BUCKETS

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_key(name: str, labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{labels[k]}"' for k in sorted(labels))
    return f"{name}{{{inner}}}"


# --------------------------------------------------------------------------- #
# Metric primitives
# --------------------------------------------------------------------------- #
class Counter:
    """Monotone counter.  ``inc`` is the zero-sync hot path."""

    __slots__ = ("name", "labels", "help", "_value", "_lock")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None,
                 help: str = ""):
        self.name = name
        self.labels = dict(labels or {})
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins gauge, or a lazy callable sampled at snapshot time.
    ``set`` is the zero-sync hot path."""

    __slots__ = ("name", "labels", "help", "_value", "_fn", "_lock")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None,
                 help: str = "", fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.labels = dict(labels or {})
        self.help = help
        self._value = 0.0
        self._fn = fn
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = value

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return 0.0
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram; bucket ``i`` counts observations ≤
    ``bounds[i]``, plus one +Inf overflow bucket.  ``observe`` is the
    zero-sync hot path."""

    __slots__ = ("name", "labels", "help", "bounds", "_counts", "_sum",
                 "_count", "_lock")

    def __init__(self, name: str, labels: Optional[Dict[str, str]] = None,
                 help: str = "", bounds: Sequence[float] = DEFAULT_MS_BUCKETS):
        self.name = name
        self.labels = dict(labels or {})
        self.help = help
        self.bounds = tuple(sorted(set(b * 1.0 for b in bounds)))
        self._counts = [0] * (len(self.bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value):
        i = _stats.bucket_index(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            counts = list(self._counts)
        return _stats.quantile_from_buckets(self.bounds, counts, q)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


# --------------------------------------------------------------------------- #
# Registry
# --------------------------------------------------------------------------- #
class MetricsRegistry:
    """Get-or-create metric store with a consistent snapshot view.

    Creation takes the registry lock; updates take only the metric's own
    lock, so concurrent writers never contend with the scraper beyond a
    single value read.  Instrumentation sites should cache the returned
    metric object rather than re-looking it up per event.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        # pod-level merged view, refreshed by the periodic cross-rank fold
        self.pod_snapshot: Optional[Dict[str, Any]] = None
        self.pod_snapshot_unix: Optional[float] = None

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None,
                help: str = "") -> Counter:
        key = _metric_key(name, labels)
        with self._lock:
            m = self._counters.get(key)
            if m is None:
                m = self._counters[key] = Counter(name, labels, help)
            return m

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None,
              help: str = "", fn: Optional[Callable[[], float]] = None) -> Gauge:
        key = _metric_key(name, labels)
        with self._lock:
            m = self._gauges.get(key)
            if m is None:
                m = self._gauges[key] = Gauge(name, labels, help, fn=fn)
            elif fn is not None:
                m._fn = fn
            return m

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  help: str = "",
                  bounds: Sequence[float] = DEFAULT_MS_BUCKETS) -> Histogram:
        key = _metric_key(name, labels)
        with self._lock:
            m = self._histograms.get(key)
            if m is None:
                m = self._histograms[key] = Histogram(name, labels, help,
                                                      bounds=bounds)
            return m

    # -- read side -------------------------------------------------------- #
    def snapshot(self) -> Dict[str, Any]:
        """Consistent host-value view of every metric (lazy gauges are
        sampled here)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
        snap: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
        for key, c in counters.items():
            snap["counters"][key] = {"name": c.name, "labels": c.labels,
                                     "value": c.value}
        for key, g in gauges.items():
            snap["gauges"][key] = {"name": g.name, "labels": g.labels,
                                   "value": g.value}
        for key, h in hists.items():
            with h._lock:
                counts = list(h._counts)
                hsum = h._sum
                hcount = h._count
            snap["histograms"][key] = {
                "name": h.name, "labels": h.labels,
                "bounds": list(h.bounds), "counts": counts,
                "sum": hsum, "count": hcount,
            }
        return snap


# --------------------------------------------------------------------------- #
# Snapshot algebra (host side)
# --------------------------------------------------------------------------- #
def merge_snapshots(snaps: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Host-side cross-rank fold — the reference the device fold must
    match: counters sum, gauges collapse to min/max/mean, histograms
    merge by bucket-count addition."""
    out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
    for snap in snaps:
        for key, c in (snap.get("counters") or {}).items():
            e = out["counters"].setdefault(
                key, {"name": c["name"], "labels": dict(c["labels"]),
                      "value": 0.0})
            e["value"] += float(c["value"])
        for key, g in (snap.get("gauges") or {}).items():
            v = float(g["value"])
            e = out["gauges"].get(key)
            if e is None:
                out["gauges"][key] = {"name": g["name"],
                                      "labels": dict(g["labels"]),
                                      "min": v, "max": v, "sum": v, "n": 1}
            else:
                e["min"] = min(e["min"], v)
                e["max"] = max(e["max"], v)
                e["sum"] += v
                e["n"] += 1
        for key, h in (snap.get("histograms") or {}).items():
            e = out["histograms"].get(key)
            if e is None:
                out["histograms"][key] = {
                    "name": h["name"], "labels": dict(h["labels"]),
                    "bounds": list(h["bounds"]), "counts": list(h["counts"]),
                    "sum": float(h["sum"]), "count": int(h["count"])}
            else:
                if list(e["bounds"]) != list(h["bounds"]):
                    raise ValueError(
                        f"histogram {key}: bucket bounds differ across ranks")
                e["counts"] = _stats.merge_bucket_counts(e["counts"],
                                                         h["counts"])
                e["sum"] += float(h["sum"])
                e["count"] += int(h["count"])
    for e in out["gauges"].values():
        e["mean"] = e["sum"] / e["n"]
    return out


def pack_snapshot(snapshot: Dict[str, Any]):
    """Flatten a snapshot into ``(schema, vector)`` for the device fold.

    Vector layout: ``[counter values | gauge values | histogram cells]``
    where each histogram contributes ``counts + [sum, count]``.  The
    schema (key order + histogram shapes) must be identical on every
    rank — it is derived from sorted metric keys, so ranks running the
    same instrumentation agree by construction.
    """
    schema = {
        "counters": sorted(snapshot.get("counters") or {}),
        "gauges": sorted(snapshot.get("gauges") or {}),
        "histograms": [
            (key, list((snapshot["histograms"][key])["bounds"]))
            for key in sorted(snapshot.get("histograms") or {})],
        "meta": {
            key: {"name": ent["name"], "labels": dict(ent["labels"])}
            for section in ("counters", "gauges", "histograms")
            for key, ent in (snapshot.get(section) or {}).items()},
    }
    vec: List[float] = []
    for key in schema["counters"]:
        vec.append(float(snapshot["counters"][key]["value"]))
    for key in schema["gauges"]:
        vec.append(float(snapshot["gauges"][key]["value"]))
    for key, bounds in schema["histograms"]:
        h = snapshot["histograms"][key]
        vec.extend(float(c) for c in h["counts"])
        vec.append(float(h["sum"]))
        vec.append(float(h["count"]))
    return schema, vec


def fold_packed_over_mesh(vectors: Sequence[Sequence[float]],
                          n_counters: int, n_gauges: int,
                          axis: str = "obs"):
    """Reduce stacked per-rank vectors on the device mesh through the
    ``deepspeed_tpu.comm`` collectives.

    ``vectors`` is ``[R, N]`` (one row per rank, R ≤ device count); the
    result is the folded host vector
    ``[counter sums | gauge mins | gauge maxs | gauge sums | hist sums]``
    read back from rank 0's shard after one psum/pmin/pmax program.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.comm import comm as C

    stacked = np.asarray(vectors, dtype=np.float32)
    r, n = stacked.shape
    devices = jax.devices()[:r]
    if len(devices) < r:
        raise ValueError(f"fold needs ≥{r} devices, have {len(devices)}")
    mesh = Mesh(np.array(devices), (axis,))
    nc, ng = int(n_counters), int(n_gauges)

    def _fold(block):            # [1, N] local shard = one rank's vector
        v = block[0]
        summed = C.all_reduce(v, op=C.ReduceOp.SUM, group=axis)
        mins = C.all_reduce(v[nc:nc + ng], op=C.ReduceOp.MIN, group=axis)
        maxs = C.all_reduce(v[nc:nc + ng], op=C.ReduceOp.MAX, group=axis)
        import jax.numpy as jnp
        out = jnp.concatenate([summed[:nc], mins, maxs,
                               summed[nc:nc + ng], summed[nc + ng:]])
        return out[None, :]

    from jax.experimental.shard_map import shard_map
    arr = jax.device_put(stacked, NamedSharding(mesh, P(axis, None)))
    folded = jax.jit(shard_map(_fold, mesh=mesh, in_specs=P(axis, None),
                               out_specs=P(axis, None)))(arr)
    # every shard holds the same folded vector; read rank 0's copy
    return np.asarray(folded.addressable_shards[0].data)[0]


def unpack_folded(schema: Dict[str, Any], folded: Sequence[float],
                  n_ranks: int) -> Dict[str, Any]:
    """Rebuild a merged snapshot (same shape as :func:`merge_snapshots`
    output) from the device-folded vector."""
    meta = schema.get("meta") or {}

    def _ent(key):
        m = meta.get(key) or {"name": key, "labels": {}}
        return {"name": m["name"], "labels": dict(m["labels"])}

    folded = [float(v) for v in folded]
    nc = len(schema["counters"])
    ng = len(schema["gauges"])
    out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
    for i, key in enumerate(schema["counters"]):
        out["counters"][key] = {**_ent(key), "value": folded[i]}
    mins = folded[nc:nc + ng]
    maxs = folded[nc + ng:nc + 2 * ng]
    sums = folded[nc + 2 * ng:nc + 3 * ng]
    for i, key in enumerate(schema["gauges"]):
        out["gauges"][key] = {**_ent(key), "min": mins[i], "max": maxs[i],
                              "sum": sums[i], "n": n_ranks,
                              "mean": sums[i] / max(1, n_ranks)}
    pos = nc + 3 * ng
    for key, bounds in schema["histograms"]:
        ncells = len(bounds) + 1
        counts = [int(round(v)) for v in folded[pos:pos + ncells]]
        pos += ncells
        hsum = folded[pos]
        hcount = int(round(folded[pos + 1]))
        pos += 2
        out["histograms"][key] = {**_ent(key), "bounds": list(bounds),
                                  "counts": counts, "sum": hsum,
                                  "count": hcount}
    return out


def snapshot_from_vector(schema: Dict[str, Any],
                         vec: Sequence[float]) -> Dict[str, Any]:
    """Inverse of :func:`pack_snapshot` for one rank's vector — rebuilds
    a plain (un-merged) snapshot so gathered rank vectors can be re-merged
    host-side."""
    meta = schema.get("meta") or {}

    def _ent(key):
        m = meta.get(key) or {"name": key, "labels": {}}
        return {"name": m["name"], "labels": dict(m["labels"])}

    vec = [float(v) for v in vec]
    out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
    pos = 0
    for key in schema["counters"]:
        out["counters"][key] = {**_ent(key), "value": vec[pos]}
        pos += 1
    for key in schema["gauges"]:
        out["gauges"][key] = {**_ent(key), "value": vec[pos]}
        pos += 1
    for key, bounds in schema["histograms"]:
        ncells = len(bounds) + 1
        counts = [int(round(v)) for v in vec[pos:pos + ncells]]
        pos += ncells
        out["histograms"][key] = {**_ent(key), "bounds": list(bounds),
                                  "counts": counts, "sum": vec[pos],
                                  "count": int(round(vec[pos + 1]))}
        pos += 2
    return out


def cross_rank_snapshot(registry: MetricsRegistry,
                        per_rank_snapshots: Optional[Sequence[Dict]] = None,
                        axis: str = "obs") -> Dict[str, Any]:
    """Produce the pod-level merged snapshot and cache it on the registry.

    ``per_rank_snapshots`` (tests / offline replay) folds explicit rank
    snapshots through the device mesh; the production path gathers every
    process's packed vector and merges host-side (under a single
    controller the local registry already aggregates all local devices'
    host instrumentation, so the single-process fold is the identity
    merge)."""
    if per_rank_snapshots:
        snaps = list(per_rank_snapshots)
        schema, _ = pack_snapshot(snaps[0])
        vectors = []
        for s in snaps:
            s_schema, vec = pack_snapshot(s)
            if (s_schema["counters"] != schema["counters"]
                    or s_schema["gauges"] != schema["gauges"]
                    or s_schema["histograms"] != schema["histograms"]):
                raise ValueError("rank snapshots disagree on metric schema")
            vectors.append(vec)
        folded = fold_packed_over_mesh(vectors, len(schema["counters"]),
                                       len(schema["gauges"]), axis=axis)
        merged = unpack_folded(schema, folded, len(snaps))
    else:
        snap = registry.snapshot()
        nproc = 1
        try:
            import jax
            nproc = jax.process_count()
        except Exception:
            pass
        if nproc > 1:
            import numpy as np
            from jax.experimental import multihost_utils
            schema, vec = pack_snapshot(snap)
            gathered = np.atleast_2d(multihost_utils.process_allgather(
                np.asarray(vec, dtype=np.float32)))
            merged = merge_snapshots(
                [snapshot_from_vector(schema, row) for row in gathered])
        else:
            merged = merge_snapshots([snap])
    registry.pod_snapshot = merged
    registry.pod_snapshot_unix = time.time()
    return merged


# --------------------------------------------------------------------------- #
# Prometheus text exposition
# --------------------------------------------------------------------------- #
def _prom_name(prefix: str, name: str) -> str:
    return prefix + _NAME_SANITIZE.sub("_", name)


def _prom_labels(labels: Dict[str, str], extra: Optional[Dict] = None) -> str:
    items = dict(labels or {})
    if extra:
        items.update(extra)
    if not items:
        return ""
    inner = ",".join(
        f'{k}="{str(v)}"' for k, v in sorted(items.items()))
    return "{" + inner + "}"


def render_prometheus(snapshot: Dict[str, Any], prefix: str = "dstpu_",
                      merged: bool = False) -> str:
    """Prometheus text-exposition (v0.0.4) rendering of a snapshot.

    ``merged=True`` renders a :func:`merge_snapshots`-shaped pod snapshot
    (gauges carry min/max/mean as an ``agg`` label)."""
    lines: List[str] = []
    typed = set()

    def _type(pname, kind):
        if pname not in typed:
            lines.append(f"# TYPE {pname} {kind}")
            typed.add(pname)

    for key in sorted(snapshot.get("counters") or {}):
        c = snapshot["counters"][key]
        pname = _prom_name(prefix, c["name"])
        _type(pname, "counter")
        lines.append(f"{pname}{_prom_labels(c['labels'])} {c['value']:g}")
    for key in sorted(snapshot.get("gauges") or {}):
        g = snapshot["gauges"][key]
        pname = _prom_name(prefix, g["name"])
        _type(pname, "gauge")
        if merged:
            for agg in ("min", "max", "mean"):
                lines.append(
                    f"{pname}{_prom_labels(g['labels'], {'agg': agg})} "
                    f"{g[agg]:g}")
        else:
            lines.append(f"{pname}{_prom_labels(g['labels'])} {g['value']:g}")
    for key in sorted(snapshot.get("histograms") or {}):
        h = snapshot["histograms"][key]
        pname = _prom_name(prefix, h["name"])
        _type(pname, "histogram")
        cum = 0
        for bound, count in zip(h["bounds"], h["counts"]):
            cum += int(count)
            lines.append(
                f"{pname}_bucket{_prom_labels(h['labels'], {'le': bound})} "
                f"{cum}")
        cum += int(h["counts"][len(h["bounds"])])
        lines.append(
            f"{pname}_bucket{_prom_labels(h['labels'], {'le': '+Inf'})} {cum}")
        lines.append(f"{pname}_sum{_prom_labels(h['labels'])} {h['sum']:g}")
        lines.append(f"{pname}_count{_prom_labels(h['labels'])} {cum}")
    return "\n".join(lines) + "\n"


# --------------------------------------------------------------------------- #
# Drain-path sink: telemetry records → registry
# --------------------------------------------------------------------------- #
class MetricsSink:
    """TelemetrySink fed from the hub's windowed drain — every record
    arriving here already holds host values (the hub drained the device
    once for the whole window), so the updates below are pure host math.

    Maps the established event kinds onto the registry: train ``step``
    records feed the step-time histogram and loss/lr gauges; serving
    request/step/preempt/restage records feed the TTFT and latency
    histograms, arena/tier occupancy gauges and stall counters; offload
    ``offload_staged`` deltas feed ring-hit and byte counters; stability
    and comm summaries feed anomaly/rollback counters and per-op wire
    bytes.
    """

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        r = registry
        self._steps = r.counter("train_steps_total")
        self._step_ms = r.histogram("train_step_time_ms")
        self._loss = r.gauge("train_loss")
        self._lr = r.gauge("train_lr")
        self._grad_norm = r.gauge("train_grad_norm")
        self._samples = r.gauge("train_samples_per_sec")
        self._comm_bytes = r.counter("train_comm_bytes_total")
        self._peak = r.gauge("device_peak_bytes")
        self._anomalies = r.counter("stability_anomalies_total")
        self._rollbacks = r.counter("stability_rollbacks_total")
        self._backoffs = r.counter("stability_lr_backoffs_total")
        self._quarantined = r.counter("stability_batches_quarantined_total")
        self._ttft = r.histogram("serve_ttft_ms")
        self._latency = r.histogram("serve_latency_ms")
        self._submitted = r.counter("serve_submitted_total")
        self._finished = r.counter("serve_finished_total")
        self._new_tokens = r.counter("serve_new_tokens_total")
        self._preempts = r.counter("serve_preemptions_total")
        self._spills = r.counter("kv_spills_total")
        self._restage_ok = r.counter("kv_restages_total")
        self._restage_fail = r.counter("kv_restage_failures_total")
        self._restage_wait = r.histogram("kv_restage_wait_ms")
        self._prefix_hits = r.counter("prefix_hits_total")

    def write(self, records):
        for rec in records:
            kind = rec.get("kind")
            handler = _SINK_HANDLERS.get(kind)
            if handler is not None:
                try:
                    handler(self, rec)
                except (TypeError, ValueError, KeyError):
                    pass    # malformed record: never poison the drain

    def close(self):
        ...

    # -- per-kind handlers (host values only) ------------------------------ #
    def _on_step(self, rec):
        self._steps.inc()
        if isinstance(rec.get("step_time_ms"), (int, float)):
            self._step_ms.observe(rec["step_time_ms"])
        for gauge, field in ((self._loss, "loss"), (self._lr, "lr"),
                             (self._grad_norm, "grad_norm"),
                             (self._samples, "samples_per_sec"),
                             (self._peak, "device_peak_bytes")):
            v = rec.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                gauge.set(v)
        cb = rec.get("comm_bytes")
        if isinstance(cb, (int, float)) and cb > 0:
            self._comm_bytes.inc(cb)

    def _on_serve_request(self, rec):
        if rec.get("event") == "submitted":
            self._submitted.inc()
        elif rec.get("event") == "finished":
            self._finished.inc()
            self._new_tokens.inc(int(rec.get("new_tokens", 0)))
            if isinstance(rec.get("ttft_ms"), (int, float)):
                self._ttft.observe(rec["ttft_ms"])
            if isinstance(rec.get("latency_ms"), (int, float)):
                self._latency.observe(rec["latency_ms"])

    SERVE_STEP_GAUGES = ("queue_depth", "active", "blocks_in_use",
                         "kv_host_bytes", "kv_nvme_bytes", "elapsed_ms")

    def _on_serve_step(self, rec):
        r = self.registry
        for field in self.SERVE_STEP_GAUGES:
            v = rec.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                r.gauge(f"serve_{field}").set(v)
        lookups = rec.get("prefix_lookups")
        if isinstance(lookups, (int, float)) and lookups:
            r.gauge("prefix_hit_rate").set(
                int(rec.get("prefix_hits", 0)) / int(lookups))

    def _on_serve_preempt(self, rec):
        self._preempts.inc()

    def _on_serve_shed(self, rec):
        if rec.get("event") == "level":
            self.registry.gauge("serve_shed_level").set(
                int(rec.get("level", 0)))
        else:
            self.registry.counter(
                "serve_shed_total",
                {"slo": str(rec.get("slo", "unknown"))}).inc()

    def _on_serve_expired(self, rec):
        self.registry.counter(
            "serve_expired_total",
            {"slo": str(rec.get("slo", "unknown"))}).inc()

    def _on_serve_incident(self, rec):
        if rec.get("event") != "recovered":
            return
        self.registry.counter("serve_incidents_total").inc()
        if isinstance(rec.get("recovery_s"), (int, float)):
            self.registry.histogram("serve_incident_recovery_s").observe(
                rec["recovery_s"])

    def _on_kv_spill(self, rec):
        self._spills.inc()
        tier = str(rec.get("tier", "unknown"))
        self.registry.counter("kv_spill_bytes_total",
                              {"tier": tier}).inc(int(rec.get("bytes", 0)))

    def _on_kv_restage(self, rec):
        if rec.get("ok"):
            self._restage_ok.inc()
            if isinstance(rec.get("wait_ms"), (int, float)):
                self._restage_wait.observe(rec["wait_ms"])
        else:
            self._restage_fail.inc()

    def _on_prefix_hit(self, rec):
        self._prefix_hits.inc()

    OFFLOAD_FIELDS = (("bytes_written", "offload_bytes_written_total"),
                      ("bytes_read", "offload_bytes_read_total"),
                      ("ring_hits", "offload_ring_hits_total"),
                      ("ring_misses", "offload_ring_misses_total"),
                      ("wait_ms", "offload_wait_ms_total"))

    def _on_offload_staged(self, rec):
        # records carry per-store DELTA fields `{store}_{field}` plus the
        # aggregate ring_hits/ring_misses/wait_ms keys
        r = self.registry
        stores = set()
        for key in rec:
            for field, _ in self.OFFLOAD_FIELDS:
                if key.endswith(f"_{field}"):
                    stores.add(key[:-(len(field) + 1)])
        for store in stores:
            for field, metric in self.OFFLOAD_FIELDS:
                v = rec.get(f"{store}_{field}")
                if isinstance(v, (int, float)) and not isinstance(v, bool) \
                        and v > 0:
                    r.counter(metric, {"store": store}).inc(v)
        hits = rec.get("ring_hits")
        misses = rec.get("ring_misses")
        if isinstance(hits, (int, float)) and isinstance(misses, (int, float)) \
                and (hits or misses):
            r.gauge("offload_ring_hit_rate").set(hits / (hits + misses))

    def _on_offload_wait(self, rec):
        # aggregate stall counter — the SLO `offload_stall_frac` numerator
        if isinstance(rec.get("wait_ms"), (int, float)):
            self.registry.counter("offload_stall_ms_total").inc(rec["wait_ms"])

    def _on_anomaly(self, rec):
        self._anomalies.inc()

    def _on_auto_rollback(self, rec):
        self._rollbacks.inc()

    def _on_lr_backoff(self, rec):
        self._backoffs.inc()

    def _on_batch_quarantined(self, rec):
        self._quarantined.inc()

    def _on_comm_summary(self, rec):
        # the CommsLogger fold is CUMULATIVE, so it lands on gauges (the
        # per-op `comm_bytes_total` counters are fed live, per staged op,
        # by the comm facade's registry hook); the trimmed-mean bucket
        # latencies feed the collective-latency histogram
        r = self.registry
        ops = rec.get("ops") or {}
        if isinstance(ops, dict):
            for op, ent in ops.items():
                if not isinstance(ent, dict):
                    continue
                tb = ent.get("total_bytes")
                if isinstance(tb, (int, float)):
                    r.gauge("comm_total_bytes", {"op": str(op)}).set(tb)
                cr = ent.get("compression_ratio")
                if isinstance(cr, (int, float)) and cr > 0:
                    r.gauge("comm_compression_ratio",
                            {"op": str(op)}).set(cr)
                for b in ent.get("buckets") or []:
                    lat = b.get("latency_ms") if isinstance(b, dict) else None
                    if isinstance(lat, (int, float)):
                        r.histogram("comm_collective_latency_ms").observe(lat)
        total = rec.get("total_bytes")
        logical = rec.get("total_logical_bytes")
        if isinstance(total, (int, float)) and total > 0 \
                and isinstance(logical, (int, float)) and logical > 0:
            r.gauge("comm_compression_ratio",
                    {"op": "all"}).set(logical / total)

    def _on_collective_health(self, rec):
        # the cross-rank fold verdict: incremental skew samples → the
        # `collective_skew_ms` histogram, straggler scores → gauges — the
        # SINGLE feed path for dstpu_collective_* series, so the live
        # registry and offline replay agree by construction
        _cm.feed_registry(self.registry, rec)

    def _on_collective_desync(self, rec):
        self.registry.counter("collective_desync_total").inc()
        desync = rec.get("desync") or rec
        if isinstance(desync.get("first_seq"), (int, float)):
            self.registry.gauge("collective_desync_first_seq").set(
                float(desync["first_seq"]))

    def _on_slo_burn(self, rec):
        self.registry.counter(
            "slo_burn_total", {"rule": str(rec.get("rule", "unknown")),
                               "severity": str(rec.get("severity", "fast"))}
        ).inc()

    def _on_downtime(self, rec):
        # elastic-agent restart gap: feeds the same category counter the
        # GoodputLedger mirrors, so the agent's /metrics carries it
        self.registry.counter(
            "goodput_seconds_total", {"category": "downtime"}
        ).inc(float(rec.get("downtime_s", 0.0)))
        self.registry.counter("goodput_downtime_events_total").inc()


_SINK_HANDLERS = {
    "step": MetricsSink._on_step,
    "serve_request": MetricsSink._on_serve_request,
    "serve_step": MetricsSink._on_serve_step,
    "serve_preempt": MetricsSink._on_serve_preempt,
    "serve_shed": MetricsSink._on_serve_shed,
    "serve_expired": MetricsSink._on_serve_expired,
    "serve_incident": MetricsSink._on_serve_incident,
    "kv_spill": MetricsSink._on_kv_spill,
    "kv_restage": MetricsSink._on_kv_restage,
    "prefix_hit": MetricsSink._on_prefix_hit,
    "offload_staged": MetricsSink._on_offload_staged,
    "offload_wait": MetricsSink._on_offload_wait,
    "anomaly": MetricsSink._on_anomaly,
    "auto_rollback": MetricsSink._on_auto_rollback,
    "lr_backoff": MetricsSink._on_lr_backoff,
    "batch_quarantined": MetricsSink._on_batch_quarantined,
    "comm_summary": MetricsSink._on_comm_summary,
    "collective_health": MetricsSink._on_collective_health,
    "collective_desync": MetricsSink._on_collective_desync,
    "slo_burn": MetricsSink._on_slo_burn,
    "downtime": MetricsSink._on_downtime,
}


def replay_jsonl(registry: MetricsRegistry, records) -> MetricsRegistry:
    """Feed already-loaded telemetry records through a MetricsSink —
    the offline path ``tools/obs_report.py`` uses so its registry view is
    bit-identical to what the live sink would have accumulated."""
    sink = MetricsSink(registry)
    sink.write(list(records))
    return registry


def dumps_snapshot(snapshot: Dict[str, Any]) -> str:
    return json.dumps(snapshot, indent=2, sort_keys=True)
