"""InferenceEngine (reference ``deepspeed/inference/engine.py:89``).

The reference wraps an HF torch model, swaps its transformer blocks for
fused CUDA modules (module_inject), builds an inference TP process group,
and optionally captures CUDA graphs.  TPU-native redesign:

* "Injection" = choosing the model's fused decode path: a model here is
  an object implementing the DECODE PROTOCOL —
  ``init_params(rng)`` / ``partition_specs()`` (optional) /
  ``apply_with_cache(params, input_ids, cache) -> (logits, cache)`` /
  ``init_cache(batch, max_len)`` / ``generate(...)`` — which the GPT
  family implements via ``gpt_apply_with_cache`` (KV cache per layer,
  the analogue of ``inference_context.h``'s workspace).
* TP: parameters are placed by the model's partition specs over a mesh
  whose ``tensor`` axis has ``tensor_parallel.tp_size`` devices — the
  AutoTP analogue (``module_inject/auto_tp.py:13``) is that specs are
  *derived from the model structure*, not hand-listed per architecture.
* CUDA graphs -> jit: each (batch, seq) decode program is compiled once
  and replayed; ``enable_cuda_graph`` is accepted and ignored.
* Checkpoint loading accepts the training engine's checkpoints
  (``load_checkpoint``) for the same model.
"""

import inspect
import time
from collections import OrderedDict
from contextlib import nullcontext
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.parallel import mesh as mesh_lib
from deepspeed_tpu.telemetry.tracing import get_global_tracer
from deepspeed_tpu.utils.logging import log_dist


class InferenceEngine:

    def __init__(self, model, config: Optional[DeepSpeedInferenceConfig] = None,
                 params=None, mesh=None, seed: int = 0, policy=None,
                 telemetry=None, tracer=None):
        self._config = config or DeepSpeedInferenceConfig()
        # per-request latency/throughput records; None (the default) keeps
        # serving fully async — no block_until_ready is ever issued
        self.telemetry = telemetry
        # span tracing; None falls back to the process-global tracer (set
        # by a co-resident training engine or by the serving harness)
        self.tracer = tracer
        self._request_count = 0
        self.dtype = self._config.jnp_dtype
        # dtype="int8" means weight-only int8 (reference quantizes injected
        # weights when config.dtype == torch.int8, GroupQuantizer
        # ``module_inject/replace_module.py:138``); compute stays bf16
        self.quantize_weights = (self.dtype == jnp.int8
                                 and self._config.quant.enabled
                                 and self._config.quant.weight.enabled)
        if self.dtype == jnp.int8:
            self.dtype = jnp.bfloat16

        # ---- foreign-model injection (reference :180-204 → module_inject)
        # an HF torch model is converted to the fused scan decode path;
        # its weights become the params pytree (TP slicing = sharding).
        # ``policy`` is the custom-architecture escape hatch (reference
        # ``injection_policy`` kwarg); caller-supplied ``params`` win over
        # the weights derived from the HF state dict.
        from deepspeed_tpu.module_inject.replace_module import (inject_hf_model,
                                                                is_hf_model)
        if is_hf_model(model):
            model, injected = inject_hf_model(model, policy=policy,
                                              dtype=self.dtype)
            params = injected if params is None else params
            log_dist("module_inject: replaced HF model with fused decode path",
                     ranks=[0])
        self.module = model

        # ---- mesh: inference TP group (reference :261) ----------------- #
        if mesh is None:
            if mesh_lib.has_mesh():
                mesh = mesh_lib.get_mesh()
            else:
                tp = max(int(self._config.tensor_parallel.tp_size), 1)
                n = jax.device_count()
                assert n % tp == 0, f"tp_size {tp} does not divide {n} devices"
                spec = mesh_lib.MeshSpec(tensor=tp, data=n // tp, device_count=n)
                mesh = spec.build()
                mesh_lib.set_mesh(mesh, spec)
        self.mesh = mesh

        # propagate inference dtype via a shallow model copy — never mutate
        # the caller's model (it may be shared with a training engine)
        if hasattr(model, "cfg") and hasattr(model.cfg, "dtype") \
                and model.cfg.dtype != self.dtype:
            import copy
            import dataclasses
            model = copy.copy(model)
            model.cfg = dataclasses.replace(model.cfg, dtype=self.dtype)
            self.module = model

        # ---- parameters ------------------------------------------------ #
        if params is None:
            assert hasattr(model, "init_params"), (
                "pass params= or a model with init_params(rng)")
            params = model.init_params(jax.random.PRNGKey(seed))
        specs = (model.partition_specs() if hasattr(model, "partition_specs")
                 else jax.tree.map(lambda _: PartitionSpec(), params))
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s or PartitionSpec()), specs,
            is_leaf=lambda x: x is None or isinstance(x, PartitionSpec))
        params = jax.tree.map(lambda p: jnp.asarray(p, self.dtype)
                              if jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating) else p,
                              params)
        if self.quantize_weights:
            # GroupQuantizer analogue: block matmul weights → int8 payload
            # + per-channel scales; the model dequantizes at the matmul
            # (models/gpt.py:_wget) so decode reads half the weight bytes
            from deepspeed_tpu.module_inject.quantization import (
                quantize_block_params, quantize_partition_specs)
            specs = quantize_partition_specs(specs, params)
            params = jax.jit(quantize_block_params)(params)
            self.param_shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s or PartitionSpec()), specs,
                is_leaf=lambda x: x is None or isinstance(x, PartitionSpec))
            log_dist("int8 weight quantization applied to injected blocks "
                     "(reference GroupQuantizer analogue)", ranks=[0])
        self.params = jax.device_put(params, self.param_shardings)
        # per-shape compiled-program caches, LRU-bounded by
        # config.program_cache_size (an adversarial mix of request shapes
        # must evict old programs, not grow device memory without limit)
        self._generate_fns: "OrderedDict[Any, Callable]" = OrderedDict()
        self._forward_fns: "OrderedDict[bool, Callable]" = OrderedDict()
        # input shapes traced into each forward jit since its last clear —
        # lets forward() evict lazily (only when a NEW shape would push the
        # inner cache past the cap) instead of dropping warm programs
        self._forward_seen: "dict[bool, set]" = {}
        self.program_cache_evictions = 0
        self._bucketed_generate = (
            hasattr(self.module, "generate")
            and "prompt_len" in inspect.signature(
                self.module.generate).parameters)
        log_dist(f"InferenceEngine ready: dtype={self.dtype.__name__}, "
                 f"tp={int(self.mesh.shape['tensor'])}, "
                 f"kernel_inject={self._config.replace_with_kernel_inject}", ranks=[0])

    # ------------------------------------------------------------------ #
    def load_checkpoint(self, load_dir, tag=None):
        """Load weights saved by the training engine (reference sharded-
        checkpoint load ``inference/engine.py:419``; resharding happens on
        restore, the TPU analogue of MP-resize via state_dict_factory)."""
        from deepspeed_tpu.runtime.checkpointing import load_params_only
        self.params = load_params_only(load_dir, tag, self.params,
                                       self.param_shardings, dtype=self.dtype)
        return self

    # ------------------------------------------------------------------ #
    def _span(self, name, **args):
        tr = self.tracer if self.tracer is not None else get_global_tracer()
        return tr.span(name, **args) if tr is not None else nullcontext()

    def _record_request(self, op, t0, out, new_tokens=0):
        """Per-request telemetry record.  Blocks on the request's own output
        (not the whole device) to get a true end-to-end latency; compiled
        here means telemetry-off serving never blocks at all."""
        if self.telemetry is None:
            return out
        # the decode span covers device-side token generation: it opens at
        # dispatch return and closes when the request's output is ready
        with self._span("inference.decode", op=op, new_tokens=new_tokens):
            jax.block_until_ready(out)
        dt = max(time.perf_counter() - t0, 1e-9)
        rec = {"op": op, "latency_ms": dt * 1000.0}
        if hasattr(out, "shape") and getattr(out, "ndim", 0) >= 1:
            rec["batch"] = int(out.shape[0])
        if new_tokens:
            rec["new_tokens"] = int(new_tokens)
            rec["tokens_per_sec"] = new_tokens / dt
        self._request_count += 1
        self.telemetry.emit("inference_request", rec, step=self._request_count)
        return out

    # ---- LRU program-cache plumbing ---------------------------------- #
    def _cache_get(self, cache: OrderedDict, key):
        fn = cache.get(key)
        if fn is not None:
            cache.move_to_end(key)
        return fn

    def _cache_put(self, cache: OrderedDict, key, fn, which: str):
        cache[key] = fn
        cap = max(1, int(self._config.program_cache_size))
        while len(cache) > cap:
            old_key, _ = cache.popitem(last=False)
            self._program_evicted(which, old_key)
        return fn

    def _program_evicted(self, which: str, key):
        self.program_cache_evictions += 1
        if self.telemetry is not None:
            self.telemetry.emit("program_cache_evict",
                                {"cache": which, "key": repr(key),
                                 "evictions": self.program_cache_evictions})

    def forward(self, input_ids, *args, attention_mask=None, **kwargs):
        """Full-sequence logits (one jitted program per input shape).
        ``attention_mask`` [B, S] is honored when the model's
        ``forward_logits`` accepts it (encoder serving with padded
        batches).  The compiled function is cached PER MASK PRESENCE —
        a masked call never reuses (or pays for) the maskless program."""
        input_ids = jnp.asarray(input_ids)
        model = self.module
        takes_mask = (hasattr(model, "forward_logits") and "attention_mask"
                      in inspect.signature(model.forward_logits).parameters)
        if attention_mask is not None and not takes_mask:
            raise ValueError("this model's forward path does not accept "
                             "attention_mask")
        use_mask = attention_mask is not None
        fn = self._cache_get(self._forward_fns, use_mask)
        if fn is None:

            def fwd(params, ids, mask=None):
                if hasattr(model, "forward_logits"):
                    if use_mask:
                        return model.forward_logits(params, ids,
                                                    attention_mask=mask)
                    return model.forward_logits(params, ids)
                logits, _ = model.apply_with_cache(
                    params, ids, model.init_cache(ids.shape[0], ids.shape[1]))
                return logits

            fn = jax.jit(fwd) if use_mask else jax.jit(lambda p, i: fwd(p, i))
            self._cache_put(self._forward_fns, use_mask, fn, "forward")
            self._forward_seen[use_mask] = set()
        # one jit holds one program per input shape; keep that inner cache
        # bounded too, but evict LAZILY: only a call that would trace a NEW
        # shape past the cap clears it — a steady-state workload sitting at
        # exactly the cap keeps replaying its warm programs
        seen = self._forward_seen.setdefault(use_mask, set())
        shape_key = (tuple(input_ids.shape), str(input_ids.dtype))
        if shape_key not in seen:
            if len(seen) >= max(1, int(self._config.program_cache_size)):
                fn.clear_cache()
                seen.clear()
                self._program_evicted("forward_shapes", use_mask)
            seen.add(shape_key)
        t0 = time.perf_counter()
        with self._span("inference.forward", batch=int(input_ids.shape[0]),
                        seq=int(input_ids.shape[1]), masked=use_mask):
            if use_mask:
                out = fn(self.params, input_ids, jnp.asarray(attention_mask))
            else:
                out = fn(self.params, input_ids)
            return self._record_request("forward", t0, out)

    __call__ = forward

    PROMPT_BUCKET = 64   # prompt lengths are padded up to multiples of this

    def generate(self, input_ids, max_new_tokens: int = 32, temperature: float = 0.0,
                 rng=None, **kwargs):
        """Autoregressive generation (reference patched ``generate`` :588).

        Prompt lengths are BUCKETED (right-padded to a multiple of
        ``PROMPT_BUCKET``, with the true length passed as a traced scalar):
        a serving workload compiles one program per (batch, bucket,
        max_new_tokens) instead of one per exact prompt length — the role
        the reference's fixed-workspace CUDA graphs play
        (``inference/engine.py:500-528``)."""
        input_ids = jnp.asarray(input_ids)
        B, S = input_ids.shape
        model = self.module
        bucketed = self._bucketed_generate
        if bucketed:
            S_pad = max(self.PROMPT_BUCKET,
                        -(-S // self.PROMPT_BUCKET) * self.PROMPT_BUCKET)
            limit = getattr(getattr(model, "cfg", None), "n_positions", None)
            if limit is not None and S_pad + max_new_tokens > limit:
                # padding would overflow the cache capacity — fall back to
                # the exact-shape program for this (rare, near-limit) call
                bucketed = False
        if bucketed:
            pad = jnp.zeros((B, S_pad - S), input_ids.dtype)
            ids = jnp.concatenate([input_ids, pad], axis=1)
            key = ((B, S_pad), max_new_tokens, float(temperature), "bucketed")
            fn = self._cache_get(self._generate_fns, key)
            if fn is None:
                def gen(params, ids, plen, r):
                    return model.generate(params, ids, max_new_tokens,
                                          rng=r, temperature=temperature,
                                          prompt_len=plen)
                fn = self._cache_put(self._generate_fns, key, jax.jit(gen),
                                     "generate")
            r = rng if rng is not None else jax.random.PRNGKey(self._config.seed)
            t0 = time.perf_counter()
            with self._span("inference.generate", batch=B, prompt_len=S,
                            max_new_tokens=max_new_tokens, bucketed=True):
                # prefill = host-side staging/dispatch of the fused
                # prefill+decode program; device-side completion is the
                # decode span inside _record_request
                with self._span("inference.prefill", batch=B, prompt_len=S,
                                bucket=S_pad):
                    out = fn(self.params, ids, jnp.asarray(S, jnp.int32), r)
                # drop the pad tail: [prompt | pad | new] -> [prompt | new]
                out = jnp.concatenate([out[:, :S], out[:, S_pad:]], axis=1)
                return self._record_request("generate", t0, out,
                                            new_tokens=B * max_new_tokens)
        key = (input_ids.shape, max_new_tokens, float(temperature))
        fn = self._cache_get(self._generate_fns, key)
        if fn is None:
            def gen(params, ids, r):
                return model.generate(params, ids, max_new_tokens,
                                      rng=r, temperature=temperature)

            fn = self._cache_put(self._generate_fns, key, jax.jit(gen),
                                 "generate")
        r = rng if rng is not None else jax.random.PRNGKey(self._config.seed)
        t0 = time.perf_counter()
        with self._span("inference.generate", batch=B, prompt_len=S,
                        max_new_tokens=max_new_tokens, bucketed=False):
            with self._span("inference.prefill", batch=B, prompt_len=S):
                out = fn(self.params, input_ids, r)
            return self._record_request("generate", t0, out,
                                        new_tokens=B * max_new_tokens)


def init_inference(model=None, config=None, **kwargs):
    """Module-level helper mirroring ``deepspeed.init_inference``
    (``deepspeed/__init__.py:215``): merge config dict + kwargs."""
    cfg_dict = dict(config or {})
    cfg_dict.update(kwargs)
    mesh = cfg_dict.pop("mesh", None)
    params = cfg_dict.pop("params", None)
    policy = cfg_dict.pop("injection_policy", cfg_dict.pop("policy", None))
    # "telemetry" is either a TelemetryHub instance (shared with a training
    # engine) or a telemetry config dict to build a standalone hub from
    telemetry = cfg_dict.pop("telemetry", None)
    tracer = cfg_dict.pop("tracer", None)
    if isinstance(telemetry, dict):
        from deepspeed_tpu.runtime.config import DeepSpeedTelemetryConfig
        from deepspeed_tpu.telemetry import TelemetryHub
        tcfg = DeepSpeedTelemetryConfig(**telemetry)
        telemetry = TelemetryHub.from_config(tcfg) if tcfg.enabled else None
    ds_config = DeepSpeedInferenceConfig(**cfg_dict)
    return InferenceEngine(model, config=ds_config, params=params, mesh=mesh,
                           policy=policy, telemetry=telemetry, tracer=tracer)
