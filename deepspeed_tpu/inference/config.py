"""Inference config (reference ``deepspeed/inference/config.py:128``).

Same JSON surface; TPU semantics noted per field:
* ``tensor_parallel.tp_size``  -> size of the ``tensor`` mesh axis.
* ``enable_cuda_graph``        -> no-op: every jitted decode program is
  already captured/replayed by XLA (the reference's graph capture is
  ``inference/engine.py:500-528``).
* ``replace_with_kernel_inject`` -> selects the fused (Pallas) decode path
  where available instead of the reference's CUDA kernel modules.
"""

from typing import Any, Dict, Optional

from pydantic import Field

from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigModel


class DeepSpeedTPConfig(DeepSpeedConfigModel):
    enabled: bool = True
    tp_size: int = 1
    mpu: Optional[Any] = None
    tp_group: Optional[Any] = None


class DeepSpeedMoEConfig(DeepSpeedConfigModel):
    enabled: bool = True
    ep_size: int = 1
    moe_experts: Any = Field(default=1, alias="num_experts")
    type: str = "standard"


class QuantTypeEnum:
    asym = "asymmetric"
    sym = "symmetric"


class BaseQuantConfig(DeepSpeedConfigModel):
    enabled: bool = True
    num_bits: int = 8
    q_type: str = "symmetric"
    q_groups: int = 1


class WeightQuantConfig(BaseQuantConfig):
    enabled: bool = True


class ActivationQuantConfig(BaseQuantConfig):
    enabled: bool = True


class QuantizationConfig(DeepSpeedConfigModel):
    enabled: bool = True
    activation: ActivationQuantConfig = ActivationQuantConfig()
    weight: WeightQuantConfig = WeightQuantConfig()


class DeepSpeedInferenceConfig(DeepSpeedConfigModel):
    replace_with_kernel_inject: bool = Field(False, alias="kernel_inject")
    dtype: str = "bfloat16"
    tensor_parallel: DeepSpeedTPConfig = Field(DeepSpeedTPConfig(), alias="tp")
    enable_cuda_graph: bool = False
    zero: Dict[str, Any] = {}
    triangular_masking: bool = Field(True, alias="tm")
    moe: DeepSpeedMoEConfig = DeepSpeedMoEConfig()
    quant: QuantizationConfig = QuantizationConfig()
    max_tokens: int = Field(1024, alias="max_out_tokens")
    min_out_tokens: int = Field(1, alias="min_out_tokens")
    max_batch_size: Optional[int] = None
    replace_method: str = "auto"
    injection_policy: Optional[Dict] = Field(None, alias="injection_dict")
    return_tuple: bool = True
    checkpoint: Optional[Any] = None
    base_dir: str = ""
    seed: int = 0
    # LRU bound on the per-shape compiled-program caches (generate/forward);
    # an adversarial mix of shapes evicts oldest instead of growing forever
    program_cache_size: int = 32

    @property
    def jnp_dtype(self):
        import jax.numpy as jnp
        return {"bfloat16": jnp.bfloat16, "bf16": jnp.bfloat16,
                "float16": jnp.float16, "fp16": jnp.float16, "half": jnp.float16,
                "float32": jnp.float32, "fp32": jnp.float32, "float": jnp.float32,
                "int8": jnp.int8}[str(self.dtype).replace("torch.", "")]
