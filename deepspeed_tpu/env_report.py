"""``dst_report`` — environment / op-compatibility report (reference
``deepspeed/env_report.py:113``, surfaced as ``ds_report``).

The reference prints a compat matrix of CUDA op builders; the TPU analogue
reports platform/device inventory, the JAX software stack, and whether each
Pallas fast-path kernel actually lowers on this backend (compile probe), so
"op compatible" keeps its meaning."""

import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"
YELLOW = "\033[93m[WARN]\033[0m"


def _versions():
    rows = []
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint", "numpy"):
        try:
            m = __import__(mod)
            for part in mod.split(".")[1:]:
                m = getattr(m, part)
            rows.append((mod, getattr(m, "__version__", "?")))
        except Exception:
            rows.append((mod, None))
    return rows


def _probe_pallas_op(fn):
    try:
        fn()
        return True, ""
    except Exception as e:  # noqa: BLE001 — report, don't raise
        return False, str(e).split("\n")[0][:80]


def op_compatibility():
    """(name, ok, note) per fast-path op — each probe actually compiles and
    runs the kernel on the current backend."""
    import jax
    import jax.numpy as jnp

    def flash():
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
        x = jnp.zeros((1, 128, 2, 64), jnp.bfloat16)
        jax.block_until_ready(flash_attention(x, x, x, causal=True))

    def fused_adam():
        import optax
        from deepspeed_tpu.runtime.optimizers import get_optimizer
        tx = get_optimizer("adamw", {"lr": 1e-3})
        p = {"w": jnp.zeros((128,))}
        s = tx.init(p)
        jax.jit(tx.update)(p, s, p)

    def ring():
        from deepspeed_tpu.parallel.sequence import ring_attention  # noqa: F401

    def sparse_attn():
        import numpy as np

        from deepspeed_tpu.ops.pallas.block_sparse_attention import (
            block_sparse_attention)
        x = jnp.zeros((1, 128, 1, 64), jnp.bfloat16)
        layout = np.ones((1, 2, 2), np.int32)
        jax.block_until_ready(block_sparse_attention(x, x, x, layout))

    def async_io():
        from deepspeed_tpu.ops.aio import AsyncIOBuilder
        b = AsyncIOBuilder()
        assert b.is_compatible(), "g++ or csrc/aio missing"
        b.load()

    def quantizer():
        from deepspeed_tpu.ops.quantizer import quantize_dequantize
        jax.block_until_ready(quantize_dequantize(jnp.ones((128,)), bits=8))

    probes = [("pallas_flash_attention", flash),
              ("pallas_block_sparse_attention", sparse_attn),
              ("fused_optimizer", fused_adam),
              ("ring_attention", ring),
              ("async_io (native)", async_io),
              ("quantizer", quantizer)]
    out = []
    for name, fn in probes:
        ok, note = _probe_pallas_op(fn)
        out.append((name, ok, note))
    return out


def main() -> int:
    import jax

    print("-" * 64)
    print("deepspeed_tpu environment report (dst_report)")
    print("-" * 64)
    print("software stack:")
    for mod, ver in _versions():
        mark = GREEN_OK if ver else RED_NO
        print(f"  {mod:20s} {ver or 'not installed':16s} {mark}")

    print("devices:")
    try:
        devs = jax.devices()
        print(f"  platform={devs[0].platform}  count={len(devs)}  "
              f"process_count={jax.process_count()}")
        for d in devs[:8]:
            stats = {}
            try:
                stats = d.memory_stats() or {}
            except Exception:
                pass
            hbm = stats.get("bytes_limit")
            hbm_s = f"  hbm={hbm / 2**30:.1f}GiB" if hbm else ""
            print(f"    {d}{hbm_s}")
    except Exception as e:  # noqa: BLE001
        print(f"  {RED_NO} no usable backend: {e}")
        return 1

    print("op compatibility (compile probes on this backend):")
    any_fail = False
    for name, ok, note in op_compatibility():
        mark = GREEN_OK if ok else YELLOW
        any_fail |= not ok
        extra = f"  ({note})" if note else ""
        print(f"  {name:28s} {mark}{extra}")
    print("-" * 64)
    return 0


cli_main = main  # console-script entry (pyproject [project.scripts])


if __name__ == "__main__":
    sys.exit(main())
