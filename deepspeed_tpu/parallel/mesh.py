"""Device mesh construction — the TPU-native core of all parallelism.

Replaces the reference's process-group machinery (``deepspeed/utils/groups.py``,
``deepspeed/runtime/pipe/topology.py:ProcessTopology``): one
``jax.sharding.Mesh`` with named axes subsumes every "group".  A process group
over ranks sharing all-but-one axis coordinate is simply that axis name; a
collective over the group is a ``psum``/``all_gather`` over the axis.

Axis conventions (outermost → innermost, i.e. slowest → fastest varying on
the ICI torus):

    pipe   — pipeline stages (crosses DCN on multi-slice; lowest volume)
    data   — pure data parallelism (gradient allreduce only)
    fsdp   — ZeRO parameter/optimizer sharding (allgather + reduce-scatter)
    expert — MoE expert parallelism (all-to-all)
    seq    — sequence/context parallelism (all-to-all / ppermute ring)
    tensor — tensor (Megatron-style) parallelism (allreduce every layer;
             highest volume → innermost, rides nearest-neighbor ICI)
"""

import functools
from typing import Dict, Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical axis order in every Mesh this framework builds.
MESH_AXES = ("pipe", "data", "fsdp", "expert", "seq", "tensor")

# Axes a batch is sharded over (every one of these sees distinct samples).
# Expert-parallel ranks are data-parallel ranks for non-expert tensors,
# matching the reference's E+D group arithmetic (``utils/groups.py:108``).
BATCH_AXES = ("data", "fsdp", "expert")


class MeshSpec:
    """Resolved axis sizes for a device mesh.

    ``data=-1`` means "all remaining devices".  Validates that the product
    covers the device count (reference analogue: the implicit
    world = pp*dp*mp factoring in ``PipeModelDataParallelTopology``,
    ``pipe/topology.py:244``).
    """

    def __init__(self, *, pipe: int = 1, data: int = -1, fsdp: int = 1, expert: int = 1,
                 seq: int = 1, tensor: int = 1, device_count: Optional[int] = None):
        if device_count is None:
            device_count = jax.device_count()
        sizes = dict(pipe=pipe, data=data, fsdp=fsdp, expert=expert, seq=seq, tensor=tensor)
        known = 1
        for name, s in sizes.items():
            if s != -1:
                assert s >= 1, f"mesh axis {name} must be >=1 or -1, got {s}"
                known *= s
        if data == -1:
            assert device_count % known == 0, (
                f"device count {device_count} not divisible by fixed axes product {known}")
            sizes["data"] = device_count // known
            known *= sizes["data"]
        assert known == device_count, (
            f"mesh axes product {known} != device count {device_count}: {sizes}")
        self.sizes: Dict[str, int] = sizes
        self.device_count = device_count

    @classmethod
    def from_config(cls, ds_config, device_count: Optional[int] = None) -> "MeshSpec":
        m = ds_config.mesh_config
        tp = max(ds_config.tensor_parallel_config.tp_size, m.tensor, 1)
        pp = max(ds_config.pipeline_config.stages, m.pipe, 1)
        sp = max(ds_config.sequence_parallel_config.sp_size, m.seq, 1)
        fsdp = m.fsdp
        # ZeRO >= 1 shards over the fsdp axis; if the user didn't size it,
        # fold ALL data parallelism into fsdp (the reference partitions over
        # every DP rank: ``stage_1_and_2.py:90``).
        if ds_config.zero_config.stage >= 1 and fsdp == 1:
            if device_count is None:
                device_count = jax.device_count()
            model = tp * pp * sp * max(m.expert, 1)
            assert device_count % model == 0
            fsdp = device_count // model
            data = 1
            # hpZ (ZeRO++): shrink the fsdp axis to the secondary-partition
            # size and put the rest on data, so the (data, fsdp) split IS the
            # (slow, fast) topology the compressed collectives key off.
            hpz = getattr(ds_config.zero_config, "zero_hpz_partition_size", 1)
            if ds_config.zero_config.stage >= 3 and hpz > 1:
                assert fsdp % hpz == 0, (
                    f"zero_hpz_partition_size {hpz} must divide the ZeRO "
                    f"world size {fsdp}")
                if fsdp // hpz > 1:
                    data = fsdp // hpz
                    fsdp = hpz
        else:
            data = m.data
        return cls(pipe=pp, data=data, fsdp=fsdp, expert=max(m.expert, 1), seq=sp,
                   tensor=tp, device_count=device_count)

    def shape(self) -> Sequence[int]:
        return tuple(self.sizes[a] for a in MESH_AXES)

    def build(self, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
        if devices is None:
            devices = jax.devices()
        shape = self.shape()
        n = int(np.prod(shape))
        assert n == len(devices), f"{shape} needs {n} devices, have {len(devices)}"
        if len(devices) > 1 and devices[0].platform == "tpu":
            try:
                from jax.experimental import mesh_utils
                dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
                return Mesh(dev_array, MESH_AXES)
            except Exception:
                pass
        dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, MESH_AXES)


# --------------------------------------------------------------------------- #
# Global mesh registry — the analogue of the reference's module-level groups
# (``utils/groups.py`` keeps _WORLD_GROUP/_EXPERT_PARALLEL_GROUP/... globals).
# --------------------------------------------------------------------------- #
_MESH: Optional[Mesh] = None
_MESH_SPEC: Optional[MeshSpec] = None


def set_mesh(mesh: Mesh, spec: Optional[MeshSpec] = None):
    global _MESH, _MESH_SPEC
    _MESH = mesh
    _MESH_SPEC = spec


def get_mesh() -> Mesh:
    assert _MESH is not None, "mesh not initialized; call deepspeed_tpu.initialize() first"
    return _MESH


def has_mesh() -> bool:
    return _MESH is not None


def reset_mesh():
    global _MESH, _MESH_SPEC
    _MESH = None
    _MESH_SPEC = None


def axis_size(axis: str) -> int:
    mesh = get_mesh()
    return int(mesh.shape[axis])


def get_data_parallel_world_size() -> int:
    """DP world size incl. fsdp and expert axes (ZeRO ranks are DP ranks and
    EP ranks are a subset of DP ranks, reference ``utils/groups.py:108,331``)."""
    mesh = get_mesh()
    return int(mesh.shape["data"] * mesh.shape["fsdp"] * mesh.shape["expert"])


def get_model_parallel_world_size() -> int:
    mesh = get_mesh()
    return int(mesh.shape["tensor"])


def get_pipe_parallel_world_size() -> int:
    return axis_size("pipe")


def get_expert_parallel_world_size() -> int:
    return axis_size("expert")


def get_sequence_parallel_world_size() -> int:
    return axis_size("seq")


import contextlib
import threading

_manual = threading.local()


@contextlib.contextmanager
def manual_sharding():
    """Mark code being traced inside a ``shard_map`` body: sharding
    constraints are per-device no-ops there (and would be rejected by jax).
    Trace-time only — wrap the body function's execution."""
    prev = getattr(_manual, "on", False)
    _manual.on = True
    try:
        yield
    finally:
        _manual.on = prev


def in_manual_mode() -> bool:
    return getattr(_manual, "on", False)


def constrain(x, *spec):
    """Activation sharding constraint on the global mesh; no-op when no
    mesh is set (single place for the has_mesh/with_sharding_constraint
    idiom used by models, MoE and sequence parallelism)."""
    if not has_mesh() or in_manual_mode():
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(get_mesh(), PartitionSpec(*spec)))


def batch_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Sharding for a [batch, ...] array: batch split over data+fsdp."""
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, PartitionSpec(BATCH_AXES))


def replicated(mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    return NamedSharding(mesh, PartitionSpec())


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across JAX versions.  Newer releases expose it at
    the top level with ``check_vma``; older ones only have
    ``jax.experimental.shard_map.shard_map`` with ``check_rep`` (same
    meaning).  New subsystems route through this so they run on either."""
    import inspect
    if hasattr(jax, "shard_map"):
        sm = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as sm
    kw = ("check_vma" if "check_vma" in inspect.signature(sm).parameters
          else "check_rep")
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              **{kw: check_vma})


def manual_axis_size(name: str) -> int:
    """Static size of a named mesh axis from inside a ``shard_map`` body,
    across JAX versions (``lax.axis_size`` is newer than the pinned
    toolchain; older releases answer via ``core.axis_frame``)."""
    from jax import lax as _lax
    if hasattr(_lax, "axis_size"):
        return int(_lax.axis_size(name))
    from jax import core as _core
    frame = _core.axis_frame(name)
    return int(getattr(frame, "size", frame))


@functools.lru_cache(None)
def cpu_mesh(n: int = 8) -> Mesh:
    """A host-platform mesh for tests (reference tests fork N procs over
    loopback NCCL, ``tests/unit/common.py:88``; on TPU we use XLA's virtual
    CPU devices instead)."""
    devices = jax.devices("cpu")[:n]
    return Mesh(np.asarray(devices).reshape(1, len(devices), 1, 1, 1, 1), MESH_AXES)
