from deepspeed_tpu.parallel.mesh import (BATCH_AXES, MESH_AXES, MeshSpec, batch_sharding, cpu_mesh,
                                         get_data_parallel_world_size, get_expert_parallel_world_size,
                                         get_mesh, get_model_parallel_world_size,
                                         get_pipe_parallel_world_size,
                                         get_sequence_parallel_world_size, has_mesh, replicated,
                                         reset_mesh, set_mesh)
