"""Sequence/context parallelism — first-class here, absent in the reference.

The reference snapshot (v0.8.3) predates DeepSpeed-Ulysses and has no
SP/CP implementation (SURVEY.md §5.7); its long-sequence answer was
block-sparse attention.  This module fills the gap with the two standard
TPU-native schemes over the ``seq`` mesh axis:

* **Ulysses-style all-to-all** (`ulysses_attention`): activations arrive
  sequence-sharded ``[B, S/sp, H, D]``; re-shard to head-sharded
  ``[B, S, H/sp, D]`` for exact attention, then back.  Expressed purely as
  sharding constraints — XLA inserts the two all-to-alls (this is the
  idiomatic SPMD formulation; DeepSpeed-Ulysses codes the a2a by hand).

* **Ring attention** (`ring_attention`): KV blocks rotate around the
  ``seq`` ICI ring via ``ppermute`` while each device keeps its Q shard.
  The per-hop body is the **Pallas flash kernel**
  (``ops/pallas/flash_attention.flash_block_fwd``) — O(block) memory, MXU
  tiles, fp32 online softmax — and hop outputs are merged by their
  log-sum-exp, so nothing ever materializes an ``[Sl, Sl]`` score tensor.
  Under ``causal=True`` hops whose KV block lies entirely in the future are
  **skipped** (``lax.cond``): the ring computes sp(sp+1)/2 of sp^2 score
  blocks, matching flash's causal block skipping.  The backward pass is a
  custom VJP that re-rotates KV with dK/dV accumulators riding alongside
  (one extra ppermute pair per hop) and evaluates the flash backward
  kernels against the *final* merged lse — exact gradients with O(S/sp)
  memory and no stored probabilities.

Both keep the framework-wide attention signature
``fn(q, k, v, *, causal, bias=None, alibi=None) -> out`` with
``[batch, seq, heads, head_dim]``.  ALiBi goes through ``alibi`` (per-head
slopes, [H]): the flash kernel synthesizes ``slope * (k_pos - q_pos)`` from
*local* iotas, and the per-hop global-offset term ``slope * (src - idx) *
Sl`` — constant over a hop's score block — is folded into that hop's lse
(softmax is shift-invariant per hop; the constant re-enters through the
merge).  O(H) memory, so BLOOM-style models train sequence-parallel at any
length.  A dense ``bias`` (rel-pos etc.) is also supported: its Q rows are
sharded with the local shard and KV-block columns are dynamic-sliced per
hop (O(Hb·S/sp·S) per device — inherent to a dense O(S^2) bias the caller
already materialized; prefer ``alibi``).  Both bias forms are constants
under differentiation, the framework-wide kernel-path contract
(``ops/attention.py`` module docstring).
"""

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel import mesh as mesh_lib

NEG_INF = -1e30


_constrain = mesh_lib.constrain


def ulysses_attention(q, k, v, *, causal: bool = True, bias=None, alibi=None,
                      inner: Optional[Callable] = None):
    """All-to-all head/sequence re-sharding attention (DeepSpeed-Ulysses
    scheme, built after the reference's era).  Requires ``heads % sp == 0``
    for q AND for the (grouped) KV head count.  Uneven KV heads (with even
    q heads) are expanded to full head count so the a2a shards evenly —
    O(S · H) KV memory, the documented trade; uneven q heads reroute to
    ring attention (sequence-sharded, never expands) unless the caller
    pinned an ``inner`` kernel."""
    from deepspeed_tpu.ops.attention import (reference_attention,
                                             expand_kv_heads, canonical_bias)
    caller_inner = inner is not None
    inner = inner or reference_attention
    if mesh_lib.has_mesh() and not mesh_lib.in_manual_mode():
        mesh = mesh_lib.get_mesh()
        head_div = int(mesh.shape["seq"] * mesh.shape["tensor"])
        H, Hkv = q.shape[2], k.shape[2]
        if head_div > 1 and H % head_div == 0 and Hkv % head_div:
            # grouped KV with too few heads for the a2a head sharding:
            # expand to full head count so the re-shard stays even (memory
            # cost documented; ring is the alternative that never expands)
            k, v = expand_kv_heads(q, k, v)
        elif head_div > 1 and H % head_div and not caller_inner:
            # q heads themselves can't be head-sharded: ring shards the
            # sequence axis instead.  Only reroute on the default inner —
            # an explicit caller kernel keeps the (GSPMD-padded) a2a path.
            return ring_attention(q, k, v, causal=causal, bias=bias,
                                  alibi=alibi)
    B = mesh_lib.BATCH_AXES
    # seq-sharded on entry (the transformer keeps activations seq-sharded);
    # heads keep their Megatron 'tensor' sharding throughout
    q, k, v = (_constrain(x, B, "seq", "tensor", None) for x in (q, k, v))
    # a2a: full sequence, heads split over seq x tensor
    q, k, v = (_constrain(x, B, None, ("seq", "tensor"), None) for x in (q, k, v))
    bias = canonical_bias(bias)
    if bias is not None and bias.shape[1] > 1:
        # per-head bias follows the head sharding; the inner kernel slices it
        bias = _constrain(bias, None, ("seq", "tensor"), None, None)
    o = inner(q, k, v, causal=causal, bias=bias, alibi=alibi)
    # a2a back to seq-sharded
    return _constrain(o, B, "seq", "tensor", None)


# --------------------------------------------------------------------------- #
# Ring attention: flash-kernel hop body + lse merge, custom VJP
# --------------------------------------------------------------------------- #
def _hop_bias(bias, src, Sl):
    """Dynamic-slice the in-flight KV block's columns out of the local
    dense-bias slice [Bb, Hb, Sl, S]."""
    if bias is None:
        return None
    return jax.lax.dynamic_slice_in_dim(bias, src * Sl, Sl, axis=3)


def _alibi_shift(slopes, src, idx, Sl):
    """Per-head constant ALiBi term for a whole hop block:
    slope * (k_global - q_global) = slope*(src - idx)*Sl + local part."""
    return (slopes[None, :, None, None]
            * ((src - idx) * Sl).astype(jnp.float32))


def _ring_fwd_impl(q, k, v, bias, slopes, causal, sp, scale, blk):
    """[B, H, Sl, D] local shards inside shard_map.  Returns (o, lse).

    Hop 0 (the diagonal block — the only one needing a causal kernel) is
    peeled; hops 1..sp-1 run in a single rolled ``fori_loop`` so the flash
    kernel is traced once, not O(sp) times."""
    from deepspeed_tpu.ops.pallas.flash_attention import flash_block_fwd
    idx = jax.lax.axis_index("seq")
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    B, H, Sl, D = q.shape

    def hop(j, kc, vc, hop_causal):
        src = (idx - j) % sp
        o_j, lse_j = flash_block_fwd(q, kc, vc, _hop_bias(bias, src, Sl),
                                     slopes, causal=hop_causal, scale=scale,
                                     bq=blk, bk=blk)
        if slopes is not None:
            lse_j = lse_j + _alibi_shift(slopes, src, idx, Sl)
        return o_j.astype(jnp.float32), lse_j

    o, lse = hop(0, k, v, causal)

    def body(j, carry):
        o, lse, kc, vc = carry
        kc = jax.lax.ppermute(kc, "seq", perm)
        vc = jax.lax.ppermute(vc, "seq", perm)
        if causal:
            # hop j's block is fully in the future for devices idx < j:
            # skip the kernel entirely (sp(sp+1)/2 of sp^2 blocks computed)
            o_j, lse_j = jax.lax.cond(
                idx >= j,
                lambda kv: hop(j, kv[0], kv[1], False),
                lambda kv: (jnp.zeros((B, H, Sl, D), jnp.float32),
                            jnp.full((B, H, Sl, 1), NEG_INF, jnp.float32)),
                (kc, vc))
        else:
            o_j, lse_j = hop(j, kc, vc, False)
        lse_new = jnp.logaddexp(lse, lse_j)
        o = o * jnp.exp(lse - lse_new) + o_j * jnp.exp(lse_j - lse_new)
        return o, lse_new, kc, vc

    o, lse, _, _ = jax.lax.fori_loop(1, sp, body, (o, lse, k, v))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _ring_flash(q, k, v, bias, slopes, causal, sp, scale, blk):
    o, _ = _ring_fwd_impl(q, k, v, bias, slopes, causal, sp, scale, blk)
    return o


def _ring_flash_vjp_fwd(q, k, v, bias, slopes, causal, sp, scale, blk):
    o, lse = _ring_fwd_impl(q, k, v, bias, slopes, causal, sp, scale, blk)
    return o, (q, k, v, bias, slopes, o, lse)


def _ring_flash_vjp_bwd(causal, sp, scale, blk, res, do):
    """Distributed flash backward: KV re-rotates with dK/dV accumulators
    riding alongside; each hop runs the flash backward kernels against the
    final merged lse.  kc/vc rotate at hop START (j>=1, mirroring the
    forward — the last hop's blocks are dead after compute); dk/dv rotate
    at hop END every hop, so after sp ppermutes the accumulators are home —
    holding the full dK/dV for the device's own block.  Hop 0 is peeled
    (causal kernel); hops 1..sp-1 are a rolled ``fori_loop``."""
    from deepspeed_tpu.ops.pallas.flash_attention import flash_block_bwd
    q, k, v, bias, slopes, o, lse = res
    idx = jax.lax.axis_index("seq")
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    B, H, Sl, D = q.shape
    Hkv = k.shape[1]
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1, keepdims=True)                      # [B,H,Sl,1]

    def hop_bwd(j, kc, vc, hop_causal):
        src = (idx - j) % sp
        lse_adj = lse
        if slopes is not None:   # undo the per-hop global-offset fold
            lse_adj = lse - _alibi_shift(slopes, src, idx, Sl)
        dq_j, dk_j, dv_j = flash_block_bwd(
            q, kc, vc, do, lse_adj, delta, _hop_bias(bias, src, Sl), slopes,
            causal=hop_causal, scale=scale, bq=blk, bk=blk)
        return (dq_j.astype(jnp.float32), dk_j.astype(jnp.float32),
                dv_j.astype(jnp.float32))

    zq = lambda: jnp.zeros((B, H, Sl, D), jnp.float32)
    zkv = lambda: jnp.zeros((B, Hkv, Sl, D), jnp.float32)
    dq, dk, dv = hop_bwd(0, k, v, causal)
    dk = jax.lax.ppermute(dk, "seq", perm)
    dv = jax.lax.ppermute(dv, "seq", perm)

    def body(j, carry):
        dq, dk, dv, kc, vc = carry
        kc = jax.lax.ppermute(kc, "seq", perm)
        vc = jax.lax.ppermute(vc, "seq", perm)
        if causal:
            dq_j, dk_j, dv_j = jax.lax.cond(
                idx >= j, lambda kv: hop_bwd(j, kv[0], kv[1], False),
                lambda kv: (zq(), zkv(), zkv()), (kc, vc))
        else:
            dq_j, dk_j, dv_j = hop_bwd(j, kc, vc, False)
        dk = jax.lax.ppermute(dk + dk_j, "seq", perm)
        dv = jax.lax.ppermute(dv + dv_j, "seq", perm)
        return dq + dq_j, dk, dv, kc, vc

    dq, dk, dv, _, _ = jax.lax.fori_loop(1, sp, body, (dq, dk, dv, k, v))
    db = None if bias is None else jnp.zeros_like(bias)
    da = None if slopes is None else jnp.zeros_like(slopes)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            db, da)


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_attention(q, k, v, *, causal: bool = True, bias=None, alibi=None):
    """Ring attention over the ``seq`` mesh axis (Liu et al. 2023 scheme,
    pipelined KV ppermute, Pallas flash hop body).  Falls back to plain
    attention when sp == 1.  Grouped KV circulates at its native head
    count [B, Hkv, Sl, D] — the flash kernels index grouped KV via their
    BlockSpecs, so ppermute traffic and per-device KV memory stay
    O(S/sp · Hkv), never expanded."""
    from deepspeed_tpu.ops.attention import reference_attention, canonical_bias
    if not mesh_lib.has_mesh() or mesh_lib.in_manual_mode():
        return reference_attention(q, k, v, causal=causal, bias=bias, alibi=alibi)
    mesh = mesh_lib.get_mesh()
    sp = int(mesh.shape["seq"])
    if sp == 1:
        return reference_attention(q, k, v, causal=causal, bias=bias, alibi=alibi)
    B, S, H, D = q.shape
    Hkv = k.shape[2]
    Sl = S // sp
    # largest flash block that tiles the local shard (128 when it divides;
    # any divisor keeps the O(Sl·blk) kernel memory bound — only truly
    # degenerate shards fall back to the dense path)
    blk = next((b for b in range(min(128, Sl), 0, -1) if Sl % b == 0), 1)
    if S % sp or H % Hkv or blk < 8:
        return reference_attention(q, k, v, causal=causal, bias=bias, alibi=alibi)
    scale = 1.0 / np.sqrt(D)
    slopes = None if alibi is None else jnp.asarray(alibi, jnp.float32).reshape(H)
    bias = canonical_bias(bias)

    # full-manual shard_map (the Pallas call has no SPMD partitioning rule):
    # batch over data/fsdp/expert, heads over tensor, sequence manual over
    # the ring axis — replicate any dim the shapes can't split evenly.
    batch_axes = mesh_lib.BATCH_AXES
    batch_div = int(np.prod([mesh.shape[a] for a in batch_axes]))
    tp = int(mesh.shape["tensor"])
    b_ax = batch_axes if batch_div > 1 and B % batch_div == 0 else None
    h_ax = ("tensor" if tp > 1 and H % tp == 0 and Hkv % tp == 0 else None)
    spec = PartitionSpec(b_ax, "seq", h_ax, None)
    in_specs = [spec, spec, spec]
    args = [q, k, v]
    if bias is not None:
        if bias.shape[3] != S:      # columns must be sliceable per hop
            bias = jnp.broadcast_to(bias, bias.shape[:3] + (S,))
        bias = bias.astype(jnp.float32)
        in_specs.append(PartitionSpec(
            b_ax if bias.shape[0] > 1 else None,
            h_ax if bias.shape[1] > 1 else None,
            "seq" if bias.shape[2] == S else None, None))
        args.append(bias)
    if slopes is not None:
        in_specs.append(PartitionSpec(h_ax))
        args.append(slopes)
    nb, ns = bias is not None, slopes is not None

    def body(q, k, v, *rest):
        b = rest[0] if nb else None
        sl = rest[-1] if ns else None
        # [B, Sl, H, D] -> kernel layout [B, H, Sl, D]
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        if b is not None and b.shape[2] == 1:
            # kernel BlockSpecs index q-rows; expand a broadcast row dim
            b = jnp.broadcast_to(b, b.shape[:2] + (qt.shape[2], b.shape[3]))
        with mesh_lib.manual_sharding():
            o = _ring_flash(qt, kt, vt, b, sl, causal, sp, scale, blk)
        return o.transpose(0, 2, 1, 3)

    fn = jax.shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                       out_specs=spec, check_vma=False)
    return fn(*args)
