"""Sequence/context parallelism — first-class here, absent in the reference.

The reference snapshot (v0.8.3) predates DeepSpeed-Ulysses and has no
SP/CP implementation (SURVEY.md §5.7); its long-sequence answer was
block-sparse attention.  This module fills the gap with the two standard
TPU-native schemes over the ``seq`` mesh axis:

* **Ulysses-style all-to-all** (`ulysses_attention`): activations arrive
  sequence-sharded ``[B, S/sp, H, D]``; re-shard to head-sharded
  ``[B, S, H/sp, D]`` for exact attention, then back.  Expressed purely as
  sharding constraints — XLA inserts the two all-to-alls (this is the
  idiomatic SPMD formulation; DeepSpeed-Ulysses codes the a2a by hand).

* **Ring attention** (`ring_attention`): KV blocks rotate around the
  ``seq`` ICI ring via ``ppermute`` while each device keeps its Q shard;
  online-softmax merging keeps O(S/sp) memory per device and never
  materializes the full sequence anywhere.  shard_map manual over ``seq``.

Both keep the framework-wide attention signature
``fn(q, k, v, *, causal, bias=None, alibi=None) -> out`` with
``[batch, seq, heads, head_dim]``.  ALiBi goes through ``alibi`` (per-head
slopes, [H]): the ring body synthesizes ``slope * (k_pos - q_pos)`` from
global position iotas each hop — O(H) memory, so BLOOM-style models train
sequence-parallel at any length.  A dense ``bias`` (rel-pos etc.) is also
supported: its Q rows are sharded with the local shard and KV-block columns
are dynamic-sliced per hop (O(Hb·S/sp·S) per device — inherent to a dense
O(S^2) bias the caller already materialized; prefer ``alibi``).
"""

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from deepspeed_tpu.parallel import mesh as mesh_lib

NEG_INF = -1e30


_constrain = mesh_lib.constrain


def ulysses_attention(q, k, v, *, causal: bool = True, bias=None, alibi=None,
                      inner: Optional[Callable] = None):
    """All-to-all head/sequence re-sharding attention (DeepSpeed-Ulysses
    scheme, built after the reference's era).  Requires ``heads % sp == 0``."""
    from deepspeed_tpu.ops.attention import reference_attention, canonical_bias
    inner = inner or reference_attention
    B = mesh_lib.BATCH_AXES
    # seq-sharded on entry (the transformer keeps activations seq-sharded);
    # heads keep their Megatron 'tensor' sharding throughout
    q, k, v = (_constrain(x, B, "seq", "tensor", None) for x in (q, k, v))
    # a2a: full sequence, heads split over seq x tensor
    q, k, v = (_constrain(x, B, None, ("seq", "tensor"), None) for x in (q, k, v))
    bias = canonical_bias(bias)
    if bias is not None and bias.shape[1] > 1:
        # per-head bias follows the head sharding; the inner kernel slices it
        bias = _constrain(bias, None, ("seq", "tensor"), None, None)
    o = inner(q, k, v, causal=causal, bias=bias, alibi=alibi)
    # a2a back to seq-sharded
    return _constrain(o, B, "seq", "tensor", None)


def _ring_body(q, k, v, bias, slopes, *, causal: bool, sp: int):
    """shard_map body: q/k/v are local shards [B, Sl, H, D].  ``bias`` (or
    None) is the local Q-row slice [Bb, Hb, Sl|1, S] of the dense bias —
    columns for the in-flight KV block are dynamic-sliced each hop.
    ``slopes`` (or None) is the [H] ALiBi vector; the bias term is rebuilt
    from global position iotas per hop (no [S, S] materialization)."""
    idx = jax.lax.axis_index("seq")
    Bq, Sl, H, D = q.shape
    scale = 1.0 / np.sqrt(D)
    qf = q.astype(jnp.float32)

    perm = [(i, (i + 1) % sp) for i in range(sp)]

    def step(j, carry):
        m, l, acc, kc, vc = carry
        src = (idx - j) % sp
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32)) * scale
        if causal or slopes is not None:
            rows = idx * Sl + jax.lax.broadcasted_iota(jnp.int32, (Sl, Sl), 0)
            cols = src * Sl + jax.lax.broadcasted_iota(jnp.int32, (Sl, Sl), 1)
        if bias is not None:
            bcols = jax.lax.dynamic_slice_in_dim(bias, src * Sl, Sl, axis=3)
            s = s + bcols.astype(jnp.float32)
        if slopes is not None:   # ALiBi from iotas: slope * (k_pos - q_pos)
            dist = (cols - rows).astype(jnp.float32)
            s = s + slopes.astype(jnp.float32)[None, :, None, None] * dist[None, None]
        if causal:
            s = jnp.where((rows >= cols)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))   # [B,H,Sl,1]
        p = jnp.exp(s - m_new)                                        # [B,H,Sl,Sl]
        alpha = jnp.exp(m - m_new)                                    # [B,H,Sl,1]
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        a = alpha[..., 0].transpose(0, 2, 1)[..., None]               # [B,Sl,H,1]
        acc = acc * a + jnp.einsum("bhqk,bkhd->bqhd", p, vc.astype(jnp.float32))
        kc = jax.lax.ppermute(kc, "seq", perm)
        vc = jax.lax.ppermute(vc, "seq", perm)
        return m_new, l, acc, kc, vc

    m0 = jnp.full((Bq, H, Sl, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Bq, H, Sl, 1), jnp.float32)
    a0 = jnp.zeros((Bq, Sl, H, D), jnp.float32)
    m, l, acc, _, _ = jax.lax.fori_loop(0, sp, step, (m0, l0, a0, k, v))
    linv = l[..., 0].transpose(0, 2, 1)[..., None]                    # [B,Sl,H,1]
    return (acc / jnp.maximum(linv, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, *, causal: bool = True, bias=None, alibi=None):
    """Ring attention over the ``seq`` mesh axis (Liu et al. 2023 scheme,
    pipelined KV ppermute).  Falls back to plain attention when sp == 1.
    Grouped KV is expanded per-shard (memory stays O(S/sp))."""
    from deepspeed_tpu.ops.attention import (reference_attention,
                                             expand_kv_heads, canonical_bias)
    if not mesh_lib.has_mesh():
        return reference_attention(q, k, v, causal=causal, bias=bias, alibi=alibi)
    mesh = mesh_lib.get_mesh()
    sp = int(mesh.shape["seq"])
    if sp == 1:
        return reference_attention(q, k, v, causal=causal, bias=bias, alibi=alibi)
    k, v = expand_kv_heads(q, k, v)
    S = q.shape[1]
    slopes = None if alibi is None else jnp.asarray(alibi, jnp.float32)
    bias = canonical_bias(bias)
    # partial-manual: specs may only mention the manual axis; data/fsdp/
    # tensor shardings stay automatic inside the body
    spec = PartitionSpec(None, "seq", None, None)
    in_specs = [spec, spec, spec]
    args = [q, k, v]
    if bias is not None:
        if bias.shape[3] != S:      # columns must be sliceable per hop
            bias = jnp.broadcast_to(bias, bias.shape[:3] + (S,))
        # Q rows travel with the local shard when present; a broadcast row
        # dim (1) stays replicated
        in_specs.append(PartitionSpec(
            None, None, "seq" if bias.shape[2] == S else None, None))
        args.append(bias)
    if slopes is not None:
        in_specs.append(PartitionSpec(None))
        args.append(slopes)
    nb, ns = bias is not None, slopes is not None

    def body(q, k, v, *rest):
        b = rest[0] if nb else None
        sl = rest[-1] if ns else None
        return _ring_body(q, k, v, b, sl, causal=causal, sp=sp)

    fn = jax.shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                       out_specs=spec, axis_names={"seq"}, check_vma=False)
    return fn(*args)
