"""1-bit (communication-compressed) optimizers.

Reference: ``deepspeed/runtime/fp16/onebit/{adam,lamb,zoadam}.py`` —
Adam/LAMB variants that, after a full-precision warmup, communicate only the
sign of the momentum plus a scale, keeping a local error-feedback
(compensation) buffer.

TPU-native recast: XLA owns the collectives, so the compression is applied
to the *momentum representation* with the same error-feedback math — after
``freeze_step`` updates use ``sign(m + e) * scale`` where ``e`` accumulates
the quantization residual (exactly the compensated compression of
``onebit/adam.py``; variance is frozen at the freeze step as in the
reference).  A future comm-level path can move the sign/scale exchange into
a shard_map reduce without changing this state.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class OneBitAdamState(NamedTuple):
    count: jnp.ndarray
    mu: optax.Updates          # momentum (m)
    nu: optax.Updates          # second moment (frozen after freeze_step)
    error: optax.Updates       # error-feedback buffer


def onebit_adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
                freeze_step=100, use_trust_ratio=False,
                comm_compression=False) -> optax.GradientTransformation:
    """1-bit Adam (reference ``onebit/adam.py:OnebitAdam:13``).

    Before ``freeze_step``: exact Adam.  After: variance frozen; the update
    direction is the compensated 1-bit momentum sign times its mean
    magnitude (error feedback keeps the quantization unbiased over time).
    ``use_trust_ratio`` turns this into 1-bit LAMB's layerwise scaling.

    ``comm_compression=True`` means the engine already exchanges gradients
    through the compensated 1-bit allreduce (``runtime/comm/compressed.py``)
    — the local momentum quantization is then skipped (quantizing twice
    would double the error with no wire saving); the optimizer contributes
    the frozen-variance Adam math, as the reference's server-side step does.
    """

    def init_fn(params):
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        return OneBitAdamState(count=jnp.zeros((), jnp.int32), mu=zeros(), nu=zeros(),
                               error=zeros())

    def update_fn(updates, state, params=None):
        count = state.count + 1
        in_warmup = count <= freeze_step
        if comm_compression:
            # engine contract: during warmup ``updates`` are exact gradients;
            # after the freeze they are the compensated-compressed momentum
            # m_t itself (formed and exchanged in the engine's compress step,
            # reference optimizer.step's compressed_allreduce of m)
            mu = jax.tree.map(
                lambda m, u: jnp.where(in_warmup, b1 * m + (1 - b1) * u, u),
                state.mu, updates)
        else:
            mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, updates)
        # variance only updates during warmup (frozen afterwards)
        # (in comm_compression mode post-freeze, ``updates`` are momentum,
        # but nu is frozen then anyway — the where keeps warmup exact)
        nu = jax.tree.map(
            lambda v, g: jnp.where(in_warmup, b2 * v + (1 - b2) * jnp.square(g), v),
            state.nu, updates)

        bc1 = 1 - b1**count.astype(jnp.float32)
        bc2 = 1 - b2**count.astype(jnp.float32)

        def adam_dir(m, v):
            return (m / bc1) / (jnp.sqrt(v / bc2) + eps)

        def compressed_dir(m, v, e):
            comp = m + e                                  # compensated momentum
            scale = jnp.mean(jnp.abs(comp))
            quant = jnp.sign(comp) * scale                # 1-bit + scale
            new_e = comp - quant                          # error feedback
            return quant / (jnp.sqrt(v / bc2) + eps), new_e

        def choose(m, v, e):
            if comm_compression:
                # grads arrived through the compressed allreduce; after the
                # freeze the variance is held, exactly the reference's
                # post-warmup server math
                return adam_dir(m, v), e
            d_warm = adam_dir(m, v)
            d_comp, new_e = compressed_dir(m, v, e)
            d = jnp.where(in_warmup, d_warm, d_comp)
            e_out = jnp.where(in_warmup, e, new_e)
            return d, e_out

        pairs = jax.tree.map(choose, mu, nu, state.error)
        direction = jax.tree.map(lambda p: p[0], pairs, is_leaf=lambda x: isinstance(x, tuple))
        error = jax.tree.map(lambda p: p[1], pairs, is_leaf=lambda x: isinstance(x, tuple))

        lr = learning_rate(count - 1) if callable(learning_rate) else learning_rate

        def scaled(d, p):
            upd = d + weight_decay * p if (weight_decay and params is not None) else d
            if use_trust_ratio and params is not None:
                w_norm = jnp.linalg.norm(p)
                u_norm = jnp.linalg.norm(upd)
                trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
                return -lr * trust * upd
            return -lr * upd

        if params is not None:
            new_updates = jax.tree.map(scaled, direction, params)
        else:
            new_updates = jax.tree.map(lambda d: -lr * d, direction)
        return new_updates, OneBitAdamState(count=count, mu=mu, nu=nu, error=error)

    return optax.GradientTransformation(init_fn, update_fn)


def zero_one_adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.0,
                  var_freeze_step=100, var_update_scaler=16,
                  comm_compression=False, **_):
    """0/1 Adam (reference ``onebit/zoadam.py:ZeroOneAdam:13``): like 1-bit
    Adam but the variance keeps updating on a geometric cadence; approximated
    here with the same freeze point (cadence policies are a host-side detail
    the XLA program can't cheaply express)."""
    return onebit_adam(learning_rate, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay,
                       freeze_step=var_freeze_step, comm_compression=comm_compression)


def get_onebit_optimizer(name: str, params: dict, lr):
    betas = params.get("betas", (0.9, 0.999))
    kwargs = dict(b1=betas[0], b2=betas[1], eps=params.get("eps", 1e-8),
                  weight_decay=params.get("weight_decay", 0.0),
                  freeze_step=params.get("freeze_step", 100),
                  comm_compression=params.get("comm_compression", False))
    if name == "onebitadam":
        return onebit_adam(lr, **kwargs)
    if name == "onebitlamb":
        return onebit_adam(lr, use_trust_ratio=True, **kwargs)
    if name == "zerooneadam":
        kwargs.pop("freeze_step")
        return zero_one_adam(lr, var_freeze_step=params.get("var_freeze_step", 100), **kwargs)
    raise ValueError(name)
