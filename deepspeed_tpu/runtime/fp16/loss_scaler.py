"""Loss scaling for fp16 training.

Functional re-design of the reference's ``runtime/fp16/loss_scaler.py``
(``LossScaler:60``, ``DynamicLossScaler:89``, factory ``:202``): the scaler
is an immutable pytree state threaded through the jitted step, updated with
``lax``-friendly arithmetic so the overflow check/skip lives *inside* the
compiled program (no host sync per step).
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

INITIAL_LOSS_SCALE = "init_scale"
SCALE_WINDOW = "scale_window"
DELAYED_SHIFT = "delayed_shift"
CONSECUTIVE_HYSTERESIS = "consecutive_hysteresis"
MIN_LOSS_SCALE = "min_scale"


class LossScalerState(NamedTuple):
    """Carried inside the train step. ``scale`` is f32; counters are i32."""
    scale: jnp.ndarray          # current loss scale
    good_steps: jnp.ndarray     # consecutive overflow-free steps
    hysteresis: jnp.ndarray     # remaining tolerated overflows before backoff
    # static config (kept as arrays so the state is a uniform pytree)
    scale_window: jnp.ndarray
    min_scale: jnp.ndarray
    scale_factor: jnp.ndarray
    delayed_shift: jnp.ndarray
    dynamic: jnp.ndarray        # bool: False => static scale, never updates
    # bool: re-arm hysteresis after every clean step (reference
    # ``consecutive_hysteresis``); False => re-arm per completed clean window
    consecutive_hysteresis: jnp.ndarray


def create_loss_scaler(*, static_loss_scale: float = 0.0, initial_scale_power: int = 16,
                       loss_scale_window: int = 1000, min_loss_scale: float = 1.0,
                       hysteresis: int = 2, scale_factor: float = 2.0,
                       consecutive_hysteresis: bool = False) -> LossScalerState:
    """``static_loss_scale > 0`` selects a fixed scale (reference
    ``CreateLossScaler``/``loss_scaler.py:202``); 0 selects dynamic scaling
    starting at ``2**initial_scale_power``."""
    dynamic = static_loss_scale == 0
    scale = float(2.0**initial_scale_power) if dynamic else float(static_loss_scale)
    return LossScalerState(
        scale=jnp.asarray(scale, jnp.float32),
        good_steps=jnp.asarray(0, jnp.int32),
        hysteresis=jnp.asarray(hysteresis, jnp.int32),
        scale_window=jnp.asarray(loss_scale_window, jnp.int32),
        min_scale=jnp.asarray(min_loss_scale, jnp.float32),
        scale_factor=jnp.asarray(scale_factor, jnp.float32),
        delayed_shift=jnp.asarray(hysteresis, jnp.int32),
        dynamic=jnp.asarray(dynamic, jnp.bool_),
        consecutive_hysteresis=jnp.asarray(consecutive_hysteresis, jnp.bool_),
    )


def unit_loss_scaler() -> LossScalerState:
    """Identity scaler used for bf16/fp32 paths (keeps one step signature)."""
    return create_loss_scaler(static_loss_scale=1.0)


def has_overflow(grads) -> jnp.ndarray:
    """Global overflow check: any non-finite value in any gradient leaf.

    The reference checks per-partition then all-reduces
    (``has_overflow_serial``/``has_overflow`` in the fp16 optimizers); under
    SPMD the reduction over sharded leaves is inserted by XLA.
    """
    leaves = jax.tree.leaves(grads)
    if not leaves:
        return jnp.asarray(False)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(g))) for g in leaves]
    return jnp.any(jnp.stack(flags))


def update_scale(state: LossScalerState, overflow: jnp.ndarray) -> LossScalerState:
    """Dynamic-scale transition (reference ``DynamicLossScaler.update_scale``,
    ``loss_scaler.py:139``-ish): on overflow consume hysteresis then halve
    (floored at min_scale) and reset the window; otherwise grow 2x every
    ``scale_window`` clean steps."""

    def on_overflow(s: LossScalerState) -> LossScalerState:
        new_hyst = jnp.maximum(s.hysteresis - 1, 0)
        do_backoff = new_hyst <= 0
        new_scale = jnp.where(do_backoff,
                              jnp.maximum(s.scale / s.scale_factor, s.min_scale),
                              s.scale)
        new_hyst = jnp.where(do_backoff, s.delayed_shift, new_hyst)
        return s._replace(scale=new_scale, good_steps=jnp.zeros_like(s.good_steps),
                          hysteresis=new_hyst)

    def on_success(s: LossScalerState) -> LossScalerState:
        grown = (s.good_steps + 1) % s.scale_window == 0
        new_scale = jnp.where(grown, s.scale * s.scale_factor, s.scale)
        # Re-arm hysteresis: a clean window (or, with consecutive_hysteresis,
        # any clean step) restores the full overflow tolerance — without this
        # a single early overflow leaves the scaler permanently hair-trigger.
        rearm = jnp.logical_or(s.consecutive_hysteresis, grown)
        new_hyst = jnp.where(rearm, jnp.maximum(s.delayed_shift, s.hysteresis),
                             s.hysteresis)
        return s._replace(scale=new_scale, good_steps=s.good_steps + 1,
                          hysteresis=new_hyst)

    new_state = jax.lax.cond(overflow, on_overflow, on_success, state)
    # Static scalers never change.
    return jax.tree.map(lambda new, old: jnp.where(state.dynamic, new, old), new_state, state)


def at_min_scale(state: LossScalerState) -> jnp.ndarray:
    """In-program bool: dynamic scale pinned at its floor (every overflow
    backoff is now a no-op — the skip-loop signal the stability sentinel's
    scale-collapse detector watches)."""
    return jnp.logical_and(state.dynamic, state.scale <= state.min_scale)


# Object-style veneer for API parity with the reference ------------------- #
class LossScalerBase:

    def __init__(self, state: LossScalerState):
        self.state = state

    @property
    def loss_scale(self):
        return float(self.state.scale)

    def scale_gradient(self, grad):
        return jax.tree.map(lambda g: g * self.state.scale, grad)

    def backward(self, loss):
        return loss * self.state.scale


class LossScaler(LossScalerBase):
    """Static loss scaler."""

    def __init__(self, scale=1.0):
        super().__init__(create_loss_scaler(static_loss_scale=scale))


class DynamicLossScaler(LossScalerBase):

    def __init__(self, init_scale=2**32, scale_factor=2.0, scale_window=1000, min_scale=1,
                 delayed_shift=1, consecutive_hysteresis=False, raise_error_at_min_scale=True,
                 dtype=jnp.float16):
        import math
        super().__init__(
            create_loss_scaler(static_loss_scale=0.0,
                               initial_scale_power=int(math.log2(init_scale)),
                               loss_scale_window=scale_window, min_loss_scale=min_scale,
                               hysteresis=delayed_shift, scale_factor=scale_factor,
                               consecutive_hysteresis=consecutive_hysteresis))


def CreateLossScaler(dtype, static_loss_scale, dynamic_scaling, dynamic_loss_args):
    """Factory matching the reference signature (``loss_scaler.py:202``)."""
    if dtype == jnp.float16 and dynamic_scaling:
        kwargs = dynamic_loss_args or {}
        return DynamicLossScaler(
            init_scale=kwargs.get(INITIAL_LOSS_SCALE, 2**16),
            scale_window=kwargs.get(SCALE_WINDOW, 1000),
            min_scale=kwargs.get(MIN_LOSS_SCALE, 1),
            delayed_shift=kwargs.get(DELAYED_SHIFT, 2),
            consecutive_hysteresis=kwargs.get(CONSECUTIVE_HYSTERESIS, False),
        )
    loss_scale_value = static_loss_scale if dtype == jnp.float16 else 1.0
    return LossScaler(scale=loss_scale_value)
