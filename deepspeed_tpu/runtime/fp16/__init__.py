from deepspeed_tpu.runtime.fp16.loss_scaler import (CreateLossScaler, DynamicLossScaler, LossScaler,
                                                    LossScalerState, create_loss_scaler, has_overflow,
                                                    unit_loss_scaler, update_scale)
