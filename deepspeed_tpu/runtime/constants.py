"""Config key constants and defaults.

Mirrors the *product surface* of the reference's
``deepspeed/runtime/constants.py`` (417 LoC): the JSON keys users put in a
ds_config file.  Only keys that are meaningful on TPU (plus compat aliases)
are retained; CUDA-only knobs are accepted and ignored with a warning.
"""

#############################################
# Batch size
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE_DEFAULT = None
OPTIMIZER_PARAMS = "params"
TYPE = "type"
LEGACY_FUSION = "legacy_fusion"

SCHEDULER = "scheduler"
SCHEDULER_TYPE_DEFAULT = None
SCHEDULER_PARAMS = "params"

MAX_GRAD_NORM = "max_grad_norm"

ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_ALLOW_UNTESTED_OPTIMIZER_DEFAULT = False
ZERO_FORCE_DS_CPU_OPTIMIZER = "zero_force_ds_cpu_optimizer"
ZERO_FORCE_DS_CPU_OPTIMIZER_DEFAULT = True

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0
FP16_AUTO_CAST = "auto_cast"
FP16_AUTO_CAST_DEFAULT = False
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1
FP16_MASTER_WEIGHTS_AND_GRADS = "fp16_master_weights_and_grads"
FP16_MASTER_WEIGHTS_AND_GRADS_DEFAULT = False

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"  # legacy key accepted by the reference
BFLOAT16_ENABLED = "enabled"
BFLOAT16_ENABLED_DEFAULT = False

AMP = "amp"
AMP_ENABLED = "enabled"
AMP_ENABLED_DEFAULT = False

GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False

COMMUNICATION_DATA_TYPE = "communication_data_type"
COMMUNICATION_DATA_TYPE_DEFAULT = None

#############################################
# Logging / profiling
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

#############################################
# Misc engine knobs
#############################################
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"

GRADIENT_ACCUMULATION_PLUGIN = "gradient_accumulation_plugin"

CHECKPOINT = "checkpoint"
CHECKPOINT_TAG_VALIDATION = "tag_validation"
CHECKPOINT_TAG_VALIDATION_DEFAULT = "Warn"
CHECKPOINT_TAG_VALIDATION_MODES = ["Warn", "Ignore", "Fail"]
LOAD_UNIVERSAL_CHECKPOINT = "load_universal"
LOAD_UNIVERSAL_CHECKPOINT_DEFAULT = False

DATA_TYPES = "data_types"
GRAD_ACCUM_DTYPE = "grad_accum_dtype"
GRAD_ACCUM_DTYPE_DEFAULT = None

USE_DATA_BEFORE_EXPERT_PARALLEL = "use_data_before_expert_parallelism"

#############################################
# Parallelism (TPU-native extension: explicit mesh spec in the JSON)
#############################################
MESH = "mesh"                      # {"data": -1, "fsdp": 1, "tensor": 1, ...}
TENSOR_PARALLEL = "tensor_parallel"
PIPELINE_PARALLEL = "pipeline"
SEQUENCE_PARALLEL = "sequence_parallel"

#############################################
# Sub-configs handled by pydantic models
#############################################
ZERO_OPTIMIZATION = "zero_optimization"
FAULT_TOLERANCE = "fault_tolerance"
STABILITY = "stability"
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
COMMS_LOGGER = "comms_logger"
TELEMETRY = "telemetry"
SERVING = "serving"
MONITOR_CONFIG_TENSORBOARD = "tensorboard"
MONITOR_CONFIG_WANDB = "wandb"
MONITOR_CONFIG_CSV = "csv_monitor"
FLOPS_PROFILER = "flops_profiler"
AUTOTUNING = "autotuning"
ELASTICITY = "elasticity"
COMPRESSION_TRAINING = "compression_training"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
EIGENVALUE = "eigenvalue"
QUANTIZE_TRAINING = "quantize_training"

#############################################
# Routing / PLD defaults
#############################################
PLD_ENABLED = "enabled"
PLD_ENABLED_DEFAULT = False
PLD_THETA = "theta"
PLD_THETA_DEFAULT = 1.0
PLD_GAMMA = "gamma"
PLD_GAMMA_DEFAULT = 0.001

#############################################
# Sparse attention (ref constants.py SPARSE_ATTENTION)
#############################################
SPARSE_ATTENTION = "sparse_attention"
SPARSE_MODE = "mode"
SPARSE_MODE_DEFAULT = "fixed"
