"""Training-stability sentinel: in-step anomaly detection + recovery ladder.

Two halves, split the same way as ``fp16/loss_scaler.py``:

* **Device half** — :class:`SentinelState` (a NamedTuple of device scalars)
  threaded through the compiled apply-step, updated by the pure function
  :func:`sentinel_observe`.  Detectors (non-finite loss/grads, grad-norm
  spike vs. an EMA window, loss-spike z-score, loss-scale collapse) run
  *inside* the jitted program and produce a single int32 cause code; the
  anomalous update is suppressed in-program with ``lax.cond``.  Nothing on
  this path forces a host sync.

* **Host half** — :class:`StabilitySentinel`, the policy ladder.  The engine
  hands it the step stats at each optimizer boundary; the sentinel buffers
  them and reads the *previous* boundary's cause code (which the prior
  dispatch has already materialized, so the read does not block the device
  on the happy path — the same lagged-read discipline as the telemetry
  windowed drain).  An anomaly therefore surfaces on the host at most one
  step after it happened, matching the "detected ≤ 1 step later" contract.
  The ladder escalates: skip (already done in-program) → LR backoff after K
  consecutive anomalies → auto-rollback to the last verified checkpoint
  after M, quarantining the fingerprints of the offending batches so the
  replayed run skips them.

Batch fingerprints are content hashes of host-resident batch leaves
(:func:`fingerprint_batch`); device-resident batches are not fingerprinted
(hashing them would force a transfer).  The quarantine set and ladder
counters round-trip through the checkpoint manifest
(``state_dict``/``load_state_dict``), with merge semantics chosen for the
rollback path: quarantine entries union, ``auto_rollbacks`` never moves
backwards.
"""

import hashlib
from collections import OrderedDict, deque
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils import logger

# ---------------------------------------------------------------------------
# cause codes (int32, 0 = clean).  Order is detection priority: when several
# detectors fire on one step the lowest code wins.
# ---------------------------------------------------------------------------
OK = 0
NONFINITE_LOSS = 1
NONFINITE_GRADS = 2
GRAD_SPIKE = 3
LOSS_SPIKE = 4
SCALE_COLLAPSE = 5

CAUSE_NAMES = {
    OK: "ok",
    NONFINITE_LOSS: "nonfinite_loss",
    NONFINITE_GRADS: "nonfinite_grads",
    GRAD_SPIKE: "grad_norm_spike",
    LOSS_SPIKE: "loss_spike",
    SCALE_COLLAPSE: "scale_collapse",
}

# ladder actions (host side)
ACTION_SKIP = "skip"
ACTION_LR_BACKOFF = "lr_backoff"
ACTION_ROLLBACK = "rollback"


class SentinelState(NamedTuple):
    """Device-resident detector state (all scalars), threaded through the
    apply-step exactly like :class:`~..fp16.loss_scaler.LossScalerState`."""
    loss_ema: jnp.ndarray        # EW mean of the loss over clean steps
    loss_var: jnp.ndarray        # EW variance of the loss (West's update)
    gnorm_ema: jnp.ndarray       # EW mean of the global grad norm
    good_steps: jnp.ndarray      # clean steps seen (arms detectors)
    consecutive: jnp.ndarray     # current anomaly streak
    anomaly_count: jnp.ndarray   # total anomalies since init
    last_code: jnp.ndarray       # cause code of the latest observation
    scale_low_streak: jnp.ndarray  # boundaries with dynamic scale at min


def init_sentinel_state() -> SentinelState:
    """Fresh (unarmed) sentinel state; EMAs seed from the first clean step."""
    f = lambda v: jnp.asarray(v, jnp.float32)
    i = lambda v: jnp.asarray(v, jnp.int32)
    return SentinelState(
        loss_ema=f(0.0), loss_var=f(0.0), gnorm_ema=f(0.0),
        good_steps=i(0), consecutive=i(0), anomaly_count=i(0),
        last_code=i(0), scale_low_streak=i(0))


def sentinel_observe(state: SentinelState,
                     loss: jnp.ndarray,
                     grad_norm: jnp.ndarray,
                     overflow: jnp.ndarray,
                     at_min_scale: jnp.ndarray,
                     *,
                     warmup_steps: int,
                     ema_alpha: float,
                     grad_spike_factor: float,
                     loss_spike_zscore: float,
                     scale_collapse_windows: int) -> Tuple[SentinelState, jnp.ndarray]:
    """One in-program detector pass → (new state, int32 cause code).

    Pure/jittable; the keyword thresholds are trace-time constants from
    :class:`DeepSpeedStabilityConfig`.  EMA statistics update only on clean
    steps (an anomalous loss must not poison the baseline it is judged
    against), and the spike detectors stay disarmed until ``warmup_steps``
    clean observations have seeded the window.
    """
    loss = jnp.asarray(loss, jnp.float32).reshape(())
    grad_norm = jnp.asarray(grad_norm, jnp.float32).reshape(())
    overflow = jnp.asarray(overflow, bool).reshape(())
    at_min_scale = jnp.asarray(at_min_scale, bool).reshape(())
    a = jnp.float32(ema_alpha)

    nf_loss = ~jnp.isfinite(loss)
    nf_grads = overflow | ~jnp.isfinite(grad_norm)
    armed = state.good_steps >= warmup_steps
    l_dev = loss - state.loss_ema
    g_spike = armed & (grad_norm >
                       grad_spike_factor * jnp.maximum(state.gnorm_ema, 1e-12))
    l_sigma = jnp.sqrt(jnp.maximum(state.loss_var, 0.0)) + 1e-8
    # one-sided: a loss *drop* is never an anomaly
    l_spike = armed & (l_dev > loss_spike_zscore * l_sigma)
    low_streak = jnp.where(at_min_scale, state.scale_low_streak + 1, 0)
    collapse = low_streak >= scale_collapse_windows

    code = jnp.where(nf_loss, NONFINITE_LOSS,
           jnp.where(nf_grads, NONFINITE_GRADS,
           jnp.where(g_spike, GRAD_SPIKE,
           jnp.where(l_spike, LOSS_SPIKE,
           jnp.where(collapse, SCALE_COLLAPSE, OK))))).astype(jnp.int32)
    anomaly = code > 0
    clean = ~anomaly
    first = state.good_steps == 0

    # EW mean/variance (West): only clean steps move the window; the very
    # first clean step seeds the mean so warmup needs no special init value.
    new_loss_ema = jnp.where(
        clean, jnp.where(first, loss, state.loss_ema + a * l_dev),
        state.loss_ema)
    new_loss_var = jnp.where(
        clean, jnp.where(first, 0.0,
                         (1.0 - a) * (state.loss_var + a * l_dev * l_dev)),
        state.loss_var)
    new_gnorm_ema = jnp.where(
        clean, jnp.where(first, grad_norm,
                         state.gnorm_ema + a * (grad_norm - state.gnorm_ema)),
        state.gnorm_ema)

    new_state = SentinelState(
        loss_ema=new_loss_ema,
        loss_var=new_loss_var,
        gnorm_ema=new_gnorm_ema,
        good_steps=state.good_steps + clean.astype(jnp.int32),
        consecutive=jnp.where(anomaly, state.consecutive + 1, 0).astype(jnp.int32),
        anomaly_count=state.anomaly_count + anomaly.astype(jnp.int32),
        last_code=code,
        scale_low_streak=low_streak.astype(jnp.int32))
    return new_state, code


# ---------------------------------------------------------------------------
# batch fingerprinting
# ---------------------------------------------------------------------------

def fingerprint_batch(batch: Any) -> Optional[str]:
    """Content hash (blake2b/64-bit hex) of a batch pytree, or ``None``.

    Hashes dtype+shape+bytes of every host-resident leaf.  Returns ``None``
    when any leaf already lives on device (``jax.Array``): pulling it back
    would force the very sync the sentinel is designed to avoid, so such
    batches are simply not quarantine-eligible.
    """
    leaves = jax.tree_util.tree_leaves(batch)
    if not leaves:
        return None
    h = hashlib.blake2b(digest_size=8)
    for leaf in leaves:
        if isinstance(leaf, jax.Array) and not isinstance(leaf, np.ndarray):
            return None
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


class StabilitySentinel:
    """Host-side policy ladder over the device sentinel's cause codes.

    ``observe(step, stats, fingerprints)`` buffers the current boundary's
    stats and *processes the previous one* (lagged read → no blocking sync
    on the clean path).  It returns ``None`` on a clean previous step, or an
    action dict ``{"action": skip|lr_backoff|rollback, "step", "code",
    "cause", "consecutive"}`` for the engine to execute.  The sentinel emits
    ``anomaly`` telemetry itself; the engine emits the action kinds
    (``lr_backoff``/``auto_rollback``/``batch_quarantined``) once it has
    actually performed them.
    """

    def __init__(self, config, telemetry=None, read_fn=None):
        self.config = config
        self.telemetry = telemetry
        # injectable for the zero-sync unit tests: the only host reads of
        # device values go through this.
        self.read_fn = read_fn if read_fn is not None else (
            lambda v: float(np.asarray(v)))
        self._pending = None            # last boundary, not yet judged
        self.consecutive = 0            # host view of the anomaly streak
        self.lr_backoffs = 0
        self.auto_rollbacks = 0
        self.anomalies_total = 0
        # fingerprints of batches consumed by the current anomaly episode —
        # the quarantine candidates if the episode escalates to rollback.
        self._episode_fps: List[str] = []
        # recent per-step fingerprints, newest last (forensics / manifest)
        self.ring = deque(maxlen=max(int(config.quarantine_ring), 1))
        # fp -> global step at which it was quarantined (insertion-ordered)
        self._quarantined: "OrderedDict[str, int]" = OrderedDict()

    # -- quarantine ------------------------------------------------------- #
    fingerprint = staticmethod(fingerprint_batch)

    def is_quarantined(self, fp: Optional[str]) -> bool:
        return bool(fp) and fp in self._quarantined

    def quarantine(self, fps: Sequence[str], step: int) -> List[str]:
        """Add fingerprints to the quarantine set → the newly added ones."""
        if not self.config.quarantine:
            return []
        added = []
        for fp in fps:
            if fp and fp not in self._quarantined:
                self._quarantined[fp] = int(step)
                added.append(fp)
        # bound the set like the ring: oldest entries age out
        while len(self._quarantined) > self.ring.maxlen:
            self._quarantined.popitem(last=False)
        return added

    def quarantined(self) -> Dict[str, int]:
        return dict(self._quarantined)

    def episode_fingerprints(self) -> List[str]:
        """Quarantine candidates of the current anomaly episode (deduped)."""
        out, seen = [], set()
        for fp in self._episode_fps:
            if fp not in seen:
                seen.add(fp)
                out.append(fp)
        return out

    # -- the ladder ------------------------------------------------------- #
    def observe(self, step: int, stats: Dict[str, Any],
                fingerprints: Sequence[str] = ()) -> Optional[Dict[str, Any]]:
        fps = [fp for fp in fingerprints if fp]
        if fps:
            # dslint: ok(zero-sync) — host-side step counter, never traced
            self.ring.append({"step": int(step), "fps": fps})
        prev, self._pending = self._pending, {
            "step": int(step),  # dslint: ok(zero-sync) — host step counter
            "code": stats.get("anomaly_code"),
            "loss": stats.get("loss"),
            "grad_norm": stats.get("grad_norm"),
            "loss_scale": stats.get("loss_scale"),
            "fps": fps,
        }
        if prev is None:
            return None
        # dslint: ok(zero-sync) — host-side step counter, never traced
        return self._judge(prev, detected_at=int(step))

    def drain(self) -> Optional[Dict[str, Any]]:
        """Judge the buffered boundary immediately (end of run / tests)."""
        prev, self._pending = self._pending, None
        if prev is None:
            return None
        return self._judge(prev, detected_at=prev["step"])

    def _judge(self, rec, detected_at: int) -> Optional[Dict[str, Any]]:
        code = 0 if rec["code"] is None else int(self.read_fn(rec["code"]))
        if code <= 0:
            if self.consecutive:
                self.consecutive = 0
                self._episode_fps = []
            return None

        self.consecutive += 1
        self.anomalies_total += 1
        self._episode_fps.extend(rec["fps"])
        cause = CAUSE_NAMES.get(code, f"code_{code}")
        payload = {
            "step": rec["step"],
            "detected_at": detected_at,
            "code": code,
            "cause": cause,
            "consecutive": self.consecutive,
        }
        for key in ("loss", "grad_norm", "loss_scale"):
            if rec[key] is not None:
                try:
                    payload[key] = self.read_fn(rec[key])
                except (TypeError, ValueError):
                    pass
        if self.telemetry is not None:
            self.telemetry.emit("anomaly", dict(payload), step=rec["step"])
        logger.warning(
            f"[stability] anomaly at step {rec['step']} ({cause}), "
            f"streak {self.consecutive}")

        cfg = self.config
        action = ACTION_SKIP
        if (cfg.rollback_after > 0 and self.consecutive >= cfg.rollback_after
                and self.auto_rollbacks < cfg.max_auto_rollbacks):
            action = ACTION_ROLLBACK
        elif (cfg.lr_backoff_after > 0
              and self.consecutive >= cfg.lr_backoff_after
              and (self.consecutive - cfg.lr_backoff_after)
              % cfg.lr_backoff_after == 0
              and self.lr_backoffs < cfg.max_lr_backoffs):
            action = ACTION_LR_BACKOFF
        return {"action": action, **payload}

    def note_lr_backoff(self):
        self.lr_backoffs += 1

    def after_rollback(self, candidate_fps: Sequence[str], step: int) -> List[str]:
        """Bookkeeping once the engine's checkpoint load succeeded →
        the newly quarantined fingerprints."""
        added = self.quarantine(candidate_fps, step)
        self.auto_rollbacks += 1
        self.reset_episode()
        return added

    def reset_episode(self):
        """Forget the in-flight boundary and the anomaly streak (the arrays
        it references belong to a trajectory that no longer exists)."""
        self._pending = None
        self.consecutive = 0
        self._episode_fps = []

    # -- checkpoint round-trip ------------------------------------------- #
    def state_dict(self) -> Dict[str, Any]:
        return {
            "quarantine": [[fp, s] for fp, s in self._quarantined.items()],
            "ring": list(self.ring),
            "lr_backoffs": self.lr_backoffs,
            "auto_rollbacks": self.auto_rollbacks,
            "anomalies_total": self.anomalies_total,
        }

    def load_state_dict(self, sd: Optional[Dict[str, Any]]):
        """Restore from a manifest entry.  Merge semantics serve the
        rollback path: the quarantine set unions (a rollback must not forget
        what it just quarantined), and ``auto_rollbacks`` never decreases
        (the saved value predates the rollback that loaded it)."""
        sd = sd or {}
        for fp, s in sd.get("quarantine", []):
            if fp not in self._quarantined:
                self._quarantined[str(fp)] = int(s)
        self.ring.clear()
        for rec in sd.get("ring", []):
            self.ring.append(rec)
        self.lr_backoffs = int(sd.get("lr_backoffs", self.lr_backoffs))
        self.auto_rollbacks = max(self.auto_rollbacks,
                                  int(sd.get("auto_rollbacks", 0)))
        self.anomalies_total = int(sd.get("anomalies_total",
                                          self.anomalies_total))
        self.reset_episode()
