"""Activation checkpointing (rematerialization).

Reference: ``deepspeed/runtime/activation_checkpointing/checkpointing.py``
(``CheckpointFunction:474``, ``checkpoint():708``, ``configure():789``,
partition/cpu-offload helpers ``:255,366,421``).

TPU mapping (SURVEY §5.7):
- ``checkpoint(fn, *args)``      → ``jax.checkpoint`` with the configured
  rematerialization policy (XLA re-runs the forward in the backward pass;
  no RNG-state stashing needed — jax PRNG is functional).
- ``partition_activations``      → subsumed by SPMD: saved activations
  inherit the model's sharding constraints, so with a ``seq``/``tensor``
  axis they are already partitioned across ranks; the flag selects the
  dots-saveable policy so what *is* saved is the sharded matmul outputs.
- ``cpu_checkpointing``          → offload policy: saved dot products are
  kept in pinned host memory (``offload_dot_with_no_batch_dims``).
- ``contiguous_memory_optimization`` → XLA owns the arena; accepted as a
  no-op (there is no fragmentation to manage by hand).
- ``number_checkpoints``/``profile`` → recorded and surfaced via
  ``get_config``; segment counts are a model-side choice in functional
  code (e.g. scan-over-layers checkpoints once per layer).
"""

from typing import Any, Callable, Dict, Optional

import jax

from deepspeed_tpu.utils.logging import log_dist

# Module-global by design: the reference's ``deepspeed.checkpointing`` is
# likewise process-global configuration (``configure():789`` sets module
# state every caller shares).  Multi-engine processes that need different
# remat policies should configure between builds (the policy is read at
# trace time).
_config: Dict[str, Any] = {
    "partition_activations": False,
    "contiguous_memory_optimization": False,
    "cpu_checkpointing": False,
    "number_checkpoints": None,
    "synchronize_checkpoint_boundary": False,
    "profile": False,
}


def configure(mpu_=None, deepspeed_config: Optional[Dict] = None,
              partition_activations: Optional[bool] = None,
              contiguous_checkpointing: Optional[bool] = None,
              checkpoint_in_cpu: Optional[bool] = None,
              synchronize: Optional[bool] = None,
              profile: Optional[bool] = None,
              num_checkpoints: Optional[int] = None):
    """Reference ``configure():789`` surface: flags from kwargs or the
    ``activation_checkpointing`` config block."""
    block = {}
    if deepspeed_config:
        block = (deepspeed_config.get("activation_checkpointing", {})
                 if isinstance(deepspeed_config, dict) else {})
    for key, arg in (("partition_activations", partition_activations),
                     ("contiguous_memory_optimization", contiguous_checkpointing),
                     ("cpu_checkpointing", checkpoint_in_cpu),
                     ("synchronize_checkpoint_boundary", synchronize),
                     ("profile", profile),
                     ("number_checkpoints", num_checkpoints)):
        if arg is not None:
            _config[key] = arg
        elif key in block:
            _config[key] = block[key]
    log_dist(f"activation checkpointing configured: {_config}", ranks=[0])


def get_config() -> Dict[str, Any]:
    return dict(_config)


def checkpoint_policy():
    """The jax.checkpoint policy the current config selects.

    Every device-memory policy additionally saves the flash-attention
    kernel outputs (tagged ``flash_o``/``flash_lse`` in
    ``ops/pallas/flash_attention.py``): recomputing them means re-running
    the whole Pallas forward kernel in the backward pass — profiled at
    ~25% extra attention time — for a saving of only O(B·S·H·D) bytes."""
    if _config["cpu_checkpointing"]:
        return jax.checkpoint_policies.offload_dot_with_no_batch_dims(
            "device", "pinned_host")
    attn = jax.checkpoint_policies.save_only_these_names("flash_o", "flash_lse")
    if _config["partition_activations"]:
        # keep the (sharded) matmul outputs, recompute elementwise work
        base = jax.checkpoint_policies.dots_saveable
    else:
        base = jax.checkpoint_policies.nothing_saveable
    return jax.checkpoint_policies.save_from_both_policies(base, attn)


def checkpoint(function: Callable, *args):
    """Reference ``checkpoint():708``: run ``function`` under remat with
    the configured policy."""
    return jax.checkpoint(function, policy=checkpoint_policy())(*args)


def is_configured() -> bool:
    return any(_config[k] for k in ("partition_activations",
                                    "cpu_checkpointing",
                                    "contiguous_memory_optimization"))
