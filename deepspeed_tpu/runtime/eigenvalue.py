"""Block eigenvalue estimation (MoQ curvature signal).

Reference: ``deepspeed/runtime/eigenvalue.py:12`` (``Eigenvalue``): power
iteration on each transformer block's Hessian (via double-backward
Hessian-vector products) producing per-block max eigenvalues that MoQ
uses to delay quantization of high-curvature layers
(``engine.py:2013-2017``).

TPU redesign: the HVP is ``jax.jvp`` over ``jax.grad`` — one extra
forward+backward per iteration, jitted; no retain_graph bookkeeping.
``compute_eigenvalue`` takes the loss as a function of the *block*
sub-pytree (curvature w.r.t. one block) and runs normalized power
iteration with a convergence tolerance, exactly the reference loop.
"""

from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.utils.logging import log_dist


class Eigenvalue:

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "blocks", layer_num: int = 0):
        self.verbose = verbose
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.gas_boundary_resolution = gas_boundary_resolution
        self.layer_name = layer_name
        self.layer_num = layer_num

    # ------------------------------------------------------------------ #
    def _normalize(self, v):
        sq = sum(jnp.sum(jnp.square(x)) for x in jax.tree.leaves(v))
        norm = jnp.sqrt(sq) + self.stability
        return jax.tree.map(lambda x: jnp.nan_to_num(x / norm, posinf=0.0,
                                                     neginf=0.0), v)

    def make_hvp(self, loss_fn: Callable) -> Callable:
        """A jitted Hessian-vector product for ``loss_fn``.  Build ONCE
        and reuse across calls — re-jitting per call would recompile the
        whole forward+backward+jvp every invocation."""
        grad_fn = jax.grad(loss_fn)
        return jax.jit(lambda p, vec: jax.jvp(grad_fn, (p,), (vec,))[1])

    def compute_eigenvalue(self, loss_fn: Callable, block_params,
                           rng: Optional[jax.Array] = None,
                           hvp_fn: Optional[Callable] = None) -> float:
        """Max |eigenvalue| of the Hessian of ``loss_fn`` at
        ``block_params`` by power iteration on HVPs.  Pass a cached
        ``hvp_fn`` (from :meth:`make_hvp`) on hot paths."""
        rng = rng if rng is not None else jax.random.key(0)
        keys = jax.random.split(rng, len(jax.tree.leaves(block_params)))
        v = jax.tree.unflatten(
            jax.tree.structure(block_params),
            [jax.random.normal(k, p.shape, jnp.float32)
             for k, p in zip(keys, jax.tree.leaves(block_params))])
        v = self._normalize(v)
        hvp = hvp_fn if hvp_fn is not None else self.make_hvp(loss_fn)

        eig = 0.0
        for i in range(self.max_iter):
            hv = hvp(block_params, v)
            new_eig = float(sum(jnp.sum(a * b) for a, b in
                                zip(jax.tree.leaves(hv), jax.tree.leaves(v))))
            v = self._normalize(hv)
            if abs(new_eig) < 1e-12:
                eig = new_eig
                break
            if i > 0 and abs(new_eig - eig) / (abs(new_eig) + 1e-12) < self.tol:
                eig = new_eig
                break
            eig = new_eig
        if self.verbose:
            log_dist(f"eigenvalue: {eig:.4e} ({i + 1} iters)", ranks=[0])
        return eig

    def compute_block_eigenvalues(self, loss_of_blocks: Callable,
                                  blocks: List, rng=None) -> Dict[int, float]:
        """Per-block eigenvalues + normalized scaling factors (reference
        ``compute_eigenvalue`` over ``layer_num`` blocks; MoQ divides each
        layer's ratio by its factor)."""
        rng = rng if rng is not None else jax.random.key(0)
        eigs = {}
        for i, block in enumerate(blocks):
            eigs[i] = self.compute_eigenvalue(
                lambda b, i=i: loss_of_blocks(b, i), block,
                jax.random.fold_in(rng, i))
        mx = max(abs(v) for v in eigs.values()) or 1.0
        return {i: (v, abs(v) / mx + 1.0) for i, v in eigs.items()}
